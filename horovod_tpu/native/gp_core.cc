// Gaussian-process + expected-improvement core for the autotuner.
//
// Reference equivalents (reimplemented natively, not copied):
//   - gaussian_process.cc (RBF-kernel GP regression; the reference uses
//     Eigen — here a self-contained Cholesky solve, no dependency)
//   - bayesian_optimization.cc (expected-improvement acquisition; the
//     reference maximizes EI with LBFGS over a continuous space — our
//     tunables are a small discrete grid, so EI is evaluated per
//     candidate and argmax'd, same as the Python fallback in
//     common/autotune.py)
//
// One stateless call: fit on (x, y), score EI on candidates. The
// matrices involved are tiny (tens of samples, 1-2 dims), so the O(n^3)
// Cholesky is microseconds — the win over the Python path is removing
// numpy-allocation jitter from the per-cycle tuning step.

#include <cmath>
#include <cstdint>
#include <vector>

namespace {

// Dense Cholesky A = L L^T (in place, lower). Returns false if not PD.
bool cholesky(std::vector<double>& a, int n) {
  for (int i = 0; i < n; ++i) {
    for (int j = 0; j <= i; ++j) {
      double s = a[i * n + j];
      for (int k = 0; k < j; ++k) s -= a[i * n + k] * a[j * n + k];
      if (i == j) {
        if (s <= 0.0) return false;
        a[i * n + i] = std::sqrt(s);
      } else {
        a[i * n + j] = s / a[j * n + j];
      }
    }
    for (int j = i + 1; j < n; ++j) a[i * n + j] = 0.0;
  }
  return true;
}

// Solve L L^T x = b given the Cholesky factor.
void chol_solve(const std::vector<double>& l, int n, std::vector<double>& b) {
  for (int i = 0; i < n; ++i) {  // forward: L y = b
    double s = b[i];
    for (int k = 0; k < i; ++k) s -= l[i * n + k] * b[k];
    b[i] = s / l[i * n + i];
  }
  for (int i = n - 1; i >= 0; --i) {  // backward: L^T x = y
    double s = b[i];
    for (int k = i + 1; k < n; ++k) s -= l[k * n + i] * b[k];
    b[i] = s / l[i * n + i];
  }
}

double rbf(const double* a, const double* b, int d, double ls) {
  double s = 0.0;
  for (int k = 0; k < d; ++k) {
    double diff = a[k] - b[k];
    s += diff * diff;
  }
  return std::exp(-0.5 * s / (ls * ls));
}

double norm_cdf(double z) { return 0.5 * (1.0 + std::erf(z / M_SQRT2)); }

double norm_pdf(double z) {
  return std::exp(-0.5 * z * z) / std::sqrt(2.0 * M_PI);
}

}  // namespace

extern "C" {

// GP fit on (x[n*d], y[n]) with RBF(length_scale) + noise, then compute
// expected improvement for cand[m*d] into ei_out[m] (and optionally the
// posterior mean into mu_out[m] if non-null). Returns the argmax index
// of EI, or -1 on numerical failure (caller falls back).
int64_t hvd_gp_ei(const double* x, const double* y, int64_t n, int64_t d,
                  const double* cand, int64_t m, double length_scale,
                  double noise, double xi, double* ei_out, double* mu_out) {
  if (n <= 0 || m <= 0 || d <= 0) return -1;
  const int ni = static_cast<int>(n);
  std::vector<double> k(ni * ni);
  for (int i = 0; i < ni; ++i) {
    for (int j = 0; j < ni; ++j)
      k[i * ni + j] = rbf(x + i * d, x + j * d, d, length_scale);
    k[i * ni + i] += noise;
  }
  if (!cholesky(k, ni)) return -1;

  std::vector<double> alpha(y, y + ni);  // K^-1 y
  chol_solve(k, ni, alpha);

  double best = y[0];
  for (int i = 1; i < ni; ++i)
    if (y[i] > best) best = y[i];

  int64_t argmax = 0;
  double ei_max = -1.0;
  std::vector<double> ks(ni), v(ni);
  for (int64_t c = 0; c < m; ++c) {
    for (int i = 0; i < ni; ++i)
      ks[i] = rbf(cand + c * d, x + i * d, d, length_scale);
    double mu = 0.0;
    for (int i = 0; i < ni; ++i) mu += ks[i] * alpha[i];
    v = ks;
    chol_solve(k, ni, v);  // K^-1 ks
    double var = 1.0;      // k(c,c) = 1 for RBF
    for (int i = 0; i < ni; ++i) var -= ks[i] * v[i];
    if (var < 1e-12) var = 1e-12;
    double sigma = std::sqrt(var);
    double imp = mu - best - xi;
    double z = imp / sigma;
    double ei = imp * norm_cdf(z) + sigma * norm_pdf(z);
    if (ei_out) ei_out[c] = ei;
    if (mu_out) mu_out[c] = mu;
    if (ei > ei_max) {
      ei_max = ei;
      argmax = c;
    }
  }
  return argmax;
}

}  // extern "C"
