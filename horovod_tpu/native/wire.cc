// Wire format — packed binary serialization of controller messages.
//
// TPU-native equivalent of the reference's FlatBuffers-based wire layer
// (horovod/common/message.cc + wire/message.fbs): Request{rank, op_type,
// reduce_op, root_rank, dtype, name, shape[]} and Response{ok, error,
// name} encoded into a compact length-prefixed little-endian buffer, so
// multi-process controller rounds ship bytes (not JSON) through the
// coordination-service KV store. ~10x smaller + no Python json overhead
// on the negotiation path.
//
// Layout (all little-endian):
//   Request:  u8 tag=1 | i32 rank | u8 op_type | u8 reduce_op
//             | i32 root_rank | u8 dtype | u16 name_len | name bytes
//             | u8 ndim | i64 shape[ndim]
//   Response: u8 tag=2 | u8 ok | u16 name_len | name | u16 err_len | err
//
// C ABI: encode into caller buffer, return bytes written (or -1 if the
// buffer is too small / malformed). Decode fills out-params.

#include <cstdint>
#include <cstring>

namespace {

inline void put_i32(uint8_t*& p, int32_t v) { memcpy(p, &v, 4); p += 4; }
inline void put_i64(uint8_t*& p, int64_t v) { memcpy(p, &v, 8); p += 8; }
inline void put_u16(uint8_t*& p, uint16_t v) { memcpy(p, &v, 2); p += 2; }
inline int32_t get_i32(const uint8_t*& p) {
  int32_t v; memcpy(&v, p, 4); p += 4; return v;
}
inline int64_t get_i64(const uint8_t*& p) {
  int64_t v; memcpy(&v, p, 8); p += 8; return v;
}
inline uint16_t get_u16(const uint8_t*& p) {
  uint16_t v; memcpy(&v, p, 2); p += 2; return v;
}

}  // namespace

extern "C" {

// Returns bytes written, or -1 on overflow.
int64_t hvt_encode_request(int32_t rank, uint8_t op_type, uint8_t reduce_op,
                           int32_t root_rank, uint8_t dtype,
                           const char* name, const int64_t* shape,
                           uint8_t ndim, uint8_t* out, int64_t out_cap) {
  uint16_t name_len = (uint16_t)strnlen(name, 65535);
  int64_t need = 1 + 4 + 1 + 1 + 4 + 1 + 2 + name_len + 1 + 8LL * ndim;
  if (need > out_cap) return -1;
  uint8_t* p = out;
  *p++ = 1;
  put_i32(p, rank);
  *p++ = op_type;
  *p++ = reduce_op;
  put_i32(p, root_rank);
  *p++ = dtype;
  put_u16(p, name_len);
  memcpy(p, name, name_len); p += name_len;
  *p++ = ndim;
  for (uint8_t i = 0; i < ndim; ++i) put_i64(p, shape[i]);
  return p - out;
}

// Decodes into out-params; name copied into name_out (cap name_cap).
// Returns 0 ok, -1 malformed.
int64_t hvt_decode_request(const uint8_t* buf, int64_t len, int32_t* rank,
                           uint8_t* op_type, uint8_t* reduce_op,
                           int32_t* root_rank, uint8_t* dtype,
                           char* name_out, int64_t name_cap,
                           int64_t* shape_out, uint8_t* ndim_out,
                           uint8_t shape_cap) {
  if (len < 14 || buf[0] != 1) return -1;
  const uint8_t* p = buf + 1;
  *rank = get_i32(p);
  *op_type = *p++;
  *reduce_op = *p++;
  *root_rank = get_i32(p);
  *dtype = *p++;
  uint16_t name_len = get_u16(p);
  if ((p - buf) + name_len + 1 > len || name_len + 1 > name_cap) return -1;
  memcpy(name_out, p, name_len);
  name_out[name_len] = 0;
  p += name_len;
  uint8_t ndim = *p++;
  if (ndim > shape_cap || (p - buf) + 8LL * ndim > len) return -1;
  for (uint8_t i = 0; i < ndim; ++i) shape_out[i] = get_i64(p);
  *ndim_out = ndim;
  return 0;
}

int64_t hvt_encode_response(uint8_t ok, const char* name, const char* error,
                            uint8_t* out, int64_t out_cap) {
  uint16_t name_len = (uint16_t)strnlen(name, 65535);
  uint16_t err_len = (uint16_t)strnlen(error, 65535);
  int64_t need = 1 + 1 + 2 + name_len + 2 + err_len;
  if (need > out_cap) return -1;
  uint8_t* p = out;
  *p++ = 2;
  *p++ = ok;
  put_u16(p, name_len);
  memcpy(p, name, name_len); p += name_len;
  put_u16(p, err_len);
  memcpy(p, error, err_len); p += err_len;
  return p - out;
}

int64_t hvt_decode_response(const uint8_t* buf, int64_t len, uint8_t* ok,
                            char* name_out, int64_t name_cap,
                            char* err_out, int64_t err_cap) {
  if (len < 6 || buf[0] != 2) return -1;
  const uint8_t* p = buf + 1;
  *ok = *p++;
  uint16_t name_len = get_u16(p);
  if ((p - buf) + name_len + 2 > len || name_len + 1 > name_cap) return -1;
  memcpy(name_out, p, name_len);
  name_out[name_len] = 0;
  p += name_len;
  uint16_t err_len = get_u16(p);
  if ((p - buf) + err_len > len || err_len + 1 > err_cap) return -1;
  memcpy(err_out, p, err_len);
  err_out[err_len] = 0;
  return 0;
}

}  // extern "C"
