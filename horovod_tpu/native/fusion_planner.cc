// Fusion planner — native greedy bucketing for large parameter trees.
//
// TPU-native equivalent of the reference's native fusion machinery
// (horovod/common/controller.cc:686-809 FuseResponses +
// fusion_buffer_manager.cc): given per-leaf (elem_count, dtype_code,
// itemsize), assign each leaf to a fusion bucket of <= threshold bytes,
// grouping same-dtype leaves in order. Pure index computation — the
// actual data movement is XLA's — but for 100k-leaf trees (large LLM
// param sets re-planned per signature) the native pass keeps plan time
// off the Python profile.
//
// C ABI: hvt_plan_fusion(n, elem_counts[], dtype_codes[], itemsizes[],
//                        threshold_bytes, bucket_ids_out[]) -> n_buckets

#include <cstdint>
#include <unordered_map>
#include <vector>

extern "C" {

int64_t hvt_plan_fusion(int64_t n, const int64_t* elem_counts,
                        const int32_t* dtype_codes,
                        const int32_t* itemsizes,
                        int64_t threshold_bytes,
                        int32_t* bucket_ids_out) {
  // Per-dtype running bucket: {dtype -> (bucket id, bytes used)}.
  struct Open { int32_t id; int64_t used; };
  std::unordered_map<int32_t, Open> open;
  int32_t next_bucket = 0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t bytes = elem_counts[i] * (int64_t)itemsizes[i];
    auto it = open.find(dtype_codes[i]);
    if (it == open.end()) {
      open[dtype_codes[i]] = {next_bucket, bytes};
      bucket_ids_out[i] = next_bucket++;
      continue;
    }
    Open& o = it->second;
    if (o.used > 0 && o.used + bytes > threshold_bytes) {
      o.id = next_bucket++;
      o.used = 0;
    }
    o.used += bytes;
    bucket_ids_out[i] = o.id;
  }
  return next_bucket;
}

}  // extern "C"
