// Controller negotiation core — native implementation of the rank-0
// coordinator bookkeeping and the response cache.
//
// Reference equivalents (reimplemented, not copied):
//   - IncrementTensorCount: controller.cc:837-860 — a tensor becomes
//     "ready" when all world_size ranks have reported it.
//   - ResponseCache: response_cache.cc/h:45-100 — LRU bit-indexed cache of
//     negotiated signatures so repeat iterations skip the coordinator
//     round-trip; bounded capacity with LRU eviction.
//
// Exposed as a C ABI consumed via ctypes (horovod_tpu/native/__init__.py),
// mirroring how the reference exposes its core through extern "C"
// (operations.cc:690-878).

#include <cstdint>
#include <cstring>
#include <list>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct NegotiationTable {
  int world_size;
  std::mutex mu;
  // name -> bitmask-ish count of ranks that reported (vector<bool> per
  // name keeps duplicate reports idempotent, as the reference's
  // std::unordered_set<int32_t> ranks does).
  std::unordered_map<std::string, std::vector<uint8_t>> pending;
};

struct LruCache {
  size_t capacity;
  std::mutex mu;
  std::list<std::string> order;  // front = most recent
  std::unordered_map<std::string, std::list<std::string>::iterator> index;
};

}  // namespace

extern "C" {

// -- negotiation table ------------------------------------------------------

void* hvd_nt_new(int world_size) {
  auto* t = new NegotiationTable();
  t->world_size = world_size;
  return t;
}

void hvd_nt_free(void* h) { delete static_cast<NegotiationTable*>(h); }

// Record that `rank` submitted `name`. Returns 1 when the entry just
// became complete (all ranks reported; entry is then cleared), 0 when
// still pending, -1 on duplicate submission by the same rank (the
// duplicate-in-flight error of common.h:163-166).
int hvd_nt_increment(void* h, const char* name, int rank) {
  auto* t = static_cast<NegotiationTable*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  // Validate BEFORE touching the table: an out-of-range rank must not
  // default-construct a phantom pending entry (which could never
  // complete and would inflate pending_count forever).
  if (rank < 0 || rank >= t->world_size) return -1;
  auto& ranks = t->pending[name];
  if (ranks.empty()) ranks.assign(t->world_size, 0);
  if (ranks[rank]) return -1;
  ranks[rank] = 1;
  int count = 0;
  for (uint8_t r : ranks) count += r;
  if (count == t->world_size) {
    t->pending.erase(name);
    return 1;
  }
  return 0;
}

// Number of tensors currently mid-negotiation (StallInspector input).
int64_t hvd_nt_pending(void* h) {
  auto* t = static_cast<NegotiationTable*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  return static_cast<int64_t>(t->pending.size());
}

// Ranks missing for `name`, written as bytes into out (1 = missing);
// returns count of missing ranks, or -1 if name unknown.
int hvd_nt_missing(void* h, const char* name, uint8_t* out, int out_len) {
  auto* t = static_cast<NegotiationTable*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  auto it = t->pending.find(name);
  if (it == t->pending.end()) return -1;
  int missing = 0;
  for (int r = 0; r < t->world_size && r < out_len; ++r) {
    out[r] = it->second[r] ? 0 : 1;
    missing += out[r];
  }
  return missing;
}

// -- LRU response cache -----------------------------------------------------

void* hvd_lru_new(int64_t capacity) {
  auto* c = new LruCache();
  c->capacity = capacity > 0 ? static_cast<size_t>(capacity) : 1;
  return c;
}

void hvd_lru_free(void* h) { delete static_cast<LruCache*>(h); }

// Returns 1 on hit (and refreshes recency), 0 on miss.
int hvd_lru_lookup(void* h, const char* key) {
  auto* c = static_cast<LruCache*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  auto it = c->index.find(key);
  if (it == c->index.end()) return 0;
  c->order.splice(c->order.begin(), c->order, it->second);
  return 1;
}

// Insert key; if capacity exceeded, evicts LRU entry and copies the
// evicted key into evicted_out (if non-null, up to out_len-1 chars).
// Returns 1 if an eviction happened else 0.
int hvd_lru_put(void* h, const char* key, char* evicted_out, int out_len) {
  auto* c = static_cast<LruCache*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  auto it = c->index.find(key);
  if (it != c->index.end()) {
    c->order.splice(c->order.begin(), c->order, it->second);
    return 0;
  }
  c->order.push_front(key);
  c->index[key] = c->order.begin();
  if (c->order.size() > c->capacity) {
    const std::string& victim = c->order.back();
    if (evicted_out && out_len > 0) {
      std::strncpy(evicted_out, victim.c_str(), out_len - 1);
      evicted_out[out_len - 1] = '\0';
    }
    c->index.erase(victim);
    c->order.pop_back();
    return 1;
  }
  return 0;
}

int64_t hvd_lru_size(void* h) {
  auto* c = static_cast<LruCache*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  return static_cast<int64_t>(c->order.size());
}

void hvd_lru_erase(void* h, const char* key) {
  auto* c = static_cast<LruCache*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  auto it = c->index.find(key);
  if (it == c->index.end()) return;
  c->order.erase(it->second);
  c->index.erase(it);
}

}  // extern "C"
