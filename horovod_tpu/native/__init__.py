"""Native runtime bindings (ctypes over libhvdtpu_native.so).

The reference keeps its runtime core in C++ (SURVEY.md §2.1: operations,
timeline, wire format, fusion — ~18.5k LoC); this package is the
TPU-native counterpart for the pieces that remain host-side under XLA:
the timeline writer (lock-free ring + writer thread), the controller wire
format, and the fusion planner. Built on first import with the system
toolchain; every consumer has a pure-Python fallback, so the framework
works (slower) without a compiler.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple
from ..common.config import runtime_env

logger = logging.getLogger("horovod_tpu")

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libhvdtpu_native.so")
_lib: Optional[ctypes.CDLL] = None
_lib_lock = threading.Lock()
_build_attempted = False


def _build() -> bool:
    try:
        r = subprocess.run(["make", "-C", _DIR, "-s"],
                           capture_output=True, text=True, timeout=120)
        if r.returncode != 0:
            logger.warning("native build failed:\n%s", r.stderr[-2000:])
            return False
        return os.path.exists(_LIB_PATH)
    except (OSError, subprocess.TimeoutExpired) as e:
        logger.warning("native build unavailable: %s", e)
        return False


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _build_attempted
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB_PATH):
            if _build_attempted:
                return None
            _build_attempted = True
            if runtime_env("DISABLE_NATIVE") == "1":
                return None
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as e:
            logger.warning("native library load failed: %s", e)
            return None
        try:
            _bind_signatures(lib)
        except AttributeError:
            # Stale .so from an older source tree (missing new symbols):
            # rebuild once, then either bind or fall back to pure Python.
            if _build_attempted:
                logger.warning("native library is stale and rebuild "
                               "already failed; using Python fallbacks")
                return None
            _build_attempted = True
            if not _build():
                return None
            try:
                lib = ctypes.CDLL(_LIB_PATH)
                _bind_signatures(lib)
            except (OSError, AttributeError) as e:
                logger.warning("native library unusable after rebuild: %s",
                               e)
                return None
        _lib = lib
        return _lib


def _bind_signatures(lib: ctypes.CDLL) -> None:
        # Signatures.
        lib.hvt_timeline_start.argtypes = [ctypes.c_char_p]
        lib.hvt_timeline_start.restype = ctypes.c_int
        lib.hvt_timeline_event.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                           ctypes.c_char, ctypes.c_double]
        lib.hvt_timeline_event.restype = None
        lib.hvt_timeline_stop.restype = ctypes.c_int
        lib.hvt_timeline_dropped.restype = ctypes.c_uint64
        lib.hvt_plan_fusion.argtypes = [
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int32)]
        lib.hvt_plan_fusion.restype = ctypes.c_int64
        lib.hvt_encode_request.restype = ctypes.c_int64
        lib.hvt_encode_request.argtypes = [
            ctypes.c_int32, ctypes.c_uint8, ctypes.c_uint8, ctypes.c_int32,
            ctypes.c_uint8, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_uint8,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64]
        lib.hvt_decode_request.restype = ctypes.c_int64
        lib.hvt_decode_request.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_char_p,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint8]
        lib.hvt_encode_response.restype = ctypes.c_int64
        lib.hvt_encode_response.argtypes = [
            ctypes.c_uint8, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64]
        lib.hvt_decode_response.restype = ctypes.c_int64
        lib.hvt_decode_response.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_char_p,
            ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64]
        # controller core
        lib.hvd_nt_new.argtypes = [ctypes.c_int]
        lib.hvd_nt_new.restype = ctypes.c_void_p
        lib.hvd_nt_free.argtypes = [ctypes.c_void_p]
        lib.hvd_nt_increment.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                         ctypes.c_int]
        lib.hvd_nt_increment.restype = ctypes.c_int
        lib.hvd_nt_pending.argtypes = [ctypes.c_void_p]
        lib.hvd_nt_pending.restype = ctypes.c_int64
        lib.hvd_nt_missing.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.POINTER(ctypes.c_uint8),
                                       ctypes.c_int]
        lib.hvd_nt_missing.restype = ctypes.c_int
        lib.hvd_lru_new.argtypes = [ctypes.c_int64]
        lib.hvd_lru_new.restype = ctypes.c_void_p
        lib.hvd_lru_free.argtypes = [ctypes.c_void_p]
        lib.hvd_lru_lookup.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.hvd_lru_lookup.restype = ctypes.c_int
        lib.hvd_lru_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_char_p, ctypes.c_int]
        lib.hvd_lru_put.restype = ctypes.c_int
        lib.hvd_lru_size.argtypes = [ctypes.c_void_p]
        lib.hvd_lru_size.restype = ctypes.c_int64
        lib.hvd_lru_erase.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        # GP/EI autotuner core
        lib.hvd_gp_ei.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
            ctypes.c_double, ctypes.c_double, ctypes.c_double,
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double)]
        lib.hvd_gp_ei.restype = ctypes.c_int64


def available() -> bool:
    return load() is not None


# -- fusion planner --------------------------------------------------------

def plan_fusion_native(elem_counts: Sequence[int],
                       dtype_codes: Sequence[int],
                       itemsizes: Sequence[int],
                       threshold_bytes: int) -> Optional[List[int]]:
    """Bucket ids per leaf, or None if native is unavailable."""
    lib = load()
    if lib is None:
        return None
    n = len(elem_counts)
    ec = (ctypes.c_int64 * n)(*elem_counts)
    dc = (ctypes.c_int32 * n)(*dtype_codes)
    it = (ctypes.c_int32 * n)(*itemsizes)
    out = (ctypes.c_int32 * n)()
    lib.hvt_plan_fusion(n, ec, dc, it, threshold_bytes, out)
    return list(out)


# -- wire format -----------------------------------------------------------

OP_CODES = {"allreduce": 0, "allgather": 1, "broadcast": 2, "alltoall": 3,
            "reducescatter": 4, "barrier": 5, "join": 6}
DTYPE_CODES = {"float32": 0, "bfloat16": 1, "float16": 2, "float64": 3,
               "int32": 4, "int64": 5, "int8": 6, "uint8": 7, "bool": 8}


def encode_request(rank: int, op_type: str, reduce_op: int, root_rank: int,
                   dtype: str, name: str,
                   shape: Sequence[int]) -> Optional[bytes]:
    lib = load()
    if lib is None:
        return None
    ndim = len(shape)
    shp = (ctypes.c_int64 * max(ndim, 1))(*shape) if ndim else \
        (ctypes.c_int64 * 1)()
    cap = 64 + len(name) + 8 * ndim
    buf = (ctypes.c_uint8 * cap)()
    n = lib.hvt_encode_request(
        rank, OP_CODES[op_type], reduce_op, root_rank,
        DTYPE_CODES.get(dtype, 0), name.encode(), shp, ndim, buf, cap)
    if n < 0:
        return None
    return bytes(buf[:n])


def decode_request(data: bytes) -> Optional[Tuple]:
    lib = load()
    if lib is None:
        return None
    buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
    rank = ctypes.c_int32()
    op = ctypes.c_uint8()
    rop = ctypes.c_uint8()
    root = ctypes.c_int32()
    dt = ctypes.c_uint8()
    name = ctypes.create_string_buffer(65536)
    shape = (ctypes.c_int64 * 32)()
    ndim = ctypes.c_uint8()
    rc = lib.hvt_decode_request(
        buf, len(data), ctypes.byref(rank), ctypes.byref(op),
        ctypes.byref(rop), ctypes.byref(root), ctypes.byref(dt),
        name, 65536, shape, ctypes.byref(ndim), 32)
    if rc != 0:
        return None
    op_names = {v: k for k, v in OP_CODES.items()}
    dt_names = {v: k for k, v in DTYPE_CODES.items()}
    op_name = op_names.get(op.value)
    dt_name = dt_names.get(dt.value)
    if op_name is None or dt_name is None:
        return None  # unknown code = malformed/version-skewed message
    return (rank.value, op_name, rop.value, root.value,
            dt_name, name.value.decode(),
            tuple(shape[i] for i in range(ndim.value)))


def encode_response(ok: bool, name: str, error: str) -> Optional[bytes]:
    lib = load()
    if lib is None:
        return None
    cap = 16 + len(name) + len(error)
    buf = (ctypes.c_uint8 * cap)()
    n = lib.hvt_encode_response(1 if ok else 0, name.encode(),
                                error.encode(), buf, cap)
    return bytes(buf[:n]) if n >= 0 else None


def decode_response(data: bytes) -> Optional[Tuple[bool, str, str]]:
    lib = load()
    if lib is None:
        return None
    buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
    ok = ctypes.c_uint8()
    name = ctypes.create_string_buffer(65536)
    err = ctypes.create_string_buffer(65536)
    rc = lib.hvt_decode_response(buf, len(data), ctypes.byref(ok),
                                 name, 65536, err, 65536)
    if rc != 0:
        return None
    return bool(ok.value), name.value.decode(), err.value.decode()


# -- controller negotiation core -------------------------------------------

class NegotiationTable:
    """Native tensor-readiness table (reference IncrementTensorCount,
    controller.cc:837-860). Falls back to a dict when the native library
    is unavailable."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self._lib = load()
        if self._lib is not None:
            self._h = self._lib.hvd_nt_new(world_size)
        else:
            self._h = None
            self._pending = {}
            self._py_lock = threading.Lock()

    def increment(self, name: str, rank: int) -> int:
        """1 = just became ready (all ranks in), 0 = pending,
        -1 = duplicate/invalid."""
        if self._h is not None:
            return self._lib.hvd_nt_increment(self._h, name.encode(), rank)
        with self._py_lock:
            if not 0 <= rank < self.world_size:
                return -1
            ranks = self._pending.setdefault(name, set())
            if rank in ranks:
                return -1
            ranks.add(rank)
            if len(ranks) == self.world_size:
                del self._pending[name]
                return 1
            return 0

    def pending_count(self) -> int:
        if self._h is not None:
            return int(self._lib.hvd_nt_pending(self._h))
        with self._py_lock:
            return len(self._pending)

    def missing_ranks(self, name: str) -> Optional[List[int]]:
        """Ranks that have not yet reported `name` (StallInspector input);
        None if the name is unknown/complete."""
        if self._h is not None:
            out = (ctypes.c_uint8 * self.world_size)()
            n = self._lib.hvd_nt_missing(self._h, name.encode(), out,
                                         self.world_size)
            if n < 0:
                return None
            return [i for i in range(self.world_size) if out[i]]
        with self._py_lock:
            if name not in self._pending:
                return None
            got = self._pending[name]
            return [r for r in range(self.world_size) if r not in got]

    def __del__(self):
        if getattr(self, "_h", None) is not None:
            self._lib.hvd_nt_free(self._h)
            self._h = None


class ResponseCacheNative:
    """Bounded LRU signature cache (reference response_cache.cc LRU bits).
    Falls back to an ordered-dict LRU without the native library."""

    def __init__(self, capacity: int):
        self.capacity = max(int(capacity), 1)
        self._lib = load()
        if self._lib is not None:
            self._h = self._lib.hvd_lru_new(self.capacity)
            # One reusable out-buffer per cache (not per put call).
            self._evict_buf = ctypes.create_string_buffer(65536)
        else:
            self._h = None
            import collections

            self._od = collections.OrderedDict()
            self._py_lock = threading.Lock()

    def lookup(self, key: str) -> bool:
        if self._h is not None:
            return bool(self._lib.hvd_lru_lookup(self._h, key.encode()))
        with self._py_lock:
            if key in self._od:
                self._od.move_to_end(key)
                return True
            return False

    def put(self, key: str, want_evicted: bool = True) -> Optional[str]:
        """Insert; returns the evicted key if capacity forced one out.
        Pass ``want_evicted=False`` on hot paths to skip the out-buffer
        (the native side accepts NULL)."""
        if self._h is not None:
            if not want_evicted:
                self._lib.hvd_lru_put(self._h, key.encode(), None, 0)
                return None
            buf = self._evict_buf
            if self._lib.hvd_lru_put(self._h, key.encode(), buf,
                                     len(buf)):
                return buf.value.decode()
            return None
        with self._py_lock:
            if key in self._od:
                self._od.move_to_end(key)
                return None
            self._od[key] = True
            if len(self._od) > self.capacity:
                victim, _ = self._od.popitem(last=False)
                return victim
            return None

    def erase(self, key: str) -> None:
        if self._h is not None:
            self._lib.hvd_lru_erase(self._h, key.encode())
            return
        with self._py_lock:
            self._od.pop(key, None)

    def __len__(self) -> int:
        if self._h is not None:
            return int(self._lib.hvd_lru_size(self._h))
        with self._py_lock:
            return len(self._od)

    def __del__(self):
        if getattr(self, "_h", None) is not None:
            self._lib.hvd_lru_free(self._h)
            self._h = None


# -- GP / expected-improvement core ----------------------------------------

def gp_ei_native(x, y, candidates, length_scale: float = 1.0,
                 noise: float = 1e-4, xi: float = 0.01
                 ) -> Optional[Tuple[int, List[float]]]:
    """(argmax index, EI per candidate) via the native GP core, or None if
    unavailable/numerically failed (caller uses the numpy path)."""
    lib = load()
    if lib is None:
        return None
    import numpy as np

    x = np.ascontiguousarray(np.atleast_2d(np.asarray(x, dtype=np.float64)))
    y = np.ascontiguousarray(np.asarray(y, dtype=np.float64))
    c = np.ascontiguousarray(np.atleast_2d(
        np.asarray(candidates, dtype=np.float64)))
    if x.shape[0] != y.shape[0] or x.shape[1] != c.shape[1]:
        return None
    n, d = x.shape
    m = c.shape[0]
    ei = np.empty(m, dtype=np.float64)
    dp = ctypes.POINTER(ctypes.c_double)
    idx = lib.hvd_gp_ei(
        x.ctypes.data_as(dp), y.ctypes.data_as(dp), n, d,
        c.ctypes.data_as(dp), m, length_scale, noise, xi,
        ei.ctypes.data_as(dp), None)
    if idx < 0:
        return None
    return int(idx), ei.tolist()


# -- timeline --------------------------------------------------------------

class NativeTimelineWriter:
    """Thin wrapper used by horovod_tpu.common.timeline.Timeline."""

    def __init__(self):
        self._lib = load()

    @property
    def available(self) -> bool:
        return self._lib is not None

    def start(self, path: str) -> bool:
        return self._lib is not None and \
            self._lib.hvt_timeline_start(path.encode()) == 0

    def event(self, tid: str, name: str, phase: str, ts_us: float) -> None:
        self._lib.hvt_timeline_event(tid.encode(), name.encode(),
                                     phase.encode()[0], ts_us)

    def stop(self) -> None:
        if self._lib is not None:
            self._lib.hvt_timeline_stop()

    def dropped(self) -> int:
        return int(self._lib.hvt_timeline_dropped()) if self._lib else 0
