"""Native runtime bindings (ctypes over libhvdtpu_native.so).

The reference keeps its runtime core in C++ (SURVEY.md §2.1: operations,
timeline, wire format, fusion — ~18.5k LoC); this package is the
TPU-native counterpart for the pieces that remain host-side under XLA:
the timeline writer (lock-free ring + writer thread), the controller wire
format, and the fusion planner. Built on first import with the system
toolchain; every consumer has a pure-Python fallback, so the framework
works (slower) without a compiler.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

logger = logging.getLogger("horovod_tpu")

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libhvdtpu_native.so")
_lib: Optional[ctypes.CDLL] = None
_lib_lock = threading.Lock()
_build_attempted = False


def _build() -> bool:
    try:
        r = subprocess.run(["make", "-C", _DIR, "-s"],
                           capture_output=True, text=True, timeout=120)
        if r.returncode != 0:
            logger.warning("native build failed:\n%s", r.stderr[-2000:])
            return False
        return os.path.exists(_LIB_PATH)
    except (OSError, subprocess.TimeoutExpired) as e:
        logger.warning("native build unavailable: %s", e)
        return False


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _build_attempted
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB_PATH):
            if _build_attempted:
                return None
            _build_attempted = True
            if os.environ.get("HVD_TPU_DISABLE_NATIVE") == "1":
                return None
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as e:
            logger.warning("native library load failed: %s", e)
            return None
        # Signatures.
        lib.hvt_timeline_start.argtypes = [ctypes.c_char_p]
        lib.hvt_timeline_start.restype = ctypes.c_int
        lib.hvt_timeline_event.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                           ctypes.c_char, ctypes.c_double]
        lib.hvt_timeline_event.restype = None
        lib.hvt_timeline_stop.restype = ctypes.c_int
        lib.hvt_timeline_dropped.restype = ctypes.c_uint64
        lib.hvt_plan_fusion.argtypes = [
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int32)]
        lib.hvt_plan_fusion.restype = ctypes.c_int64
        lib.hvt_encode_request.restype = ctypes.c_int64
        lib.hvt_encode_request.argtypes = [
            ctypes.c_int32, ctypes.c_uint8, ctypes.c_uint8, ctypes.c_int32,
            ctypes.c_uint8, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_uint8,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64]
        lib.hvt_decode_request.restype = ctypes.c_int64
        lib.hvt_decode_request.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_char_p,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint8]
        lib.hvt_encode_response.restype = ctypes.c_int64
        lib.hvt_encode_response.argtypes = [
            ctypes.c_uint8, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64]
        lib.hvt_decode_response.restype = ctypes.c_int64
        lib.hvt_decode_response.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_char_p,
            ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64]
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


# -- fusion planner --------------------------------------------------------

def plan_fusion_native(elem_counts: Sequence[int],
                       dtype_codes: Sequence[int],
                       itemsizes: Sequence[int],
                       threshold_bytes: int) -> Optional[List[int]]:
    """Bucket ids per leaf, or None if native is unavailable."""
    lib = load()
    if lib is None:
        return None
    n = len(elem_counts)
    ec = (ctypes.c_int64 * n)(*elem_counts)
    dc = (ctypes.c_int32 * n)(*dtype_codes)
    it = (ctypes.c_int32 * n)(*itemsizes)
    out = (ctypes.c_int32 * n)()
    lib.hvt_plan_fusion(n, ec, dc, it, threshold_bytes, out)
    return list(out)


# -- wire format -----------------------------------------------------------

OP_CODES = {"allreduce": 0, "allgather": 1, "broadcast": 2, "alltoall": 3,
            "reducescatter": 4, "barrier": 5, "join": 6}
DTYPE_CODES = {"float32": 0, "bfloat16": 1, "float16": 2, "float64": 3,
               "int32": 4, "int64": 5, "int8": 6, "uint8": 7, "bool": 8}


def encode_request(rank: int, op_type: str, reduce_op: int, root_rank: int,
                   dtype: str, name: str,
                   shape: Sequence[int]) -> Optional[bytes]:
    lib = load()
    if lib is None:
        return None
    ndim = len(shape)
    shp = (ctypes.c_int64 * max(ndim, 1))(*shape) if ndim else \
        (ctypes.c_int64 * 1)()
    cap = 64 + len(name) + 8 * ndim
    buf = (ctypes.c_uint8 * cap)()
    n = lib.hvt_encode_request(
        rank, OP_CODES[op_type], reduce_op, root_rank,
        DTYPE_CODES.get(dtype, 0), name.encode(), shp, ndim, buf, cap)
    if n < 0:
        return None
    return bytes(buf[:n])


def decode_request(data: bytes) -> Optional[Tuple]:
    lib = load()
    if lib is None:
        return None
    buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
    rank = ctypes.c_int32()
    op = ctypes.c_uint8()
    rop = ctypes.c_uint8()
    root = ctypes.c_int32()
    dt = ctypes.c_uint8()
    name = ctypes.create_string_buffer(65536)
    shape = (ctypes.c_int64 * 32)()
    ndim = ctypes.c_uint8()
    rc = lib.hvt_decode_request(
        buf, len(data), ctypes.byref(rank), ctypes.byref(op),
        ctypes.byref(rop), ctypes.byref(root), ctypes.byref(dt),
        name, 65536, shape, ctypes.byref(ndim), 32)
    if rc != 0:
        return None
    op_names = {v: k for k, v in OP_CODES.items()}
    dt_names = {v: k for k, v in DTYPE_CODES.items()}
    op_name = op_names.get(op.value)
    dt_name = dt_names.get(dt.value)
    if op_name is None or dt_name is None:
        return None  # unknown code = malformed/version-skewed message
    return (rank.value, op_name, rop.value, root.value,
            dt_name, name.value.decode(),
            tuple(shape[i] for i in range(ndim.value)))


def encode_response(ok: bool, name: str, error: str) -> Optional[bytes]:
    lib = load()
    if lib is None:
        return None
    cap = 16 + len(name) + len(error)
    buf = (ctypes.c_uint8 * cap)()
    n = lib.hvt_encode_response(1 if ok else 0, name.encode(),
                                error.encode(), buf, cap)
    return bytes(buf[:n]) if n >= 0 else None


def decode_response(data: bytes) -> Optional[Tuple[bool, str, str]]:
    lib = load()
    if lib is None:
        return None
    buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
    ok = ctypes.c_uint8()
    name = ctypes.create_string_buffer(65536)
    err = ctypes.create_string_buffer(65536)
    rc = lib.hvt_decode_response(buf, len(data), ctypes.byref(ok),
                                 name, 65536, err, 65536)
    if rc != 0:
        return None
    return bool(ok.value), name.value.decode(), err.value.decode()


# -- timeline --------------------------------------------------------------

class NativeTimelineWriter:
    """Thin wrapper used by horovod_tpu.common.timeline.Timeline."""

    def __init__(self):
        self._lib = load()

    @property
    def available(self) -> bool:
        return self._lib is not None

    def start(self, path: str) -> bool:
        return self._lib is not None and \
            self._lib.hvt_timeline_start(path.encode()) == 0

    def event(self, tid: str, name: str, phase: str, ts_us: float) -> None:
        self._lib.hvt_timeline_event(tid.encode(), name.encode(),
                                     phase.encode()[0], ts_us)

    def stop(self) -> None:
        if self._lib is not None:
            self._lib.hvt_timeline_stop()

    def dropped(self) -> int:
        return int(self._lib.hvt_timeline_dropped()) if self._lib else 0
