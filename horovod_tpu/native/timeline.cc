// Native timeline recorder — chrome-trace JSON writer.
//
// TPU-native equivalent of the reference's C++ Timeline
// (horovod/common/timeline.cc:205-290: lock-free SPSC queue feeding a
// dedicated writer thread, so the hot collective-dispatch path never
// blocks on file IO). Here: a Vyukov-style MPSC ring buffer (per-slot
// sequence numbers make producer writes visible to the writer without
// locks) drained by a std::thread; events are dropped (and counted)
// rather than blocking when the buffer is full — the policy a profiler
// wants on the dispatch path.
//
// C ABI (consumed via ctypes from horovod_tpu/common/timeline.py):
//   hvt_timeline_start(path)        -> 0 ok
//   hvt_timeline_event(tid, name, phase, ts_us)   phase: 'B','E','i'
//   hvt_timeline_stop()             flush + close (writes valid JSON)
//   hvt_timeline_dropped()          -> events dropped due to full buffer

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Slot {
  std::atomic<uint64_t> seq{0};
  char tid[64];
  char name[64];
  char phase;
  double ts_us;
};

constexpr size_t kCapacity = 1 << 16;  // 65536 in-flight events

struct Recorder {
  std::vector<Slot> ring;
  std::atomic<uint64_t> head{0};   // next write ticket (producers)
  uint64_t tail = 0;               // next read ticket (writer thread only)
  std::atomic<uint64_t> dropped{0};
  std::atomic<bool> running{false};
  std::thread writer;
  FILE* out = nullptr;
  bool first = true;

  Recorder() : ring(kCapacity) { Reset(); }

  void Reset() {
    head.store(0);
    tail = 0;
    first = true;
    for (size_t i = 0; i < kCapacity; ++i) ring[i].seq.store(i);
  }

  void WriterLoop() {
    for (;;) {
      Slot& s = ring[tail % kCapacity];
      if (s.seq.load(std::memory_order_acquire) == tail + 1) {
        fprintf(out,
                "%s{\"name\":\"%s\",\"ph\":\"%c\",\"ts\":%.3f,"
                "\"pid\":0,\"tid\":\"%s\"%s}",
                first ? "" : ",\n", s.name, s.phase, s.ts_us, s.tid,
                s.phase == 'i' ? ",\"s\":\"g\"" : "");
        first = false;
        // Recycle the slot for lap tail/kCapacity + 1.
        s.seq.store(tail + kCapacity, std::memory_order_release);
        ++tail;
        continue;
      }
      if (!running.load(std::memory_order_acquire) &&
          tail == head.load(std::memory_order_acquire)) {
        return;
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
    }
  }
};

// A single never-deleted recorder instance: producers that race with
// stop() see running==false at worst — no use-after-free is possible
// because the object outlives the process (the reference's Timeline is
// likewise a process-lifetime singleton, horovod/common/timeline.h).
Recorder& TheRecorder() {
  static Recorder* r = new Recorder();
  return *r;
}
std::mutex g_mu;

}  // namespace

extern "C" {

int hvt_timeline_start(const char* path) {
  std::lock_guard<std::mutex> lk(g_mu);
  Recorder& r = TheRecorder();
  if (r.running.load(std::memory_order_acquire)) return 1;
  r.out = fopen(path, "w");
  if (r.out == nullptr) return 2;
  r.Reset();
  fprintf(r.out, "{\"displayTimeUnit\":\"ms\",\"traceEvents\":[\n");
  r.running.store(true, std::memory_order_release);
  r.writer = std::thread([&r] { r.WriterLoop(); });
  return 0;
}

void hvt_timeline_event(const char* tid, const char* name, char phase,
                        double ts_us) {
  Recorder& r = TheRecorder();
  if (!r.running.load(std::memory_order_acquire)) return;
  // Vyukov enqueue with fail-on-full: claim a ticket only when its slot is
  // free (seq == ticket), so every claimed ticket IS written and the
  // writer never waits on a hole. seq > ticket just means another
  // producer won this ticket — reload head and retry; only seq < ticket
  // (previous lap unconsumed) means the ring is genuinely full.
  uint64_t ticket = r.head.load(std::memory_order_relaxed);
  for (;;) {
    Slot& s = r.ring[ticket % kCapacity];
    uint64_t seq = s.seq.load(std::memory_order_acquire);
    if (seq == ticket) {
      if (r.head.compare_exchange_weak(ticket, ticket + 1,
                                       std::memory_order_acq_rel)) {
        snprintf(s.tid, sizeof(s.tid), "%s", tid);
        snprintf(s.name, sizeof(s.name), "%s", name);
        s.phase = phase;
        s.ts_us = ts_us;
        s.seq.store(ticket + 1, std::memory_order_release);
        return;
      }
      // CAS lost: `ticket` was refreshed by compare_exchange, retry.
    } else if ((int64_t)(seq - ticket) < 0) {
      r.dropped.fetch_add(1, std::memory_order_relaxed);
      return;  // full: drop rather than block the dispatch path
    } else {
      ticket = r.head.load(std::memory_order_relaxed);
    }
  }
}

uint64_t hvt_timeline_dropped() { return TheRecorder().dropped.load(); }

int hvt_timeline_stop() {
  std::lock_guard<std::mutex> lk(g_mu);
  Recorder& r = TheRecorder();
  if (!r.running.load(std::memory_order_acquire)) return 1;
  r.running.store(false, std::memory_order_release);
  r.writer.join();
  fprintf(r.out, "\n]}\n");
  fclose(r.out);
  r.out = nullptr;
  return 0;
}

}  // extern "C"
