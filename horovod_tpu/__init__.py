"""horovod_tpu — a TPU-native distributed training framework.

A ground-up JAX/XLA/Pallas re-design with the capabilities of the reference
Horovod (data-parallel collectives + fusion + Adasum + elastic + launcher +
timeline), built for TPU hardware: SPMD over ``jax.sharding.Mesh``, XLA
collectives over ICI/DCN, compiled-step fusion instead of a background
thread, and sequence/expert parallel building blocks over the same
primitive set.

Top-level API mirrors the reference's ``hvd.*`` surface
(reference: horovod/tensorflow/__init__.py, horovod/torch/__init__.py,
horovod/common/basics.py) with JAX-idiomatic semantics documented per
function.

Quick start (single-controller SPMD, the idiomatic TPU path)::

    import horovod_tpu as hvd
    hvd.init()                     # or init(compression="int8_ef") to put
                                   # int8 gradients on every reduce hop
                                   # (HVD_TPU_COMPRESSION; docs/compression.md)
    tx = hvd.DistributedOptimizer(optax.adam(1e-3), axis_name=hvd.rank_axis())

    @hvd.spmd_step                       # shard_map over the rank mesh
    def train_step(params, opt_state, batch):
        ...

Eager collectives operate on rank-major distributed tensors
(``hvd.scatter`` / ``hvd.gather``) — see horovod_tpu/ops/eager.py.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from .common import jax_compat as _jax_compat

_jax_compat.ensure()  # fill jax.shard_map / lax.axis_size on older jax

from .common import basics as _basics
from .common.basics import (ccl_built, cuda_built, ddl_built, gloo_built,
                            gloo_enabled, init, is_initialized, mpi_built,
                            mpi_enabled, mpi_threads_supported, nccl_built,
                            rocm_built, shutdown, tpu_available, xla_built)
from .common.exceptions import (CheckpointCorruptError, DivergenceError,
                                HorovodInternalError, HostsUpdatedInterrupt,
                                MismatchError, NonFiniteError,
                                NotInitializedError, StallError,
                                StallTimeoutError,
                                TensorShapeMismatchError)
from .ops import collectives as collective_ops
from .ops.collectives import AxisPhase, WirePlan
from .ops.collectives import (Adasum, Average, Max, Min, Product, ReduceOp,
                              Sum)
from .ops.compression import Compression
from .optim import (AutotunedStepper, DistributedGradFn,
                    DistributedOptimizer, FSDPOptimizer, ShardedOptimizer,
                    StepTimer, ZeroOptimizer, accumulate_gradients,
                    auto_shard_threshold, broadcast_parameters,
                    observe_ef_residual, resolve_remat_policy,
                    sharded_init, sharded_update, should_shard_update)
from .common import integrity
from .common import metrics as _metrics_lib
from .common.faults import recovery_stats
from .common.integrity import (DivergenceDetector, current_loss_scale,
                               observe_guard)
from .data import (BackgroundPrefetcher, DeviceInfeed, infeed_pipeline,
                   prefetch_to_device, shard_batch)
from .functions import allgather_object, broadcast_object, broadcast_variables
from .parallel.pipeline import (pipeline_accumulate_gradients,
                                pipeline_apply, pipeline_train_step_1f1b,
                                select_last_stage)
from .parallel.respec import RespecDecision, solve_respec
from .parallel.spec import ParallelSpec
from .parallel.tensor_parallel import (column_parallel,
                                       combine_slice_grads, row_parallel,
                                       shard_column, shard_head_rows,
                                       shard_heads, shard_row,
                                       tp_attention_qkv, tp_mlp)
from .process_set import ProcessSet

__version__ = "0.1.0"

_ctx = _basics.context


def __getattr__(name):
    # Lazy submodules with heavy deps (orbax, TF) — imported on first use.
    if name == "run":
        # Reference horovod/__init__.py: `from horovod.runner import run`
        # — lazily here (runner pulls cloudpickle).
        from .runner import run as _run

        globals()["run"] = _run
        return _run
    if name in ("checkpoint", "callbacks", "elastic", "executor",
                "tensorflow", "torch", "mxnet", "store", "estimator",
                "spark", "serve"):
        import importlib

        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'horovod_tpu' has no attribute {name!r}")


# -- basics (reference common/basics.py surface) ---------------------------

def rank() -> int:
    return _ctx().rank()


def size() -> int:
    return _ctx().size()


def local_rank() -> int:
    return _ctx().local_rank()


def local_size() -> int:
    return _ctx().local_size()


def cross_rank() -> int:
    return _ctx().cross_rank()


def cross_size() -> int:
    return _ctx().cross_size()


def is_homogeneous() -> bool:
    return _ctx().is_homogeneous()


def mesh():
    """The global 1-D rank mesh (jax.sharding.Mesh)."""
    return _ctx().mesh


def hierarchical_mesh():
    """The 2-D (cross, local) mesh, if multi-host; else None."""
    return _ctx().hier_mesh


def mesh_axes():
    """Routing-axis factorization of the topology (fast axis first) —
    pod metadata or the HVD_TPU_MESH_SHAPE / init(mesh_shape=) override;
    the per-axis model the collective router keys on
    (docs/topology.md). None when discovery failed."""
    return _ctx().mesh_axes


def route_mesh():
    """The N-D jax Mesh matching :func:`mesh_axes` when the
    factorization is multi-axis (shard over it to use route= plans);
    else None."""
    return _ctx().route_mesh


def parallel_spec():
    """The resolved hybrid :class:`ParallelSpec` from
    ``HVD_TPU_PARALLEL`` / ``init(parallel=)`` (docs/pipeline.md) —
    pass it EXPLICITLY to ``DistributedOptimizer(parallel=...)``; else
    None."""
    return _ctx().parallel_spec


def parallel_mesh():
    """The role-named (dp/pp/tp/ep) jax Mesh matching
    :func:`parallel_spec` — shard_map your hybrid step over it; else
    None."""
    return _ctx().parallel_mesh


def rank_axis() -> str:
    return _ctx().config.rank_axis


def add_process_set(process_set) -> ProcessSet:
    """Register a ProcessSet (or rank list) and build its sub-mesh
    engine. See process_set.py."""
    return _ctx().add_process_set(process_set)


def remove_process_set(process_set) -> None:
    _ctx().remove_process_set(process_set)


# -- eager collectives (rank-major distributed tensors) --------------------

def _engine(process_set=None):
    """Route to the world engine or a registered process set's sub-mesh
    engine; non-member processes fail loudly (the set's XLA program
    spans member devices only — see process_set.py)."""
    if process_set is None:
        return _ctx().engine
    if not process_set.included():
        raise ValueError(
            f"this process drives none of {process_set!r}; only member "
            f"processes may call set-scoped collectives")
    return process_set.engine


def _communicator_size(process_set=None) -> int:
    """Size of the communicator a collective runs over: the SET's when
    one is given, else the world's — the denominator every averaging/
    predivide split must use (one definition; the shims share it)."""
    if process_set is not None:
        return process_set.size()
    return size()


def scatter(stacked, process_set=None):
    """Host-stacked (size, *shape) -> rank-sharded distributed tensor."""
    return _engine(process_set).scatter(stacked)


def gather(dt, process_set=None):
    """Distributed tensor -> host numpy (size, *shape)."""
    return _engine(process_set).gather(dt)


def allreduce(x, op: ReduceOp = ReduceOp.AVERAGE, name: Optional[str] = None,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              compression=None, process_set=None):
    """``compression=None`` uses the configured default
    (``HVD_TPU_COMPRESSION`` / ``init(compression=)``, falling back to
    the legacy ``HVD_TPU_COMPRESSION_DTYPE`` wire knob).
    ``Compression.int8_ef`` runs the reduction as a reduce-safe
    quantized allreduce — int8 payload on every hop, error bounded per
    block (docs/compression.md); stateless here, so rounding is
    round-to-nearest (the error-feedback residual lives on the
    DistributedOptimizer surfaces)."""
    return _engine(process_set).allreduce(x, op, name, prescale_factor,
                                          postscale_factor, compression)


def grouped_allreduce(tensors, op: ReduceOp = ReduceOp.AVERAGE,
                      name: Optional[str] = None,
                      compression=None,
                      prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0,
                      process_set=None):
    return _engine(process_set).allreduce_tree(
        tensors, op, name, compression,
        prescale_factor=prescale_factor,
        postscale_factor=postscale_factor)


def allgather(x, name: Optional[str] = None, process_set=None):
    return _engine(process_set).allgather(x, name)


def grouped_allgather(tensors, name: Optional[str] = None,
                      process_set=None):
    """Allgather every leaf of a list/dict (the later-Horovod grouped
    surface): per-leaf dispatch (XLA's async dispatch pipelines the
    copies; unlike allreduce there is no flat-buffer win to fuse, so
    leaves stay separate executables). Unnamed calls pass None through
    so each leaf gets the engine's unique auto-naming — a constant
    default prefix would collide across distinct unnamed calls."""
    e = _engine(process_set)
    leaves, treedef = jax.tree.flatten(tensors)
    outs = [e.allgather(v, f"{name}.{i}" if name else None)
            for i, v in enumerate(leaves)]
    return jax.tree.unflatten(treedef, outs)


def broadcast(x, root_rank: int = 0, name: Optional[str] = None,
              process_set=None):
    """With ``process_set``, ``root_rank`` is the GLOBAL rank of the
    root (it must be a member); position within the set is resolved
    here."""
    if process_set is not None:
        if root_rank not in process_set.ranks:
            raise ValueError(f"root_rank {root_rank} is not a member of "
                             f"{process_set!r}")
        root_rank = process_set.ranks.index(root_rank)
    return _engine(process_set).broadcast(x, root_rank, name)


def alltoall(x, name: Optional[str] = None, splits=None, process_set=None,
             chunked: Optional[bool] = None, wire=None):
    """Even all-to-all, or — with ``splits`` — the dynamic uneven variant
    where recv splits are negotiated through the controller (reference:
    operations.cc:1020-1081, controller.h:56-58 AlltoallGetRecvSplits).
    See EagerEngine.alltoallv for the two call conventions. ``chunked``
    (extension) selects the uneven wire form: None auto-routes skewed
    tables through the bounded per-hop exchange, True/False forces it.
    ``wire`` (extension, docs/moe.md) compresses the exchanged payload:
    ``"bf16"``/``"int8"``/``"auto"`` or a ``Compression`` class — part
    of the compile-cache signature and the cross-rank contract; with
    ``splits`` it requires the chunked form."""
    return _engine(process_set).alltoall(x, name, splits=splits,
                                         chunked=chunked, wire=wire)


_rs_default_warned = False


def _reducescatter_default_op() -> ReduceOp:
    """One-release transition warning (ADVICE r4): the eager-surface
    default flipped SUM -> AVERAGE in r4 for upstream parity — a silent
    1/n scaling change for callers relying on the old default. Warns
    once per process when ``op`` is left defaulted."""
    global _rs_default_warned
    if not _rs_default_warned:
        _rs_default_warned = True
        import sys
        import warnings

        # Attribute the once-per-process warning to the USER's call
        # site: the depth to it varies by surface (core vs torch vs the
        # TF shim's autograph wrappers vs grouped_*), so walk out of
        # this package instead of hard-coding a stacklevel.
        pkg = os.path.dirname(os.path.abspath(__file__))
        level = 2
        f = sys._getframe(1)
        while (f.f_back is not None
               and f.f_code.co_filename.startswith(pkg)):
            f = f.f_back
            level += 1
        warnings.warn(
            "reducescatter's default op is AVERAGE as of round 4 "
            "(upstream parity; previously SUM on this surface). Pass "
            "op=hvd.Sum explicitly for the unscaled reduction. Note the "
            "in-jit horovod_tpu.ops.collectives.reducescatter still "
            "defaults to SUM.", UserWarning, stacklevel=level)
    return ReduceOp.AVERAGE


def reducescatter(x, op: Optional[ReduceOp] = None,
                  name: Optional[str] = None, process_set=None):
    """This rank's 1/n slice of the elementwise reduction over dim 0.
    Default op is AVERAGE on every surface (core + torch + TF),
    matching upstream's reducescatter default — pass op=Sum for the
    unscaled reduction. (The in-jit ``ops.collectives.reducescatter``
    keeps the SUM default; see docs/api.md.)"""
    if op is None:
        op = _reducescatter_default_op()
    return _engine(process_set).reducescatter(x, op, name)


def grouped_reducescatter(tensors, op: Optional[ReduceOp] = None,
                          name: Optional[str] = None, process_set=None):
    """Reducescatter every leaf of a list/dict (later-Horovod grouped
    surface; per-leaf dispatch — same naming contract as
    :func:`grouped_allgather`). Defaulted ``op`` is AVERAGE (see
    :func:`reducescatter` for the SUM->AVERAGE transition note)."""
    if op is None:
        op = _reducescatter_default_op()
    e = _engine(process_set)
    leaves, treedef = jax.tree.flatten(tensors)
    outs = [e.reducescatter(v, op, f"{name}.{i}" if name else None)
            for i, v in enumerate(leaves)]
    return jax.tree.unflatten(treedef, outs)


def barrier(process_set=None):
    _engine(process_set).barrier()


def join() -> int:
    """Mark this process as done; block until every process has joined,
    meanwhile participating in the remaining processes' allreduces with
    zero tensors. Returns the last-joined rank.

    Reference: operations.cc:1085-1109 EnqueueJoin + JoinOp
    (collective_operations.h:259-267) + torch/mpi_ops.py:631-644.
    Multi-process worlds must ``init(join_mode=True)`` (or set
    HVD_TPU_JOIN_MODE=1) so every collective runs a coordination round —
    the cost the reference pays on every background cycle. In
    single-controller SPMD every rank reaches join() at the same program
    point, so the call is vacuous and returns ``size - 1``."""
    return _ctx().engine.join()


# -- async handle surface (reference torch/mpi_ops.py) ---------------------

def allreduce_async(x, op: ReduceOp = ReduceOp.AVERAGE,
                    name: Optional[str] = None) -> int:
    e = _ctx().engine
    return e.async_call(e.allreduce, x, op, name)


def allgather_async(x, name: Optional[str] = None) -> int:
    e = _ctx().engine
    return e.async_call(e.allgather, x, name)


def broadcast_async(x, root_rank: int = 0, name: Optional[str] = None) -> int:
    e = _ctx().engine
    return e.async_call(e.broadcast, x, root_rank, name)


def poll(handle: int) -> bool:
    return _ctx().engine.poll(handle)


def synchronize(handle: int):
    return _ctx().engine.synchronize(handle)


# -- unified telemetry (docs/metrics.md) -----------------------------------

def metrics() -> dict:
    """Snapshot of the process-wide metrics registry: every counter,
    gauge, and histogram each layer reports (dispatch latency, raw-vs-
    wire bytes, cache hits, fusion fill, autotune state, recovery
    counters...). Empty when disabled via ``HVD_TPU_METRICS=0``. The
    same data is exportable as a JSON-lines file
    (``HVD_TPU_METRICS_FILE``) and a Prometheus ``/metrics`` endpoint
    (``HVD_TPU_METRICS_PORT`` / :func:`start_metrics_server`)."""
    return _metrics_lib.snapshot()


def start_metrics_server(port: int = 0) -> int:
    """Start (or return) the Prometheus ``/metrics`` endpoint on a
    stdlib HTTP background thread; returns the bound port (``port=0``
    binds an ephemeral one). Also serves the raw snapshot at
    ``/metrics.json``. Samples carry ``rank=``/``size=`` labels once
    ``init()`` has run, so rank 0 (or any scraper) can aggregate a pod
    view across workers."""
    return _metrics_lib.serve(port)


def stop_metrics_server() -> None:
    _metrics_lib.stop_serving()


def flight_recorder():
    """The process-wide flight recorder (docs/podmon.md): the ring of
    the last N collective events plus the black-box dump surface.
    ``flight_recorder().events()`` is the live ring;
    ``flight_recorder().dump("manual")`` writes a black box on demand
    (the same payload SIGUSR2 or a fatal stall produces). Usable before
    ``init()`` — the env-configured recorder is created on first use."""
    from .common import flightrec as _flightrec_lib

    return _flightrec_lib.recorder()


# -- timeline (reference operations.cc:720-746) ----------------------------

def start_timeline(filename: str, mark_cycles: bool = False,
                   xprof_dir: Optional[str] = None) -> None:
    """Start the chrome-trace collective timeline; ``xprof_dir``
    additionally starts a ``jax.profiler`` trace there for device-side
    detail (view with TensorBoard/xprof). Both lifecycles live on the
    Timeline, so every stop path — including shutdown() — flushes the
    device trace."""
    t = _ctx().timeline
    t._mark_cycles = mark_cycles
    t.start(filename, xprof_dir=xprof_dir)


def stop_timeline() -> None:
    _ctx().timeline.stop()


# -- SPMD helpers ----------------------------------------------------------

def spmd_step(fn=None, *, in_specs=None, out_specs=None, check_vma=False,
              donate_argnums=()):
    """Decorator: run ``fn`` as a jitted shard_map over the rank mesh with
    per-rank collectives available under ``rank_axis()``. Default specs
    shard the leading axis of every argument over ranks.

    ``check_vma=False`` (default) restores the reference's mental model
    exactly: every value inside the step is rank-local, ``jax.grad`` of a
    replicated parameter yields the LOCAL gradient (no auto-psum), and the
    framework's explicit allreduce is the only cross-rank reduction —
    matching how N reference processes behave (torch/optimizer.py hook
    model). With ``check_vma=True`` JAX's varying-manual-axes type system
    is enforced instead; use ``collective_ops.to_local`` on replicated
    params before ``jax.grad`` in that mode.

    ``donate_argnums``: positions of carry-state arguments (params,
    opt_state, ...) whose HBM buffers may be reused for the outputs —
    halves peak memory for the update and avoids a copy. Donated inputs
    are invalidated; only pass state you immediately overwrite with the
    step's outputs.
    """
    from jax.sharding import PartitionSpec as P

    def deco(f):
        ctx = _ctx()
        spec = P(ctx.config.rank_axis)
        ins = in_specs if in_specs is not None else spec
        outs = out_specs if out_specs is not None else spec
        return jax.jit(jax.shard_map(f, mesh=ctx.mesh, in_specs=ins,
                                     out_specs=outs, check_vma=check_vma),
                       donate_argnums=donate_argnums)
    return deco(fn) if fn is not None else deco


__all__ = [
    "init", "shutdown", "is_initialized", "rank", "size", "local_rank",
    "local_size", "cross_rank", "cross_size", "is_homogeneous", "mesh",
    "hierarchical_mesh", "mesh_axes", "route_mesh", "WirePlan",
    "AxisPhase", "rank_axis", "scatter", "gather", "allreduce",
    "grouped_allreduce", "allgather", "grouped_allgather", "broadcast",
    "alltoall", "reducescatter", "grouped_reducescatter", "barrier",
    "join", "allreduce_async",
    "allgather_async",
    "broadcast_async", "poll", "synchronize", "start_timeline",
    "stop_timeline", "spmd_step", "ReduceOp", "Average", "Sum", "Adasum",
    "Min", "Max", "Product", "Compression", "DistributedOptimizer",
    "DistributedGradFn", "AutotunedStepper", "ShardedOptimizer",
    "FSDPOptimizer", "ZeroOptimizer", "sharded_init", "sharded_update",
    "broadcast_parameters", "broadcast_object",
    "allgather_object", "broadcast_variables", "collective_ops",
    "HorovodInternalError", "HostsUpdatedInterrupt", "NotInitializedError",
    "StallError", "TensorShapeMismatchError", "__version__",
    "mpi_built", "mpi_enabled", "mpi_threads_supported", "gloo_built",
    "gloo_enabled", "nccl_built", "ddl_built", "ccl_built", "cuda_built",
    "rocm_built", "xla_built", "tpu_available",
    "ProcessSet", "add_process_set", "remove_process_set", "run",
    "recovery_stats", "metrics", "start_metrics_server",
    "stop_metrics_server", "flight_recorder",
    "StepTimer", "observe_ef_residual",
    "integrity", "observe_guard", "current_loss_scale",
    "DivergenceDetector", "MismatchError", "NonFiniteError",
    "DivergenceError", "CheckpointCorruptError", "StallTimeoutError",
    "accumulate_gradients", "resolve_remat_policy",
    "auto_shard_threshold", "should_shard_update", "DeviceInfeed",
    "prefetch_to_device", "BackgroundPrefetcher", "shard_batch",
    "infeed_pipeline", "serve",
    "ParallelSpec", "parallel_spec", "parallel_mesh",
    "pipeline_accumulate_gradients", "pipeline_apply",
    "pipeline_train_step_1f1b", "select_last_stage",
    "column_parallel", "row_parallel", "tp_mlp", "tp_attention_qkv",
    "shard_column", "shard_row", "shard_heads", "shard_head_rows",
    "combine_slice_grads",
]
