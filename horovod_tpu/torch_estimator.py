"""TorchEstimator — the reference's Spark Torch estimator
(spark/torch/estimator.py: ship a torch model into cluster workers,
train under hvd.DistributedOptimizer, return a transformer) re-hosted
on the executor pool + Store.

Torch models cloudpickle cleanly, so unlike the Keras path the model
object itself crosses the boundary; each worker wraps the user's
optimizer factory in ``horovod_tpu.torch.DistributedOptimizer``,
broadcasts initial parameters, and trains its rank shard. Shards are
equalized so the per-step allreduce count matches on every rank.
"""

from __future__ import annotations


from .common.config import runtime_env
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .estimator import (load_parquet_shard, load_parquet_val,
                         rank_shard, split_validation,
                         stage_data, validate_data_format)
from .store import Store


def _torch_train_worker(store: Store, run_id: str, model,
                        optimizer_factory: Callable, loss_name: str,
                        epochs: int, batch_size: int,
                        has_val: bool,
                        data_format: str = "pickle") -> Dict[str, Any]:
    """Reference spark/torch/remote.py RemoteTrainer recipe."""
    import torch

    import horovod_tpu as hvd
    import horovod_tpu.torch as hvdt

    hvd.init()
    nproc = max(int(runtime_env("NUM_PROC", "1")), 1)
    rank = int(runtime_env("PROC_ID", "0"))

    if data_format == "parquet":
        Xs, ys = load_parquet_shard(store, run_id, rank, nproc)
        val = load_parquet_val(store, run_id) \
            if (has_val and rank == 0) else None
    else:
        X, y = store.read_obj(store.get_data_path(run_id, "train"))
        # Only rank 0's val_history is persisted/consumed — the other
        # ranks must not pay the full-set read + per-epoch forward.
        val = store.read_obj(store.get_data_path(run_id, "val")) \
            if (has_val and rank == 0) else None
        Xs, ys = rank_shard(X, y, rank, nproc)
    # Cast to the model's parameter dtype (numpy defaults to float64,
    # torch modules to float32); cross-entropy targets must be long.
    pdtype = next(model.parameters()).dtype

    def to_tensors(xa, ya):
        xt = torch.from_numpy(np.ascontiguousarray(xa)).to(pdtype)
        yt = torch.from_numpy(np.ascontiguousarray(ya))
        yt = yt.long() if loss_name == "cross_entropy" \
            else yt.to(pdtype)
        return xt, yt

    Xt, yt = to_tensors(Xs, ys)
    val_t = to_tensors(*val) if val is not None else None

    loss_fn = {"mse": torch.nn.MSELoss(),
               "cross_entropy": torch.nn.CrossEntropyLoss()}[loss_name]
    opt = hvdt.DistributedOptimizer(
        optimizer_factory(model.parameters()),
        named_parameters=model.named_parameters())
    hvdt.broadcast_parameters(model.state_dict(), root_rank=0)

    # ceil-stepping covers the tail partial batch (identical count on
    # every rank because shards are equalized).
    starts = list(range(0, len(Xt), batch_size)) or [0]
    history: List[float] = []
    val_history: List[float] = []
    for _ in range(epochs):
        model.train()
        epoch_loss = 0.0
        for s0 in starts:
            xb = Xt[s0:s0 + batch_size]
            yb = yt[s0:s0 + batch_size]
            opt.zero_grad()
            l = loss_fn(model(xb), yb)
            l.backward()
            opt.step()
            epoch_loss += float(l)
        history.append(epoch_loss / len(starts))
        if val_t is not None:
            model.eval()
            with torch.no_grad():
                vl = loss_fn(model(val_t[0]), val_t[1])
            val_history.append(float(vl))
    if rank == 0:
        store.write_obj(
            store.path_join(store.get_checkpoint_path(run_id),
                            "torch_final.pkl"),
            {k: v.cpu().numpy() for k, v in model.state_dict().items()})
        store.write_obj(
            store.path_join(store.get_logs_path(run_id),
                            "history.pkl"),
            {"train": history, "val": val_history})
    return {"rank": rank}


class TrainedTorchModel:
    """Reference TorchModel Spark Transformer: batched host predict."""

    def __init__(self, model, store: Store, run_id: str,
                 history=None, val_history=None):
        self.model = model
        self.store = store
        self.run_id = run_id
        self.history = history or []
        self.val_history = val_history or []

    @classmethod
    def load(cls, store: Store, run_id: str,
             model) -> "TrainedTorchModel":
        import torch

        weights = store.read_obj(store.path_join(
            store.get_checkpoint_path(run_id), "torch_final.pkl"))
        model.load_state_dict({k: torch.from_numpy(np.array(v))
                               for k, v in weights.items()})
        history: List[float] = []
        val_history: List[float] = []
        hist_path = store.path_join(store.get_logs_path(run_id),
                                    "history.pkl")
        if store.exists(hist_path):
            logged = store.read_obj(hist_path)
            history = logged.get("train", [])
            val_history = logged.get("val", [])
        return cls(model, store, run_id, history, val_history)

    def transform(self, X, batch_size: int = 1024) -> np.ndarray:
        import torch

        self.model.eval()
        pdtype = next(self.model.parameters()).dtype
        outs = []
        with torch.no_grad():
            for i in range(0, len(X), batch_size):
                xb = torch.from_numpy(np.ascontiguousarray(
                    X[i:i + batch_size])).to(pdtype)
                outs.append(self.model(xb).cpu().numpy())
        if outs:
            return np.concatenate(outs)
        # Empty input: derive the output shape from a 0-row forward so
        # the result still concatenates/indexes like real predictions.
        with torch.no_grad():
            empty = self.model(torch.zeros((0,) + tuple(X.shape[1:]),
                                           dtype=pdtype))
        return empty.cpu().numpy()


class TorchEstimator:
    """fit/transform for torch models over the executor pool
    (reference spark/torch/estimator.py TorchEstimator).

    ``optimizer`` is a FACTORY ``params -> torch.optim.Optimizer``
    (e.g. ``lambda p: torch.optim.SGD(p, lr=0.05)``) so each worker
    builds its optimizer against its own model replica.
    """

    LOSSES = ("mse", "cross_entropy")

    def __init__(self, model, optimizer: Callable,
                 loss: str = "mse", store: Optional[Store] = None,
                 num_proc: int = 2, epochs: int = 1,
                 batch_size: int = 32, run_id: Optional[str] = None,
                 worker_env: Optional[Dict[str, str]] = None,
                 data_format: str = "pickle"):
        validate_data_format(data_format)
        self.data_format = data_format
        if loss not in self.LOSSES:
            raise ValueError(f"loss must be one of {self.LOSSES}, "
                             f"got {loss!r}")
        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.store = store
        self.num_proc = num_proc
        self.epochs = epochs
        self.batch_size = batch_size
        self.run_id = run_id
        self.worker_env = worker_env

    def fit(self, X, y, validation=None,
            executor=None) -> TrainedTorchModel:
        import time

        from .executor import Executor

        if self.store is None:
            raise ValueError("TorchEstimator requires a store=")
        run_id = self.run_id or f"trun_{int(time.time() * 1000):x}"
        X, y, validation = split_validation(X, y, validation)
        stage_data(self.store, run_id, X, y, validation,
                   self.data_format, num_shards=self.num_proc)

        args = (self.store, run_id, self.model, self.optimizer,
                self.loss, self.epochs, self.batch_size,
                validation is not None, self.data_format)
        if executor is not None:
            executor.run(_torch_train_worker, args=args)
        else:
            with Executor(np=self.num_proc,
                          env=self.worker_env) as ex:
                ex.run(_torch_train_worker, args=args)
        # A FRESH replica: mutating the caller's model in place would
        # make a second fit() warm-start silently (the Keras path
        # rebuilds from JSON for the same reason).
        import copy

        return TrainedTorchModel.load(self.store, run_id,
                                      copy.deepcopy(self.model))
