"""LSF cluster detection — the js_run/jsrun analog.

Reference: horovod/runner/util/lsf.py:1-103 (LSFUtils reading
LSB_DJOB_HOSTFILE / LSB_HOSTS / LSB_MCPU_HOSTS to derive the host list)
+ horovod/runner/js_run.py (jsrun command synthesis). On TPU there is no
jsrun to exec — the useful capability is deriving the host set from the
scheduler's environment so ``hvdtpurun`` inside an LSF allocation needs
no -H flag; the ssh fan-out then rides the allocation."""

from __future__ import annotations

import collections
import os
from typing import List

from . import hosts as hosts_lib


def in_lsf() -> bool:
    """True inside an LSF job allocation (reference lsf.py using
    LSB_JOBID presence)."""
    return "LSB_JOBID" in os.environ


def lsf_hosts() -> List[hosts_lib.HostInfo]:
    """Host list with slot counts from the LSF environment.

    Precedence mirrors the reference: LSB_DJOB_HOSTFILE (one hostname
    per slot, one per line) > LSB_MCPU_HOSTS ("h1 n1 h2 n2 ...") >
    LSB_HOSTS ("h1 h1 h2 ...")."""
    hostfile = os.environ.get("LSB_DJOB_HOSTFILE")
    if hostfile and os.path.exists(hostfile):
        counts: "collections.OrderedDict[str, int]" = \
            collections.OrderedDict()
        with open(hostfile) as f:
            for line in f:
                name = line.strip()
                if name:
                    counts[name] = counts.get(name, 0) + 1
        return [hosts_lib.HostInfo(h, n) for h, n in counts.items()]

    mcpu = os.environ.get("LSB_MCPU_HOSTS")
    if mcpu:
        parts = mcpu.split()
        return [hosts_lib.HostInfo(parts[i], int(parts[i + 1]))
                for i in range(0, len(parts) - 1, 2)]

    hosts = os.environ.get("LSB_HOSTS")
    if hosts:
        counts = collections.OrderedDict()
        for name in hosts.split():
            counts[name] = counts.get(name, 0) + 1
        return [hosts_lib.HostInfo(h, n) for h, n in counts.items()]

    raise RuntimeError(
        "inside an LSF job (LSB_JOBID set) but no host environment "
        "found (LSB_DJOB_HOSTFILE / LSB_MCPU_HOSTS / LSB_HOSTS)")
