"""Rendezvous HTTP KV server.

Reference: horovod/runner/http/http_server.py:35-234 — a threading HTTP
server exposing a scoped GET/PUT/DELETE KV store, used for gloo rendezvous
and elastic coordination. The TPU runtime's *data plane* does not need it
(jax.distributed has its own coordination service), but the launcher and
elastic driver do: slot handout, worker heartbeats, host-update
notification — so the same minimal KV protocol is provided.

Protocol: PUT /kv/<scope>/<key> (body = value bytes), GET returns 200+body
or 404, DELETE removes. GET /kv/<scope>?list=1 returns JSON key list.

Authentication: like the reference's service layer (runner/common/util/
secret.py + network.py — every message carries an HMAC over its
content), requests may carry ``X-HVD-Auth: HMAC-SHA256(secret,
method|path?query|body)``. A server constructed with a secret (or with
HVD_TPU_RENDEZVOUS_SECRET set) rejects missing/invalid digests with
403; the launcher generates a fresh per-job secret and hands it to the
workers through their env.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import logging
import os
import threading
from http.server import BaseHTTPRequestHandler
from typing import Dict, Optional, Tuple
from urllib.parse import urlparse, parse_qs

from ..common.httpd import BackgroundHTTPServer
from ..common.config import runtime_env

logger = logging.getLogger("horovod_tpu")

_AUTH_HEADER = "X-HVD-Auth"


def _env_secret() -> Optional[bytes]:
    s = runtime_env("RENDEZVOUS_SECRET", "")
    return s.encode() if s else None


def _digest(secret: bytes, method: str, path_qs: str,
            body: bytes) -> str:
    mac = hmac.new(secret, digestmod=hashlib.sha256)
    mac.update(method.encode())
    mac.update(b"|")
    mac.update(path_qs.encode())
    mac.update(b"|")
    mac.update(body)
    return mac.hexdigest()


class _Handler(BaseHTTPRequestHandler):
    server_version = "HvdTpuRendezvous/0.1"

    def log_message(self, fmt, *args):  # quiet
        pass

    def _store(self) -> Dict[str, bytes]:
        return self.server.kv_store  # type: ignore[attr-defined]

    def _lock(self) -> threading.Lock:
        return self.server.kv_lock  # type: ignore[attr-defined]

    def _authorized(self, body: bytes = b"") -> bool:
        secret = self.server.kv_secret  # type: ignore[attr-defined]
        if secret is None:
            return True
        given = self.headers.get(_AUTH_HEADER, "")
        want = _digest(secret, self.command, self.path, body)
        if hmac.compare_digest(given, want):
            return True
        self.send_response(403)
        self.end_headers()
        return False

    def do_PUT(self):
        parsed = urlparse(self.path)
        path = parsed.path
        nx = bool(parse_qs(parsed.query).get("nx"))
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        if not self._authorized(body):
            return
        with self._lock():
            if nx and path in self._store():
                # Atomic put-if-absent: first writer wins; the loser gets
                # the stored value back (409) so concurrent publishers
                # converge on one value (the retried-task-0 case).
                val = self._store()[path]
                self.send_response(409)
                self.send_header("Content-Length", str(len(val)))
                self.end_headers()
                self.wfile.write(val)
                return
            self._store()[path] = body
        self.send_response(200)
        self.end_headers()

    def do_GET(self):
        if not self._authorized():
            return
        parsed = urlparse(self.path)
        qs = parse_qs(parsed.query)
        with self._lock():
            if qs.get("list"):
                prefix = parsed.path.rstrip("/") + "/"
                keys = [k[len(prefix):] for k in self._store()
                        if k.startswith(prefix)]
                data = json.dumps(sorted(keys)).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
            val = self._store().get(parsed.path)
        if val is None:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(val)))
        self.end_headers()
        self.wfile.write(val)

    def do_DELETE(self):
        if not self._authorized():
            return
        path = urlparse(self.path).path
        with self._lock():
            existed = self._store().pop(path, None) is not None
        self.send_response(200 if existed else 404)
        self.end_headers()


class RendezvousServer:
    """Reference: http/http_server.py RendezvousServer (start/stop,
    ephemeral port). The serve-forever-on-a-daemon-thread lifecycle is
    the shared ``common/httpd.BackgroundHTTPServer`` (the metrics
    ``/metrics`` endpoint rides the same plumbing)."""

    def __init__(self, host: str = "0.0.0.0",
                 secret: Optional[bytes] = None):
        self._secret = secret if secret is not None else _env_secret()
        self._http = BackgroundHTTPServer(_Handler, host=host)

    def start(self, port: int = 0) -> int:
        return self._http.start(port, kv_store={},
                                kv_lock=threading.Lock(),
                                kv_secret=self._secret)

    @property
    def port(self) -> int:
        return self._http.port

    def stop(self) -> None:
        self._http.stop()

    # Direct (in-process) access for the driver side.
    def put(self, scope: str, key: str, value: bytes) -> None:
        srv = self._http.server
        with srv.kv_lock:  # type: ignore[attr-defined]
            srv.kv_store[f"/kv/{scope}/{key}"] = value  # type: ignore

    def get(self, scope: str, key: str) -> Optional[bytes]:
        srv = self._http.server
        with srv.kv_lock:  # type: ignore[attr-defined]
            return srv.kv_store.get(f"/kv/{scope}/{key}")  # type: ignore

    def scope_items(self, scope: str) -> Dict[str, bytes]:
        """All (key, value) pairs under a scope — the autoscale engine
        reads every worker's ``autoscale/steptime.<rank>`` report in one
        snapshot without N HTTP round-trips (driver-side only)."""
        srv = self._http.server
        prefix = f"/kv/{scope}/"
        with srv.kv_lock:  # type: ignore[attr-defined]
            return {k[len(prefix):]: v
                    for k, v in srv.kv_store.items()  # type: ignore
                    if k.startswith(prefix)}


class RendezvousClient:
    """Worker-side client (reference: http/http_client.py). Signs every
    request when a secret is configured (argument or
    HVD_TPU_RENDEZVOUS_SECRET).

    Every request retries transient failures — connection errors,
    timeouts, HTTP 5xx — with exponential full-jitter backoff
    (``retries`` attempts beyond the first; knobs
    ``HVD_TPU_RENDEZVOUS_RETRIES`` /
    ``HVD_TPU_RENDEZVOUS_BACKOFF_{BASE_S,MAX_S}``). 4xx responses
    (404 absent key, 403 auth, 409 put-if-absent conflict) carry
    protocol meaning and surface immediately."""

    def __init__(self, addr: str, port: int, timeout_s: float = 30.0,
                 secret: Optional[bytes] = None,
                 retries: Optional[int] = None):
        self.base = f"http://{addr}:{port}"
        self.timeout_s = timeout_s
        self._secret = secret if secret is not None else _env_secret()
        if retries is None:
            try:
                retries = int(runtime_env("RENDEZVOUS_RETRIES", "4"))
            except ValueError:
                retries = 4
        self.retries = max(0, retries)

    def _backoff(self):
        from ..common import faults as faults_lib

        return faults_lib.Backoff.from_env(
            "HVD_TPU_RENDEZVOUS_BACKOFF", base_s=0.1, cap_s=2.0)

    def _request(self, path_qs: str, method: str,
                 data: Optional[bytes] = None):
        import urllib.error
        import urllib.request

        from ..common import faults as faults_lib

        backoff = self._backoff()
        attempt = 0
        while True:
            try:
                # Chaos site: per-attempt, so an injected 5xx/drop is
                # absorbed by this very retry loop.
                faults_lib.maybe_rendezvous_fault()
                req = urllib.request.Request(self.base + path_qs,
                                             data=data, method=method)
                if self._secret is not None:
                    req.add_header(_AUTH_HEADER,
                                   _digest(self._secret, method, path_qs,
                                           data or b""))
                return urllib.request.urlopen(req,
                                              timeout=self.timeout_s)
            except urllib.error.HTTPError as e:
                # 4xx is protocol semantics; only server-side errors are
                # retryable.
                if e.code < 500 or attempt >= self.retries:
                    raise
                err = e
            except (urllib.error.URLError, ConnectionError,
                    TimeoutError, OSError) as e:
                if attempt >= self.retries:
                    raise
                err = e
            attempt += 1
            faults_lib.stats.bump("rendezvous_retries")
            logger.debug(
                "rendezvous: %s %s failed (%s); retry %d/%d",
                method, path_qs, err, attempt, self.retries)
            backoff.sleep()

    def put(self, scope: str, key: str, value: bytes) -> None:
        self._request(f"/kv/{scope}/{key}", "PUT", value).read()

    def put_if_absent(self, scope: str, key: str, value: bytes) -> bytes:
        """Atomic first-writer-wins PUT; returns the WINNING value (the
        caller's on success, the already-stored one on conflict)."""
        import urllib.error

        try:
            self._request(f"/kv/{scope}/{key}?nx=1", "PUT", value).read()
            return value
        except urllib.error.HTTPError as e:
            if e.code == 409:
                return e.read()
            raise

    def get(self, scope: str, key: str) -> Optional[bytes]:
        import urllib.error

        try:
            return self._request(f"/kv/{scope}/{key}", "GET").read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    def wait(self, scope: str, key: str,
             timeout_s: float = 60.0) -> bytes:
        """Poll until the key exists. Polling backs off exponentially
        (full jitter, capped at ``HVD_TPU_RENDEZVOUS_WAIT_MAX_POLL_S``,
        default 1 s) — N workers hot-polling a slow coordinator at 50 ms
        is a self-inflicted thundering herd."""
        import time

        from ..common import faults as faults_lib

        try:
            cap = float(runtime_env("RENDEZVOUS_WAIT_MAX_POLL_S", "1.0"))
        except ValueError:
            cap = 1.0
        backoff = faults_lib.Backoff(base_s=0.05, cap_s=cap)
        deadline = time.monotonic() + timeout_s
        while True:
            val = self.get(scope, key)
            if val is not None:
                return val
            now = time.monotonic()
            if now > deadline:
                raise TimeoutError(f"rendezvous key {scope}/{key} not set "
                                   f"within {timeout_s}s")
            # Never jitter past the caller's deadline (plus a floor so a
            # nearly-expired wait still yields the CPU).
            time.sleep(min(max(backoff.next_delay(), 0.005),
                           max(deadline - now, 0.01)))

    def list(self, scope: str) -> list:
        return json.loads(self._request(f"/kv/{scope}?list=1",
                                        "GET").read())

    def delete(self, scope: str, key: str) -> None:
        import urllib.error

        try:
            self._request(f"/kv/{scope}/{key}", "DELETE").read()
        except urllib.error.HTTPError as e:
            if e.code != 404:  # 403 etc. must surface, only absent is ok
                raise
