"""TPU pod topology discovery for the launcher.

The reference's driver discovers the cluster by ssh-probing NICs on a
user-supplied host list (driver_service.py:49-257). On Cloud TPU pods
the platform already publishes the topology to every worker VM through
environment metadata, so `hvdtpurun` run on any pod worker can derive
the full host set, slot counts, and its own position with zero probing
— the TPU-native answer to SURVEY §7.6 ("discovers TPU pod topology").

Environment contract (set by the TPU runtime on every pod VM):
  TPU_WORKER_HOSTNAMES   comma-separated worker hostnames/IPs, pod order
  TPU_WORKER_ID          this VM's index into that list
  TPU_ACCELERATOR_TYPE   e.g. "v5litepod-16", "v4-32"
  TPU_CHIPS_PER_HOST_BOUNDS  e.g. "2,2,1" — chip grid per host

No metadata-server fallback on purpose: the env block is present on
every supported pod runtime, and an HTTP dependency would make launch
behavior differ between hermetic tests and production.
"""

from __future__ import annotations

import dataclasses
import os
from typing import List, Mapping, Optional

from . import hosts as hosts_lib


@dataclasses.dataclass(frozen=True)
class PodTopology:
    hosts: tuple            # worker hostnames in pod order
    worker_id: int          # this VM's index
    chips_per_host: int
    accelerator_type: str

    @property
    def num_hosts(self) -> int:
        return len(self.hosts)

    @property
    def num_chips(self) -> int:
        return self.num_hosts * self.chips_per_host

    def host_infos(self) -> List[hosts_lib.HostInfo]:
        return [hosts_lib.HostInfo(hostname=h, slots=self.chips_per_host)
                for h in self.hosts]


def _chips_per_host(environ: Mapping[str, str], num_hosts: int) -> int:
    bounds = environ.get("TPU_CHIPS_PER_HOST_BOUNDS", "")
    if bounds:
        chips = 1
        for d in bounds.split(","):
            chips *= int(d)
        return chips
    accel = environ.get("TPU_ACCELERATOR_TYPE", "")
    if "-" in accel:
        tail = accel.rsplit("-", 1)[1]
        if tail.isdigit():
            total = int(tail)
            # v2/v3 sizes count CORES (2 per chip), v4+ count chips —
            # the visible generations all divide evenly by the host
            # count either way, which is what assignment needs.
            if accel.startswith(("v2-", "v3-")):
                total //= 2
            if total and total % num_hosts == 0:
                return total // num_hosts
    # Conservative default: the common 4-chip TPU host board.
    return 4


def discover_pod(environ: Optional[Mapping[str, str]] = None
                 ) -> Optional[PodTopology]:
    """Topology from TPU pod env metadata, or None off-pod."""
    environ = os.environ if environ is None else environ
    hostnames = environ.get("TPU_WORKER_HOSTNAMES", "")
    if not hostnames.strip():
        return None
    hosts = tuple(h.strip() for h in hostnames.split(",") if h.strip())
    worker_id = int(environ.get("TPU_WORKER_ID", "0") or "0")
    if not 0 <= worker_id < len(hosts):
        raise ValueError(
            f"TPU_WORKER_ID={worker_id} outside TPU_WORKER_HOSTNAMES "
            f"({len(hosts)} hosts)")
    return PodTopology(
        hosts=hosts, worker_id=worker_id,
        chips_per_host=_chips_per_host(environ, len(hosts)),
        accelerator_type=environ.get("TPU_ACCELERATOR_TYPE", ""))
