"""hvdtpurun — the launcher CLI (horovodrun equivalent).

Reference: horovod/runner/launch.py:239-523 (argparse surface), :524-614
(_run_static), gloo_run.py:65-99 (per-slot env wiring), :226-284 (fan-out,
fail-fast). TPU-native differences:

* no MPI/gloo choice — workers bootstrap through ``jax.distributed`` whose
  coordinator runs in rank-0's process; the launcher only wires env vars
  (HVD_TPU_COORDINATOR / NUM_PROC / PROC_ID — the HOROVOD_RANK/... analog);
* one process **per host** (each process drives all local TPU chips; ranks
  are per-chip inside the SPMD program), not one per GPU;
* local mode forks subprocesses (the test/dev path — the reference's
  localhost gloo launch); multi-host mode fans out over ssh.

Config flags export the same knobs as the reference CLI
(--fusion-threshold-mb, --cycle-time-ms, --timeline-filename, ...,
launch.py:392-523 + config_parser.py).
"""

from __future__ import annotations

import argparse
import os
import shlex
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from . import hosts as hosts_lib
from ..common.config import runtime_env


def build_env_for_slot(base_env: Dict[str, str], coordinator: str,
                       num_proc: int, proc_id: int,
                       extra: Optional[Dict[str, str]] = None
                       ) -> Dict[str, str]:
    """Reference: gloo_run.py:65-99 slot env construction."""
    env = dict(base_env)
    env["HVD_TPU_COORDINATOR"] = coordinator
    env["HVD_TPU_NUM_PROC"] = str(num_proc)
    env["HVD_TPU_PROC_ID"] = str(proc_id)
    if num_proc > 1 and env.get("HVD_TPU_METRICS_FILE"):
        # One JSON-lines dump per worker: N processes appending
        # snapshots to one file would interleave rank states. The
        # .rank<k> suffix is what analyze_trace.py --metrics globs to
        # build its per-rank + merged report (docs/podmon.md).
        env["HVD_TPU_METRICS_FILE"] = \
            f"{env['HVD_TPU_METRICS_FILE']}.rank{proc_id}"
    if extra:
        env.update(extra)
    return env


def _slot_local_env(local_rank: int, local_size: int) -> Dict[str, str]:
    """Per-slot local topology (reference HOROVOD_LOCAL_RANK/LOCAL_SIZE,
    gloo_run.py:65-99)."""
    return {"HVD_TPU_LOCAL_RANK": str(local_rank),
            "HVD_TPU_LOCAL_SIZE": str(local_size)}


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_fail_fast(procs,
                    threads: List[threading.Thread],
                    poll_interval: float = 0.1) -> int:
    """Wait for all workers; on the FIRST non-zero exit kill the rest
    (reference fail-fast: gloo_run.py:226-284 kills the job when any slot
    exits non-zero). Polls all processes so a late-indexed crash is acted
    on while earlier workers still block on their peers."""
    rc = 0
    try:
        while True:
            running = False
            for p in procs:
                code = p.poll()
                if code is None:
                    running = True
                elif code != 0 and rc == 0:
                    rc = code
                    for q in procs:
                        if q.poll() is None:
                            q.terminate()
            if not running:
                break
            time.sleep(poll_interval)
        for t in threads:
            t.join(timeout=2)
        return rc
    except KeyboardInterrupt:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGINT)
        for p in procs:
            p.wait()
        return 1


def run_local(np: int, command: List[str], env_extra: Dict[str, str],
              verbose: bool = False) -> int:
    """Fork np local worker processes (the localhost-gloo analog).
    Workers run under a pty (safe_shell_exec: children see a tty, output
    line-buffered + prefixed, group-signal termination)."""
    from . import safe_shell_exec as sse

    port = _free_port()
    coordinator = f"127.0.0.1:{port}"
    handles: List[sse.SpawnedProcess] = []
    for i in range(np):
        env = build_env_for_slot(dict(os.environ), coordinator, np, i,
                                 {**env_extra, **_slot_local_env(i, np)})
        handles.append(sse.spawn(command, env=env, prefix=str(i)))
    return _wait_fail_fast(handles, [h.thread for h in handles])


def used_hosts(host_infos: List[hosts_lib.HostInfo], np: int) -> List[str]:
    """Ordered dedup of the hosts covering ``np`` slots — the single source
    of truth for the ssh process count (shared with runner.run so the
    driver polls for exactly the result files run_ssh spawns)."""
    slots = hosts_lib.get_host_assignments(host_infos, np)
    ordered: List[str] = []
    for s in slots:
        if s.hostname not in ordered:
            ordered.append(s.hostname)
    return ordered


def run_ssh(host_infos: List[hosts_lib.HostInfo], command: List[str],
            env_extra: Dict[str, str], np: int,
            verbose: bool = False,
            ssh_port: Optional[int] = None) -> int:
    """One process per *used* host over ssh (reference gloo_run ssh
    fan-out). TPU model: ``-np`` requests total slots (chips); a host's
    process drives all of that host's assigned chips, so the process count
    is the number of hosts covering ``np`` slots — unlike local mode which
    forks one process per slot. Rank-0 host runs the jax.distributed
    coordinator."""
    from . import safe_shell_exec as sse

    hosts = used_hosts(host_infos, np)
    num_proc = len(hosts)
    coord_host = hosts[0]
    if runtime_env("NIC_DISCOVERY") == "1" and num_proc > 1:
        picked = _nic_discovery_coordinator(hosts, ssh_port)
        if picked:
            coord_host = picked
    coord = f"{coord_host}:{_free_port()}"
    handles = []
    for i, hostname in enumerate(hosts):
        # HVD_TPU_HOSTNAME rides along like the elastic/spark paths:
        # podmon.register_endpoint advertises it as the scrape address
        # (without it a remote worker falls back to loopback and the
        # driver-side aggregator scrapes itself).
        env = build_env_for_slot({}, coord, num_proc, i,
                                 {**env_extra, **_slot_local_env(0, 1),
                                  "HVD_TPU_HOSTNAME": hostname})
        # *_SECRET vars must not ride the remote argv (any local user on
        # the worker reads it via ps); they travel over ssh stdin as one
        # export line the bootstrap evals before exec'ing the command.
        secrets = {k: v for k, v in env.items() if k.endswith("_SECRET")}
        plain = {k: v for k, v in env.items() if k not in secrets}
        env_str = " ".join(f"{k}={shlex.quote(v)}"
                           for k, v in plain.items())
        remote_cmd = f"cd {shlex.quote(os.getcwd())} && {env_str} " + \
            " ".join(shlex.quote(c) for c in command)
        input_data = None
        if secrets:
            exports = " ".join(f"{k}={shlex.quote(v)}"
                               for k, v in secrets.items())
            remote_cmd = ('IFS= read -r __HVD_SECRET_ENV__ && '
                          'eval "export $__HVD_SECRET_ENV__"; '
                          + remote_cmd)
            input_data = (exports + "\n").encode()
        ssh_cmd = ["ssh", "-o", "StrictHostKeyChecking=no"]
        if ssh_port:
            ssh_cmd += ["-p", str(ssh_port)]
        ssh_cmd += [hostname, remote_cmd]
        handles.append(sse.spawn(ssh_cmd, prefix=hostname,
                                 input_data=input_data))
    return _wait_fail_fast(handles, [h.thread for h in handles])


def _nic_discovery_coordinator(hosts: List[str],
                               ssh_port: Optional[int]) -> Optional[str]:
    """Routable-NIC discovery before the fan-out (HVD_TPU_NIC_DISCOVERY=1
    — reference driver_service.py:49-257): start a task server on every
    host over ssh, intersect the registered interface sets, and return
    the rank-0 host's IP on the first common interface. Returns None
    (fall back to the hostname) on any failure — discovery must never
    make a working launch fail."""
    import select

    from . import driver_service as ds

    servers: List[subprocess.Popen] = []
    try:
        task_addrs = {}
        for hostname in hosts:
            ssh_cmd = ["ssh", "-o", "StrictHostKeyChecking=no",
                       "-o", "BatchMode=yes"]
            if ssh_port:
                ssh_cmd += ["-p", str(ssh_port)]
            # --ttl: servers self-terminate, so a dropped ssh control
            # channel cannot strand listeners on the remote host.
            ssh_cmd += [hostname, sys.executable, "-m",
                        "horovod_tpu.runner.driver_service", "--serve",
                        "--ttl", "120"]
            p = subprocess.Popen(ssh_cmd, stdout=subprocess.PIPE,
                                 stderr=subprocess.DEVNULL, text=True)
            servers.append(p)
            # Bounded banner wait — a hung host must degrade discovery,
            # not hang the launch.
            ready, _, _ = select.select([p.stdout], [], [], 20.0)
            line = (p.stdout.readline() or "").strip() if ready else ""
            if not line.startswith("TASKSERVER "):
                return None
            task_addrs[hostname] = (hostname, int(line.split()[1]))
        common = ds.discover_routable_interfaces(task_addrs)
        ifaces = ds.query_interfaces(task_addrs[hosts[0]])
        port0 = task_addrs[hosts[0]][1]
        for iface in common:
            ip = ifaces.get(iface)
            # Verify the candidate actually routes to rank 0's server
            # from here — a host-local bridge (docker0, virbr0) exists
            # everywhere but answers with the WRONG machine's stack, so
            # its probe fails and it is skipped.
            if ip and ds.probe_reachable((ip, port0)):
                return ip
        return None
    except (OSError, RuntimeError, ValueError):
        return None
    finally:
        for p in servers:
            if p.poll() is None:
                p.terminate()
        for p in servers:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()


def check_build() -> str:
    """Capability matrix (reference horovodrun --check-build,
    launch.py:107-143) — honest answers: shims are available when
    their framework imports; the one tensor-op plane is XLA."""
    from .. import __version__
    from ..common import basics

    def mark(v):
        return "X" if v else " "

    def importable(mod):
        import importlib.util

        return importlib.util.find_spec(mod) is not None

    return f"""\
horovod_tpu v{__version__}:

Available Frameworks:
    [X] JAX (native)
    [{mark(importable('tensorflow'))}] TensorFlow (shim)
    [{mark(importable('torch'))}] PyTorch (shim)
    [{mark(importable('mxnet'))}] MXNet (shim)

Available Controllers:
    [X] XLA single-controller (SPMD)
    [X] jax.distributed + rendezvous KV (multi-process)
    [{mark(basics.mpi_built())}] MPI
    [{mark(basics.gloo_built())}] Gloo

Available Tensor Operations:
    [{mark(basics.xla_built())}] XLA (ICI/DCN)
    [{mark(basics.nccl_built())}] NCCL
    [{mark(basics.ddl_built())}] DDL
    [{mark(basics.ccl_built())}] CCL
    [{mark(basics.mpi_built())}] MPI
    [{mark(basics.gloo_built())}] Gloo

Available Parallelism Strategies (beyond the reference):
    [X] DP (fused/hierarchical/Adasum/quantized-DCN allreduce)
    [X] TP (Megatron column/row-parallel)
    [X] PP (GPipe + interleaved 1F1B)
    [X] SP (ring attention + Ulysses)
    [X] EP (GShard top-2 MoE)
    [X] ZeRO-1 (sharded optimizer state)
    [X] FSDP/ZeRO-3 (fully-sharded parameters)"""


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    return _build_parser().parse_args(argv)


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="hvdtpurun",
        description="Launch a horovod_tpu training job "
                    "(horovodrun equivalent for TPU).")
    p.add_argument("-np", "--num-proc", type=int, default=None,
                   help="number of worker processes (default 1; on a TPU "
                        "pod an UNSET -np auto-scales to the pod's chips)")
    p.add_argument("-H", "--hosts", default=None,
                   help="host list, e.g. host1:4,host2:4")
    p.add_argument("--hostfile", default=None,
                   help="hostfile with 'hostname slots=N' lines")
    p.add_argument("--config-file", default=None,
                   help="YAML config supplying any of these flags "
                        "(explicit CLI flags win — reference "
                        "launch.py:290 --config-file)")
    p.add_argument("--ssh-port", type=int, default=None)
    p.add_argument("-v", "--verbose", action="store_true")
    p.add_argument("--version", action="store_true")
    p.add_argument("-cb", "--check-build", action="store_true",
                   help="print the capability matrix (reference "
                        "horovodrun --check-build, launch.py:107-143) "
                        "and exit")
    # Knob flags -> env (reference launch.py:392-523 / config_parser.py).
    p.add_argument("--fusion-threshold-mb", type=float, default=None)
    p.add_argument("--cache-capacity", type=int, default=None)
    p.add_argument("--hierarchical-allreduce", action="store_true")
    p.add_argument("--timeline-filename", default=None)
    p.add_argument("--timeline-mark-cycles", action="store_true")
    p.add_argument("--stall-check-time-seconds", type=float, default=None)
    p.add_argument("--stall-shutdown-time-seconds", type=float, default=None)
    p.add_argument("--no-stall-check", action="store_true")
    p.add_argument("--compression", default=None,
                   choices=["none", "fp16", "bf16"])
    p.add_argument("--autotune", action="store_true")
    p.add_argument("--autotune-log-file", default=None)
    p.add_argument("--metrics-port", type=int, default=None,
                   help="serve the Prometheus /metrics endpoint on each "
                        "worker (0 = ephemeral, logged at init; exported "
                        "as HVD_TPU_METRICS_PORT — docs/metrics.md). "
                        "With >1 worker per host pass 0: a fixed port "
                        "would collide")
    p.add_argument("--metrics-file", default=None,
                   help="per-worker metrics JSON-lines dump path "
                        "(.rank<k> is appended in multi-proc runs; "
                        "HVD_TPU_METRICS_FILE)")
    p.add_argument("--pod-metrics-port", type=int, default=None,
                   help="driver-side pod aggregator (docs/podmon.md): "
                        "scrape every worker's /metrics.json and serve "
                        "the merged rank-labeled view + "
                        "hvd_tpu_pod_step_skew_seconds on ONE "
                        "/pod/metrics endpoint at this port (0 = "
                        "ephemeral; HVD_TPU_POD_METRICS_PORT). Workers "
                        "default to --metrics-port 0 when unset so "
                        "there is something to scrape")
    p.add_argument("--log-level", default=None)
    # Elastic (reference launch.py elastic flags).
    p.add_argument("--elastic", action="store_true")
    p.add_argument("--min-np", type=int, default=None)
    p.add_argument("--max-np", type=int, default=None)
    p.add_argument("--host-discovery-script", default=None)
    p.add_argument("--fault-plan", default=None,
                   help="chaos: JSON fault plan (or @/path/to/plan.json) "
                        "exported to workers as HVD_TPU_FAULT_PLAN — see "
                        "horovod_tpu/common/faults.py for sites/format")
    p.add_argument("--autoscale-policy", default=None,
                   help="telemetry-driven autoscaling policy for the "
                        "elastic driver: a JSON file path or inline JSON "
                        "object (docs/autoscale.md). Validated eagerly — "
                        "a bad field fails the launch naming it. Implies "
                        "--elastic; exported as HVD_TPU_AUTOSCALE_POLICY "
                        "(+ HVD_TPU_AUTOSCALE=1)")
    p.add_argument("--autoscale-log", default=None,
                   help="driver-side autoscale decision log path "
                        "(JSON lines; HVD_TPU_AUTOSCALE_LOG)")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="training command")
    return p


def _coerce_bool(v) -> bool:
    if isinstance(v, bool):
        return v
    if isinstance(v, str):
        return v.strip().lower() in ("1", "true", "yes", "on")
    return bool(v)


def apply_config_file(args: argparse.Namespace,
                      argv: Optional[List[str]] = None
                      ) -> argparse.Namespace:
    """Fill unset args from a YAML config (reference launch.py:510-523 +
    config_parser.py set_args_from_config). Keys may be flat or nested
    under sections; dashes and underscores are interchangeable.

    Explicit CLI flags win — "explicit" is determined by re-parsing
    ``argv`` with SUPPRESS defaults (so ``--cache-capacity 0`` counts as
    set even though 0 is falsy, and the config CAN supply flags with
    non-None defaults like -np). Config values are coerced/validated
    through the same argparse type/choices as the CLI path.
    """
    if not getattr(args, "config_file", None):
        return args
    import yaml

    probe = _build_parser()
    actions = {}
    for a in probe._actions:
        actions[a.dest] = a
        a.default = argparse.SUPPRESS
    explicit = set(vars(probe.parse_args(argv if argv is not None
                                         else sys.argv[1:])))

    with open(args.config_file) as f:
        cfg = yaml.safe_load(f) or {}
    flat: Dict[str, object] = {}

    def walk(d):
        for k, v in d.items():
            if isinstance(v, dict):
                walk(v)
            else:
                flat[str(k).replace("-", "_")] = v

    walk(cfg)
    for k, v in flat.items():
        if k in explicit or not hasattr(args, k) or k == "config_file":
            continue
        action = actions.get(k)
        if action is not None:
            if isinstance(action, argparse._StoreTrueAction):
                v = _coerce_bool(v)
            elif action.type is not None and v is not None:
                v = action.type(v)
            if action.choices is not None and v not in action.choices:
                raise ValueError(
                    f"config file: {k}={v!r} not in {action.choices}")
        setattr(args, k, v)
    return args


def knob_env(args: argparse.Namespace) -> Dict[str, str]:
    env = {}
    if args.fusion_threshold_mb is not None:
        env["HVD_TPU_FUSION_THRESHOLD"] = str(
            int(args.fusion_threshold_mb * 1024 * 1024))
    if args.cache_capacity is not None:
        env["HVD_TPU_CACHE_CAPACITY"] = str(args.cache_capacity)
    if args.hierarchical_allreduce:
        env["HVD_TPU_HIERARCHICAL_ALLREDUCE"] = "1"
    if args.timeline_filename:
        env["HVD_TPU_TIMELINE"] = args.timeline_filename
    if args.timeline_mark_cycles:
        env["HVD_TPU_TIMELINE_MARK_CYCLES"] = "1"
    if args.stall_check_time_seconds is not None:
        env["HVD_TPU_STALL_CHECK_TIME_SECONDS"] = str(
            args.stall_check_time_seconds)
    if args.stall_shutdown_time_seconds is not None:
        env["HVD_TPU_STALL_SHUTDOWN_TIME_SECONDS"] = str(
            args.stall_shutdown_time_seconds)
    if args.no_stall_check:
        env["HVD_TPU_STALL_CHECK_DISABLE"] = "1"
    if args.compression:
        env["HVD_TPU_COMPRESSION_DTYPE"] = args.compression
    if args.autotune:
        env["HVD_TPU_AUTOTUNE"] = "1"
    if args.autotune_log_file:
        env["HVD_TPU_AUTOTUNE_LOG"] = args.autotune_log_file
    if args.metrics_port is not None:
        env["HVD_TPU_METRICS_PORT"] = str(args.metrics_port)
    if args.metrics_file:
        env["HVD_TPU_METRICS_FILE"] = args.metrics_file
    if getattr(args, "pod_metrics_port", None) is not None:
        env["HVD_TPU_POD_METRICS_PORT"] = str(args.pod_metrics_port)
        # The aggregator scrapes the workers' /metrics.json — an
        # explicit --metrics-port wins, otherwise each worker binds an
        # ephemeral endpoint and advertises it over the KV.
        env.setdefault("HVD_TPU_METRICS_PORT",
                       str(args.metrics_port
                           if args.metrics_port is not None else 0))
    if args.log_level:
        env["HVD_TPU_LOG_LEVEL"] = args.log_level
    if args.elastic:
        env["HVD_TPU_ELASTIC"] = "1"
    if args.fault_plan:
        plan = args.fault_plan
        if plan.startswith("@"):
            with open(plan[1:]) as f:
                plan = f.read()
        # Parse eagerly: a malformed plan must fail the launch, not
        # silently strip the chaos from every worker.
        from ..common.faults import FaultPlan

        FaultPlan.from_json(plan)
        env["HVD_TPU_FAULT_PLAN"] = plan
    if getattr(args, "autoscale_policy", None):
        # Parse eagerly: a typo'd threshold must fail THIS launch with
        # the field named, not silently run the job on defaults. The
        # canonical (validated) JSON is what gets exported, so file
        # paths work on the driver even when workers can't read them.
        from ..common.autoscale import AutoscalePolicy

        policy = AutoscalePolicy.load(args.autoscale_policy)
        env["HVD_TPU_AUTOSCALE"] = "1"
        env["HVD_TPU_AUTOSCALE_POLICY"] = policy.to_json()
    if getattr(args, "autoscale_log", None):
        env["HVD_TPU_AUTOSCALE_LOG"] = args.autoscale_log
    return env


def _start_pod_monitor(env_extra: Dict[str, str],
                       advertise_host: str = "127.0.0.1"):
    """Start the driver-side pod aggregator (docs/podmon.md) when
    ``HVD_TPU_POD_METRICS_PORT`` requests one for a STATIC launch.
    Without a rendezvous KV in play, one is started here purely for
    worker endpoint advertisement (workers ignore it otherwise —
    elastic host-update polling only arms under ``--elastic``).
    Returns ``(monitor, owned_rdv)``; the caller stops both."""
    from ..common import podmon as podmon_lib

    merged_env = {**os.environ, **env_extra}
    port = podmon_lib.monitor_port_from_env(merged_env)
    if port is None:
        return None, None
    from .rendezvous import RendezvousServer

    owned_rdv = None
    sources = [podmon_lib.static_endpoints(
        merged_env.get(podmon_lib.ENV_ENDPOINTS))]
    if "HVD_TPU_RENDEZVOUS" not in merged_env:
        owned_rdv = RendezvousServer("0.0.0.0")
        kv_port = owned_rdv.start()
        env_extra["HVD_TPU_RENDEZVOUS"] = f"{advertise_host}:{kv_port}"
        sources.append(podmon_lib.kv_endpoints(owned_rdv))
    monitor = podmon_lib.PodMonitor(
        podmon_lib.combined_endpoints(*sources))
    monitor.start(port)
    return monitor, owned_rdv


def run_commandline(argv: Optional[List[str]] = None) -> int:
    args = parse_args(argv)
    if args.version:
        from .. import __version__

        print(__version__)
        return 0
    args = apply_config_file(args, argv)
    # After the config merge so `check-build: true` in a YAML file works
    # like the flag (the config contract covers every flag).
    if args.check_build:
        print(check_build())
        return 0
    # An explicit -np 1 must survive pod auto-scaling; only an UNSET -np
    # may be grown to the pod size below.
    np_unset = args.num_proc is None
    if np_unset:
        args.num_proc = 1
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        print("hvdtpurun: no command given", file=sys.stderr)
        return 2

    env_extra = knob_env(args)

    if getattr(args, "autoscale_policy", None) and not args.elastic:
        # Autoscaling is a property of the elastic driver; the flag
        # implies the mode (scaling a static world is a contradiction).
        args.elastic = True

    if args.elastic:
        from .elastic_driver import run_elastic

        return run_elastic(args, command, env_extra)

    if args.hostfile:
        host_infos = hosts_lib.parse_host_files(args.hostfile)
    elif args.hosts:
        host_infos = hosts_lib.parse_hosts(args.hosts)
    else:
        host_infos = None
        # Inside an LSF allocation the scheduler already owns the host
        # set (reference js_run/LSFUtils detection, launch.py:672-707).
        from . import lsf as lsf_lib

        if lsf_lib.in_lsf():
            try:
                host_infos = lsf_lib.lsf_hosts()
            except RuntimeError as e:
                # A stale LSB_JOBID without host variables must not turn
                # a working local launch into a crash.
                print(f"hvdtpurun: ignoring LSF environment ({e}); "
                      "launching locally", file=sys.stderr)
        if host_infos is None:
            # On a Cloud TPU pod VM the platform publishes the full
            # topology as env metadata — no -H/--hostfile needed
            # (tpu_pod.py; SURVEY §7.6 "discovers TPU pod topology").
            from . import tpu_pod

            try:
                pod = tpu_pod.discover_pod()
            except ValueError as e:
                # Stale/inconsistent pod metadata must not turn a working
                # local launch into a crash (same contract as LSF above).
                print(f"hvdtpurun: ignoring TPU pod environment ({e}); "
                      "launching locally", file=sys.stderr)
                pod = None
            if pod is not None:
                # Single-host "pods" publish an internal IP that won't
                # match gethostname() — keep those on run_local instead
                # of demanding working ssh-to-self.
                host_infos = (pod.host_infos() if pod.num_hosts > 1
                              else None)
                if np_unset and pod.num_chips > 1:
                    print(f"hvdtpurun: TPU pod detected "
                          f"({pod.accelerator_type or 'unknown type'}, "
                          f"{pod.num_hosts} hosts x {pod.chips_per_host} "
                          f"chips); running -np {pod.num_chips}",
                          file=sys.stderr)
                    args.num_proc = pod.num_chips

    if host_infos is not None:
        # Validate np against available slots (reference: horovodrun errors
        # on -np > slots rather than oversubscribing, hosts.py:100).
        hosts_lib.get_host_assignments(host_infos, args.num_proc)

    monitor = owned_rdv = None
    try:
        if host_infos is None or all(
                h.hostname in ("localhost", "127.0.0.1",
                               socket.gethostname())
                for h in host_infos):
            monitor, owned_rdv = _start_pod_monitor(env_extra)
            return run_local(args.num_proc, command, env_extra,
                             args.verbose)
        monitor, owned_rdv = _start_pod_monitor(
            env_extra, advertise_host=socket.gethostname())
        return run_ssh(host_infos, command, env_extra, args.num_proc,
                       args.verbose, args.ssh_port)
    finally:
        if monitor is not None:
            monitor.stop()
        if owned_rdv is not None:
            owned_rdv.stop()


def main() -> None:
    sys.exit(run_commandline())


if __name__ == "__main__":
    main()
