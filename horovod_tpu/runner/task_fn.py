"""Worker entry point for the programmatic ``run()`` API.

Reference: horovod/runner/task_fn.py (66 LoC) — each launched worker
deserializes the cloudpickled user function, executes it, and reports the
result back to the driver. Here results travel over the shared filesystem
(one pickle per process id) instead of the reference's network service;
the launcher already wired HVD_TPU_PROC_ID/NUM_PROC/COORDINATOR env so the
function can ``hvd.init()`` into the multi-process world.
"""

from __future__ import annotations

import os
import pickle
import sys
import traceback
from ..common.config import runtime_env


def main(payload_path: str, out_dir: str) -> int:
    import cloudpickle

    pid = int(runtime_env("PROC_ID", "0"))
    try:
        with open(payload_path, "rb") as f:
            func, args, kwargs = cloudpickle.load(f)
        result = func(*args, **kwargs)
        status = "ok"
    except BaseException as e:  # report, then re-raise for the exit code
        result = "".join(traceback.format_exception(
            type(e), e, e.__traceback__))
        status = "error"
    tmp = os.path.join(out_dir, f".result_{pid}.tmp")
    with open(tmp, "wb") as f:
        pickle.dump((status, result), f)
    os.replace(tmp, os.path.join(out_dir, f"result_{pid}.pkl"))
    if status == "error":
        sys.stderr.write(result)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1], sys.argv[2]))
