"""Elastic driver — host discovery, worker lifecycle, rank stability.

Reference: horovod/runner/elastic/driver.py:68-309 (ElasticDriver),
discovery.py:25-164 (HostManager + pluggable HostDiscovery / discovery
script), registration.py (WorkerStateRegistry). Semantics preserved:

* a discovery source is polled every ``discovery_interval`` seconds;
* on host set changes, workers are notified (HostsUpdatedInterrupt on
  their side at the next commit());
* rank assignment keeps surviving workers' ranks stable, filling gaps
  with new hosts (driver.py _update_host_assignments);
* hosts whose workers fail are blacklisted (driver.py blacklist logic);
* the job continues while >= min_np slots are available.

On TPU the "hosts" are TPU-VM workers; preemption looks like a host
disappearing from the discovery source (e.g. the GCE instance list or a
queued-resource status probe).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import subprocess
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Set

from . import hosts as hosts_lib
from .launch import build_env_for_slot, run_local
from .rendezvous import RendezvousServer

logger = logging.getLogger("horovod_tpu")


class HostDiscovery:
    """Pluggable discovery source (reference discovery.py:25-60)."""

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        raise NotImplementedError


class FixedHostDiscovery(HostDiscovery):
    def __init__(self, hosts: Dict[str, int]):
        self._hosts = dict(hosts)

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        return dict(self._hosts)


class ScriptHostDiscovery(HostDiscovery):
    """Discovery via a user script printing 'hostname:slots' lines
    (reference discovery.py HostDiscoveryScript; the integration tests
    mutate the script's output to simulate host churn — elastic_common.py).
    """

    def __init__(self, script: str, timeout_s: float = 30.0):
        self._script = script
        self._timeout_s = timeout_s
        self._last: Dict[str, int] = {}

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        # A hung or transiently failing script must not kill the discovery
        # thread or wipe the host set — fall back to the last good answer
        # (the reference's HostManager likewise only applies *successful*
        # discovery results).
        try:
            out = subprocess.run([self._script], capture_output=True,
                                 text=True, timeout=self._timeout_s)
        except (subprocess.TimeoutExpired, OSError) as e:
            logger.warning("elastic: discovery script failed (%s); keeping "
                           "last known hosts", e)
            return dict(self._last)
        if out.returncode != 0:
            logger.warning("elastic: discovery script exited %d; keeping "
                           "last known hosts", out.returncode)
            return dict(self._last)
        hosts: Dict[str, int] = {}
        for line in out.stdout.splitlines():
            line = line.strip()
            if not line:
                continue
            if ":" in line:
                name, slots = line.rsplit(":", 1)
                hosts[name] = int(slots)
            else:
                hosts[line] = 1
        self._last = dict(hosts)
        return hosts


@dataclasses.dataclass
class HostState:
    slots: int
    blacklisted: bool = False


class HostManager:
    """Tracks current/blacklisted hosts (reference discovery.py:61-164).

    The blacklist is a persistent, separate set: a failed host that drops
    out of discovery and later reappears stays blacklisted (the reference
    excludes blacklisted hosts permanently)."""

    def __init__(self, discovery: HostDiscovery):
        self._discovery = discovery
        self._hosts: Dict[str, HostState] = {}
        self._blacklist: Set[str] = set()
        self._lock = threading.Lock()

    def update_available_hosts(self) -> bool:
        """Poll discovery; returns True if the usable host set changed."""
        found = self._discovery.find_available_hosts_and_slots()
        with self._lock:
            changed = False
            for name, slots in found.items():
                usable = name not in self._blacklist
                if name not in self._hosts:
                    self._hosts[name] = HostState(slots)
                    changed = changed or usable
                elif self._hosts[name].slots != slots:
                    self._hosts[name].slots = slots
                    changed = changed or usable
            for name in list(self._hosts):
                if name not in found:
                    del self._hosts[name]
                    changed = changed or name not in self._blacklist
            return changed

    def blacklist(self, hostname: str) -> None:
        with self._lock:
            self._blacklist.add(hostname)
        logger.warning("elastic: blacklisted host %s", hostname)

    def current_hosts(self) -> Dict[str, int]:
        with self._lock:
            return {n: h.slots for n, h in self._hosts.items()
                    if n not in self._blacklist}

    def is_blacklisted(self, hostname: str) -> bool:
        with self._lock:
            return hostname in self._blacklist


class ElasticDriver:
    """Discovery loop + stable rank assignment (reference driver.py:68-309).
    """

    def __init__(self, discovery: HostDiscovery, min_np: int, max_np: int,
                 discovery_interval: float = 1.0):
        self.host_manager = HostManager(discovery)
        self.min_np = min_np
        self.max_np = max_np
        self.discovery_interval = discovery_interval
        self._assignments: Dict[str, List[hosts_lib.SlotInfo]] = {}
        self._shutdown = threading.Event()
        self._host_change = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    # -- discovery loop (reference driver.py:90-92, 1 s poll) -------------

    def start_discovery(self) -> None:
        self.host_manager.update_available_hosts()

        def loop():
            while not self._shutdown.is_set():
                if self.host_manager.update_available_hosts():
                    self._host_change.set()
                self._shutdown.wait(self.discovery_interval)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._shutdown.set()
        if self._thread:
            self._thread.join(timeout=5)

    def hosts_updated(self) -> bool:
        """Consumed by workers' check_host_updates()."""
        if self._host_change.is_set():
            self._host_change.clear()
            return True
        return False

    def wait_for_available_slots(self, min_np: Optional[int] = None,
                                 timeout_s: float = 600.0) -> Dict[str, int]:
        """Block until >= min_np slots exist (reference driver.py:139-160).
        """
        need = min_np if min_np is not None else self.min_np
        deadline = time.monotonic() + timeout_s
        while True:
            hosts = self.host_manager.current_hosts()
            if sum(hosts.values()) >= need:
                return hosts
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"fewer than min_np={need} slots available after "
                    f"{timeout_s}s")
            self.host_manager.update_available_hosts()
            time.sleep(self.discovery_interval)

    # -- rank assignment (reference driver.py _update_host_assignments) ---

    def update_assignments(self) -> List[hosts_lib.SlotInfo]:
        """Re-assign ranks, keeping existing hosts' ranks stable."""
        hosts = self.host_manager.current_hosts()
        with self._lock:
            prev_order = [h for h in self._assignments if h in hosts]
            new_hosts = [h for h in hosts if h not in self._assignments]
            ordered = prev_order + sorted(new_hosts)
            np_total = min(self.max_np,
                           sum(hosts[h] for h in ordered))
            infos = hosts_lib.get_host_assignments(
                [hosts_lib.HostInfo(h, hosts[h]) for h in ordered], np_total)
            self._assignments = {}
            for s in infos:
                self._assignments.setdefault(s.hostname, []).append(s)
            return infos

    def record_failure(self, hostname: str) -> None:
        self.host_manager.blacklist(hostname)
        self._host_change.set()


def run_elastic(args, command: List[str],
                env_extra: Dict[str, str]) -> int:
    """Driver-side elastic launch (reference gloo_run_elastic
    gloo_run.py:326 + launch.py:616): workers restart with fresh topology
    env until success or the reset limit / min-np floor is hit.

    The driver runs a rendezvous KV server and publishes a monotonically
    increasing ``topology/version`` on every host-set change; workers poll
    it at commit() points (Context.host_update_notifier) and raise
    HostsUpdatedInterrupt for graceful re-rendezvous — the reference's
    WorkerNotificationClient channel (elastic/worker.py).

    Local-process implementation: the worker set is re-forked on every
    topology change; real multi-host ssh fan-out reuses the same loop with
    run_ssh per epoch.
    """
    min_np = args.min_np or args.num_proc
    max_np = args.max_np or args.num_proc
    if args.host_discovery_script:
        discovery: HostDiscovery = ScriptHostDiscovery(
            args.host_discovery_script)
    else:
        host_infos = (hosts_lib.parse_hosts(args.hosts) if args.hosts
                      else [hosts_lib.HostInfo("localhost", max_np)])
        discovery = FixedHostDiscovery(
            {h.hostname: h.slots for h in host_infos})

    driver = ElasticDriver(discovery, min_np, max_np)
    driver.start_discovery()
    rdv = RendezvousServer("127.0.0.1")
    rdv_port = rdv.start()
    topo_version = 0
    rdv.put("elastic", "topology_version", str(topo_version).encode())
    env_extra = dict(env_extra)
    env_extra["HVD_TPU_RENDEZVOUS"] = f"127.0.0.1:{rdv_port}"

    def bump_version():
        nonlocal topo_version
        topo_version += 1
        rdv.put("elastic", "topology_version", str(topo_version).encode())

    try:
        attempts = 0
        while True:
            hosts = driver.wait_for_available_slots(min_np)
            np_now = min(max_np, sum(hosts.values()))
            logger.info("elastic launch attempt %d with np=%d", attempts,
                        np_now)

            # Publish topology changes while workers run.
            stop_pub = threading.Event()

            def publisher():
                while not stop_pub.is_set():
                    if driver.hosts_updated():
                        bump_version()
                    stop_pub.wait(driver.discovery_interval)

            pub = threading.Thread(target=publisher, daemon=True)
            pub.start()
            try:
                rc = run_local(np_now, command, env_extra)
            finally:
                stop_pub.set()
                pub.join(timeout=2)
            if rc == 0:
                return 0
            bump_version()
            attempts += 1
            if attempts > int(os.environ.get(
                    "HVD_TPU_ELASTIC_RESET_LIMIT", "100")):
                return rc
    finally:
        rdv.stop()
        driver.stop()
