"""Elastic driver — host discovery, worker lifecycle, rank stability.

Reference: horovod/runner/elastic/driver.py:68-309 (ElasticDriver),
discovery.py:25-164 (HostManager + pluggable HostDiscovery / discovery
script), registration.py (WorkerStateRegistry). Semantics preserved:

* a discovery source is polled every ``discovery_interval`` seconds;
* on host set changes, workers are notified (HostsUpdatedInterrupt on
  their side at the next commit());
* rank assignment keeps surviving workers' ranks stable, filling gaps
  with new hosts (driver.py _update_host_assignments);
* hosts whose workers fail are blacklisted (driver.py blacklist logic);
* the job continues while >= min_np slots are available.

On TPU the "hosts" are TPU-VM workers; preemption looks like a host
disappearing from the discovery source (e.g. the GCE instance list or a
queued-resource status probe).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import subprocess
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Set

from ..common import faults as faults_lib
from ..common.config import runtime_env
from . import hosts as hosts_lib
from .launch import build_env_for_slot
from .rendezvous import RendezvousServer

logger = logging.getLogger("horovod_tpu")


class HostDiscovery:
    """Pluggable discovery source (reference discovery.py:25-60)."""

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        raise NotImplementedError


class FixedHostDiscovery(HostDiscovery):
    def __init__(self, hosts: Dict[str, int]):
        self._hosts = dict(hosts)

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        return dict(self._hosts)


class ScriptHostDiscovery(HostDiscovery):
    """Discovery via a user script printing 'hostname:slots' lines
    (reference discovery.py HostDiscoveryScript; the integration tests
    mutate the script's output to simulate host churn — elastic_common.py).

    Flap debounce: a CHANGED host set is only reported upward after the
    script returns the same new set ``debounce`` consecutive polls
    (``HVD_TPU_DISCOVERY_DEBOUNCE``, default 2) — one bad scrape (a
    truncated instance list, a half-registered VM) must not trigger a
    spurious reshard that throws away a healthy epoch. The first
    successful scrape is adopted immediately (there is nothing to
    debounce against), and ``debounce<=1`` restores the trusting
    historical behavior.
    """

    def __init__(self, script: str, timeout_s: float = 30.0,
                 debounce: Optional[int] = None):
        self._script = script
        self._timeout_s = timeout_s
        self._last: Dict[str, int] = {}
        self._primed = False
        if debounce is None:
            try:
                debounce = int(runtime_env("DISCOVERY_DEBOUNCE", "2"))
            except ValueError:
                debounce = 2
        self._debounce = max(1, debounce)
        self._pending: Optional[Dict[str, int]] = None
        self._pending_count = 0
        # Failure backoff: a flapping/crashing discovery script gets
        # re-run on an exponential full-jitter schedule
        # (HVD_TPU_DISCOVERY_BACKOFF_{BASE_S,MAX_S}) instead of every
        # poll — the last good answer serves in between.
        self._backoff = faults_lib.Backoff.from_env(
            "HVD_TPU_DISCOVERY_BACKOFF", base_s=1.0, cap_s=30.0)
        self._retry_at = 0.0

    def _fail(self, why: str) -> Dict[str, int]:
        delay = self._backoff.next_delay()
        self._retry_at = time.monotonic() + delay
        faults_lib.stats.bump("discovery_retries")
        logger.warning("elastic: discovery script failed (%s); keeping "
                       "last known hosts, next attempt in %.1fs",
                       why, delay)
        return dict(self._last)

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        # A hung or transiently failing script must not kill the discovery
        # thread or wipe the host set — fall back to the last good answer
        # (the reference's HostManager likewise only applies *successful*
        # discovery results).
        if time.monotonic() < self._retry_at:
            return dict(self._last)
        try:
            out = subprocess.run([self._script], capture_output=True,
                                 text=True, timeout=self._timeout_s)
        except (subprocess.TimeoutExpired, OSError) as e:
            return self._fail(str(e))
        if out.returncode != 0:
            return self._fail(f"exit code {out.returncode}")
        hosts: Dict[str, int] = {}
        for line in out.stdout.splitlines():
            line = line.strip()
            if not line:
                continue
            if ":" in line:
                name, slots = line.rsplit(":", 1)
                hosts[name] = int(slots)
            else:
                hosts[line] = 1
        self._backoff.reset()
        self._retry_at = 0.0
        return self._debounced(hosts)

    def _debounced(self, hosts: Dict[str, int]) -> Dict[str, int]:
        """Adopt a changed host set only after ``debounce`` consecutive
        identical scrapes; the last adopted answer serves meanwhile."""
        if not self._primed:
            # First successful scrape: nothing to debounce against.
            self._primed = True
            self._last = dict(hosts)
            return dict(hosts)
        if hosts == self._last:
            self._pending = None
            self._pending_count = 0
            return dict(hosts)
        if self._pending is not None and hosts == self._pending:
            self._pending_count += 1
        else:
            self._pending = dict(hosts)
            self._pending_count = 1
        if self._pending_count >= self._debounce:
            logger.info(
                "elastic: discovery change confirmed after %d "
                "consecutive scrapes: %s -> %s", self._pending_count,
                sorted(self._last), sorted(hosts))
            self._last = dict(hosts)
            self._pending = None
            self._pending_count = 0
            return dict(hosts)
        logger.info(
            "elastic: discovery reported a changed host set (%s -> %s); "
            "debouncing (%d/%d consecutive scrapes)",
            sorted(self._last), sorted(hosts), self._pending_count,
            self._debounce)
        return dict(self._last)


@dataclasses.dataclass
class HostState:
    slots: int
    blacklisted: bool = False


@dataclasses.dataclass
class _BlacklistEntry:
    until: float       # monotonic expiry; inf = permanent
    strikes: int       # failures so far — doubles the next exile
    announced: bool = False  # recovery-probe eligibility logged once


class HostManager:
    """Tracks current/blacklisted hosts (reference discovery.py:61-164).

    The blacklist carries a TTL (``HVD_TPU_BLACKLIST_TTL_S``, default
    300 s; <= 0 restores the reference's permanent exile): a TPU-VM that
    failed once is routinely healthy again after a reboot/reschedule, and
    permanent exile slowly bleeds a long-lived job of capacity. When an
    entry expires the host becomes eligible again (the recovery probe —
    it simply re-enters assignment); a host that fails again is exiled
    for twice as long per accumulated strike."""

    def __init__(self, discovery: HostDiscovery,
                 blacklist_ttl_s: Optional[float] = None,
                 clock=time.monotonic):
        self._discovery = discovery
        self._hosts: Dict[str, HostState] = {}
        if blacklist_ttl_s is None:
            try:
                blacklist_ttl_s = float(runtime_env("BLACKLIST_TTL_S",
                                                    "300"))
            except ValueError:
                blacklist_ttl_s = 300.0
        self._ttl = blacklist_ttl_s
        self._clock = clock
        self._blacklist: Dict[str, _BlacklistEntry] = {}
        self._last_usable: Optional[Dict[str, int]] = None
        self._lock = threading.Lock()

    def _is_blacklisted_locked(self, hostname: str) -> bool:
        e = self._blacklist.get(hostname)
        if e is None:
            return False
        if self._clock() < e.until:
            return True
        if not e.announced:
            # Recovery probe: the exile expired; the host re-enters
            # assignment on the next topology change. Strikes persist so
            # a re-failure is exiled longer, not forever-flapping.
            e.announced = True
            faults_lib.stats.bump("blacklist_recoveries")
            logger.warning(
                "elastic: blacklist TTL expired for host %s (strike %d); "
                "eligible for recovery probe", hostname, e.strikes)
        return False

    def update_available_hosts(self) -> bool:
        """Poll discovery; returns True if the USABLE host set changed —
        including a blacklist TTL expiring with no discovery change."""
        found = self._discovery.find_available_hosts_and_slots()
        found = faults_lib.maybe_discovery_flap(found)
        with self._lock:
            self._hosts = {n: HostState(s) for n, s in found.items()}
            usable = {n: s for n, s in found.items()
                      if not self._is_blacklisted_locked(n)}
            prev = self._last_usable
            self._last_usable = usable
            if prev is None:
                return bool(usable)
            return usable != prev

    def blacklist(self, hostname: str, ttl_s: Optional[float] = None,
                  permanent: bool = False) -> None:
        """Exile a host. ``ttl_s`` overrides the configured TTL for this
        entry (the autoscale engine passes its policy's
        ``evict_ttl_s``); strike doubling applies to either TTL.
        ``permanent=True`` exiles forever (the engine's escalation
        decisions — repeated stragglers, struck-out hosts)."""
        with self._lock:
            e = self._blacklist.get(hostname)
            strikes = (e.strikes if e else 0) + 1
            ttl = self._ttl if ttl_s is None else ttl_s
            if permanent or ttl <= 0:
                until = float("inf")
            else:
                until = self._clock() + ttl * (2 ** (strikes - 1))
            self._blacklist[hostname] = _BlacklistEntry(until, strikes)
        faults_lib.stats.bump("blacklist_events")
        if permanent or ttl <= 0:
            logger.warning("elastic: blacklisted host %s (permanent)",
                           hostname)
        else:
            logger.warning(
                "elastic: blacklisted host %s for %.0fs (strike %d)",
                hostname, ttl * (2 ** (strikes - 1)), strikes)

    def current_hosts(self) -> Dict[str, int]:
        with self._lock:
            return {n: h.slots for n, h in self._hosts.items()
                    if not self._is_blacklisted_locked(n)}

    def is_blacklisted(self, hostname: str) -> bool:
        with self._lock:
            return self._is_blacklisted_locked(hostname)

    def blacklist_snapshot(self) -> Dict[str, Dict]:
        """Diagnostic view: hostname -> {strikes, remaining_s}."""
        with self._lock:
            now = self._clock()
            return {h: {"strikes": e.strikes,
                        "remaining_s": max(0.0, e.until - now)}
                    for h, e in self._blacklist.items()}

    def permanently_exhausted(self) -> bool:
        """True when the job can NEVER regain capacity on its own:
        discovery knows at least one host and every known host sits on
        a permanent (infinite) blacklist entry. A transiently empty
        scrape (a flap) or a finite TTL both return False — those heal
        with time, and aborting on them would turn one bad scrape into
        a dead job."""
        with self._lock:
            if not self._hosts:
                return False
            for h in self._hosts:
                e = self._blacklist.get(h)
                if e is None or e.until != float("inf"):
                    return False
            return True


class ElasticDriver:
    """Discovery loop + stable rank assignment (reference driver.py:68-309).
    """

    def __init__(self, discovery: HostDiscovery, min_np: int, max_np: int,
                 discovery_interval: float = 1.0):
        self.host_manager = HostManager(discovery)
        self.min_np = min_np
        self.max_np = max_np
        self.discovery_interval = discovery_interval
        self._assignments: Dict[str, List[hosts_lib.SlotInfo]] = {}
        # Autoscale engine handle (run_elastic installs one when the
        # control loop is enabled — docs/autoscale.md).
        self.autoscale = None
        self._shutdown = threading.Event()
        self._host_change = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    # -- discovery loop (reference driver.py:90-92, 1 s poll) -------------

    def start_discovery(self) -> None:
        self.host_manager.update_available_hosts()

        def loop():
            while not self._shutdown.is_set():
                if self.host_manager.update_available_hosts():
                    self._host_change.set()
                self._shutdown.wait(self.discovery_interval)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._shutdown.set()
        if self._thread:
            self._thread.join(timeout=5)

    def hosts_updated(self) -> bool:
        """Consumed by workers' check_host_updates()."""
        if self._host_change.is_set():
            self._host_change.clear()
            return True
        return False

    def wait_for_available_slots(self, min_np: Optional[int] = None,
                                 timeout_s: float = 600.0) -> Dict[str, int]:
        """Block until >= min_np slots exist (reference driver.py:139-160).
        """
        need = min_np if min_np is not None else self.min_np
        deadline = time.monotonic() + timeout_s
        while True:
            hosts = self.host_manager.current_hosts()
            if sum(hosts.values()) >= need:
                return hosts
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"fewer than min_np={need} slots available after "
                    f"{timeout_s}s")
            self.host_manager.update_available_hosts()
            time.sleep(self.discovery_interval)

    # -- rank assignment (reference driver.py _update_host_assignments) ---

    def update_assignments(self, np_cap: Optional[int] = None,
                           np_exact: Optional[int] = None
                           ) -> List[hosts_lib.SlotInfo]:
        """Re-assign ranks, keeping existing hosts' ranks stable.
        ``np_cap`` (autoscale hold: the policy refused new capacity —
        docs/autoscale.md) additionally caps the world below max_np
        but never below min_np. ``np_exact`` (elastic respec: the
        re-solved mesh must factor the world EXACTLY —
        docs/elastic.md "hybrid worlds") pins np even below min_np."""
        hosts = self.host_manager.current_hosts()
        with self._lock:
            prev_order = [h for h in self._assignments if h in hosts]
            new_hosts = [h for h in hosts if h not in self._assignments]
            ordered = prev_order + sorted(new_hosts)
            np_total = min(self.max_np,
                           sum(hosts[h] for h in ordered))
            if np_cap is not None:
                np_total = max(self.min_np, min(np_total, np_cap))
            if np_exact is not None:
                np_total = min(np_total, np_exact)
            infos = hosts_lib.get_host_assignments(
                [hosts_lib.HostInfo(h, hosts[h]) for h in ordered], np_total)
            self._assignments = {}
            for s in infos:
                self._assignments.setdefault(s.hostname, []).append(s)
            return infos

    def assigned_hosts(self) -> Dict[str, int]:
        """Hosts of the CURRENT epoch's assignments with their slot
        counts — the world that is actually running (the autoscale
        engine evaluates against this, not the usable set: a
        usable-but-unassigned host has no worker whose silence could
        mean a stall)."""
        with self._lock:
            return {h: len(s) for h, s in self._assignments.items()}

    def record_failure(self, hostname: str) -> None:
        # Blacklist only — no _host_change signal: the caller restarts
        # the epoch itself, and a latched event would make the NEXT
        # epoch's first poll read a phantom topology change and throw
        # away freshly spawned workers.
        self.host_manager.blacklist(hostname)

    def clear_host_updates(self) -> None:
        """Drop any pending host-change signal (called at epoch start so
        changes already folded into the new assignments don't re-fire)."""
        self._host_change.clear()


_LOCAL_NAMES = ("localhost", "127.0.0.1")


def _is_local_epoch(slots: List[hosts_lib.SlotInfo]) -> bool:
    import socket

    if runtime_env("ELASTIC_FORCE_LOCAL"):
        # Test/dev path: treat hostnames as virtual and fork everything
        # locally (the reference's integration tests alias localhost the
        # same way, elastic_common.py) — blacklist semantics stay
        # per-virtual-host.
        return True
    return all(s.hostname in _LOCAL_NAMES
               or s.hostname == socket.gethostname() for s in slots)


def _stream(proc: subprocess.Popen, tag: str) -> threading.Thread:
    import sys

    def pump():
        assert proc.stdout is not None
        for line in iter(proc.stdout.readline, b""):
            sys.stdout.write(f"[{tag}]: {line.decode(errors='replace')}")
            sys.stdout.flush()

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    return t


def _run_epoch(driver: ElasticDriver, slots: List[hosts_lib.SlotInfo],
               command: List[str], env_extra: Dict[str, str],
               ssh_port=None, poll_interval: float = 0.1,
               on_hosts_updated=None,
               grace_secs: Optional[float] = None,
               spawner=None,
               on_tick=None, tick_interval_s: Optional[float] = None):
    """Run one elastic epoch with per-worker exit tracking.

    Returns ``(rc, failed_hosts, interrupted)``: ``failed_hosts`` are
    hosts whose worker exited non-zero ON ITS OWN (candidates for the
    blacklist — reference registration.py _action); ``interrupted`` means
    the epoch ended because discovery reported a host-set change (never
    blacklisted). On a host-set change ``on_hosts_updated`` fires FIRST
    (bumping the rendezvous topology_version), then workers get
    HVD_TPU_ELASTIC_GRACE_SECS to exit gracefully at a commit() point
    (HOSTS_UPDATED_EXIT_CODE) before being terminated.

    ``spawner`` plugs in a non-subprocess execution substrate (the Spark
    task pool — reference spark/runner.py:303 runs elastic workers
    inside Spark task services the same way): called as
    ``spawner(slots, command, env_extra)`` and must return a list of
    ``(hostname, handle)`` where handle is Popen-like (``poll`` /
    ``terminate`` / ``send_signal`` / ``wait``). The spawner owns slot
    env construction (coordinator negotiation may be deferred to the
    workers themselves).

    ``on_tick`` (docs/autoscale.md) is the autoscale evaluation hook:
    called every ``tick_interval_s`` seconds of the watch loop; when it
    returns True the engine decided to reshape the world — the epoch is
    interrupted through the SAME graceful path as a discovery change
    (publish topology version, grace window, then terminate).
    """
    import shlex
    import signal
    from .launch import _free_port, _slot_local_env

    local = _is_local_epoch(slots)
    force_local = bool(runtime_env("ELASTIC_FORCE_LOCAL"))
    procs: List = []  # (hostname, Popen)
    threads: List[threading.Thread] = []
    if spawner is not None:
        procs = list(spawner(slots, command, env_extra))
    elif local:
        port = _free_port()
        coordinator = f"127.0.0.1:{port}"
        for s in slots:
            # FORCE_LOCAL simulates independent virtual hosts: each
            # worker is its OWN single-process world (the CPU backend
            # has no multiprocess collectives), while HVD_TPU_PROC_ID
            # still carries the virtual global rank and
            # HVD_TPU_VIRTUAL_NUM_PROC the epoch's virtual world size
            # for scripts that assert on topology.
            sim = ({"HVD_TPU_NUM_PROC": "1",
                    "HVD_TPU_VIRTUAL_NUM_PROC": str(len(slots)),
                    "HVD_TPU_VIRTUAL_HOSTS": ",".join(
                        dict.fromkeys(sl.hostname for sl in slots))}
                   if force_local else {})
            env = build_env_for_slot(
                dict(os.environ), coordinator, len(slots), s.rank,
                {**env_extra,
                 **_slot_local_env(s.local_rank, s.local_size),
                 "HVD_TPU_HOSTNAME": s.hostname,
                 **sim})
            p = subprocess.Popen(command, env=env,
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT)
            procs.append((s.hostname, p))
            threads.append(_stream(p, f"{s.hostname}[{s.rank}]"))
    else:
        # One process per host over ssh; the process drives all of the
        # host's chips (launch.py run_ssh model).
        host_order: List[str] = []
        for s in slots:
            if s.hostname not in host_order:
                host_order.append(s.hostname)
        coordinator = f"{host_order[0]}:{_free_port()}"
        for i, hostname in enumerate(host_order):
            env = build_env_for_slot({}, coordinator, len(host_order), i,
                                     {**env_extra,
                                      **_slot_local_env(0, 1),
                                      "HVD_TPU_HOSTNAME": hostname})
            env_str = " ".join(f"{k}={shlex.quote(v)}"
                               for k, v in env.items())
            remote = f"cd {shlex.quote(os.getcwd())} && {env_str} " + \
                " ".join(shlex.quote(c) for c in command)
            ssh_cmd = ["ssh", "-o", "StrictHostKeyChecking=no"]
            if ssh_port:
                ssh_cmd += ["-p", str(ssh_port)]
            p = subprocess.Popen(ssh_cmd + [hostname, remote],
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT)
            procs.append((hostname, p))
            threads.append(_stream(p, hostname))

    from ..common.elastic import (HOSTS_UPDATED_EXIT_CODE,
                                  PEER_FAILURE_EXIT_CODE)

    rc = 0
    failed: Set[str] = set()
    interrupted = False
    terminated = False
    epoch_ending = False
    grace_deadline = None
    grace = (grace_secs if grace_secs is not None else
             float(runtime_env("ELASTIC_GRACE_SECS", "30")))

    dumps_requested = False

    def request_dumps() -> bool:
        # Flight-recorder fan-out (docs/podmon.md): the epoch is dying
        # on a FAILURE, so ask every surviving worker for its black box
        # (SIGUSR2 -> common/flightrec.py dump) — "what was every rank
        # doing when the job hung" needs the ring from the healthy
        # ranks too. Fired the moment the first failure exit is seen
        # (a gracefully peer-failure-exiting survivor won't be alive by
        # terminate time); handles without send_signal (non-subprocess
        # spawners) are skipped.
        nonlocal dumps_requested
        sig = getattr(signal, "SIGUSR2", None)
        signaled = False
        for _, p in procs:
            send = getattr(p, "send_signal", None)
            if sig is None or send is None or p.poll() is not None:
                continue
            try:
                send(sig)
                signaled = True
            except (ProcessLookupError, OSError, ValueError):
                pass
        dumps_requested = dumps_requested or signaled
        return signaled

    def terminate_all(dump_first: bool = False):
        # Signal EVERY worker, even ones whose handle already reported
        # an exit: a KV-backed pool handle may have SYNTHESIZED rc=1
        # from a transiently stale heartbeat while the remote worker is
        # actually alive — skipping it would leave a live duplicate of
        # the dead epoch running. Popen.terminate on an exited child is
        # a no-op, so the blanket signal is safe for local epochs too.
        if dump_first and not dumps_requested:
            # Bounded grace for the dump to hit disk before the kill
            # (HVD_TPU_FLIGHTREC_SIGNAL_GRACE_S, default 1 s). Skipped
            # when request_dumps() already fired earlier — the epoch's
            # grace window was the write window.
            try:
                dump_grace = float(runtime_env(
                    "FLIGHTREC_SIGNAL_GRACE_S", "1.0"))
            except ValueError:
                dump_grace = 1.0
            if request_dumps() and dump_grace > 0:
                time.sleep(dump_grace)
        for _, p in procs:
            try:
                p.terminate()
            except (ProcessLookupError, OSError):
                pass

    next_tick = (time.monotonic() + tick_interval_s
                 if on_tick is not None and tick_interval_s else None)

    try:
        while True:
            running = False
            for hostname, p in procs:
                code = p.poll()
                if code is None:
                    running = True
                elif code != 0 and not terminated:
                    rc = rc or code
                    if code == HOSTS_UPDATED_EXIT_CODE:
                        interrupted = True
                        epoch_ending = True
                    elif code == PEER_FAILURE_EXIT_CODE:
                        # "My peer failed, not me" — restart this host's
                        # worker, don't blacklist it; but the epoch is
                        # over, so stop waiting on wedged survivors.
                        epoch_ending = True
                    else:
                        # Worker died on its own → candidate for blacklist
                        # (reference: WorkerStateRegistry FAILURE →
                        # HostManager.blacklist, registration.py:150-153).
                        failed.add(hostname)
            if (epoch_ending and not interrupted and not terminated
                    and not dumps_requested):
                # A peer-failure exit is ending the epoch: collect the
                # survivors' rings NOW, while they are still alive —
                # they exit 79 on their own within the grace window.
                request_dumps()
            if failed and not terminated:
                terminate_all(dump_first=True)
                terminated = True
            if next_tick is not None and not terminated \
                    and not interrupted and time.monotonic() >= next_tick:
                # Autoscale tick: evict/shrink decisions blacklist their
                # hosts and reshape via the same HOSTS_UPDATED channel a
                # discovery change uses (the grace/terminate machinery
                # below is shared).
                next_tick = time.monotonic() + tick_interval_s
                try:
                    reshape = bool(on_tick())
                except Exception:  # noqa: BLE001 — the control loop must
                    logger.exception(   # never kill a healthy epoch
                        "autoscale: tick evaluation failed")
                    reshape = False
                if reshape:
                    interrupted = True
                    if on_hosts_updated is not None:
                        on_hosts_updated()
                    grace_deadline = time.monotonic() + grace
            if not terminated and not interrupted and \
                    driver.hosts_updated():
                # Topology changed mid-epoch: publish the new version
                # FIRST so workers see it at their next commit() and exit
                # gracefully (HostsUpdatedInterrupt channel), then give
                # them a grace window before terminating.
                interrupted = True
                if on_hosts_updated is not None:
                    on_hosts_updated()
                grace_deadline = time.monotonic() + grace
            if epoch_ending and not terminated and grace_deadline is None:
                grace_deadline = time.monotonic() + grace
            if (grace_deadline is not None and not terminated
                    and time.monotonic() > grace_deadline):
                # dump_first only on FAILURE endings: a topology-change
                # interrupt is routine — black-boxing every reshape
                # would bury the real post-mortems in noise.
                terminate_all(dump_first=(rc != 0 and not interrupted))
                terminated = True
            if not running:
                break
            time.sleep(poll_interval)
        for t in threads:
            t.join(timeout=2)
        return rc, failed, interrupted
    except KeyboardInterrupt:
        for _, p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGINT)
        for _, p in procs:
            p.wait()
        return 1, failed, interrupted


def run_elastic(args, command: List[str],
                env_extra: Dict[str, str],
                discovery: Optional[HostDiscovery] = None,
                reset_limit: Optional[int] = None,
                slot_wait_timeout_s: Optional[float] = None,
                grace_secs: Optional[float] = None,
                spawner=None,
                rdv_server: Optional[RendezvousServer] = None,
                rdv_advertise: Optional[str] = None,
                rdv_secret: Optional[str] = None) -> int:
    """Driver-side elastic launch (reference gloo_run_elastic
    gloo_run.py:326 + launch.py:616 + elastic/driver.py:68-309).

    Per epoch: wait for >= min_np slots among non-blacklisted hosts,
    compute RANK-STABLE assignments (surviving hosts keep their ranks),
    spawn one worker per slot (local) or per host (ssh), and watch
    per-worker exits. A worker that dies on its own blacklists its host;
    a discovery change restarts the epoch with new assignments. The
    rendezvous KV publishes a monotonically increasing topology_version
    workers poll at commit() (HostsUpdatedInterrupt channel — reference
    elastic/worker.py). Workers resume from their committed state
    (full-reinit-on-reset: a changed device mesh requires recompilation,
    so the restart IS the reset)."""
    min_np = args.min_np or args.num_proc
    max_np = args.max_np or args.num_proc
    if discovery is not None:
        # Injected source (e.g. ray.RayHostDiscovery over live cluster
        # state) wins over script/hosts flags.
        pass
    elif args.host_discovery_script:
        discovery = ScriptHostDiscovery(args.host_discovery_script)
    else:
        host_infos = (hosts_lib.parse_hosts(args.hosts) if args.hosts
                      else [hosts_lib.HostInfo("localhost", max_np)])
        discovery = FixedHostDiscovery(
            {h.hostname: h.slots for h in host_infos})

    # Chaos: pick up a plan set after import (workers inherit the env —
    # ssh epochs get it via env_extra below).
    faults_lib.refresh_from_env()
    driver = ElasticDriver(discovery, min_np, max_np)
    driver.start_discovery()
    # Autoscale control plane (docs/autoscale.md): the policy engine
    # lives HERE, in the driver process, so its memory — straggler
    # strikes, eviction counts, cooldowns — spans elastic epochs. A bad
    # policy fails the launch (silently scaling on defaults the user
    # did not write would be worse than not starting).
    from ..common import autoscale as autoscale_lib

    engine = None
    autoscale_policy = None
    # Launcher knobs (hvdtpurun --autoscale-policy) arrive via
    # env_extra; a policy set in the caller's environment works too —
    # merge with env_extra winning (it carries the validated form).
    autoscale_env = {**os.environ, **{
        k: v for k, v in env_extra.items()
        if k.startswith("HVD_TPU_AUTOSCALE")}}
    if autoscale_lib.autoscale_enabled(autoscale_env):
        policy = autoscale_lib.AutoscalePolicy.from_env(autoscale_env)
        if policy.enabled:
            autoscale_policy = policy
            logger.warning("autoscale: enabled (policy: %s)",
                           policy.to_json())
    # Per-job HMAC secret (reference runner/common/util/secret.py): the
    # KV coordinates worker lifecycle, so an unauthenticated writer on
    # the network could fake topology changes.
    import secrets as _secrets

    # The driver keeps the secret out of its own os.environ: the server
    # and workers get it explicitly, and a lingering env entry would
    # leak into every later subprocess and make any secretless
    # server/client in this process silently adopt a stale key.
    owns_rdv = rdv_server is None
    if owns_rdv:
        job_secret = _secrets.token_hex(16)
        rdv = RendezvousServer("127.0.0.1", secret=job_secret.encode())
        rdv_port = rdv.start()
        advertise = f"127.0.0.1:{rdv_port}"
    else:
        # Caller-owned server (the Spark composition: one KV reachable
        # from executor hosts serves the task pool AND the elastic
        # topology channel). The caller supplies the address workers
        # can reach and the matching secret, and stops the server.
        rdv = rdv_server
        job_secret = rdv_secret
        advertise = rdv_advertise or f"127.0.0.1:{rdv.port()}"
    topo_version = 0
    rdv.put("elastic", "topology_version", str(topo_version).encode())
    env_extra = dict(env_extra)
    env_extra["HVD_TPU_RENDEZVOUS"] = advertise
    if job_secret:
        env_extra["HVD_TPU_RENDEZVOUS_SECRET"] = job_secret
    # Fault plan + injection log ride along explicitly: local epochs
    # inherit os.environ, but ssh/spawner epochs build env from scratch
    # — "any entrypoint runs under chaos unchanged" includes those.
    for chaos_var in (faults_lib.ENV_PLAN, faults_lib.ENV_LOG):
        if chaos_var in os.environ:
            env_extra.setdefault(chaos_var, os.environ[chaos_var])

    # Pod-scope aggregator (docs/podmon.md): scrape every rank's
    # /metrics.json (endpoints advertised over THIS job's KV, plus any
    # HVD_TPU_POD_METRICS_ENDPOINTS remote pods) and re-serve the
    # merged rank-labeled view + step-skew gauge on /pod/metrics. The
    # monitor lives in the driver so its series span elastic epochs.
    from ..common import podmon as podmon_lib

    pod_monitor = None
    pod_port = podmon_lib.monitor_port_from_env(
        {**os.environ, **env_extra})
    if pod_port is not None:
        pod_monitor = podmon_lib.PodMonitor(
            podmon_lib.combined_endpoints(
                podmon_lib.kv_endpoints(rdv),
                podmon_lib.static_endpoints()))
        pod_monitor.start(pod_port)
        # The scrape needs per-worker endpoints: default workers to an
        # ephemeral /metrics port when nothing chose one.
        if "HVD_TPU_METRICS_PORT" not in env_extra \
                and runtime_env("METRICS_PORT") is None:
            env_extra["HVD_TPU_METRICS_PORT"] = "0"

    on_tick = None
    if autoscale_policy is not None:
        # The engine reads worker reports straight off the in-process
        # KV; workers get the RESOLVED policy (env overrides folded in)
        # so publisher cadence and engine windows always agree.
        fetch = autoscale_lib.kv_report_fetcher(rdv)
        if pod_monitor is not None:
            # Alternative signal source (docs/podmon.md): ranks that
            # never publish to the KV — remote pods, pre-publisher
            # workers — still feed the engine through the scrape path;
            # KV reports win per rank when both exist.
            fetch = podmon_lib.merged_report_fetcher(fetch, pod_monitor)
        # Hybrid worlds (docs/elastic.md): a declared ParallelSpec
        # makes the engine role-aware — replica-grouped straggler
        # attribution, a whole-replica min_np floor (validated here; a
        # bad floor fails the LAUNCH, naming the roles), and the respec
        # ladder re-solving dp x pp x tp per epoch.
        from ..parallel.spec import ENV_PARALLEL, spec_from_env

        parallel_spec = spec_from_env(
            {**os.environ, **env_extra})
        engine = autoscale_lib.AutoscaleEngine(
            autoscale_policy, min_np, max_np, fetch,
            log_path=autoscale_env.get(autoscale_lib.ENV_LOG, ""),
            parallel=parallel_spec)
        driver.autoscale = engine
        env_extra[autoscale_lib.ENV_ENABLE] = "1"
        env_extra[autoscale_lib.ENV_POLICY] = autoscale_policy.to_json()

        def autoscale_tick() -> bool:
            # Evaluate against the RUNNING world (assigned ∩ usable),
            # same as the determinism sim: a usable-but-unassigned
            # host (e.g. held back by a grow gate, or freshly
            # TTL-recovered) has no worker — its stale KV report must
            # not read as a stall.
            usable = driver.host_manager.current_hosts()
            assigned = {h: n for h, n in driver.assigned_hosts().items()
                        if h in usable}
            decisions = engine.tick(
                assigned, driver.host_manager.blacklist_snapshot())
            acted = False
            for d in decisions:
                if d.action in ("evict", "shrink") and d.target:
                    driver.host_manager.blacklist(
                        d.target, ttl_s=d.ttl_s, permanent=d.permanent)
                    acted = True
            return acted

        on_tick = autoscale_tick

    def bump_version():
        nonlocal topo_version
        topo_version += 1
        rdv.put("elastic", "topology_version", str(topo_version).encode())

    try:
        attempts = 0
        prev_np: Optional[int] = None
        epoch_down_since: Optional[float] = None
        while True:
            # Involuntary capacity loss under a hybrid spec waits at
            # the respec ladder's floor, not at min_np: min_np floors
            # VOLUNTARY evict/shrink decisions, while a lost host is
            # survived by reshaping as far as the configured rungs
            # allow (docs/elastic.md "hybrid worlds"). The floor is
            # min_world ITSELF — below it NO permitted rung yields a
            # valid mesh, so launching (even above min_np) would hand
            # workers a spec the world cannot factor.
            wait_floor = min_np
            if engine is not None and engine.min_world is not None:
                wait_floor = engine.min_world
            try:
                driver.wait_for_available_slots(
                    wait_floor,
                    timeout_s=(600.0 if slot_wait_timeout_s is None
                               else slot_wait_timeout_s))
            except TimeoutError:
                # Graceful degradation below min_np: the job cannot
                # continue, but nothing is lost — say exactly where the
                # recovery state lives and why the world shrank.
                hosts = driver.host_manager.current_hosts()
                logger.error(
                    "elastic: world shrank below min_np=%d and stayed "
                    "there (usable hosts: %s, blacklist: %s). The last "
                    "committed state is intact — workers persist at "
                    "commit() points — so rerunning this command resumes "
                    "from the last commit once capacity returns.",
                    min_np, hosts or "{}",
                    driver.host_manager.blacklist_snapshot() or "{}")
                return 1
            if epoch_down_since is not None:
                faults_lib.stats.add_downtime(
                    time.monotonic() - epoch_down_since)
                epoch_down_since = None
            # Clear BEFORE computing assignments: a change landing after
            # the clear re-fires and interrupts the epoch; anything
            # earlier is folded into the assignments below.
            driver.clear_host_updates()
            # Fresh poll: a restarted epoch must see hosts that appeared
            # while the previous epoch was dying (the 1 s background
            # poll may not have run since), or a fast failure loop keeps
            # relaunching yesterday's topology.
            driver.host_manager.update_available_hosts()
            np_cap = None
            np_exact = None
            if engine is not None:
                # Grow gate (docs/autoscale.md): the engine decides
                # whether capacity beyond the previous world is ADOPTED
                # (a `grow` decision) or HELD (np capped at prev size).
                np_cap = engine.pre_epoch(
                    prev_np, driver.host_manager.current_hosts())
                # Respec (docs/elastic.md "hybrid worlds"): re-solve
                # the mesh for the surviving capacity; the new spec is
                # re-exported to the workers and np is pinned to its
                # exact factorization (a partial mesh would drop ranks
                # from the reduction — parallel/spec.py).
                usable = driver.host_manager.current_hosts()
                capacity = sum(usable.values())
                if np_cap is not None:
                    # A held grow caps the world: the solver must see
                    # the capacity the epoch will actually get, or it
                    # would restore a spec the capped np can't factor.
                    capacity = min(capacity, np_cap)
                rd = engine.plan_respec(capacity)
                if rd is not None:
                    env_extra[ENV_PARALLEL] = rd.spec.describe()
                    logger.warning(
                        "elastic: respec %s -> %s (np=%d)",
                        parallel_spec.describe(), rd.spec.describe(),
                        rd.np)
                if engine.current_spec is not None:
                    np_exact = engine.current_spec.total
            slots = driver.update_assignments(np_cap=np_cap,
                                              np_exact=np_exact)
            if engine is not None and engine.current_spec is not None \
                    and len(slots) != engine.current_spec.total:
                # The assignable world moved between planning and
                # assignment (a host dropped in the window): re-solve
                # for what was actually assignable; if no permitted
                # rung fits, wait for capacity instead of launching
                # workers with a spec the world cannot factor.
                rd = engine.plan_respec(len(slots))
                if rd is not None:
                    env_extra[ENV_PARALLEL] = rd.spec.describe()
                    slots = driver.update_assignments(
                        np_cap=np_cap, np_exact=rd.np)
                if len(slots) != engine.current_spec.total:
                    logger.warning(
                        "elastic: assignable world (%d slots) cannot "
                        "factor the solved spec %s; waiting for "
                        "capacity", len(slots),
                        engine.current_spec.describe())
                    faults_lib.stats.bump("resets")
                    attempts += 1
                    limit = (reset_limit if reset_limit is not None
                             else int(runtime_env("ELASTIC_RESET_LIMIT",
                                                  "100")))
                    if attempts > limit:
                        logger.error("elastic: reset limit exceeded")
                        return 1
                    time.sleep(driver.discovery_interval)
                    continue
            if engine is not None:
                engine.observe_assignment({s.hostname for s in slots})
            prev_np = len(slots)
            logger.info(
                "elastic launch attempt %d with np=%d over hosts %s",
                attempts, len(slots),
                sorted({s.hostname for s in slots}))
            rc, failed_hosts, interrupted = _run_epoch(
                driver, slots, command, env_extra,
                ssh_port=getattr(args, "ssh_port", None),
                on_hosts_updated=bump_version, grace_secs=grace_secs,
                spawner=spawner, on_tick=on_tick,
                tick_interval_s=(autoscale_policy.tick_interval_s
                                 if autoscale_policy is not None
                                 else None))
            if rc == 0 and not failed_hosts and not interrupted:
                return 0
            epoch_down_since = time.monotonic()
            faults_lib.stats.bump("resets")
            for h in failed_hosts:
                driver.record_failure(h)
            bump_version()
            attempts += 1
            limit = (reset_limit if reset_limit is not None
                     else int(runtime_env("ELASTIC_RESET_LIMIT", "100")))
            if attempts > limit:
                logger.error("elastic: reset limit exceeded")
                return rc or 1
            if not driver.host_manager.current_hosts():
                # Empty usable set: only a FAST-FAIL when it can never
                # heal (every known host permanently exiled). A flapped
                # scrape or a finite blacklist TTL recovers with time —
                # the loop-top wait_for_available_slots owns the real
                # give-up timeout for those.
                if driver.host_manager.permanently_exhausted():
                    logger.error(
                        "elastic: every host is permanently blacklisted "
                        "— job failed (reference registration.py:156). "
                        "Last committed state is preserved; blacklist: "
                        "%s",
                        driver.host_manager.blacklist_snapshot() or "{}")
                    return rc or 1
                logger.warning(
                    "elastic: no usable hosts right now (flap or "
                    "blacklist TTL pending — %s); waiting for capacity",
                    driver.host_manager.blacklist_snapshot() or "{}")
    finally:
        if pod_monitor is not None:
            pod_monitor.stop()
        if owns_rdv:
            rdv.stop()
        driver.stop()
