"""pty-based subprocess execution with signal forwarding.

Reference: horovod/runner/common/util/safe_shell_exec.py:1-270 — workers
run under a pseudo-terminal so their output is line-buffered and
terminal-shaped, output is prefixed per slot, and SIGINT/SIGTERM on the
launcher forward to the whole child process group (then escalate to
SIGKILL after a grace period).
"""

from __future__ import annotations

import os
import pty
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

GRACEFUL_TERMINATION_TIME_S = 5.0


def _pump(fd: int, prefix: Optional[str], sink) -> None:
    buf = b""
    while True:
        try:
            chunk = os.read(fd, 4096)
        except OSError:  # pty slave closed
            chunk = b""
        if not chunk:
            if buf:
                _emit(buf, prefix, sink)
            return
        buf += chunk
        while b"\n" in buf:
            line, buf = buf.split(b"\n", 1)
            _emit(line + b"\n", prefix, sink)


def _emit(line: bytes, prefix: Optional[str], sink) -> None:
    text = line.decode(errors="replace")
    if prefix is not None:
        text = f"[{prefix}]: {text}"
    sink.write(text)
    sink.flush()


class SpawnedProcess:
    """A worker under a pty with group-signal control — the handle the
    launcher's fail-fast waiter polls/terminates."""

    def __init__(self, proc: subprocess.Popen, thread: threading.Thread):
        self.proc = proc
        self.thread = thread

    def poll(self):
        return self.proc.poll()

    def wait(self):
        rc = self.proc.wait()
        self.thread.join(timeout=2)
        return rc

    def _signal_group(self, signum) -> None:
        try:
            os.killpg(os.getpgid(self.proc.pid), signum)
        except ProcessLookupError:
            pass

    def send_signal(self, signum) -> None:
        self._signal_group(signum)

    def terminate(self) -> None:
        """Group SIGTERM, escalating to SIGKILL after the grace window
        (reference safe_shell_exec GRACEFUL_TERMINATION_TIME_S)."""
        self._signal_group(signal.SIGTERM)

        def escalate():
            deadline = time.monotonic() + GRACEFUL_TERMINATION_TIME_S
            while time.monotonic() < deadline:
                if self.proc.poll() is not None:
                    return
                time.sleep(0.1)
            self._signal_group(signal.SIGKILL)

        threading.Thread(target=escalate, daemon=True).start()


def spawn(command: List[str], env: Optional[Dict[str, str]] = None,
          prefix: Optional[str] = None, use_pty: bool = True,
          sink=None, input_data: Optional[bytes] = None) -> SpawnedProcess:
    """Start ``command`` under a pseudo-terminal (children see a tty →
    line buffering, progress bars) in its own process group, with a pump
    thread prefixing output lines. Returns the control handle.
    ``input_data`` is written to the child's stdin then closed — the
    channel secrets travel on (they must never ride argv, which any
    local user can read via ps)."""
    sink = sink or sys.stdout
    stdin = subprocess.PIPE if input_data is not None else None
    if use_pty:
        try:
            master, slave = pty.openpty()
        except OSError:  # no pty available (containers without devpts)
            use_pty = False
    if use_pty:
        proc = subprocess.Popen(command, env=env, stdin=stdin,
                                stdout=slave,
                                stderr=slave, start_new_session=True)
        os.close(slave)
        fd = master
    else:
        proc = subprocess.Popen(command, env=env, stdin=stdin,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT,
                                start_new_session=True)
        fd = proc.stdout.fileno()
    if input_data is not None:
        proc.stdin.write(input_data)
        proc.stdin.close()

    def pump_and_close():
        try:
            _pump(fd, prefix, sink)
        finally:
            if use_pty:
                try:
                    os.close(master)
                except OSError:
                    pass

    t = threading.Thread(target=pump_and_close, daemon=True)
    t.start()
    return SpawnedProcess(proc, t)


def execute(command: List[str], env: Optional[Dict[str, str]] = None,
            prefix: Optional[str] = None, use_pty: bool = True,
            forward_signals: bool = True, sink=None) -> int:
    """Run ``command`` to completion; returns its exit code.

    * ``use_pty``: attach stdout/stderr to a pseudo-terminal;
    * ``forward_signals``: SIGINT/SIGTERM received by the caller are
      forwarded to the child's process group, escalating to SIGKILL
      after GRACEFUL_TERMINATION_TIME_S (reference safe_shell_exec
      forward_signals semantics).
    """
    handle = spawn(command, env=env, prefix=prefix, use_pty=use_pty,
                   sink=sink)
    old_handlers = {}

    def forward(signum, _frame):
        handle.send_signal(signum)
        if signum in (signal.SIGINT, signal.SIGTERM):
            handle.terminate()

    if forward_signals and threading.current_thread() is \
            threading.main_thread():
        for sig in (signal.SIGINT, signal.SIGTERM):
            old_handlers[sig] = signal.signal(sig, forward)
    try:
        return handle.wait()
    finally:
        for sig, handler in old_handlers.items():
            signal.signal(sig, handler)
