"""Host list parsing & slot assignment.

Reference: horovod/runner/common/util/hosts.py:22-155 (parse_hosts,
get_host_assignments producing SlotInfo{rank, local_rank, cross_rank,
sizes}). Same semantics: '-H host1:4,host2:4' or a hostfile with
'hostname slots=N' lines; ranks assigned host-major so local ranks are
contiguous (which on TPU maps a host's slots onto its chips in ICI order).
"""

from __future__ import annotations

import dataclasses
import re
from typing import List, Optional


@dataclasses.dataclass
class HostInfo:
    hostname: str
    slots: int


@dataclasses.dataclass
class SlotInfo:
    hostname: str
    rank: int
    local_rank: int
    cross_rank: int
    size: int
    local_size: int
    cross_size: int


def parse_hosts(hosts_string: str) -> List[HostInfo]:
    """'h1:4,h2:4' -> [HostInfo]. A bare 'h1' means 1 slot."""
    out = []
    for part in hosts_string.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            name, slots = part.rsplit(":", 1)
            out.append(HostInfo(name, int(slots)))
        else:
            out.append(HostInfo(part, 1))
    return out


def parse_host_files(filename: str) -> List[HostInfo]:
    """Hostfile lines: 'hostname slots=N' (reference hosts.py:66-86)."""
    out = []
    with open(filename) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            m = re.match(r"^(\S+)(?:\s+slots\s*=\s*(\d+))?", line)
            if m:
                out.append(HostInfo(m.group(1), int(m.group(2) or 1)))
    return out


def get_host_assignments(hosts: List[HostInfo], np: int,
                         min_np: Optional[int] = None) -> List[SlotInfo]:
    """Assign np ranks over hosts host-major (reference hosts.py:100-155)."""
    total = sum(h.slots for h in hosts)
    if np > total:
        raise ValueError(
            f"requested {np} processes but hosts provide only {total} slots")
    if min_np is not None and total < min_np:
        raise ValueError(f"fewer than min_np={min_np} slots available")

    assignments: List[SlotInfo] = []
    rank = 0
    used_hosts = []
    for cross_rank, h in enumerate(hosts):
        if rank >= np:
            break
        use = min(h.slots, np - rank)
        used_hosts.append((h, use))
        for local in range(use):
            assignments.append(SlotInfo(
                hostname=h.hostname, rank=rank, local_rank=local,
                cross_rank=cross_rank, size=np, local_size=use,
                cross_size=0))
            rank += 1
    cross_size = len(used_hosts)
    for s in assignments:
        s.cross_size = cross_size
    return assignments
