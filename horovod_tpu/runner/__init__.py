"""Launcher: hvdtpurun CLI, rendezvous KV server, host assignment, elastic
driver, and the programmatic ``run()`` API.

Reference: horovod/runner/__init__.py:91-206 (``horovod.run`` "interactive
run" — cloudpickles the user function and launches it through the same
machinery as the CLI). Same contract here: ``run(func, np=N)`` returns the
per-rank results as a list ordered by process id.
"""

from __future__ import annotations

import os
import pickle
import sys
import tempfile
from typing import Any, Callable, Dict, List, Optional


def run(func: Callable,
        args: tuple = (),
        kwargs: Optional[Dict[str, Any]] = None,
        np: int = 2,
        hosts: Optional[str] = None,
        env: Optional[Dict[str, str]] = None,
        use_ssh: bool = False,
        verbose: bool = False) -> List[Any]:
    """Launch ``func(*args, **kwargs)`` on ``np`` workers; return results.

    Local mode (default) forks ``np`` processes on this machine wired
    through the same env bootstrap as the ``hvdtpurun`` CLI — inside each
    worker ``hvd.init()`` joins the multi-process world. ``hosts``
    ("h1:4,h2:4") with ``use_ssh=True`` fans out over ssh instead
    (reference gloo_run ssh path).

    Returns ``[result_rank0, result_rank1, ...]`` (reference
    runner/__init__.py returns the same list shape). A worker exception
    re-raises here as RuntimeError carrying the remote traceback.
    """
    import cloudpickle

    from . import hosts as hosts_lib
    from . import launch as launch_lib

    kwargs = kwargs or {}
    # ssh mode: payload/results travel via the filesystem, so the exchange
    # dir must live on a path shared with the workers (run_ssh cd's them
    # into our cwd — assumed shared, e.g. NFS/GCS-fuse). Local mode can use
    # the faster node-local TMPDIR.
    exchange_root = os.getcwd() if use_ssh else None
    with tempfile.TemporaryDirectory(prefix=".hvd_tpu_run_",
                                     dir=exchange_root) as tmp:
        payload = os.path.join(tmp, "payload.pkl")
        with open(payload, "wb") as f:
            cloudpickle.dump((func, args, kwargs), f)
        command = [sys.executable, "-m", "horovod_tpu.runner.task_fn",
                   payload, tmp]
        env_extra = dict(env or {})
        if use_ssh:
            if not hosts:
                raise ValueError("use_ssh=True requires hosts=")
            host_infos = hosts_lib.parse_hosts(hosts)
            rc = launch_lib.run_ssh(host_infos, command, env_extra, np,
                                    verbose=verbose)
            num_proc = len(launch_lib.used_hosts(host_infos, np))
        else:
            rc = launch_lib.run_local(np, command, env_extra,
                                      verbose=verbose)
            num_proc = np

        results: List[Any] = []
        errors: List[str] = []
        for pid in range(num_proc):
            path = os.path.join(tmp, f"result_{pid}.pkl")
            if not os.path.exists(path):
                errors.append(f"worker {pid}: no result (crashed?)")
                continue
            with open(path, "rb") as f:
                status, value = pickle.load(f)
            if status == "error":
                errors.append(f"worker {pid}:\n{value}")
            else:
                results.append(value)
        if rc != 0 or errors:
            raise RuntimeError(
                "run() failed (exit code %d):\n%s" % (rc, "\n".join(errors)))
        return results
