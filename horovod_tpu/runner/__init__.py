"""Launcher: hvdtpurun CLI, rendezvous KV server, host assignment, elastic
driver."""
