"""Driver/task network services — routable-interface discovery.

Reference: horovod/runner/driver/driver_service.py:49-257 +
horovod/runner/common/service/{driver,task}_service.py +
common/util/network.py: the launcher starts a task server on every host
(over ssh); each registers its network interfaces with the driver, and
the INTERSECTION of interface sets — verified by actual connectivity
probes — selects the routable NICs used for rendezvous addresses.

TPU analog: the same protocol over a minimal TCP/JSON service. On Cloud
TPU pods the metadata service usually renders this moot (every worker
has one routable NIC), so discovery is opt-in from the launcher
(HVD_TPU_NIC_DISCOVERY=1) but fully functional for bare-VM clusters.
"""

from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
from typing import Dict, List, Optional, Tuple


def local_addresses() -> Dict[str, str]:
    """interface name -> IPv4 address for this host (reference
    network.py get_local_host_addresses)."""
    addrs: Dict[str, str] = {}
    try:
        import array
        import fcntl

        SIOCGIFCONF = 0x8912
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        max_ifaces = 64
        bufsize = max_ifaces * 40
        buf = array.array("B", b"\0" * bufsize)
        ifconf = struct.pack("iL", bufsize,
                             buf.buffer_info()[0])
        outbytes = struct.unpack("iL", fcntl.ioctl(
            s.fileno(), SIOCGIFCONF, ifconf))[0]
        data = buf.tobytes()[:outbytes]
        # Each record: 16-byte name + sockaddr (40-byte stride on 64-bit).
        for i in range(0, outbytes, 40):
            name = data[i:i + 16].split(b"\0", 1)[0].decode()
            ip = socket.inet_ntoa(data[i + 20:i + 24])
            addrs[name] = ip
        s.close()
    except (OSError, ImportError, struct.error):
        # Portable fallback: hostname resolution + loopback.
        addrs["lo"] = "127.0.0.1"
        try:
            addrs["default"] = socket.gethostbyname(socket.gethostname())
        except OSError:
            pass
    return addrs


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        line = self.rfile.readline().strip()
        if line == b"ifaces":
            self.wfile.write(
                json.dumps(local_addresses()).encode() + b"\n")
        elif line == b"ping":
            self.wfile.write(b"pong\n")


class TaskServer:
    """Per-host service answering interface queries and connectivity
    probes (reference task_service.py BasicTaskService)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        self._srv = socketserver.ThreadingTCPServer(
            (host, port), _Handler, bind_and_activate=True)
        self._srv.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._srv.server_address[1]

    def start(self) -> "TaskServer":
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


def _query(addr: Tuple[str, int], command: str,
           timeout_s: float = 5.0) -> Optional[str]:
    try:
        with socket.create_connection(addr, timeout=timeout_s) as s:
            s.sendall(command.encode() + b"\n")
            f = s.makefile("rb")
            return f.readline().decode().strip()
    except OSError:
        return None


def query_interfaces(addr: Tuple[str, int],
                     timeout_s: float = 5.0) -> Dict[str, str]:
    raw = _query(addr, "ifaces", timeout_s)
    return json.loads(raw) if raw else {}


def probe_reachable(addr: Tuple[str, int],
                    timeout_s: float = 2.0) -> bool:
    return _query(addr, "ping", timeout_s) == "pong"


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m horovod_tpu.runner.driver_service --serve`` — the
    per-host task server the launcher starts over ssh. Prints
    ``TASKSERVER <port>`` once ready, then serves until killed."""
    import argparse
    import time

    ap = argparse.ArgumentParser()
    ap.add_argument("--serve", action="store_true")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--ttl", type=float, default=0.0,
                    help="self-terminate after this many seconds "
                         "(0 = serve forever); launcher-started servers "
                         "use a TTL so a dropped ssh channel cannot "
                         "strand listeners")
    a = ap.parse_args(argv)
    if not a.serve:
        ap.error("--serve required")
    srv = TaskServer(port=a.port).start()
    print(f"TASKSERVER {srv.port}", flush=True)
    deadline = time.monotonic() + a.ttl if a.ttl > 0 else None
    try:
        while deadline is None or time.monotonic() < deadline:
            time.sleep(min(3600.0, a.ttl or 3600.0))
    except KeyboardInterrupt:
        pass
    finally:
        srv.stop()
    return 0


def common_interfaces(
        host_ifaces: Dict[str, Dict[str, str]]) -> List[str]:
    """Interface names present on EVERY host (reference
    driver_service.py:201-221 _get_common_interfaces: the driver
    intersects the registered sets)."""
    sets = [set(ifaces) for ifaces in host_ifaces.values()]
    if not sets:
        return []
    common = set.intersection(*sets)
    # Loopback can't route between hosts — exclude it when more than one
    # host is involved (reference filters lo the same way).
    if len(host_ifaces) > 1:
        common = {i for i in common if not i.startswith("lo")}
    return sorted(common)


def discover_routable_interfaces(
        task_addrs: Dict[str, Tuple[str, int]],
        wait_timeout_s: float = 30.0) -> List[str]:
    """Query every host's task server and intersect (the driver side of
    the protocol). ``task_addrs``: hostname -> (ip, port) of its
    TaskServer.

    EVERY host must answer: an interface set intersected over a subset
    of hosts is not 'routable' — the missing host might lack the chosen
    NIC (the reference driver likewise waits for all task services to
    register, driver_service.py:49-120). Slow-starting servers are
    retried until ``wait_timeout_s``, then a RuntimeError names the
    unreachable hosts."""
    import time

    host_ifaces: Dict[str, Dict[str, str]] = {}
    pending = dict(task_addrs)
    deadline = time.monotonic() + wait_timeout_s
    while pending:
        for host, addr in list(pending.items()):
            if probe_reachable(addr):
                host_ifaces[host] = query_interfaces(addr)
                del pending[host]
        if not pending:
            break
        if time.monotonic() > deadline:
            raise RuntimeError(
                f"task servers unreachable on hosts "
                f"{sorted(pending)} after {wait_timeout_s}s — cannot "
                "determine routable interfaces for the full host set")
        time.sleep(0.2)
    return common_interfaces(host_ifaces)


if __name__ == "__main__":
    import sys

    sys.exit(main())
