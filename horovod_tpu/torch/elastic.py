"""TorchState — elastic state for the torch shim.

Reference: horovod/torch/elastic/state.py:27-130 (TorchState over
ObjectState with per-type handlers: model state_dict snapshot/restore,
optimizer state_dict, plain objects via broadcast_object) +
elastic/sampler.py (covered framework-agnostically by
horovod_tpu.data.ElasticSampler).

Usage mirrors the reference::

    state = TorchState(model=model, optimizer=optimizer, epoch=0)

    @hvd.elastic.run
    def train(state):
        for epoch in range(state.epoch, epochs):
            ...
            state.epoch = epoch
            state.commit()
"""

from __future__ import annotations

import copy

import torch

from ..common.elastic import ObjectState
from . import broadcast_optimizer_state, broadcast_parameters


def _clone_state_dict(sd):
    return {k: (v.detach().clone() if isinstance(v, torch.Tensor)
                else copy.deepcopy(v)) for k, v in sd.items()}


class _ModelHandler:
    """Snapshot/restore/sync a torch nn.Module (reference
    state.py ModelStateHandler)."""

    def __init__(self, model):
        self.value = model
        self._saved = _clone_state_dict(model.state_dict())

    def save(self):
        self._saved = _clone_state_dict(self.value.state_dict())

    def restore(self):
        # load_state_dict copies values into the parameters (copy_), so
        # the snapshot cannot be aliased — no defensive clone needed.
        self.value.load_state_dict(self._saved)

    def sync(self):
        broadcast_parameters(self.value.state_dict(), root_rank=0)

    def set_value(self, model):
        self.value = model
        self.save()


class _OptimizerHandler:
    """Reference state.py OptimizerStateHandler: optimizer state_dict
    snapshot + cross-rank broadcast."""

    def __init__(self, optimizer):
        self.value = optimizer
        self._saved = copy.deepcopy(optimizer.state_dict())

    def save(self):
        self._saved = copy.deepcopy(self.value.state_dict())

    def restore(self):
        # Optimizer.load_state_dict deepcopies its input internally.
        self.value.load_state_dict(self._saved)

    def sync(self):
        broadcast_optimizer_state(self.value, root_rank=0)

    def set_value(self, optimizer):
        self.value = optimizer
        self.save()


def _make_handler(value):
    if isinstance(value, torch.nn.Module):
        return _ModelHandler(value)
    if isinstance(value, torch.optim.Optimizer) or (
            hasattr(value, "state_dict")
            and hasattr(value, "load_state_dict")
            and hasattr(value, "param_groups")):
        # Duck-typed so the shim's dynamic-subclass DistributedOptimizer
        # (and its Adasum variant) route here too.
        return _OptimizerHandler(value)
    return None


class TorchState(ObjectState):
    """Elastic state for torch training: models/optimizers get typed
    handlers (state_dict snapshot/restore, collective sync), everything
    else rides ObjectState's pickle snapshot + broadcast_object."""

    def __init__(self, model=None, optimizer=None, **kwargs):
        if model is not None:
            kwargs.setdefault("model", model)
        if optimizer is not None:
            kwargs.setdefault("optimizer", optimizer)
        handlers = {}
        plain = {}
        for name, value in kwargs.items():
            h = _make_handler(value)
            if h is not None:
                handlers[name] = h
            else:
                plain[name] = value
        object.__setattr__(self, "_handlers", handlers)
        super().__init__(**plain)
        for name, h in handlers.items():
            object.__setattr__(self, name, h.value)

    def save(self):
        for h in self._handlers.values():
            h.save()
        super().save()

    def restore(self):
        for h in self._handlers.values():
            h.restore()
        super().restore()

    def sync(self):
        for h in self._handlers.values():
            h.sync()
        super().sync()  # ObjectState.sync ends with self.save() → one
        # full snapshot (incl. every handler) after the broadcasts

    def __setattr__(self, name, value):
        if not name.startswith("_") and hasattr(self, "_handlers") \
                and name in self._handlers:
            self._handlers[name].set_value(value)
            object.__setattr__(self, name, value)
            return
        super().__setattr__(name, value)
