"""TorchState — elastic state for the torch shim.

Reference: horovod/torch/elastic/state.py:27-130 (TorchState over
ObjectState with per-type handlers: model state_dict snapshot/restore,
optimizer state_dict, plain objects via broadcast_object) +
elastic/sampler.py (covered framework-agnostically by
horovod_tpu.data.ElasticSampler).

Usage mirrors the reference::

    state = TorchState(model=model, optimizer=optimizer, epoch=0)

    @hvd.elastic.run
    def train(state):
        for epoch in range(state.epoch, epochs):
            ...
            state.epoch = epoch
            state.commit()
"""

from __future__ import annotations

import copy

import torch

from ..common.elastic import ObjectState
from . import broadcast_optimizer_state, broadcast_parameters


def _clone_state_dict(sd):
    return {k: (v.detach().clone() if isinstance(v, torch.Tensor)
                else copy.deepcopy(v)) for k, v in sd.items()}


class _ModelHandler:
    """Snapshot/restore/sync a torch nn.Module (reference
    state.py ModelStateHandler)."""

    def __init__(self, model):
        self.value = model
        self._saved = _clone_state_dict(model.state_dict())

    def save(self):
        self._saved = _clone_state_dict(self.value.state_dict())

    def restore(self):
        # load_state_dict copies values into the parameters (copy_), so
        # the snapshot cannot be aliased — no defensive clone needed.
        self.value.load_state_dict(self._saved)

    def sync(self):
        broadcast_parameters(self.value.state_dict(), root_rank=0)

    def set_value(self, model):
        self.value = model
        self.save()


class _OptimizerHandler:
    """Reference state.py OptimizerStateHandler: optimizer state_dict
    snapshot + cross-rank broadcast."""

    def __init__(self, optimizer):
        self.value = optimizer
        self._saved = copy.deepcopy(optimizer.state_dict())

    def save(self):
        self._saved = copy.deepcopy(self.value.state_dict())

    def restore(self):
        # Optimizer.load_state_dict deepcopies its input internally.
        self.value.load_state_dict(self._saved)

    def sync(self):
        broadcast_optimizer_state(self.value, root_rank=0)

    def set_value(self, optimizer):
        self.value = optimizer
        self.save()


class ElasticSampler(torch.utils.data.Sampler):
    """torch-native elastic sampler — drop-in for the reference's
    ``hvd.elastic.ElasticSampler`` (torch/elastic/sampler.py:24-135):
    a ``torch.utils.data.Sampler`` usable directly in a ``DataLoader``
    that repartitions UNPROCESSED indices after elastic resets. Thin
    torch face over the framework-neutral
    :class:`horovod_tpu.data.ElasticSampler` (same partition math,
    padding, and deterministic per-epoch shuffle)."""

    def __init__(self, dataset, shuffle: bool = True, seed: int = 0):
        from ..data import ElasticSampler as _Impl

        self.dataset = dataset
        self._impl = _Impl(len(dataset), shuffle=shuffle, seed=seed)

    # reference surface --------------------------------------------------
    @property
    def epoch(self) -> int:
        return self._impl.epoch

    @property
    def processed_indices(self):
        return self._impl.processed_indices

    def set_epoch(self, epoch: int) -> None:
        self._impl.set_epoch(epoch)

    def record_batch(self, batch_idx: int, batch_size: int) -> None:
        self._impl.record_batch(batch_idx, batch_size)

    def record_indices(self, indices) -> None:
        self._impl.record_indices(indices)

    def get_indices(self, batch_idx: int, batch_size: int):
        return self._impl.get_indices(batch_idx, batch_size)

    def reset(self) -> None:
        # Reference semantics: the dataset length is re-read on every
        # reset (a re-sharded/appended dataset repartitions correctly).
        self._impl.dataset_size = len(self.dataset)
        self._impl.reset()

    def state_dict(self) -> dict:
        return {"epoch": self._impl.epoch,
                "processed_indices": set(self._impl.processed_indices)}

    def load_state_dict(self, state_dict: dict) -> None:
        self._impl.epoch = state_dict["epoch"]
        self._impl.processed_indices = set(
            state_dict["processed_indices"])
        self.reset()  # wrapper reset: re-reads len(self.dataset) too

    def __iter__(self):
        return iter(self._impl)

    def __len__(self) -> int:
        return len(self._impl)


class _SamplerHandler:
    """Reference state.py SamplerStateHandler: snapshot the processed
    set, restore it on rollback, and on sync adopt rank 0's view then
    repartition for the NEW topology."""

    def __init__(self, sampler):
        self.value = sampler
        self._saved = sampler.state_dict()

    def save(self):
        self._saved = self.value.state_dict()

    def restore(self):
        self.value.load_state_dict(self._saved)

    def sync(self):
        # Reference SamplerStateHandler: the processed set is the UNION
        # of every rank's view (each rank recorded only its own batches
        # since the last commit) — rank 0 alone would drop the others'
        # progress and retrain those samples.
        from horovod_tpu import allgather_object

        states = allgather_object(self.value.state_dict(),
                                  name="elastic.sampler")
        merged: set = set()
        for s in states:
            merged |= set(s["processed_indices"])
        self.value.load_state_dict({
            "epoch": max(s["epoch"] for s in states),
            "processed_indices": merged,
        })  # load ends with reset() → repartition for the new world

    def set_value(self, sampler):
        self.value = sampler
        self.save()


def _make_handler(value):
    if isinstance(value, ElasticSampler):
        return _SamplerHandler(value)
    if isinstance(value, torch.nn.Module):
        return _ModelHandler(value)
    if isinstance(value, torch.optim.Optimizer) or (
            hasattr(value, "state_dict")
            and hasattr(value, "load_state_dict")
            and hasattr(value, "param_groups")):
        # Duck-typed so the shim's dynamic-subclass DistributedOptimizer
        # (and its Adasum variant) route here too.
        return _OptimizerHandler(value)
    return None


class TorchState(ObjectState):
    """Elastic state for torch training: models/optimizers get typed
    handlers (state_dict snapshot/restore, collective sync), everything
    else rides ObjectState's pickle snapshot + broadcast_object."""

    def __init__(self, model=None, optimizer=None, **kwargs):
        if model is not None:
            kwargs.setdefault("model", model)
        if optimizer is not None:
            kwargs.setdefault("optimizer", optimizer)
        handlers = {}
        plain = {}
        for name, value in kwargs.items():
            h = _make_handler(value)
            if h is not None:
                handlers[name] = h
            else:
                plain[name] = value
        object.__setattr__(self, "_handlers", handlers)
        super().__init__(**plain)
        for name, h in handlers.items():
            object.__setattr__(self, name, h.value)

    def save(self):
        for h in self._handlers.values():
            h.save()
        super().save()

    def restore(self):
        for h in self._handlers.values():
            h.restore()
        super().restore()

    def sync(self):
        for h in self._handlers.values():
            h.sync()
        super().sync()  # ObjectState.sync ends with self.save() → one
        # full snapshot (incl. every handler) after the broadcasts

    def __setattr__(self, name, value):
        if not name.startswith("_") and hasattr(self, "_handlers") \
                and name in self._handlers:
            self._handlers[name].set_value(value)
            object.__setattr__(self, name, value)
            return
        super().__setattr__(name, value)
