"""SyncBatchNorm for the torch shim — cross-rank batch statistics.

Reference: horovod/torch/sync_batch_norm.py:1-199 — a ``_BatchNorm``
subclass whose training-mode forward combines per-rank (count, mean,
invstd) via allgather + ``batch_norm_gather_stats_with_counts`` and whose
custom backward allreduces (sum_dy, sum_dy_xmu) before computing
grad_input. The reference is CUDA-only because those aten kernels are;
here the same math is written out explicitly (sum/sumsq moments packed
into ONE allreduce each way), so it runs on CPU tensors too while
keeping identical semantics.
"""

from __future__ import annotations

import torch
import torch.nn.functional as F
from torch.autograd.function import Function
from torch.nn.modules.batchnorm import _BatchNorm

from . import Sum, allreduce, size


def _channel_view(t: torch.Tensor, ndim: int) -> torch.Tensor:
    """(C,) -> (1, C, 1, 1, ...) for broadcasting over an ndim input."""
    return t.view(1, -1, *([1] * (ndim - 2)))


class SyncBatchNorm(_BatchNorm):
    """Applies synchronized batch normalization: statistics are computed
    over the GLOBAL batch (all ranks), not the per-rank shard."""

    def __init__(self, num_features, eps=1e-5, momentum=0.1, affine=True,
                 track_running_stats=True):
        super().__init__(num_features, eps, momentum, affine,
                         track_running_stats)

    def _check_input_dim(self, input):
        if input.dim() < 2:
            raise ValueError(
                f"expected at least 2D input (got {input.dim()}D input)")

    def _run_bn(self, input):
        return F.batch_norm(
            input, self.running_mean, self.running_var, self.weight,
            self.bias, self.training or not self.track_running_stats,
            self.momentum, self.eps)

    def forward(self, input):
        self._check_input_dim(input)
        if self.training and self.track_running_stats:
            self.num_batches_tracked = self.num_batches_tracked + 1
        if not self.training and self.track_running_stats:
            return self._run_bn(input)
        return _SyncBatchNorm.apply(
            input, self.weight, self.bias, self.running_mean,
            self.running_var, self.eps, self.momentum)


class _SyncBatchNorm(Function):
    @staticmethod
    def forward(ctx, input, weight, bias, running_mean, running_var,
                eps, momentum):
        input = input.contiguous()
        dims = [0] + list(range(2, input.dim()))
        n_local = float(input.numel() // input.size(1))

        # Pack the local moments into one vector so the cross-rank sync
        # is a single fused allreduce (the reference launches three
        # allgathers; the packed SUM is equivalent for moment combining).
        local = torch.cat([input.sum(dim=dims),
                           (input * input).sum(dim=dims),
                           torch.tensor([n_local],
                                        dtype=input.dtype)])
        total = allreduce(local, op=Sum, name="sync_batch_norm.moments") \
            if size() > 1 else local
        c = input.size(1)
        count = total[-1]
        mean = total[:c] / count
        var = total[c:2 * c] / count - mean * mean
        invstd = torch.rsqrt(var + eps)

        if running_mean is not None:
            with torch.no_grad():
                unbiased = var * (count / max(count - 1.0, 1.0))
                running_mean.mul_(1 - momentum).add_(momentum * mean)
                running_var.mul_(1 - momentum).add_(momentum * unbiased)

        ctx.save_for_backward(input, weight, mean, invstd,
                              count.reshape(1))
        nd = input.dim()
        out = (input - _channel_view(mean, nd)) * _channel_view(invstd,
                                                                nd)
        if weight is not None:
            out = out * _channel_view(weight, nd) + _channel_view(bias,
                                                                  nd)
        return out

    @staticmethod
    def backward(ctx, grad_output):
        grad_output = grad_output.contiguous()
        saved_input, weight, mean, invstd, count = ctx.saved_tensors
        need_input_grad, need_weight_grad, need_bias_grad = \
            ctx.needs_input_grad[0:3]
        nd = saved_input.dim()
        dims = [0] + list(range(2, nd))
        xmu = saved_input - _channel_view(mean, nd)

        # Local reductions (batch_norm_backward_reduce analog).
        sum_dy = grad_output.sum(dim=dims)
        sum_dy_xmu = (grad_output * xmu).sum(dim=dims)

        grad_weight = (sum_dy_xmu * invstd) if need_weight_grad else None
        grad_bias = sum_dy.clone() if need_bias_grad else None

        grad_input = None
        if need_input_grad:
            c = sum_dy.numel()
            packed = torch.cat([sum_dy, sum_dy_xmu])
            if size() > 1:
                packed = allreduce(packed, op=Sum,
                                   name="sync_batch_norm.grad_moments")
            g_dy = packed[:c] / count
            g_dy_xmu = packed[c:] / count
            scale = invstd if weight is None else invstd * weight
            grad_input = (
                grad_output - _channel_view(g_dy, nd)
                - xmu * _channel_view(invstd * invstd * g_dy_xmu, nd)
            ) * _channel_view(scale, nd)

        if weight is None:
            grad_weight = None
            grad_bias = None
        return (grad_input, grad_weight, grad_bias, None, None, None,
                None)
