"""PyTorch binding shim — the reference ``horovod.torch`` API surface
hosted on the TPU-native collective engine.

Reference: horovod/torch/mpi_ops.py:85-646 (handle model:
allreduce_async_/poll/synchronize), horovod/torch/optimizer.py:103-207
(DistributedOptimizer hooking each parameter's grad accumulator),
horovod/torch/functions.py:30-108 (broadcast_parameters /
broadcast_optimizer_state).

Role in the TPU framework: training *compute* belongs on TPU via JAX — but
the reference's users arrive with torch data pipelines, torch metrics, and
host-side torch models (evaluation, RL actors, teachers). This shim gives
those host-side torch components the same five collectives, backed by the
same engine/controller/fusion machinery as the JAX path, so a migration can
move one piece at a time. Tensors cross at the numpy boundary (torch CPU
tensors share memory with numpy, so the copy in is free; TPU execution
happens inside the engine).
"""

from __future__ import annotations

from typing import Iterable, Optional, Tuple

import numpy as np
import torch

import horovod_tpu as _hvd
from horovod_tpu.ops.collectives import ReduceOp

# re-exported basics (reference torch/__init__.py surface)
init = _hvd.init
shutdown = _hvd.shutdown
is_initialized = _hvd.is_initialized
rank = _hvd.rank
size = _hvd.size
local_rank = _hvd.local_rank
local_size = _hvd.local_size
Average, Sum, Adasum, Min, Max, Product = (
    _hvd.Average, _hvd.Sum, _hvd.Adasum, _hvd.Min, _hvd.Max, _hvd.Product)
Compression = _hvd.Compression
# object helpers (reference torch/functions.py broadcast_object /
# allgather_object — cloudpickle over the engine's byte collectives)
broadcast_object = _hvd.broadcast_object
allgather_object = _hvd.allgather_object
# graceful early exit (reference torch/mpi_ops.py:631-644 join)
join = _hvd.join
# capability queries (reference torch re-exports of basics.py:160-258)
from horovod_tpu.common.basics import export_capability_queries as _ecq

_ecq(globals())


def _engine(process_set=None):
    # Membership check + sub-mesh engine routing live on the core
    # surface (horovod_tpu._engine / process_set.py).
    return _hvd._engine(process_set)


def _tensor_to_np(tensor: torch.Tensor) -> np.ndarray:
    """Torch -> numpy, including bfloat16 (which ``Tensor.numpy()``
    rejects): bf16 round-trips losslessly through fp32 host memory into
    an ``ml_dtypes.bfloat16`` ndarray, so the ENGINE still computes and
    reduces in bf16 — the wire dtype the caller asked for."""
    if tensor.dtype == torch.bfloat16:
        import ml_dtypes

        return (tensor.detach().to(torch.float32).cpu().numpy()
                .astype(ml_dtypes.bfloat16))
    return tensor.detach().cpu().numpy()


def _np_to_tensor(arr: np.ndarray, dtype: torch.dtype) -> torch.Tensor:
    """numpy -> torch of the caller's dtype; bf16 ndarrays (which
    ``torch.from_numpy`` rejects) bridge through fp32 losslessly."""
    if arr.dtype.kind not in "biufc":  # ml_dtypes extension types
        arr = arr.astype(np.float32)
    return torch.from_numpy(np.array(arr, copy=True)).to(dtype)


def _replicated(tensor: torch.Tensor, process_set=None):
    """Torch tensor -> explicitly replicated distributed tensor. Explicit
    replicate (not _as_distributed) so a tensor whose leading dim happens
    to equal world size is not mis-read as an already rank-major stack
    and scattered (same hazard fixed in functions.broadcast_variables)."""
    return _engine(process_set).replicate(_tensor_to_np(tensor))


def _to_host(dt) -> np.ndarray:
    """Distributed (size, *shape) result -> this rank's row on host.
    Reads only the first addressable shard instead of device_get'ing the
    full stack (a size x overfetch on large tensors). Always an ndarray
    — a scalar row would otherwise come back as a numpy scalar, which
    torch.from_numpy rejects."""
    return np.asarray(np.asarray(dt.addressable_shards[0].data)[0])


# -- collectives (reference torch/mpi_ops.py) -------------------------------

def _validate_compression(compression) -> None:
    """Fail fast on anything that isn't a Compressor — e.g. a ReduceOp
    positionally misbound after the signature gained the reference's
    argument order (a ReduceOp would otherwise surface only as an
    AttributeError deep inside the engine)."""
    if compression is None:
        return
    if not (hasattr(compression, "compress")
            and hasattr(compression, "decompress")):
        raise TypeError(
            f"compression must be a Compressor (hvd.Compression.*), got "
            f"{compression!r} — check argument order: "
            f"(optimizer, named_parameters, compression, "
            f"backward_passes_per_step, op, gradient_predivide_factor)")
    from horovod_tpu.optim import _check_reduce_safe

    _check_reduce_safe(compression)


def allreduce(tensor: torch.Tensor, op: ReduceOp = Average,
              name: Optional[str] = None,
              prescale_factor: float = 1.0,
              postscale_factor: float = 1.0,
              compression=None, process_set=None) -> torch.Tensor:
    _validate_compression(compression)
    e = _engine(process_set)
    out = e.allreduce(_replicated(tensor, process_set), op, name,
                      prescale_factor, postscale_factor, compression)
    return _np_to_tensor(_to_host(out), tensor.dtype)


def allreduce_(tensor: torch.Tensor, op: ReduceOp = Average,
               name: Optional[str] = None,
               process_set=None) -> torch.Tensor:
    tensor.copy_(allreduce(tensor, op, name, process_set=process_set))
    return tensor


def allgather(tensor: torch.Tensor,
              name: Optional[str] = None,
              process_set=None) -> torch.Tensor:
    """Concatenate along dim 0 over ranks (reference allgather contract).
    Under single-controller SPMD every rank holds this tensor, so the
    result is ``size`` stacked copies reshaped to (size*n, ...)."""
    e = _engine(process_set)
    out = _to_host(e.allgather(_replicated(tensor, process_set), name))
    return _np_to_tensor(out.reshape((-1,) + tuple(tensor.shape[1:])),
                         tensor.dtype)


def broadcast(tensor: torch.Tensor, root_rank: int = 0,
              name: Optional[str] = None,
              process_set=None) -> torch.Tensor:
    """With ``process_set``, ``root_rank`` is the GLOBAL rank of the
    root (core-surface convention — resolution happens in
    horovod_tpu.broadcast)."""
    out = _hvd.broadcast(_replicated(tensor, process_set), root_rank,
                         name, process_set=process_set)
    return _np_to_tensor(_to_host(out), tensor.dtype)


def broadcast_(tensor: torch.Tensor, root_rank: int = 0,
               name: Optional[str] = None,
               process_set=None) -> torch.Tensor:
    tensor.copy_(broadcast(tensor, root_rank, name,
                           process_set=process_set))
    return tensor


def alltoall(tensor: torch.Tensor,
             name: Optional[str] = None,
             process_set=None) -> torch.Tensor:
    e = _engine(process_set)
    out = _to_host(e.alltoall(_replicated(tensor, process_set), name))
    return _np_to_tensor(out, tensor.dtype)


def grouped_allreduce(tensors, op: ReduceOp = Average,
                      name: Optional[str] = None,
                      prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0,
                      compression=None, process_set=None):
    """Fused-bucket allreduce of a list of tensors (reference
    torch/mpi_ops.py grouped_allreduce): one negotiation + one fused
    flat buffer instead of a dispatch per tensor."""
    _validate_compression(compression)
    e = _engine(process_set)
    arrs = {str(i): _replicated(t, process_set)
            for i, t in enumerate(tensors)}
    out = e.allreduce_tree(arrs, op, name, compression,
                           prescale_factor=prescale_factor,
                           postscale_factor=postscale_factor)
    return [_np_to_tensor(_to_host(out[str(i)]), t.dtype)
            for i, t in enumerate(tensors)]


def sparse_allreduce_async(tensor: torch.Tensor,
                           name: Optional[str] = None,
                           op: ReduceOp = Average,
                           process_set=None):
    """Allreduce a torch SPARSE COO tensor (the later-Horovod
    ``sparse_allreduce_async`` surface): values/indices ride the ragged
    controller-negotiated allgather — the mathematical equivalent of
    summing the sparse operands (the same sparse-as-allgather design as
    the TF shim's IndexedSlices path) — with AVERAGE dividing the
    gathered values by the communicator size. Returns a zero-arg
    callable resolving to the reduced sparse tensor (the reference
    returns a synchronize-style handle; a callable keeps the shim free
    of sparse entries in the dense HandleManager)."""
    if not tensor.is_sparse:
        raise ValueError("sparse_allreduce_async needs a sparse COO "
                         "tensor; use allreduce/allreduce_async for "
                         "dense tensors")
    if op not in (Average, Sum):
        raise NotImplementedError(
            "sparse allreduce supports Average/Sum")
    import jax as _jax

    if process_set is not None and _jax.process_count() > 1:
        raise NotImplementedError(
            "sparse allreduce over a process_set is not supported in "
            "multi-process worlds (the set engine has no controller to "
            "negotiate ragged row counts)")
    t = tensor.coalesce()
    e = _engine(process_set)
    n = _hvd._communicator_size(process_set)
    # _tensor_to_np handles the boundary (detach/cpu/bf16 bridge) like
    # every dense collective here. COO indices are (ndim, nnz); gather
    # along nnz -> transpose first.
    vals = e.allgather_local(_tensor_to_np(t.values()),
                             name=f"{name or 'sp'}.values")
    idxs = e.allgather_local(_tensor_to_np(t.indices()).T,
                             name=f"{name or 'sp'}.indices")

    # Only shape/dtype survive into the closure — capturing the tensor
    # itself would pin the full input gradient for the handle's life.
    shape, dtype = tuple(tensor.shape), tensor.dtype

    def handle() -> torch.Tensor:
        arr = np.array(vals, copy=True)
        if arr.dtype.kind not in "biufc":  # ml_dtypes bf16 bridge
            arr = arr.astype(np.float32)
        idx = torch.from_numpy(
            np.ascontiguousarray(np.array(idxs, copy=True).T))
        # Coalesce-sum FIRST, in the gathered dtype (exact for ints and
        # fp64), divide AFTER for Average — dividing before the sum
        # accumulates n rounding errors (1/12 summed 12x = 0.99999988,
        # truncating to 0 for ints).
        out = torch.sparse_coo_tensor(
            idx, torch.from_numpy(arr), size=shape).coalesce()
        ov = out.values()
        if op == Average:
            ov = ov.to(torch.float64) / n
            if not dtype.is_floating_point:
                ov = ov.round()
        return torch.sparse_coo_tensor(out.indices(), ov.to(dtype),
                                       size=shape)

    return handle


def reducescatter(tensor: torch.Tensor, op: Optional[ReduceOp] = None,
                  name: Optional[str] = None,
                  process_set=None) -> torch.Tensor:
    """This rank's 1/n slice of the elementwise reduction over dim 0
    (the later-Horovod torch surface; absent from the pinned era). The
    default op matches upstream's reducescatter default (Average); the
    default flipped from Sum in round 4, so a defaulted call warns once
    per process (see horovod_tpu.reducescatter)."""
    if op is None:
        from .. import _reducescatter_default_op

        op = _reducescatter_default_op()
    e = _engine(process_set)
    out = _to_host(e.reducescatter(_replicated(tensor, process_set), op,
                                   name))
    return _np_to_tensor(out, tensor.dtype)


def grouped_allgather(tensors, name: Optional[str] = None,
                      process_set=None):
    # name=None passes through per leaf: the engine auto-names each
    # uniquely (a constant default prefix would collide across calls).
    return [allgather(t, f"{name}.{i}" if name else None,
                      process_set=process_set)
            for i, t in enumerate(tensors)]


def grouped_reducescatter(tensors, op: Optional[ReduceOp] = None,
                          name: Optional[str] = None, process_set=None):
    return [reducescatter(t, op, f"{name}.{i}" if name else None,
                          process_set=process_set)
            for i, t in enumerate(tensors)]


def grouped_allreduce_(tensors, op: ReduceOp = Average,
                       name: Optional[str] = None,
                       prescale_factor: float = 1.0,
                       postscale_factor: float = 1.0,
                       compression=None, process_set=None):
    outs = grouped_allreduce(tensors, op, name, prescale_factor,
                             postscale_factor, compression, process_set)
    for t, o in zip(tensors, outs):
        t.copy_(o)
    return tensors


# -- async handle model (reference torch/mpi_ops.py:223-646) ----------------

def allreduce_async(tensor: torch.Tensor, op: ReduceOp = Average,
                    name: Optional[str] = None,
                    prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0,
                    compression=None, process_set=None) -> int:
    """Launches the collective (XLA dispatch is async — the reference's
    background-thread asynchrony maps onto the XLA stream) and returns an
    int handle; the device→host copy happens in synchronize().

    Handles always live on the WORLD engine's HandleManager (even for
    set-scoped collectives), so poll/synchronize need no process_set."""
    _validate_compression(compression)  # int8 scales don't sum
    e = _engine(process_set)
    out = e.allreduce(_replicated(tensor, process_set), op, name,
                      prescale_factor, postscale_factor, compression)
    h = _engine().handles.allocate(out)
    _inplace_targets()[h] = ("plain", tensor.dtype)
    return h


def broadcast_async(tensor: torch.Tensor, root_rank: int = 0,
                    name: Optional[str] = None, process_set=None) -> int:
    out = _hvd.broadcast(_replicated(tensor, process_set), root_rank,
                         name, process_set=process_set)
    h = _engine().handles.allocate(out)
    _inplace_targets()[h] = ("plain", tensor.dtype)
    return h


def allgather_async(tensor: torch.Tensor,
                    name: Optional[str] = None, process_set=None) -> int:
    """Reference torch/mpi_ops.py:302 — handle resolves to the
    rank-concatenated result."""
    e = _engine(process_set)
    out = e.allgather(_replicated(tensor, process_set), name)
    h = _engine().handles.allocate(out)
    _inplace_targets()[h] = ("allgather", tensor)
    return h


def alltoall_async(tensor: torch.Tensor,
                   name: Optional[str] = None, process_set=None) -> int:
    """Reference torch/mpi_ops.py:515, even-split form (matching this
    shim's sync alltoall; negotiated uneven splits live on the core
    surface, horovod_tpu.alltoall(splits=...))."""
    e = _engine(process_set)
    out = e.alltoall(_replicated(tensor, process_set), name)
    h = _engine().handles.allocate(out)
    _inplace_targets()[h] = ("plain", tensor.dtype)
    return h


def _inplace_targets() -> dict:
    """Handle -> target-tensor registry for the _-suffixed async ops.
    Lives ON the engine so it resets with shutdown()/init() exactly like
    HandleManager — a module-level dict would alias recycled handle ids
    across engine generations and write results into dead tensors."""
    e = _engine()
    reg = getattr(e, "_torch_inplace_targets", None)
    if reg is None:
        reg = e._torch_inplace_targets = {}
    return reg


def allreduce_async_(tensor: torch.Tensor, op: ReduceOp = Average,
                     name: Optional[str] = None,
                     process_set=None) -> int:
    """Reference torch/mpi_ops.py:223 allreduce_async_."""
    h = allreduce_async(tensor, op, name, process_set=process_set)
    _inplace_targets()[h] = ("inplace", tensor)
    return h


def broadcast_async_(tensor: torch.Tensor, root_rank: int = 0,
                     name: Optional[str] = None,
                     process_set=None) -> int:
    """Reference torch/mpi_ops.py:451 broadcast_async_."""
    h = broadcast_async(tensor, root_rank, name, process_set=process_set)
    _inplace_targets()[h] = ("inplace", tensor)
    return h


def poll(handle: int) -> bool:
    return _engine().poll(handle)


def synchronize(handle: int) -> torch.Tensor:
    val = _engine().synchronize(handle)
    if isinstance(val, torch.Tensor):
        out = val
    else:
        arr = _to_host(val)
        if arr.dtype.kind not in "biufc":  # bf16 via ml_dtypes
            arr = arr.astype(np.float32)
        out = torch.from_numpy(arr.copy())
    kind, target = _inplace_targets().pop(handle, (None, None))
    if kind == "inplace":
        target.copy_(out.reshape(target.shape).to(target.dtype))
        return target
    if kind == "allgather":
        # This rank's row holds the stacked gather; flatten rank-major
        # exactly like the sync allgather surface.
        return out.reshape((-1,) + tuple(target.shape[1:])).to(target.dtype)
    if kind == "plain":
        # Restore the caller's dtype (bf16 bridges through fp32 host
        # memory) — the sync surface's contract. Only the DTYPE is
        # registered for plain handles: a strong tensor ref would pin
        # every input until synchronize(), leaking on fire-and-forget
        # handles.
        return out.to(target)
    return out


# -- parameter/optimizer broadcast (reference torch/functions.py:30-108) ----

def broadcast_parameters(params, root_rank: int = 0,
                         process_set=None) -> None:
    """In-place broadcast of a state_dict or iterable of (name, tensor)."""
    if hasattr(params, "items"):
        items: Iterable[Tuple[str, torch.Tensor]] = params.items()
    else:
        items = params
    for name, p in items:
        if isinstance(p, torch.Tensor):
            broadcast_(p.data if p.requires_grad else p, root_rank,
                       name=f"bcast.{name}", process_set=process_set)


def broadcast_optimizer_state(optimizer: torch.optim.Optimizer,
                              root_rank: int = 0,
                              process_set=None) -> None:
    """Broadcast optimizer hyper/state tensors + scalars from root
    (reference torch/functions.py broadcast_optimizer_state: state tensors
    via collectives, scalars via the object channel)."""
    from horovod_tpu.functions import broadcast_object

    state_dict = optimizer.state_dict()
    tensors = {}
    for gi, group_state in state_dict["state"].items():
        for k, v in group_state.items():
            if isinstance(v, torch.Tensor):
                tensors[f"opt.{gi}.{k}"] = v
            else:
                # Scalars ride the PROCESS-level object channel (KV
                # store) — set-agnostic by construction; in the
                # single-controller world it is an identity.
                group_state[k] = broadcast_object(
                    v, root_rank, name=f"opt.{gi}.{k}")
    for name, t in tensors.items():
        broadcast_(t, root_rank, name=name, process_set=process_set)
    for gi, group in enumerate(state_dict["param_groups"]):
        for k in list(group.keys()):
            if k != "params":
                group[k] = broadcast_object(group[k], root_rank,
                                            name=f"grp.{gi}.{k}")
    optimizer.load_state_dict(state_dict)


# -- DistributedOptimizer (reference torch/optimizer.py:103-207) ------------

class _DistributedOptimizerMixin:
    """Method set grafted onto the USER's optimizer class: grad-accumulator
    hooks launch one async allreduce per parameter; ``step()`` synchronizes
    all handles then runs the base optimizer on the averaged gradients —
    the reference's overlap model (torch/optimizer.py:103-207), with the
    engine's controller/fusion doing the bucketing the C++ core did."""

    def _dist_init(self, base_cls, named_parameters, op,
                   backward_passes_per_step, compression=None,
                   gradient_predivide_factor: float = 1.0,
                   process_set=None, sparse_as_dense: bool = False):
        self._base_cls = base_cls
        self.op = op
        self._compression = compression
        self._predivide = gradient_predivide_factor
        self._process_set = process_set
        self._sparse_as_dense = sparse_as_dense
        self.backward_passes_per_step = backward_passes_per_step
        self._handles = {}          # id(p) -> (p, handle-or-None)
        self._allreduce_delay = {}  # id(p) -> remaining local passes
        self._requires_update = []
        self._names = {}
        self._should_synchronize = True
        self._synchronized = False
        if named_parameters is not None:
            self._names = {id(p): n for n, p in named_parameters}
        self._hooks = []
        for group in self.param_groups:
            for p in group["params"]:
                if p.requires_grad:
                    self._requires_update.append(p)
                    self._allreduce_delay[id(p)] = \
                        self.backward_passes_per_step
                    self._hooks.append(p.register_post_accumulate_grad_hook(
                        self._make_hook()))

    def _launch(self, p: torch.Tensor) -> int:
        if p.grad is None:
            # Reference zeroes grads at hook registration
            # (optimizer.py:107); a force-sync before any backward
            # contributes zeros.
            p.grad = torch.zeros_like(p)
        if p.grad.is_sparse:
            # Sparse embedding grads (Embedding(sparse=True)): densify
            # (the knob was validated at hook entry, before the delay
            # counter moved — a raise here would leave the counter
            # at 0 and turn a retried backward into a bare assert).
            self._check_sparse_grad(p)
            p.grad = p.grad.to_dense()
        name = self._names.get(id(p), f"grad.{id(p)}")
        op, pre, post = self.op, 1.0, 1.0
        if self._predivide != 1.0:
            # Reference optimizer.py: scale 1/f before the SUM, f/size
            # after (splits the averaging around the reduction) — size
            # is the COMMUNICATOR's, i.e. the set's when one is given.
            n = _hvd._communicator_size(self._process_set)
            op, pre, post = Sum, 1.0 / self._predivide, \
                self._predivide / n
        return allreduce_async(p.grad, op=op, name=name,
                               prescale_factor=pre, postscale_factor=post,
                               compression=self._compression,
                               process_set=self._process_set)

    def _check_sparse_grad(self, p: torch.Tensor) -> None:
        if (p.grad is not None and p.grad.is_sparse
                and not self._sparse_as_dense):
            raise ValueError(
                "DistributedOptimizer got a sparse gradient; pass "
                "sparse_as_dense=True (densify + allreduce) or "
                "reduce it yourself via sparse_allreduce_async")

    def _make_hook(self):
        def hook(p: torch.Tensor) -> None:
            # Validate sparse grads BEFORE the delay counter moves so
            # the informative error re-surfaces on a retried backward.
            self._check_sparse_grad(p)
            # Reference torch/optimizer.py:134-149: count down the local
            # aggregation delay; the allreduce fires on the k-th backward
            # (p.grad accumulated the k local passes in the meantime).
            if (id(p) in self._handles
                    and self._handles[id(p)][1] is not None):
                if self._allreduce_delay[id(p)] <= 0:
                    raise AssertionError(
                        "Gradients were computed more than "
                        "backward_passes_per_step times before call to "
                        "step(). Increase backward_passes_per_step to "
                        "accumulate gradients locally.")
            assert self._allreduce_delay[id(p)] > 0
            self._allreduce_delay[id(p)] -= 1
            handle = None
            if self._allreduce_delay[id(p)] == 0:
                handle = self._launch(p)
            self._handles[id(p)] = (p, handle)

        return hook

    def synchronize(self) -> None:
        """Wait for all in-flight reductions; force-reduce any parameter
        still mid-aggregation (reference torch/optimizer.py:152-167 —
        step() never skips: an early step() flushes the aggregate)."""
        for p in self._requires_update:
            if id(p) not in self._handles:
                self._handles[id(p)] = (p, self._launch(p))
        for pid, (p, handle) in list(self._handles.items()):
            if handle is None:
                self._handles[pid] = (p, self._launch(p))
        for pid, (p, handle) in self._handles.items():
            reduced = synchronize(handle)
            self._allreduce_delay[pid] = self.backward_passes_per_step
            p.grad.copy_(reduced)
        self._handles.clear()
        self._synchronized = True

    def skip_synchronize(self):
        """Context manager: step() without re-synchronizing (reference
        torch/optimizer.py:170-186)."""
        import contextlib

        @contextlib.contextmanager
        def ctx():
            self._should_synchronize = False
            try:
                yield
            finally:
                self._should_synchronize = True

        return ctx()

    def step(self, closure=None):
        if self._should_synchronize:
            self.synchronize()
        self._synchronized = False
        return self._base_cls.step(self, closure)

    def zero_grad(self, set_to_none: bool = True):
        if self._handles:
            raise AssertionError(
                "optimizer.zero_grad() was called after loss.backward() "
                "but before optimizer.step() or optimizer.synchronize(). "
                "This is prohibited as it can cause a race condition.")
        return self._base_cls.zero_grad(self, set_to_none=set_to_none)


class _DistributedAdasumMixin:
    """Delta-based Adasum optimizer methods, grafted onto the USER's
    optimizer class like the main mixin (reference
    torch/optimizer.py:210-378 _DistributedAdasumOptimizer): step()
    applies the base optimizer LOCALLY, extracts the resulting weight
    delta, rolls the weights back, Adasum-reduces the delta across
    ranks, and applies the reduced delta — adaptive summation over
    optimizer-shaped steps, not raw grads."""

    def _dist_init(self, base_cls, named_parameters, compression=None,
                   process_set=None):
        self._base_cls = base_cls
        self._compression = compression
        self._process_set = process_set
        self._names = {}
        if named_parameters is not None:
            self._names = {id(p): n for n, p in named_parameters}

    def step(self, closure=None):
        params = [p for group in self.param_groups
                  for p in group["params"]]
        before = {id(p): p.detach().clone() for p in params}
        result = self._base_cls.step(self, closure)
        for p in params:
            b = before[id(p)]
            delta = p.detach() - b
            name = self._names.get(id(p), f"adasum.delta.{id(p)}")
            reduced = allreduce(delta, op=Adasum, name=name,
                                compression=self._compression,
                                process_set=self._process_set)
            with torch.no_grad():
                p.copy_(b + reduced)
        return result


def DistributedOptimizer(optimizer: torch.optim.Optimizer,
                         named_parameters=None,
                         compression=None,
                         backward_passes_per_step: int = 1,
                         op: ReduceOp = Average,
                         gradient_predivide_factor: float = 1.0,
                         process_set=None,
                         sparse_as_dense: bool = False):
    """Returns an instance of a dynamic subclass of the USER's optimizer
    class with the mixin's step/synchronize grafted on — the reference's
    own architecture (torch/optimizer.py:381: ``cls = type(...,
    (optimizer.__class__,), dict(_DistributedOptimizer.__dict__))``).
    Unlike a delegation wrapper, every torch.optim.Optimizer internal
    (defaults, step pre/post hook registries, lr_scheduler's isinstance
    and step-patching machinery) is genuinely present, because the
    instance shares the fully-initialized __dict__ of the wrapped
    optimizer.

    ``op=Adasum`` grafts the delta-based mixin instead (the reference
    routes Adasum the same way, torch/optimizer.py:440+: adaptive
    summation operates on optimizer deltas, not gradients).
    ``compression`` rides each per-gradient allreduce (reference
    optimizer.py compression param); ``gradient_predivide_factor``
    splits averaging around the sum (1/f before, f/size after) and
    requires op=Average."""
    if gradient_predivide_factor != 1.0 and op != Average:
        raise ValueError("gradient_predivide_factor requires op=Average "
                         "(reference torch/optimizer.py)")
    _validate_compression(compression)
    if op == Adasum:
        if backward_passes_per_step != 1:
            raise NotImplementedError(
                "backward_passes_per_step > 1 is not supported with "
                "op=Adasum (accumulate locally by skipping zero_grad "
                "between backwards instead)")
        cls = type(optimizer.__class__.__name__,
                   (optimizer.__class__,),
                   {k: v for k, v in
                    _DistributedAdasumMixin.__dict__.items()
                    if not k.startswith("__")})
        obj = cls.__new__(cls)
        obj.__dict__.update(optimizer.__dict__)
        obj._dist_init(optimizer.__class__, named_parameters, compression,
                       process_set)
        return obj
    cls = type(optimizer.__class__.__name__,
               (optimizer.__class__,),
               {k: v for k, v in _DistributedOptimizerMixin.__dict__.items()
                if not k.startswith("__")})
    obj = cls.__new__(cls)
    obj.__dict__.update(optimizer.__dict__)  # share param_groups + state
    obj._dist_init(optimizer.__class__, named_parameters, op,
                   backward_passes_per_step, compression,
                   gradient_predivide_factor, process_set,
                   sparse_as_dense)
    return obj


# Imported last: sync_batch_norm pulls collectives from this namespace
# (reference exposes it as horovod.torch.SyncBatchNorm).
from .sync_batch_norm import SyncBatchNorm  # noqa: E402
