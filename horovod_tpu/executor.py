"""Persistent worker-pool executor — the cluster-integration layer.

Reference: horovod/ray/runner.py:90-482 (``RayExecutor``: placement-group
workers that stay alive across ``run()`` calls, a ``Coordinator`` that
collects hostnames and builds the rendezvous env) and horovod/spark's
run-fn-in-executors model (spark/runner.py:132-417).

TPU-native: workers are OS processes wired into one ``jax.distributed``
world by the same env bootstrap the launcher uses; the driver talks to them
over length-prefixed pickle frames on loopback/DCN TCP sockets (the role
Ray's actor channel / Spark's task service plays). Because workers persist,
JAX backends and compiled step caches survive across ``run()`` calls —
the property that makes RayExecutor useful for interactive work.

No Ray/Spark dependency: the scheduling substrate here is plain processes;
on a managed cluster the same Executor protocol runs over ssh fan-out.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import subprocess
import sys
import threading
from typing import Any, Callable, Dict, List, Optional
from .common.config import runtime_env


# -- framing ----------------------------------------------------------------

def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack(">Q", len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket) -> bytes:
    (n,) = struct.unpack(">Q", _recv_exact(sock, 8))
    return _recv_exact(sock, n)


class Executor:
    """Pool of ``np`` persistent workers; ``run()`` executes a function on
    every worker and returns per-rank results (RayExecutor.run contract).

    Usage::

        with hvd.executor.Executor(np=4) as ex:
            ex.run(setup_fn)          # hvd.init() once, stays warm
            for epoch in range(10):
                losses = ex.run(train_epoch, args=(epoch,))
    """

    def __init__(self, np: int = 2, env: Optional[Dict[str, str]] = None,
                 start_timeout_s: float = 60.0):
        self.np = np
        self.env = dict(env or {})
        self.start_timeout_s = start_timeout_s
        self._procs: List[subprocess.Popen] = []
        self._socks: Dict[int, socket.socket] = {}
        self._server: Optional[socket.socket] = None
        self._started = False

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Executor":
        from .runner import launch as launch_lib

        if self._started:
            return self
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(("127.0.0.1", 0))
        self._server.listen(self.np)
        self._server.settimeout(self.start_timeout_s)
        driver_addr = "127.0.0.1:%d" % self._server.getsockname()[1]

        coordinator = "127.0.0.1:%d" % launch_lib._free_port()
        try:
            for i in range(self.np):
                env = launch_lib.build_env_for_slot(
                    dict(os.environ), coordinator, self.np, i, self.env)
                p = subprocess.Popen(
                    [sys.executable, "-m", "horovod_tpu.executor",
                     driver_addr], env=env)
                self._procs.append(p)
            for _ in range(self.np):
                sock, _ = self._server.accept()
                pid = pickle.loads(_recv_frame(sock))
                self._socks[pid] = sock
        except BaseException:
            # A worker died before connecting (or accept timed out):
            # reap everything — a failed start must not leak processes
            # or the server socket (start() raising skips __exit__).
            for p in self._procs:
                p.kill()
            for p in self._procs:
                p.wait()
            for sock in self._socks.values():
                sock.close()
            self._server.close()
            self._procs.clear()
            self._socks.clear()
            self._server = None
            raise
        self._started = True
        return self

    def shutdown(self) -> None:
        for sock in self._socks.values():
            try:
                _send_frame(sock, pickle.dumps(("stop", None)))
                sock.close()
            except OSError:
                pass
        for p in self._procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
        if self._server is not None:
            self._server.close()
        self._socks.clear()
        self._procs.clear()
        self._started = False

    def __enter__(self) -> "Executor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # -- execution ---------------------------------------------------------

    def run(self, fn: Callable, args: tuple = (),
            kwargs: Optional[Dict[str, Any]] = None) -> List[Any]:
        """Run ``fn(*args, **kwargs)`` on all workers; returns results in
        rank order. A worker exception raises RuntimeError with the remote
        traceback (all workers still complete the round — SPMD programs
        must not be torn down mid-collective)."""
        import cloudpickle

        if not self._started:
            raise RuntimeError("Executor not started (use .start() or with)")
        payload = cloudpickle.dumps(("run", (fn, args, kwargs or {})))
        results: Dict[int, Any] = {}
        errors: Dict[int, str] = {}
        lock = threading.Lock()

        def one(pid: int, sock: socket.socket) -> None:
            try:
                _send_frame(sock, payload)
                status, value = pickle.loads(_recv_frame(sock))
                with lock:
                    (results if status == "ok" else errors)[pid] = value
            except (OSError, ConnectionError, EOFError) as e:
                with lock:
                    errors[pid] = f"transport error: {e!r}"

        threads = [threading.Thread(target=one, args=item)
                   for item in self._socks.items()]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            detail = "\n".join(f"worker {pid}:\n{tb}"
                               for pid, tb in sorted(errors.items()))
            raise RuntimeError(f"Executor.run failed:\n{detail}")
        return [results[pid] for pid in sorted(results)]

    def execute_single(self, fn: Callable, args: tuple = (),
                       kwargs: Optional[Dict[str, Any]] = None,
                       rank: int = 0) -> Any:
        """Run on one worker only (RayExecutor.execute_single analog).
        Note: ``fn`` must not issue collectives — the other ranks are not
        participating in this call."""
        import cloudpickle

        sock = self._socks[rank]
        _send_frame(sock, cloudpickle.dumps(("run", (fn, args,
                                                     kwargs or {}))))
        status, value = pickle.loads(_recv_frame(sock))
        if status != "ok":
            raise RuntimeError(f"worker {rank}:\n{value}")
        return value


# -- worker side ------------------------------------------------------------

def _worker_main(driver_addr: str) -> int:
    import traceback

    host, port = driver_addr.rsplit(":", 1)
    sock = socket.create_connection((host, int(port)))
    pid = int(runtime_env("PROC_ID", "0"))
    _send_frame(sock, pickle.dumps(pid))
    while True:
        cmd, payload = pickle.loads(_recv_frame(sock))
        if cmd == "stop":
            return 0
        fn, args, kwargs = payload
        try:
            reply = ("ok", fn(*args, **kwargs))
        except BaseException as e:
            reply = ("error", "".join(traceback.format_exception(
                type(e), e, e.__traceback__)))
        _send_frame(sock, pickle.dumps(reply))


if __name__ == "__main__":
    sys.exit(_worker_main(sys.argv[1]))
