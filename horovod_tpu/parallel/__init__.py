"""Parallelism strategies over the collective primitive set: mesh builders,
the hybrid dp x pp x tp ParallelSpec (docs/pipeline.md), sequence
parallelism (ring attention, Ulysses), expert parallel, tensor
parallel, pipeline (GPipe fill-drain + interleaved 1F1B, both riding
lax.scan with wire-dtyped stage-boundary sends)."""

from .mesh import build_mesh, data_spec, param_spec  # noqa: F401
from .moe import moe_layer, top2_gating  # noqa: F401
from .pipeline import (pipeline_accumulate_gradients,  # noqa: F401
                       pipeline_apply, pipeline_train_step_1f1b,
                       select_last_stage)
from .respec import (RespecDecision, min_world,  # noqa: F401
                     solve_respec)
from .ring_attention import (resolve_seq_wire,  # noqa: F401
                             ring_attend_fn, ring_attention,
                             stripe_layout, striped_attend_fn,
                             striped_attention, striped_positions,
                             unstripe_layout)
from .spec import (ParallelSpec, hybrid_param_specs,  # noqa: F401
                   hybrid_state_specs, spec_from_env)
from .tensor_parallel import (column_parallel,  # noqa: F401
                              combine_slice_grads, row_parallel,
                              shard_column, shard_head_rows,
                              shard_heads, shard_row,
                              tp_attention_qkv, tp_mlp)
from .ulysses import (ulysses_attend_fn,  # noqa: F401
                      ulysses_attention)
