"""Parallelism strategies over the collective primitive set: mesh builders,
sequence parallelism (ring attention, Ulysses), expert parallel, pipeline."""
