"""ParallelSpec — hybrid dp x pp x tp parallelism declared on ONE mesh.

The mesh-axis machinery (common/topology.py, ops/collectives.WirePlan)
routes COLLECTIVES per axis; a ParallelSpec promotes it to routing
COMPUTATION (ROADMAP item 2, the MLPerf TPU-v3 pod recipe —
arXiv:1909.09756): each mesh axis is assigned a parallelism ROLE:

  ``dp``  data parallelism      — batch shards, gradient allreduce
  ``pp``  pipeline parallelism  — decoder stages, 1F1B activation sends
                                  (parallel/pipeline.py)
  ``tp``  tensor parallelism    — column/row-parallel weights +
                                  sharded-head attention
                                  (parallel/tensor_parallel.py)
  ``ep``  expert parallelism    — MoE alltoall dispatch
                                  (parallel/moe.py)
  ``sp``  sequence parallelism  — ring/Ulysses attention over
                                  sequence-sharded activations
                                  (parallel/ring_attention.py,
                                  parallel/ulysses.py, docs/sequence.md)

Declare roles SLOW axis first, FAST axis last (row-major device order,
same convention as ``HVD_TPU_MESH_SHAPE``): the gradient allreduce
tolerates the slow hop, while tensor parallelism's per-layer allreduce
needs the fastest links — so ``dict(dp=2, pp=2, tp=2)`` puts ``dp``
on the cross/DCN hop and ``tp`` on intra-host ICI (the Megatron
placement rule). ``hvd.init(parallel=...)`` accepts a dict, a spec
string (``"dp=2,pp=2,tp=2"``, the ``HVD_TPU_PARALLEL`` env form), or a
ParallelSpec, and publishes the resolved spec as
``hvd.parallel_spec()`` / its mesh as ``hvd.parallel_mesh()``.

The optimizer surfaces consume the spec directly
(``DistributedOptimizer(..., parallel=spec)``): gradients reduce over
the ``dp`` axes ONLY (through the usual route/compression/guard
stack), tp/sp slice-gradients are pmean-combined over ``tp``/``sp``
first (tensor_parallel.combine_slice_grads — sp ranks hold identical
params but gradients from different sequence shards, so the same
pmean assembles them), the non-finite guard agrees
over the ``dp`` axes only (each pipeline stage owns different params —
docs/pipeline.md), and ZeRO shard grids span the ``dp`` axes so
stage-2/3 shards live PER PIPELINE STAGE.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

# Roles a mesh axis can play. The axis NAME in the jax Mesh is the role
# name itself, so shard_map specs and WirePlan phases read naturally
# (P("pp"), "dp:int8").
ROLES = ("dp", "pp", "tp", "ep", "sp")

# The env form hvd.init(parallel=) publishes and every role-aware
# consumer (autoscale engine, pod monitor, flight recorder, respec
# solver) resolves — one spelling, importable without a jax session.
ENV_PARALLEL = "HVD_TPU_PARALLEL"


def spec_from_env(env=None) -> Optional["ParallelSpec"]:
    """The ParallelSpec declared via ``HVD_TPU_PARALLEL``, or None.
    Raises ValueError on a malformed value (same contract as
    ``hvd.init(parallel=)`` — a typo'd spec must not silently run
    role-blind)."""
    import os

    env = os.environ if env is None else env
    raw = env.get(ENV_PARALLEL)
    if not raw or not str(raw).strip():
        return None
    return ParallelSpec.parse(raw)


@dataclasses.dataclass(frozen=True)
class ParallelSpec:
    """Immutable role -> size assignment, SLOW axis first.

    ``dims`` is an ordered tuple of ``(role, size)`` pairs; the mesh is
    built slow-major (first role = slowest links, last = fastest ICI),
    matching ``topology.parse_mesh_shape``'s row-major convention.
    """

    dims: Tuple[Tuple[str, int], ...]

    def __post_init__(self):
        if not self.dims:
            raise ValueError("ParallelSpec needs at least one axis")
        seen = set()
        for role, size in self.dims:
            if role not in ROLES:
                raise ValueError(
                    f"unknown parallelism role {role!r}; choose from "
                    f"{ROLES}")
            if role in seen:
                raise ValueError(f"duplicate role {role!r} in spec")
            seen.add(role)
            if int(size) < 1:
                raise ValueError(
                    f"axis {role!r} needs size >= 1, got {size}")

    # -- construction -------------------------------------------------

    @classmethod
    def parse(cls, spec: str) -> "ParallelSpec":
        """``"dp=2,pp=2,tp=2"`` (slow -> fast) — the HVD_TPU_PARALLEL
        env form."""
        dims = []
        for part in str(spec).split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(
                    f"bad parallel spec segment {part!r}: expected "
                    "role=size, e.g. 'dp=2,pp=2,tp=2'")
            role, size = part.split("=", 1)
            dims.append((role.strip(), int(size)))
        return cls(tuple(dims))

    @classmethod
    def resolve(cls, value) -> Optional["ParallelSpec"]:
        """Coerce a user-facing ``parallel=`` value: an existing spec,
        a role->size dict (insertion order = slow -> fast), or a spec
        string; None stays None (no hybrid parallelism)."""
        if value is None:
            return None
        if isinstance(value, ParallelSpec):
            return value
        if isinstance(value, dict):
            return cls(tuple((str(k), int(v)) for k, v in value.items()))
        return cls.parse(str(value))

    # -- views --------------------------------------------------------

    @property
    def roles(self) -> Tuple[str, ...]:
        return tuple(r for r, _ in self.dims)

    @property
    def sizes(self) -> dict:
        return {r: s for r, s in self.dims}

    def size_of(self, role: str) -> int:
        return self.sizes.get(role, 1)

    @property
    def total(self) -> int:
        n = 1
        for _, s in self.dims:
            n *= s
        return n

    @property
    def dp_axes(self) -> Tuple[str, ...]:
        """Axes the gradient allreduce runs over — ``dp`` only (a
        size-1 dp axis still binds in shard_map and reduces as a
        no-op, so it is kept)."""
        return tuple(r for r, _ in self.dims if r == "dp")

    @property
    def pp_axis(self) -> Optional[str]:
        return "pp" if self.size_of("pp") > 1 else None

    @property
    def tp_axis(self) -> Optional[str]:
        return "tp" if self.size_of("tp") > 1 else None

    @property
    def ep_axis(self) -> Optional[str]:
        return "ep" if self.size_of("ep") > 1 else None

    @property
    def sp_axis(self) -> Optional[str]:
        return "sp" if self.size_of("sp") > 1 else None

    def describe(self) -> str:
        return ",".join(f"{r}={s}" for r, s in self.dims)

    # -- rank -> role coordinates (the failure-attribution view) ------

    @property
    def replica_ranks(self) -> int:
        """Ranks per model replica — the product of every non-dp role
        size (pp x tp x ep x sp). Losing ANY of these ranks orphans the
        whole replica: it is the hard min_np unit the autoscale floor
        must respect (docs/elastic.md)."""
        n = 1
        for role, size in self.dims:
            if role != "dp":
                n *= size
        return n

    def coords(self, rank: int) -> dict:
        """Role -> index of a flat rank, row-major over ``dims`` (the
        mesh is built by reshaping the device list, so rank r sits at
        the r-th row-major cell: the LAST declared axis varies
        fastest)."""
        if not 0 <= int(rank) < self.total:
            raise ValueError(
                f"rank {rank} outside the {self.total}-rank spec "
                f"{self.describe()!r}")
        rem = int(rank)
        rev = []
        for role, size in reversed(self.dims):
            rev.append((role, rem % size))
            rem //= size
        return dict(reversed(rev))

    def role_label(self, rank: int) -> str:
        """Compact ``"dp1/pp0/tp1"`` coordinate label for a rank —
        stamped onto step reports, pod-metric series, black boxes and
        autoscale decisions so attribution names the role, not just a
        number."""
        return "/".join(f"{r}{i}" for r, i in self.coords(rank).items())

    def replica_of(self, rank: int) -> int:
        """The dp-replica index a rank belongs to (0 when the spec has
        no dp axis) — the grouping key for role-aware straggler
        scoring: 1F1B stalls a whole replica collectively, so scoring
        compares REPLICAS and convicts within one."""
        return self.coords(rank).get("dp", 0)

    # -- mesh / routing -----------------------------------------------

    def mesh(self, devices: Optional[Sequence] = None):
        """The N-D jax Mesh with role-named axes over ``devices``
        (default: the live backend's device list, mesh order). The
        spec must factor the device count exactly — a silent partial
        mesh would drop ranks from the reduction."""
        import jax
        import numpy as np

        devs = list(devices) if devices is not None else list(
            jax.devices())
        if self.total != len(devs):
            raise ValueError(
                f"parallel spec {self.describe()!r} covers {self.total} "
                f"devices but {len(devs)} are available (dp*pp*tp must "
                "factor the world size exactly)")
        arr = np.array(devs).reshape(tuple(s for _, s in self.dims))
        return jax.sharding.Mesh(arr, self.roles)

    def grad_route(self, wires=None):
        """The WirePlan the gradient allreduce runs over — the ``dp``
        axes ONLY, fast axis first (activation traffic rides the pp
        axis, tp combines via pmean; neither belongs in the gradient
        reduction). ``wires`` optionally maps axis -> wire dtype
        (``{"dp": "int8"}``). Returns None when there is no dp axis
        (pure pp x tp — nothing to reduce)."""
        from ..ops.collectives import AxisPhase, WirePlan

        axes = self.dp_axes
        if not axes:
            return None
        wires = wires or {}
        # dims are slow -> fast; WirePlan wants fast first.
        return WirePlan(tuple(AxisPhase(a, wires.get(a, "none"))
                              for a in reversed(axes)))

    def data_spec(self):
        """PartitionSpec for a batch argument: leading dim sharded over
        the dp axes, second (sequence) dim sharded over ``sp`` when
        present, replicated over pp/tp/ep (every stage and shard sees
        the replica's full microbatch stream; sp ranks each see a
        sequence slice of the SAME rows — docs/sequence.md)."""
        from jax.sharding import PartitionSpec as P

        axes = self.dp_axes
        batch = axes if len(axes) > 1 else (axes[0] if axes else None)
        if self.sp_axis is not None:
            return P(batch, self.sp_axis)
        return P(batch)


def hybrid_param_specs(pp_axis: str = "pp"):
    """shard_map spec prefix for the hybrid param tree
    ``{"stages": <stage-stacked>, "shared": <replicated>}``
    (models/gpt.stack_stage_params layout): stage-major leaves shard
    their leading axis over ``pp``; the shared (embedding/head) tree
    replicates."""
    from jax.sharding import PartitionSpec as P

    return {"stages": P(pp_axis), "shared": P()}


def hybrid_state_specs(state_shapes, pp_axis: str = "pp",
                       base_spec=None):
    """shard_map specs for an optimizer-state tree built over hybrid
    params: any leaf living under a ``"stages"`` key (optax state
    mirrors the param tree, so mu/nu/EF residuals all nest the
    stage-stacked subtree) shards its leading axis over ``pp``; every
    other leaf (step counters, guard scalars, shared-param moments)
    takes ``base_spec`` (default: replicated). Keyed on tree PATHS, not
    shapes — a hidden size that happens to equal the stage count can't
    mis-shard."""
    import jax
    from jax.sharding import PartitionSpec as P

    if base_spec is None:
        base_spec = P()

    def one(path, _leaf):
        for k in path:
            if getattr(k, "key", None) == "stages":
                return P(pp_axis)
        return base_spec

    return jax.tree_util.tree_map_with_path(one, state_shapes)
