"""Pipeline parallelism — 1F1B microbatching over a ``pp`` mesh axis.

The reference has no PP (SURVEY.md §2.7); the TPU-native implementation
uses the SPMD trick: every device holds ONE stage's parameters (stacked
stage-major and sharded over ``pp``), activations advance one stage per
tick via ``lax.ppermute``, and one ``lax.scan`` tick loop runs the
schedule so the pipeline fills and drains. Three surfaces:

- :func:`pipeline_apply` — GPipe fill-drain forward; autodiff through
  the loop gives the backward pipeline (at GPipe activation memory).
- :func:`pipeline_train_step_1f1b` — explicit interleaved 1F1B
  (PipeDream-flush): at most ``n_stages`` microbatch inputs live per
  device, backward recomputes each stage from its stored input.
- :func:`pipeline_accumulate_gradients` — the 1F1B schedule packaged
  as a drop-in for ``optim.accumulate_gradients``: same ``lax.scan``
  accumulation idiom (one compiled body per tick, fp32 accumulators,
  MEAN gradients over microbatches), same ``fn(params, *batch) ->
  (value, grads)`` contract — so ``DistributedOptimizer(...,
  parallel=spec)`` consumes the result unchanged and only the ``dp``
  axes run the gradient allreduce (docs/pipeline.md).

STAGE-BOUNDARY WIRE DTYPES: every ``ppermute`` send (forward
activations AND backward cotangents) can ride ``bf16`` or
block-scaled ``int8`` (``wire=`` / ``HVD_TPU_PP_WIRE``) through
:func:`~..ops.collectives.wired_ppermute` — the int8 path carries the
straight-through-VJP pattern from the MoE dispatch, so autodiff
through a quantized send keeps gradients flowing. Per-compiled-program
wire bytes are stamped into
``hvd_tpu_pipeline_activation_bytes_total{wire,axis}`` (per-device
planned bytes: ticks x payload — the ``planned_per_compile`` basis of
the mesh-router counters), which is how the schedule's wire mix is
PROVEN: activation bytes appear only on the pp axis, gradient-reduce
bytes only on the dp axes.

Megatron's VIRTUAL-STAGE interleaving (v chunks per device, bubble / v)
is deliberately NOT implemented: under lockstep SPMD every device
executes the same traced program every tick, so a device would pay v
gated forward evals + v recompute-VJPs per tick whether or not its
chunks are scheduled — the bubble saved is smaller than the dummy work
added for every v > 1. Virtual stages pay off in MPMD runtimes where
idle slots cost nothing; on a TPU mesh the 1F1B memory bound (this
module) plus XLA's latency-hiding scheduler is the right trade.
"""

from __future__ import annotations

import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..common import metrics as metrics_lib

# Telemetry (docs/metrics.md, docs/pipeline.md): stage-boundary send
# bytes, computed at TRACE time from the static schedule (ticks x
# payload x wire cost) — the planned_per_compile basis shared with the
# mesh-router allreduce counters, and the activation half of the
# per-axis byte accounting the hybrid acceptance test asserts.
_METRICS_ON = metrics_lib.enabled()
_M_ACT_BYTES = metrics_lib.counter(
    "hvd_tpu_pipeline_activation_bytes_total",
    "pipeline stage-boundary bytes on the wire (forward activations + "
    "backward cotangents) by wire format and mesh axis — per-device "
    "planned bytes per compiled schedule (ticks x payload; int8 "
    "includes the per-4096-block fp32 scales)",
    labels=("wire", "axis"))


def _resolve_pp_wire(explicit: Optional[str]) -> str:
    """None -> the configured default (``HVD_TPU_PP_WIRE`` /
    ``init(pp_wire=)``, falling back to ``"none"``); an explicit value
    always wins."""
    if explicit is not None:
        return explicit
    from ..common import basics

    if basics.is_initialized():
        return basics.context().config.pp_wire or "none"
    from ..common.config import _env

    return _env("PP_WIRE") or "none"


def _count_send_bytes(axis_name: str, wire: str, nelems: int,
                      itemsize: int, sends: int) -> None:
    if not _METRICS_ON or sends <= 0 or nelems <= 0:
        return
    from ..ops.collectives import _wire_elem_bytes

    _M_ACT_BYTES.labels(wire=wire, axis=axis_name).inc(
        float(sends) * float(nelems) * _wire_elem_bytes(wire, itemsize))


def _send(x, axis_name, perm, wire, key, salt):
    """One stage-boundary hop in the schedule's wire format. ``salt``
    may be a traced tick index — ``fold_in`` accepts traced data, so
    every tick's stochastic rounding draws an independent key inside
    the scan body."""
    if wire == "none":
        return lax.ppermute(x, axis_name, perm)
    from ..ops.collectives import wired_ppermute

    kk = None if key is None else jax.random.fold_in(key, salt)
    return wired_ppermute(x, axis_name, perm, wire=wire, key=kk)


def pipeline_apply(stage_fn: Callable, stage_params, x_micro,
                   axis_name: str = "pp", wire: Optional[str] = None,
                   key=None):
    """Run microbatches through the stage pipeline (GPipe fill-drain).

    Args:
      stage_fn: (params, activation (B, ...)) -> activation — the SAME
        function on every device (stages must share a signature; stack
        heterogeneous stages as homogeneous blocks, the standard SPMD
        pipelining restriction).
      stage_params: this device's stage parameters (already sharded over
        ``axis_name`` outside, e.g. in_specs=P("pp")).
      x_micro: (n_micro, B, ...) microbatches; only stage 0's copy is
        consumed (other devices may pass zeros of the same shape).
      wire: stage-boundary send format (None -> ``HVD_TPU_PP_WIRE``;
        ``"none"``/``"bf16"``/``"int8"`` — int8 sends carry the
        straight-through VJP, so autodiff through the loop still
        trains). Forward sends are stamped into the activation byte
        counter; the autodiff transpose adds the mirror-image backward
        sends at the same cost.

    Returns (n_micro, B, ...) outputs of the LAST stage (valid on stage
    n-1; other devices return garbage — select with
    :func:`select_last_stage` outside).
    """
    wire = _resolve_pp_wire(wire)
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    n_micro = x_micro.shape[0]
    state_shape = x_micro.shape[1:]
    total = n_micro + n - 1
    _count_send_bytes(axis_name, wire, math.prod(state_shape),
                      jnp.dtype(x_micro.dtype).itemsize, total)

    # j sends to j+1 (stage order); stage 0 receives nothing meaningful.
    perm = [(j, (j + 1) % n) for j in range(n)]

    outs0 = jnp.zeros((n_micro,) + state_shape, x_micro.dtype)
    carry0 = jnp.zeros(state_shape, x_micro.dtype)

    def body(loop, t):
        carry, outs = loop
        # Stage 0 injects microbatch t (while available); others use the
        # activation received on the previous tick.
        mb = x_micro[jnp.minimum(t, n_micro - 1)]
        inp = jnp.where(idx == 0, mb, carry)
        out = stage_fn(stage_params, inp)
        # Last stage records its output for microbatch (t - (n-1)).
        w = t - (n - 1)
        valid = (w >= 0) & (w < n_micro)
        outs = lax.cond(
            valid,
            lambda o: lax.dynamic_update_index_in_dim(
                o, out, jnp.maximum(w, 0), 0),
            lambda o: o, outs)
        nxt = _send(out, axis_name, perm, wire, key, t)
        return (nxt, outs), None

    (_, outs), _ = lax.scan(body, (carry0, outs0), jnp.arange(total))
    return outs


def pipeline_train_step_1f1b(stage_fn: Callable, loss_fn: Callable,
                             stage_params, x_micro, y_micro,
                             axis_name: str = "pp",
                             wire: Optional[str] = None, key=None):
    """One training step under a REAL 1F1B (PipeDream-flush) schedule.

    Unlike :func:`pipeline_apply` + autodiff (GPipe semantics: all
    forwards, then all backwards — activation memory grows with
    ``n_micro``), this interleaves one backward between forwards in
    steady state, so at most ``n_stages`` microbatch activations are
    live per device (the 1F1B memory bound). Backward recomputes the
    stage forward from the stored INPUT activation (Megatron-style
    activation recomputation), so only inputs are buffered.

    Lockstep SPMD schedule, one ``lax.scan`` tick loop of
    ``2*(n_micro + n_stages - 1)`` ticks:

    - stage ``s`` runs FORWARD of microbatch ``f`` at tick ``2f + s``
    - stage ``s`` runs BACKWARD of microbatch ``b`` at tick
      ``2b + 2n - 1 - s``

    The parities of the two tick sets differ on every device, so each
    device strictly alternates F-tick / B-tick in steady state — one
    forward, one backward. Activations advance via ``ppermute`` (+1)
    each tick; output cotangents flow via ``ppermute`` (-1), both in
    the schedule's ``wire`` format. An activation stored at tick
    ``2f+s`` is consumed at ``2f+2n-1-s`` and its ring slot
    (``f mod n``) is overwritten no earlier than ``2f+2n+s`` — the
    ``n``-slot ring is exactly the 1F1B bound.

    Args:
      stage_fn: (params, activation) -> activation, same signature on
        every device (homogeneous-stage SPMD restriction).
      loss_fn: (last_stage_out (B, ...), y (B, ...)) -> scalar loss for
        ONE microbatch.
      stage_params: this device's stage parameters (sharded over
        ``axis_name`` outside).
      x_micro: (n_micro, B, ...) microbatch inputs (consumed on stage 0).
      y_micro: (n_micro, B, ...) targets (consumed on the LAST stage).
      wire: stage-boundary send format for BOTH wavefronts (None ->
        ``HVD_TPU_PP_WIRE``). ``key`` makes int8 roundings stochastic.

    Returns ``(grads, loss_sum)``: grads = d(sum of microbatch losses)/
    d(stage_params) for THIS device's stage; loss_sum = the summed loss
    (valid on the last stage; use :func:`select_last_stage`-style psum
    or divide by ``n_micro`` for the mean). Every device pays one
    stage_fn eval + one recompute-VJP per tick (the standard cost of a
    lockstep SPMD schedule: unscheduled slots run gated dummy work).
    """
    carry = _run_1f1b(stage_fn, loss_fn, stage_params, x_micro, y_micro,
                      axis_name, _resolve_pp_wire(wire), key,
                      pre_fn=None, shared=None, fp32_accum=False)
    return carry["g_stage"], carry["loss_sum"]


def _run_1f1b(stage_fn, loss_fn, stage_params, x_micro, y_micro,
              axis_name, wire, key, pre_fn, shared, fp32_accum):
    """The shared 1F1B tick loop. With ``pre_fn``/``shared`` (the
    hybrid GPT form) stage 0 computes its input as
    ``pre_fn(shared, x_micro[f])`` (embedding), the last stage's loss is
    ``loss_fn(shared, out, y_micro[b])`` (final LN + tied head), and the
    carry accumulates ``g_shared`` contributions from both pipeline ends
    (psum over ``axis_name`` outside assembles them). ``fp32_accum``
    selects fp32 gradient/loss accumulators (the
    ``accumulate_gradients`` contract)."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    m = x_micro.shape[0]
    total = 2 * (m + n - 1)

    if pre_fn is not None:
        act_s = jax.eval_shape(
            pre_fn, shared, jax.tree.map(lambda a: a[0], x_micro))
        state_shape, act_dtype = act_s.shape, act_s.dtype
    else:
        state_shape, act_dtype = x_micro.shape[1:], x_micro.dtype

    # Both wavefronts (activations down, cotangents up) ride the wire
    # every tick.
    _count_send_bytes(axis_name, wire, math.prod(state_shape),
                      jnp.dtype(act_dtype).itemsize, 2 * total)

    fwd_perm = [(j, (j + 1) % n) for j in range(n)]
    bwd_perm = [(j, (j - 1) % n) for j in range(n)]

    def zeros_acc(t):
        if not fp32_accum:
            return jax.tree.map(jnp.zeros_like, t)
        return jax.tree.map(
            lambda s: jnp.zeros(
                jnp.shape(s), jnp.float32
                if jnp.issubdtype(jnp.asarray(s).dtype, jnp.floating)
                else jnp.asarray(s).dtype), t)

    def acc_add(acc, new, gate):
        def one(a, x):
            x = jnp.where(gate, x, jnp.zeros_like(x))
            if fp32_accum and jnp.issubdtype(
                    jnp.asarray(a).dtype, jnp.floating):
                x = x.astype(jnp.float32)
            return a + x

        return jax.tree.map(one, acc, new)

    carry0 = {
        "carry_f": jnp.zeros(state_shape, act_dtype),
        "carry_b": jnp.zeros(state_shape, act_dtype),
        "acts": jnp.zeros((n,) + state_shape, act_dtype),
        "g_stage": zeros_acc(stage_params),
        "loss_sum": jnp.zeros((), jnp.float32),
    }
    if pre_fn is not None:
        carry0["g_shared"] = zeros_acc(shared)

    def body(carry, t):
        # ---- forward slot: microbatch f at tick 2f + idx -------------
        tf_ = t - idx
        f = jnp.clip(tf_ // 2, 0, m - 1)
        do_f = (tf_ >= 0) & (tf_ % 2 == 0) & (tf_ // 2 < m)
        mb_f = jax.tree.map(lambda a: a[f], x_micro)
        inp0 = pre_fn(shared, mb_f) if pre_fn is not None else mb_f
        inp = jnp.where(idx == 0, inp0, carry["carry_f"])
        out_f = stage_fn(stage_params, inp)
        acts = lax.cond(
            do_f,
            lambda a: lax.dynamic_update_index_in_dim(a, inp, f % n, 0),
            lambda a: a, carry["acts"])

        # ---- backward slot: microbatch b at tick 2b + 2n - 1 - idx ---
        tb_ = t - (2 * n - 1 - idx)
        b = jnp.clip(tb_ // 2, 0, m - 1)
        do_b = (tb_ >= 0) & (tb_ % 2 == 0) & (tb_ // 2 < m)
        inp_b = acts[b % n]
        out_b, vjp_fn = jax.vjp(stage_fn, stage_params, inp_b)
        y_b = jax.tree.map(lambda a: a[b], y_micro)
        if pre_fn is not None:
            loss_b, (g_head, g_last) = jax.value_and_grad(
                lambda sh, o: loss_fn(sh, o, y_b),
                argnums=(0, 1))(shared, out_b)
        else:
            loss_b, g_last = jax.value_and_grad(
                lambda o: loss_fn(o, y_b))(out_b)
        g_out = jnp.where(idx == n - 1, g_last,
                          carry["carry_b"].astype(g_last.dtype))
        dp, dx = vjp_fn(g_out.astype(out_b.dtype))

        new = {
            "g_stage": acc_add(carry["g_stage"], dp, do_b),
            "loss_sum": carry["loss_sum"] + jnp.where(
                do_b & (idx == n - 1), loss_b.astype(jnp.float32), 0.0),
            "acts": acts,
        }
        if pre_fn is not None:
            # Shared-parameter gradients accrue at BOTH pipeline ends:
            # the head/final-LN grads on the last stage, and the
            # embedding grads on stage 0 by chaining this tick's input
            # cotangent through a pre_fn recompute (the same
            # recompute-from-stored-input trade as the stage backward).
            g_sh = acc_add(carry["g_shared"], g_head,
                           do_b & (idx == n - 1))
            mb_b = jax.tree.map(lambda a: a[b], x_micro)
            _, vjp_pre = jax.vjp(lambda sh: pre_fn(sh, mb_b), shared)
            (g_pre,) = vjp_pre(dx.astype(act_dtype))
            new["g_shared"] = acc_add(g_sh, g_pre, do_b & (idx == 0))

        # ---- advance the two wavefronts ------------------------------
        new["carry_f"] = _send(out_f, axis_name, fwd_perm, wire, key,
                               2 * t)
        new["carry_b"] = _send(dx.astype(act_dtype), axis_name,
                               bwd_perm, wire, key, 2 * t + 1)
        return new, None

    carry, _ = lax.scan(body, carry0, jnp.arange(total))
    return carry


def _resolve_accum(accum_steps):
    from ..optim import _resolve_accum_steps

    return _resolve_accum_steps(accum_steps)


def pipeline_accumulate_gradients(stage_fn: Callable, loss_fn: Callable,
                                  accum_steps: Optional[int] = None,
                                  axis_name: str = "pp",
                                  pre_fn: Optional[Callable] = None,
                                  wire: Optional[str] = None,
                                  key=None,
                                  remat_policy: Optional[str] = None):
    """The 1F1B schedule as a drop-in ``accumulate_gradients``: wrap the
    stage pipeline into a microbatched ``value_and_grad``.

    Rides the same ``lax.scan`` accumulation pattern as
    :func:`~..optim.accumulate_gradients` (one compiled body per tick,
    fp32 accumulators, MEAN gradients over the ``accum_steps``
    microbatches — the microbatch structure gradient accumulation
    already pays for IS the pipeline schedule) and returns the same
    ``fn(params, *batch) -> (value, grads)`` contract, so the result
    feeds ``DistributedOptimizer.update`` unchanged: only the ``dp``
    axes reduce gradients, the ``pp`` axis carries ONLY the
    stage-boundary activation/cotangent sends (in ``wire`` dtype), and
    one collective round / guard agreement / EF advance runs per
    effective step (docs/pipeline.md).

    Two forms, selected by ``pre_fn``:

    WITHOUT ``pre_fn`` (homogeneous-chain form): ``params`` is this
    device's stage parameters, ``loss_fn(out, y_mb) -> scalar``, batch
    is ``(x, y)`` whose leading dim is ``accum_steps * microbatch``.

    WITH ``pre_fn`` (the hybrid GPT form): ``params`` is the dict
    ``{"stages": <this device's stage params>, "shared": <replicated
    embedding/head params>}`` (models/gpt.stack_stage_params layout);
    stage 0 computes its input as ``pre_fn(shared, x_mb)`` (embedding)
    and the last stage's loss is ``loss_fn(shared, out, y_mb)`` (final
    LN + weight-tied head). Shared-parameter gradients accrue at both
    pipeline ends and are psum-assembled over ``axis_name`` before
    returning, so the returned ``grads["shared"]`` is replicated across
    pp and the returned ``grads["stages"]`` is per-stage — exactly the
    tree ``DistributedOptimizer(parallel=...)`` expects.

    The returned loss is the MEAN microbatch loss, replicated across
    the pp axis (psum of the last stage's masked sum); gradients are
    the MEAN over microbatches, matching the accumulation-equivalence
    contract (bitwise-pinned against the single-device
    ``accumulate_gradients`` reference in tests/test_pipeline.py).

    ``remat_policy`` wraps ``stage_fn`` in ``jax.checkpoint``
    (``optim.resolve_remat_policy`` names) — largely redundant under
    1F1B (backward already recomputes each stage from its stored
    input) but it composes for stages whose internals want a finer
    policy. ``wire``/``key`` select the stage-boundary send format
    (None -> ``HVD_TPU_PP_WIRE``) and stochastic-rounding key.
    """
    k = _resolve_accum(accum_steps)
    wire = _resolve_pp_wire(wire)
    from ..optim import _split_microbatches, resolve_remat_policy

    _, wrap, jax_policy = resolve_remat_policy(remat_policy)
    sfn = jax.checkpoint(stage_fn, policy=jax_policy) if wrap \
        else stage_fn

    def accum_fn(params, x, y):
        x_micro, y_micro = _split_microbatches((x, y), k)
        if pre_fn is not None:
            stage_params, shared = params["stages"], params["shared"]
        else:
            stage_params, shared = params, None
        carry = _run_1f1b(sfn, loss_fn, stage_params, x_micro, y_micro,
                          axis_name, wire, key, pre_fn, shared,
                          fp32_accum=True)

        def mean_like(acc, template):
            return jax.tree.map(
                lambda a, s: (a / k).astype(jnp.asarray(s).dtype)
                if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)
                else a, acc, template)

        g_stage = mean_like(carry["g_stage"], stage_params)
        # Loss lives on the last stage only; the masked psum replicates
        # it (same lowering as collectives.broadcast).
        loss = lax.psum(carry["loss_sum"], axis_name) / k
        if pre_fn is None:
            return loss, g_stage
        # Shared grads: stage 0 holds the embedding half, the last
        # stage the head half, middle stages zeros — one psum over pp
        # assembles the full tree identically on every stage.
        g_shared = jax.tree.map(lambda a: lax.psum(a, axis_name),
                                carry["g_shared"])
        g_shared = mean_like(g_shared, shared)
        return loss, {"stages": g_stage, "shared": g_shared}

    return accum_fn


def select_last_stage(outs, axis_name: str = "pp"):
    """Broadcast the final-stage outputs to every pp device (psum of the
    masked value — same lowering as collectives.broadcast)."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == n - 1, outs, jnp.zeros_like(outs))
    return lax.psum(masked, axis_name)
