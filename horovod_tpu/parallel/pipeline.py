"""Pipeline parallelism — GPipe-style microbatching over a ``pp`` axis.

The reference has no PP (SURVEY.md §2.7); the TPU-native implementation
uses the SPMD trick: every device holds ONE stage's parameters (stacked
stage-major and sharded over ``pp``), activations advance one stage per
tick via ``lax.ppermute``, and a ``lax.fori_loop`` runs
``n_micro + n_stages - 1`` ticks so the pipeline fills and drains. Autodiff
through the loop gives the backward pipeline for free (at GPipe-style
activation memory; pair with ``jax.checkpoint`` on the stage fn to trade
FLOPs for memory).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(stage_fn: Callable, stage_params, x_micro,
                   axis_name: str = "pp"):
    """Run microbatches through the stage pipeline.

    Args:
      stage_fn: (params, activation (B, ...)) -> activation — the SAME
        function on every device (stages must share a signature; stack
        heterogeneous stages as homogeneous blocks, the standard SPMD
        pipelining restriction).
      stage_params: this device's stage parameters (already sharded over
        ``axis_name`` outside, e.g. in_specs=P("pp")).
      x_micro: (n_micro, B, ...) microbatches; only stage 0's copy is
        consumed (other devices may pass zeros of the same shape).

    Returns (n_micro, B, ...) outputs of the LAST stage (valid on stage
    n-1; other devices return garbage — select with axis_index outside).
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    n_micro = x_micro.shape[0]
    state_shape = x_micro.shape[1:]
    total = n_micro + n - 1

    # j sends to j+1 (stage order); stage 0 receives nothing meaningful.
    perm = [(j, (j + 1) % n) for j in range(n)]

    outs0 = jnp.zeros((n_micro,) + state_shape, x_micro.dtype)
    carry0 = jnp.zeros(state_shape, x_micro.dtype)

    def body(t, loop):
        carry, outs = loop
        # Stage 0 injects microbatch t (while available); others use the
        # activation received on the previous tick.
        mb = x_micro[jnp.minimum(t, n_micro - 1)]
        inp = jnp.where(idx == 0, mb, carry)
        out = stage_fn(stage_params, inp)
        # Last stage records its output for microbatch (t - (n-1)).
        w = t - (n - 1)
        valid = (w >= 0) & (w < n_micro)
        outs = lax.cond(
            valid,
            lambda o: lax.dynamic_update_index_in_dim(
                o, out, jnp.maximum(w, 0), 0),
            lambda o: o, outs)
        nxt = lax.ppermute(out, axis_name, perm)
        return nxt, outs

    _, outs = lax.fori_loop(0, total, body, (carry0, outs0))
    return outs


def select_last_stage(outs, axis_name: str = "pp"):
    """Broadcast the final-stage outputs to every pp device (psum of the
    masked value — same lowering as collectives.broadcast)."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == n - 1, outs, jnp.zeros_like(outs))
    return lax.psum(masked, axis_name)
