"""Pipeline parallelism — GPipe-style microbatching over a ``pp`` axis.

The reference has no PP (SURVEY.md §2.7); the TPU-native implementation
uses the SPMD trick: every device holds ONE stage's parameters (stacked
stage-major and sharded over ``pp``), activations advance one stage per
tick via ``lax.ppermute``, and a ``lax.fori_loop`` runs
``n_micro + n_stages - 1`` ticks so the pipeline fills and drains. Autodiff
through the loop gives the backward pipeline for free (at GPipe-style
activation memory; pair with ``jax.checkpoint`` on the stage fn to trade
FLOPs for memory).

Two schedules are provided: :func:`pipeline_apply` (GPipe fill-drain,
autodiff backward) and :func:`pipeline_train_step_1f1b` (explicit
interleaved 1F1B). Megatron's VIRTUAL-STAGE interleaving (v chunks per
device, bubble ÷ v) is deliberately NOT implemented: under lockstep
SPMD every device executes the same traced program every tick, so a
device would pay v gated forward evals + v recompute-VJPs per tick
whether or not its chunks are scheduled — the bubble saved is smaller
than the dummy work added for every v > 1. Virtual stages pay off in
MPMD runtimes where idle slots cost nothing; on a TPU mesh the 1F1B
memory bound (this module) plus XLA's latency-hiding scheduler is the
right trade.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(stage_fn: Callable, stage_params, x_micro,
                   axis_name: str = "pp"):
    """Run microbatches through the stage pipeline.

    Args:
      stage_fn: (params, activation (B, ...)) -> activation — the SAME
        function on every device (stages must share a signature; stack
        heterogeneous stages as homogeneous blocks, the standard SPMD
        pipelining restriction).
      stage_params: this device's stage parameters (already sharded over
        ``axis_name`` outside, e.g. in_specs=P("pp")).
      x_micro: (n_micro, B, ...) microbatches; only stage 0's copy is
        consumed (other devices may pass zeros of the same shape).

    Returns (n_micro, B, ...) outputs of the LAST stage (valid on stage
    n-1; other devices return garbage — select with axis_index outside).
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    n_micro = x_micro.shape[0]
    state_shape = x_micro.shape[1:]
    total = n_micro + n - 1

    # j sends to j+1 (stage order); stage 0 receives nothing meaningful.
    perm = [(j, (j + 1) % n) for j in range(n)]

    outs0 = jnp.zeros((n_micro,) + state_shape, x_micro.dtype)
    carry0 = jnp.zeros(state_shape, x_micro.dtype)

    def body(t, loop):
        carry, outs = loop
        # Stage 0 injects microbatch t (while available); others use the
        # activation received on the previous tick.
        mb = x_micro[jnp.minimum(t, n_micro - 1)]
        inp = jnp.where(idx == 0, mb, carry)
        out = stage_fn(stage_params, inp)
        # Last stage records its output for microbatch (t - (n-1)).
        w = t - (n - 1)
        valid = (w >= 0) & (w < n_micro)
        outs = lax.cond(
            valid,
            lambda o: lax.dynamic_update_index_in_dim(
                o, out, jnp.maximum(w, 0), 0),
            lambda o: o, outs)
        nxt = lax.ppermute(out, axis_name, perm)
        return nxt, outs

    _, outs = lax.fori_loop(0, total, body, (carry0, outs0))
    return outs


def pipeline_train_step_1f1b(stage_fn: Callable, loss_fn: Callable,
                             stage_params, x_micro, y_micro,
                             axis_name: str = "pp"):
    """One training step under a REAL 1F1B (PipeDream-flush) schedule.

    Unlike :func:`pipeline_apply` + autodiff (GPipe semantics: all
    forwards, then all backwards — activation memory grows with
    ``n_micro``), this interleaves one backward between forwards in
    steady state, so at most ``n_stages`` microbatch activations are
    live per device (the 1F1B memory bound). Backward recomputes the
    stage forward from the stored INPUT activation (Megatron-style
    activation recomputation), so only inputs are buffered.

    Lockstep SPMD schedule, one global tick loop of
    ``2*(n_micro + n_stages - 1)`` ticks:

    - stage ``s`` runs FORWARD of microbatch ``f`` at tick ``2f + s``
    - stage ``s`` runs BACKWARD of microbatch ``b`` at tick
      ``2b + 2n - 1 - s``

    The parities of the two tick sets differ on every device, so each
    device strictly alternates F-tick / B-tick in steady state — one
    forward, one backward. Activations advance via ``ppermute`` (+1)
    each tick; output cotangents flow via ``ppermute`` (-1). An
    activation stored at tick ``2f+s`` is consumed at ``2f+2n-1-s`` and
    its ring slot (``f mod n``) is overwritten no earlier than
    ``2f+2n+s`` — the ``n``-slot ring is exactly the 1F1B bound.

    Args:
      stage_fn: (params, activation) -> activation, same signature on
        every device (homogeneous-stage SPMD restriction).
      loss_fn: (last_stage_out (B, ...), y (B, ...)) -> scalar loss for
        ONE microbatch.
      stage_params: this device's stage parameters (sharded over
        ``axis_name`` outside).
      x_micro: (n_micro, B, ...) microbatch inputs (consumed on stage 0).
      y_micro: (n_micro, B, ...) targets (consumed on the LAST stage).

    Returns ``(grads, loss_sum)``: grads = d(sum of microbatch losses)/
    d(stage_params) for THIS device's stage; loss_sum = the summed loss
    (valid on the last stage; use :func:`select_last_stage`-style psum
    or divide by ``n_micro`` for the mean). Every device pays one
    stage_fn eval + one recompute-VJP per tick (the standard cost of a
    lockstep SPMD schedule: unscheduled slots run gated dummy work).
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    m = x_micro.shape[0]
    state_shape = x_micro.shape[1:]
    total = 2 * (m + n - 1)

    fwd_perm = [(j, (j + 1) % n) for j in range(n)]
    bwd_perm = [(j, (j - 1) % n) for j in range(n)]

    acts0 = jnp.zeros((n,) + state_shape, x_micro.dtype)
    carry_f0 = jnp.zeros(state_shape, x_micro.dtype)
    carry_b0 = jnp.zeros(state_shape, x_micro.dtype)
    grads0 = jax.tree.map(jnp.zeros_like, stage_params)
    loss0 = jnp.zeros((), jnp.float32)

    def body(t, loop):
        carry_f, carry_b, acts, grads, loss_sum = loop

        # ---- forward slot: microbatch f at tick 2f + idx -------------
        tf_ = t - idx
        f = jnp.clip(tf_ // 2, 0, m - 1)
        do_f = (tf_ >= 0) & (tf_ % 2 == 0) & (tf_ // 2 < m)
        inp = jnp.where(idx == 0, x_micro[f], carry_f)
        out_f = stage_fn(stage_params, inp)
        acts = lax.cond(
            do_f,
            lambda a: lax.dynamic_update_index_in_dim(a, inp, f % n, 0),
            lambda a: a, acts)

        # ---- backward slot: microbatch b at tick 2b + 2n - 1 - idx ---
        tb_ = t - (2 * n - 1 - idx)
        b = jnp.clip(tb_ // 2, 0, m - 1)
        do_b = (tb_ >= 0) & (tb_ % 2 == 0) & (tb_ // 2 < m)
        inp_b = acts[b % n]
        out_b, vjp_fn = jax.vjp(stage_fn, stage_params, inp_b)
        loss_b, g_last = jax.value_and_grad(
            lambda o: loss_fn(o, y_micro[b]))(out_b)
        g_out = jnp.where(idx == n - 1, g_last,
                          carry_b.astype(g_last.dtype))
        dp, dx = vjp_fn(g_out.astype(out_b.dtype))
        grads = jax.tree.map(
            lambda G, d: G + jnp.where(do_b, d, jnp.zeros_like(d)),
            grads, dp)
        loss_sum = loss_sum + jnp.where(
            do_b & (idx == n - 1), loss_b.astype(jnp.float32), 0.0)

        # ---- advance the two wavefronts ------------------------------
        carry_f = lax.ppermute(out_f, axis_name, fwd_perm)
        carry_b = lax.ppermute(dx.astype(carry_b.dtype), axis_name,
                               bwd_perm)
        return carry_f, carry_b, acts, grads, loss_sum

    _, _, _, grads, loss_sum = lax.fori_loop(
        0, total, body, (carry_f0, carry_b0, acts0, grads0, loss0))
    return grads, loss_sum


def select_last_stage(outs, axis_name: str = "pp"):
    """Broadcast the final-stage outputs to every pp device (psum of the
    masked value — same lowering as collectives.broadcast)."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == n - 1, outs, jnp.zeros_like(outs))
    return lax.psum(masked, axis_name)
