"""Multi-axis mesh construction for composed parallelism strategies.

The reference is DP-only (SURVEY.md §2.7) with alltoall as the primitive
SP/EP would build on; this module is where the TPU rebuild makes those
strategies first-class: one ``jax.sharding.Mesh`` whose named axes carry
data (dp), fully-sharded-data (fsdp), tensor (tp), sequence (sp), expert
(ep) and pipeline (pp) parallelism. XLA lowers collectives per axis onto
ICI neighbors when the mesh axis order matches the physical topology —
keep fast axes (tp/sp) innermost (contiguous chips) and dp outermost
(can span DCN).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, PartitionSpec as P

from .spec import ROLES as _SPEC_ROLES

# Canonical axis order: slowest/outermost first — ONE ordering with
# ParallelSpec's slow-first Megatron placement (parallel/spec.py: dp
# tolerates the DCN hop, tp needs the fastest ICI; sp sits beside tp
# because ring K/V hops want ICI neighbors). ``fsdp`` is a mesh-only
# axis name (ZeRO-style param sharding — examples/fsdp_train.py), not
# a ParallelSpec compute role.
AXIS_ORDER = ("dp", "pp", "fsdp", "ep", "sp", "tp")

# Drift guard (regression-tested in tests/test_parallel.py): every
# ParallelSpec role must have a placement here, so adding a role to
# spec.py without one fails at import — two sources of truth cannot
# silently diverge again (they did: the seed ordered pp before dp).
_missing = set(_SPEC_ROLES) - set(AXIS_ORDER)
if _missing:
    raise RuntimeError(
        f"parallel/mesh.AXIS_ORDER is missing ParallelSpec role(s) "
        f"{sorted(_missing)} — add a placement for them")
del _missing


def build_mesh(axes: Dict[str, int],
               devices: Optional[Sequence] = None) -> Mesh:
    """Build a mesh from {axis_name: size}. Product must equal the device
    count. Axes are laid out in AXIS_ORDER so tp/sp land on contiguous
    (ICI-adjacent) devices."""
    devs = list(devices) if devices is not None else jax.devices()
    sizes = []
    names = []
    for name in AXIS_ORDER:
        if name in axes:
            # Size-1 axes are kept: code written against P('dp', ...) and
            # lax.axis_index('dp') must keep working when a degree is
            # tuned down to 1.
            names.append(name)
            sizes.append(axes[name])
    unknown = set(axes) - set(AXIS_ORDER)
    if unknown:
        raise ValueError(f"unknown mesh axes: {unknown}; "
                         f"known: {AXIS_ORDER}")
    total = int(np.prod(sizes)) if sizes else 1
    if total != len(devs):
        raise ValueError(
            f"mesh axes {dict(zip(names, sizes))} product {total} != "
            f"device count {len(devs)}")
    if not names:
        names, sizes = ["dp"], [len(devs)]
    arr = np.array(devs).reshape(sizes)
    return Mesh(arr, tuple(names))


def data_spec(mesh: Mesh, batch_axes: Tuple[str, ...] = ("dp", "fsdp"),
              seq_axis: Optional[str] = "sp") -> P:
    """PartitionSpec for (batch, seq, ...) activations on this mesh."""
    present = [a for a in batch_axes if a in mesh.axis_names]
    parts = [tuple(present) if present else None]
    if seq_axis and seq_axis in mesh.axis_names:
        parts.append(seq_axis)
    return P(*parts)


def param_spec(mesh: Mesh, shard_axis: Optional[str] = "fsdp") -> P:
    """PartitionSpec for parameters: fully replicated unless fsdp is
    present (then dim 0 sharded, ZeRO-3 style)."""
    if shard_axis and shard_axis in mesh.axis_names:
        return P(shard_axis)
    return P()
