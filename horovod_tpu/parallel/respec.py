"""Elastic reshape solver — re-solving dp x pp x tp after capacity loss.

The elastic plane (common/elastic.py, runner/elastic_driver.py) treats
the world as a FLAT rank count: lose a host, rerun with ``np - slots``.
Under a hybrid :class:`~.spec.ParallelSpec` that is wrong twice over —
a lost host orphans an entire dp replica (its pp/tp peers hold param
shards nothing else has), and an arbitrary surviving count may admit NO
valid dp x pp x tp factorization at all (7 ranks cannot host a 2x2x2
mesh). This module makes the mesh shape a *survivable* degree of
freedom: given the DECLARED spec and the surviving capacity, it
deterministically re-solves the spec through an explicit preference
ladder (docs/elastic.md "hybrid worlds"):

``shed_dp``
    Drop whole data-parallel replicas first — the cheapest rung: the
    model still fits exactly, only throughput shrinks. Refuses to go
    below ``min_dp`` (``HVD_TPU_RESPEC_MIN_DP``).
``fold_pp``
    Fold pipeline stages onto fewer ranks (2 stages' params on 1 rank):
    ``pp`` drops to its largest proper divisor that fits, preferring
    the FEWEST folds. Memory per rank grows; the schedule shortens.
``fold_sp``
    Fold sequence shards onto fewer ranks (pp already folded to 1):
    ``sp`` drops to a divisor — per-rank activation memory grows
    linearly with the fold, but params stay replicated over sp, so the
    fold needs NO weight migration; that is why sp folds BEFORE tp
    drops (docs/sequence.md).
``drop_tp``
    Give up tensor-parallel width: ``tp`` drops to a smaller divisor,
    each rank holding wider weight slices.
``dp_only``
    Degraded-mode survival: every non-dp role collapses to 1 and the
    world runs as a flat dp mesh over whatever capacity remains.

Every rung yields a VALID mesh by construction (all sizes >= 1, folded
sizes divide the declared ones, total <= capacity); a rung that cannot
fit defers to the next. When capacity covers the declared spec the
solver answers ``keep`` — so capacity recovery re-solves back to the
declared shape through the same call.

Knobs (docs/elastic.md):

* ``HVD_TPU_RESPEC`` — enable the solver in the elastic control plane
  (default on whenever a parallel spec is active; ``0`` pins the
  declared mesh and the driver simply waits for capacity).
* ``HVD_TPU_RESPEC_ORDER`` — comma list of permitted rungs in
  preference order (default
  ``shed_dp,fold_pp,fold_sp,drop_tp,dp_only``); removing a rung
  forbids that degradation.
* ``HVD_TPU_RESPEC_MIN_DP`` — replica floor for the shed/fold/drop
  rungs (default 1); ``dp_only`` ignores it (it is the last resort).

Telemetry: ``hvd_tpu_respec_total{from,to}`` counts every applied
reshape (docs/metrics.md).

State migration rides the sharded-checkpoint machinery: the new world
restores the old world's CRC-verified shards with
``checkpoint.restore_sharded`` (reshard-on-restore remaps changed
shard grids piece-by-piece — no full gather), so ZeRO-per-stage
shards, int8_ef residuals and the guard's loss-scale scalar all land
on the re-solved mesh (docs/elastic.md).

Stdlib-only at import (the driver process has no jax session).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence, Tuple

from ..common import metrics as metrics_lib
from .spec import ParallelSpec

ENV_ENABLE = "HVD_TPU_RESPEC"
ENV_ORDER = "HVD_TPU_RESPEC_ORDER"
ENV_MIN_DP = "HVD_TPU_RESPEC_MIN_DP"

# The preference ladder, in its canonical (and default) order.
RUNGS = ("shed_dp", "fold_pp", "fold_sp", "drop_tp", "dp_only")

_M_RESPEC = metrics_lib.counter(
    "hvd_tpu_respec_total",
    "applied elastic mesh reshapes by (from,to) parallel spec",
    labels=("from", "to"))


@dataclasses.dataclass(frozen=True)
class RespecDecision:
    """One solver answer: the rung that fired (``keep`` when the
    declared spec still fits), the solved spec, and its world size."""

    action: str    # keep | shed_dp | fold_pp | fold_sp | drop_tp | dp_only
    spec: ParallelSpec
    np: int                  # spec.total — the world the driver assigns

    def describe(self) -> str:
        return f"{self.action}:{self.spec.describe()}"


def note_respec(prev: str, new: str) -> None:
    """Count an APPLIED reshape (called by the control plane when a
    solved spec actually replaces the running one)."""
    _M_RESPEC.labels(**{"from": prev, "to": new}).inc()


def respec_enabled(env=None) -> bool:
    env = os.environ if env is None else env
    raw = (env.get(ENV_ENABLE) or "").strip().lower()
    return raw not in ("0", "false", "no", "off")


def respec_order(env=None) -> Tuple[str, ...]:
    """The permitted rungs, validated — an unknown rung name raises
    (a typo'd order silently pinning the mesh would be worse)."""
    env = os.environ if env is None else env
    raw = env.get(ENV_ORDER)
    if not raw:
        return RUNGS
    rungs = tuple(p.strip() for p in raw.split(",") if p.strip())
    bad = [r for r in rungs if r not in RUNGS]
    if bad:
        raise ValueError(
            f"{ENV_ORDER}: unknown rung(s) {bad}; choose from {RUNGS}")
    return rungs


def respec_min_dp(env=None) -> int:
    env = os.environ if env is None else env
    try:
        return max(1, int(env.get(ENV_MIN_DP, "1")))
    except ValueError:
        return 1


def _divisors_desc(n: int) -> list:
    """Proper divisors of n, largest first (the fewest-folds order)."""
    return [d for d in range(n - 1, 0, -1) if n % d == 0]


def _rebuild(spec: ParallelSpec, sizes: dict) -> ParallelSpec:
    """The declared spec with per-role sizes overridden — role ORDER
    (slow -> fast placement) is preserved, so the solved mesh keeps
    the declared axis names and link placement."""
    return ParallelSpec(tuple((r, int(sizes.get(r, s)))
                              for r, s in spec.dims))


def solve_respec(spec: ParallelSpec, capacity: int,
                 min_dp: Optional[int] = None,
                 order: Optional[Sequence[str]] = None
                 ) -> Optional[RespecDecision]:
    """Deterministically re-solve ``spec`` for ``capacity`` surviving
    slots. Returns the first rung (in ``order``) that admits a valid
    mesh, or None when no permitted rung fits (capacity < 1, or the
    configured order forbids every viable degradation) — the caller
    then waits for capacity instead of reshaping.

    Invariants (property-tested in tests/test_respec.py): the returned
    spec's total is <= capacity, every size >= 1, pp/sp/tp sizes
    divide the declared ones, and the same (spec, capacity, knobs)
    always returns the same answer.
    """
    if min_dp is None:
        min_dp = respec_min_dp()
    rungs = tuple(order) if order is not None else respec_order()
    bad = [r for r in rungs if r not in RUNGS]
    if bad:
        raise ValueError(f"unknown respec rung(s) {bad}; choose from "
                         f"{RUNGS}")
    capacity = int(capacity)
    if capacity < 1:
        return None
    if capacity >= spec.total:
        return RespecDecision("keep", spec, spec.total)

    d = spec.size_of("dp")
    pp = spec.size_of("pp")
    tp = spec.size_of("tp")
    sp = spec.size_of("sp")
    # Non-dp, non-foldable block (ep and any size-1 declared roles):
    # the solver never degrades ep short of the dp_only rung.
    fixed = 1
    for role, size in spec.dims:
        if role not in ("dp", "pp", "tp", "sp"):
            fixed *= size

    def fit_dp(block: int) -> int:
        """Largest dp (<= declared) whose world fits the capacity."""
        return min(d, capacity // block) if block > 0 else 0

    for rung in rungs:
        if rung == "shed_dp":
            block = pp * tp * sp * fixed
            nd = fit_dp(block)
            if nd >= max(1, min_dp):
                return RespecDecision(
                    "shed_dp", _rebuild(spec, {"dp": nd}), nd * block)
        elif rung == "fold_pp":
            for npp in _divisors_desc(pp):
                block = npp * tp * sp * fixed
                nd = fit_dp(block)
                if nd >= max(1, min_dp):
                    return RespecDecision(
                        "fold_pp", _rebuild(spec, {"dp": nd, "pp": npp}),
                        nd * block)
        elif rung == "fold_sp":
            # Sequence shards fold with pp already folded flat —
            # fold_pp's npp=1 attempt (full sp) did not fit if we got
            # here. nsp=1 keeps FULL tp, which is exactly what
            # distinguishes this rung from drop_tp.
            for nsp in _divisors_desc(sp):
                block = nsp * tp * fixed
                nd = fit_dp(block)
                if nd >= max(1, min_dp):
                    return RespecDecision(
                        "fold_sp",
                        _rebuild(spec, {"dp": nd, "pp": 1, "sp": nsp}),
                        nd * block)
        elif rung == "drop_tp":
            for ntp in _divisors_desc(tp):
                if ntp == 1:
                    continue    # tp=1 with pp=sp=1 is the dp_only rung
                block = ntp * fixed
                nd = fit_dp(block)
                if nd >= max(1, min_dp):
                    return RespecDecision(
                        "drop_tp",
                        _rebuild(spec, {"dp": nd, "pp": 1, "sp": 1,
                                        "tp": ntp}),
                        nd * block)
        elif rung == "dp_only":
            sizes = {r: 1 for r, _ in spec.dims}
            sizes["dp"] = capacity
            return RespecDecision(
                "dp_only", _rebuild(spec, sizes), capacity)
    return None


def min_world(spec: ParallelSpec, min_dp: Optional[int] = None,
              order: Optional[Sequence[str]] = None) -> int:
    """The smallest world size the configured ladder can reshape down
    to — the driver's HARD wait floor under involuntary capacity loss
    (min_np keeps flooring VOLUNTARY evict/shrink decisions;
    docs/elastic.md)."""
    if min_dp is None:
        min_dp = respec_min_dp()
    rungs = tuple(order) if order is not None else respec_order()
    lo = spec.total
    for cap in range(spec.total, 0, -1):
        dec = solve_respec(spec, cap, min_dp=min_dp, order=rungs)
        if dec is None:
            break
        lo = dec.np if dec.np < lo else lo
    return lo
