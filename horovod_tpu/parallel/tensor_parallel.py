"""Tensor parallelism — Megatron-style column/row-parallel layers.

The reference has no TP (SURVEY.md §2.7: "Nothing shards weights within
an op"); on TPU it is a mesh axis away. The canonical transformer
pattern pairs the two shardings so one allreduce covers a whole MLP
block (or attention block):

  column-parallel W1 (out-features sharded, no comm)
      -> nonlinearity on the local shard
  row-parallel W2 (in-features sharded, psum the partial outputs)

These are per-rank functions for use inside shard_map over a ``tp``
axis; weights arrive already sharded (the caller shards with
P(..., "tp") / P("tp", ...) specs — XLA's GSPMD can do the same from
annotations, but the explicit form composes with this framework's
per-rank collectives and keeps the comm visible).
"""

from __future__ import annotations

from typing import Callable

import jax
from jax import lax


def column_parallel(x, w_shard, b_shard=None):
    """y_shard = x @ W[:, shard] (+ b[shard]) — out-features sharded
    over the tp axis; input replicated; NO communication."""
    y = x @ w_shard
    if b_shard is not None:
        y = y + b_shard
    return y


def row_parallel(x_shard, w_shard, axis_name: str = "tp", b=None):
    """y = psum_tp(x[shard] @ W[shard, :]) (+ b) — in-features sharded;
    each rank holds the matching activation shard; ONE allreduce
    produces the replicated output (the Megatron g-operator)."""
    y = lax.psum(x_shard @ w_shard, axis_name)
    if b is not None:
        y = y + b
    return y


def tp_mlp(x, w1_shard, b1_shard, w2_shard, b2,
           axis_name: str = "tp",
           activation: Callable = jax.nn.gelu):
    """The paired block: column-parallel in, row-parallel out — exactly
    one allreduce for the whole MLP regardless of width."""
    h = activation(column_parallel(x, w1_shard, b1_shard))
    return row_parallel(h, w2_shard, axis_name, b2)


def shard_column(w, axis_name: str = "tp"):
    """Slice a replicated (..., out) weight to this rank's out-feature
    shard — for initializing TP from a replicated checkpoint."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    if w.shape[-1] % n:
        raise ValueError(f"out dim {w.shape[-1]} not divisible by tp "
                         f"size {n} (a silent truncation would drop "
                         f"features)")
    chunk = w.shape[-1] // n
    return lax.dynamic_slice_in_dim(w, idx * chunk, chunk,
                                    axis=w.ndim - 1)


def shard_row(w, axis_name: str = "tp"):
    """Slice a replicated (in, out) weight to this rank's in-feature
    shard."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    if w.shape[0] % n:
        raise ValueError(f"in dim {w.shape[0]} not divisible by tp "
                         f"size {n}")
    chunk = w.shape[0] // n
    return lax.dynamic_slice_in_dim(w, idx * chunk, chunk, axis=0)


def shard_heads(w, num_heads: int, axis_name: str = "tp",
                fused: int = 1):
    """Slice the HEAD dimension of an attention projection parameter —
    the column-parallel sharding attention wants (contiguous
    ``shard_column`` slices would mix q/k/v in a fused kernel).

    ``w``: (..., fused * num_heads * head_dim), the last dim laid out
    as ``fused`` consecutive blocks (e.g. the GPT fused QKV kernel
    (h, 3h) with ``fused=3``, layout [q|k|v]) of ``num_heads`` heads
    each. Returns this rank's (..., fused, heads_local, head_dim)
    slice. Raises when ``num_heads`` does not divide over the axis or
    the last dim does not factor."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    if num_heads % n:
        raise ValueError(f"num_heads {num_heads} not divisible by tp "
                         f"size {n} (a silent truncation would drop "
                         "heads)")
    if w.shape[-1] % (fused * num_heads):
        raise ValueError(
            f"last dim {w.shape[-1]} does not factor as fused={fused} "
            f"x num_heads={num_heads} x head_dim")
    hl = num_heads // n
    hd = w.shape[-1] // (fused * num_heads)
    wr = w.reshape(w.shape[:-1] + (fused, num_heads, hd))
    return lax.dynamic_slice_in_dim(wr, idx * hl, hl, axis=wr.ndim - 2)


def shard_head_rows(w, num_heads: int, axis_name: str = "tp"):
    """Slice the head-major INPUT rows of an attention output
    projection (num_heads * head_dim, out) to this rank's
    (heads_local * head_dim, out) — the row-parallel partner of
    :func:`shard_heads` (pair with ``row_parallel``'s psum)."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    if num_heads % n:
        raise ValueError(f"num_heads {num_heads} not divisible by tp "
                         f"size {n}")
    if w.shape[0] % num_heads:
        raise ValueError(f"in dim {w.shape[0]} does not factor into "
                         f"{num_heads} heads")
    hl = num_heads // n
    hd = w.shape[0] // num_heads
    wr = w.reshape((num_heads, hd) + w.shape[1:])
    loc = lax.dynamic_slice_in_dim(wr, idx * hl, hl, axis=0)
    return loc.reshape((hl * hd,) + w.shape[1:])


def combine_slice_grads(grads, axis_name: str = "tp"):
    """Combine gradients of SLICE-used replicated params (those fed
    through :func:`shard_column` / :func:`shard_row`) taken with
    ``jax.grad`` inside ``shard_map(check_vma=False)``.

    Under per-rank semantics every tp rank computes its own copy of the
    loss, and :func:`row_parallel`'s psum transposes to a psum of
    cotangents — so each rank's slice-grad (nonzero only in its shard
    slice) arrives scaled by the axis size. ``pmean`` over the axis
    both assembles the disjoint slices and cancels that factor.

    Do NOT pass grads of params used replicated AFTER the psum (e.g.
    ``row_parallel``'s bias): those are already exact on every rank,
    and averaging them is a no-op while summing would scale by tp.
    Pinned against the unsharded step by
    tests/test_parallel.py::test_tp_manual_grad_combine_matches_unsharded.
    """
    return jax.tree.map(lambda v: lax.pmean(v, axis_name), grads)


def tp_attention_qkv(x, wq_shard, wk_shard, wv_shard, num_heads_local):
    """Column-parallel QKV: heads shard over tp (each rank computes its
    head subset); pair with a row-parallel output projection."""
    b, s, _ = x.shape

    def split(w):
        y = x @ w
        return y.reshape(b, s, num_heads_local, -1)

    return split(wq_shard), split(wk_shard), split(wv_shard)
