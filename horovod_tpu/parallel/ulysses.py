"""Ulysses-style sequence parallelism — all-to-all head scatter.

The reference's ``alltoall`` was added precisely for this class of use
(SURVEY.md §2.7: "the building block Ulysses-style SP would use"); here it
becomes a real capability. With sequence sharded over the ``sp`` axis and
H heads:

  1. all-to-all converts (B, S/n, H, D) -> (B, S, H/n, D): every device
     gathers the FULL sequence for a 1/n subset of heads;
  2. plain (or flash) attention runs per head subset with no masking
     complications — any attend fn works unchanged;
  3. the inverse all-to-all restores (B, S/n, H, D).

Two alltoalls per attention vs ring's n permute hops: Ulysses wins when
H >= n and ICI all-to-all bandwidth is good (intra-slice); ring wins for
very long S or when H < n. Both are provided; models select via
``attend_fn`` (models/bert.py).
"""

from __future__ import annotations

from typing import Callable, Optional

from jax import lax


def _a2a(x, axis_name, split_axis, concat_axis):
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def ulysses_attention(q, k, v, axis_name: str = "sp",
                      attend_fn: Optional[Callable] = None,
                      mask=None):
    """Attention over sequence-sharded q/k/v via head scatter.

    q/k/v: (B, S_local, H, D); H must be divisible by the axis size.
    attend_fn(q, k, v, mask) operates on full-sequence inputs
    (B, S, H/n, D) — defaults to models.bert.default_attend.
    """
    n = lax.axis_size(axis_name)
    h = q.shape[2]
    if h % n != 0:
        raise ValueError(f"num heads {h} not divisible by sp size {n}")
    if attend_fn is None:
        from ..models.bert import default_attend

        attend_fn = default_attend

    # (B, S/n, H, D) -> (B, S, H/n, D): split heads, gather sequence.
    qg = _a2a(q, axis_name, split_axis=2, concat_axis=1)
    kg = _a2a(k, axis_name, split_axis=2, concat_axis=1)
    vg = _a2a(v, axis_name, split_axis=2, concat_axis=1)

    og = attend_fn(qg, kg, vg, mask)

    # Inverse: (B, S, H/n, D) -> (B, S/n, H, D).
    return _a2a(og, axis_name, split_axis=1, concat_axis=2)


def ulysses_attend_fn(axis_name: str = "sp",
                      inner: Optional[Callable] = None) -> Callable:
    """Adapter producing an ``attend_fn`` for models.bert.Bert: drop-in
    sequence parallelism for any model that accepts attend_fn."""

    def attend(q, k, v, mask=None):
        return ulysses_attention(q, k, v, axis_name, inner, mask)

    return attend
