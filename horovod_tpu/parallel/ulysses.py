"""Ulysses-style sequence parallelism — all-to-all head scatter.

The reference's ``alltoall`` was added precisely for this class of use
(SURVEY.md §2.7: "the building block Ulysses-style SP would use"); here it
becomes a real capability. With sequence sharded over the ``sp`` axis and
H heads:

  1. all-to-all converts (B, S/n, H, D) -> (B, S, H/n, D): every device
     gathers the FULL sequence for a 1/n subset of heads;
  2. plain (or flash) attention runs per head subset with no masking
     complications — any attend fn works unchanged;
  3. the inverse all-to-all restores (B, S/n, H, D).

Two alltoalls per attention vs ring's n permute hops: Ulysses wins when
H >= n and ICI all-to-all bandwidth is good (intra-slice); ring wins for
very long S or when H < n. Both are provided; models select via
``attend_fn`` / ``GPT(seq_impl=)`` (models/bert.py, models/gpt.py).

Each head/sequence scatter rides the WIRED stack (docs/sequence.md):
lossy wires (``bf16``/``int8``) decompose the tiled exchange onto
``collectives.mesh_alltoall`` — block-scaled payloads, fp32 scales, and
the STRAIGHT-THROUGH gradient of ``_int8_a2a`` — so the scatter is
trainable through a quantized hop. The wire defaults from
``HVD_TPU_SEQ_WIRE`` / ``init(seq_wire=)``; exchange bytes stamp
``hvd_tpu_seq_kv_bytes_total{wire,axis}`` at trace time.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax


def _a2a_wired(x, axis_name, split_axis, concat_axis, wire,
               key=None, use_pallas=None):
    """Tiled ``lax.all_to_all`` in a wire format. ``"none"`` is the
    native exchange; lossy wires decompose the (split, concat) form
    onto the dim-0 :func:`collectives.mesh_alltoall` — reshape dim
    ``split_axis`` into ``(n, k)``, exchange source-major chunks, merge
    the received source dim into ``concat_axis`` — which is exactly the
    tiled semantics, so the three forms agree bit-for-bit at
    ``wire="none"`` precision."""
    n = lax.axis_size(axis_name)
    if n == 1:
        return x
    if wire == "none":
        return lax.all_to_all(x, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)
    from ..ops.collectives import AxisPhase, WirePlan, mesh_alltoall

    s, c = split_axis, concat_axis
    k = x.shape[s] // n
    xs = jnp.moveaxis(
        x.reshape(x.shape[:s] + (n, k) + x.shape[s + 1:]), s, 0)
    lead = xs.shape
    plan = WirePlan((AxisPhase(axis_name, wire),))
    got = mesh_alltoall(xs.reshape(n, -1), plan, key=key,
                        use_pallas=use_pallas).reshape(lead)
    out = jnp.moveaxis(got, 0, c)
    return out.reshape(out.shape[:c] + (n * out.shape[c + 1],)
                       + out.shape[c + 2:])


def _a2a(x, axis_name, split_axis, concat_axis):
    # Back-compat alias (pre-wire call sites and tests).
    return _a2a_wired(x, axis_name, split_axis, concat_axis, "none")


def ulysses_attention(q, k, v, axis_name: str = "sp",
                      attend_fn: Optional[Callable] = None,
                      mask=None,
                      wire: Optional[str] = None,
                      wire_key=None,
                      use_pallas=None):
    """Attention over sequence-sharded q/k/v via head scatter.

    q/k/v: (B, S_local, H, D); H must be divisible by the axis size.
    attend_fn(q, k, v, mask) operates on full-sequence inputs
    (B, S, H/n, D) — defaults to models.bert.default_attend.
    ``wire`` selects the exchange format (None ->
    :func:`ring_attention.resolve_seq_wire`); lossy wires round ONCE
    per scatter (4 per attention), unlike the ring's per-hop
    re-quantization — bounds in docs/sequence.md. ``wire_key`` makes
    int8 rounding stochastic (folded per scatter).
    """
    from .ring_attention import resolve_seq_wire

    wire = resolve_seq_wire(wire)
    n = lax.axis_size(axis_name)
    h = q.shape[2]
    if h % n != 0:
        raise ValueError(f"num heads {h} not divisible by sp size {n}")
    if attend_fn is None:
        from ..models.bert import default_attend

        attend_fn = default_attend

    def kk(j):
        return None if wire_key is None else jax.random.fold_in(
            wire_key, j)

    # Trace-time byte accounting: 4 scatters (q/k/v out, o back), each
    # keeping (n-1)/n of its buffer on the wire.
    from ..ops.collectives import count_seq_kv_bytes

    tot = 2 * int(q.size) + int(k.size) + int(v.size)
    count_seq_kv_bytes(axis_name, wire, tot // n, n,
                       q.dtype.itemsize, n - 1)

    # (B, S/n, H, D) -> (B, S, H/n, D): split heads, gather sequence.
    qg = _a2a_wired(q, axis_name, 2, 1, wire, kk(0), use_pallas)
    kg = _a2a_wired(k, axis_name, 2, 1, wire, kk(1), use_pallas)
    vg = _a2a_wired(v, axis_name, 2, 1, wire, kk(2), use_pallas)

    og = attend_fn(qg, kg, vg, mask)

    # Inverse: (B, S, H/n, D) -> (B, S/n, H, D).
    return _a2a_wired(og, axis_name, 1, 2, wire, kk(3), use_pallas)


def ulysses_attend_fn(axis_name: str = "sp",
                      inner: Optional[Callable] = None,
                      wire: Optional[str] = None,
                      wire_key=None) -> Callable:
    """Adapter producing an ``attend_fn`` for models.bert.Bert: drop-in
    sequence parallelism for any model that accepts attend_fn."""

    def attend(q, k, v, mask=None):
        return ulysses_attention(q, k, v, axis_name, inner, mask,
                                 wire=wire, wire_key=wire_key)

    return attend
