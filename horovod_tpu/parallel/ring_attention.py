"""Ring attention — sequence parallelism over the collective-permute ring.

The reference has NO sequence parallelism (SURVEY.md §5 "Long-context:
absent"); its closest primitive is the LOCAL/CROSS split + alltoall. This
module adds the capability the TPU-native way: Q/K/V are sharded along the
sequence dimension across the ``sp`` mesh axis; each device attends its
local Q block against K/V blocks that rotate around the ring via
``collectives.wired_ppermute`` (one ICI neighbor hop per step —
bandwidth-optimal, and XLA overlaps the permute with the attention math
of the current block). Softmax is computed online (flash-attention style
running max/denominator in fp32), so the full S×S score matrix never
materializes.

Every K/V hop rides the WIRED stack (docs/sequence.md): ``wire="none"``
sends the native dtype, ``"bf16"`` halves the bytes, ``"int8"`` sends
block-scaled payload + fp32 scales with a STRAIGHT-THROUGH gradient
(the PR 13 stage-boundary pattern — trainable through a quantized hop).
The wire defaults from ``HVD_TPU_SEQ_WIRE`` / ``init(seq_wire=)``; hop
bytes stamp ``hvd_tpu_seq_kv_bytes_total{wire,axis}`` at trace time.

Matches the blockwise/ring formulation of Liu et al. (Ring Attention,
2023) — see PAPERS.md.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def resolve_seq_wire(explicit: Optional[str] = None) -> str:
    """None -> the configured default (``HVD_TPU_SEQ_WIRE`` /
    ``init(seq_wire=)``, falling back to ``"none"``); an explicit value
    always wins. Shared by the ring and Ulysses exchanges so one knob
    governs every sequence-parallel hop."""
    if explicit is not None:
        return explicit
    from ..common import basics

    if basics.is_initialized():
        return getattr(basics.context().config, "seq_wire",
                       None) or "none"
    from ..common.config import _env

    return _env("SEQ_WIRE") or "none"


def _seq_hop(x, axis_name, perm, wire, key, salt):
    """One K/V ring hop in the sequence wire format. ``salt`` may be a
    traced ring-step index — ``fold_in`` accepts traced data, so every
    hop's stochastic rounding draws an independent key inside the
    fori_loop body."""
    if wire == "none":
        return lax.ppermute(x, axis_name, perm)
    from ..ops.collectives import wired_ppermute

    kk = None if key is None else jax.random.fold_in(key, salt)
    return wired_ppermute(x, axis_name, perm, wire=wire, key=kk)


def _stamp_ring_bytes(axis_name: str, wire: str, n: int, nelems: int,
                      itemsize: int, hops: int) -> None:
    """Trace-time byte accounting for a full K/V rotation (``hops``
    wired hops of ``nelems`` elements each around the ``n``-rank
    ring)."""
    from ..ops.collectives import count_seq_kv_bytes

    count_seq_kv_bytes(axis_name, wire, nelems, n, itemsize, hops)


def _block_attend(q, k, v, m, l, o, mask=None):
    """One online-softmax accumulation step.

    q: (B, Sq, H, D); k/v: (B, Sk, H, D); m,l: (B, H, Sq) fp32 running
    max / denominator; o: (B, Sq, H, D) fp32 running numerator.
    """
    d = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    scores = scores / jnp.sqrt(jnp.asarray(d, jnp.float32))
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)
    m_new = jnp.maximum(m, scores.max(axis=-1))
    correction = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    l_new = l * correction + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    o_new = o * correction.transpose(0, 2, 1)[..., None] + \
        pv.astype(jnp.float32)
    return m_new, l_new, o_new


def ring_attention(q, k, v, axis_name: str = "sp",
                   causal: bool = False,
                   mask=None,
                   use_flash: Optional[bool] = None,
                   wire: Optional[str] = None,
                   wire_key=None):
    """Attention over sequence-sharded q/k/v.

    Args:
      q, k, v: (B, S_local, H, D) — the local sequence shard on each
        device of the ``axis_name`` ring.
      causal: apply a causal mask over *global* positions.
      mask: optional (B, S_local) key mask for the LOCAL shard (1 =
        attend); it rotates around the ring alongside its K/V block
        (always at the native dtype — a 0/1 mask has nothing to
        compress).
      use_flash: run each ring step's block attention through the Pallas
        flash kernel (ops/flash_attention.py) and combine blocks via
        their logsumexp — auto on TPU, jnp blockwise math elsewhere.
      wire: K/V hop wire format (``None`` -> :func:`resolve_seq_wire`).
        int8 re-quantizes a block on EVERY hop, so the error grows with
        ring distance — bounds in docs/sequence.md.
      wire_key: PRNG key for stochastic int8 rounding (folded per hop).

    Returns (B, S_local, H, D) attention output for the local Q block.
    """
    wire = resolve_seq_wire(wire)
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, s, h, d = q.shape

    if use_flash is not False and _ring_flash_available(q, use_flash):
        return _ring_attention_flash(q, k, v, axis_name, causal, mask,
                                     use_flash, wire, wire_key)

    m = jnp.full((b, h, s), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, s), jnp.float32)
    o = jnp.zeros((b, s, h, d), jnp.float32)

    q_pos = idx * s + jnp.arange(s)
    # mask is a TRACE-TIME value: the no-mask path carries no extra ring
    # traffic and skips the where entirely (same zero-cost property the
    # flash path keeps).
    has_mask = mask is not None
    key_mask = (mask.astype(jnp.float32) if has_mask
                else jnp.zeros((b, 0), jnp.float32))

    # Ring: each step, device j hands its current K/V block to j+1, so
    # after i steps device idx holds block (idx - i) mod n.
    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(i, carry):
        m, l, o, k_cur, v_cur, m_cur = carry
        src = (idx - i) % n
        blk = None
        if has_mask:
            blk = m_cur[:, None, None, :] > 0            # (B,1,1,Sk)
        if causal:
            k_pos = src * s + jnp.arange(s)
            cmask = (q_pos[:, None] >= k_pos[None, :])[None, None]
            blk = cmask if blk is None else blk & cmask
        m, l, o = _block_attend(q, k_cur, v_cur, m, l, o, blk)
        k_nxt = _seq_hop(k_cur, axis_name, perm, wire, wire_key, 2 * i)
        v_nxt = _seq_hop(v_cur, axis_name, perm, wire, wire_key,
                         2 * i + 1)
        m_nxt = (lax.ppermute(m_cur, axis_name, perm) if has_mask
                 else m_cur)
        return m, l, o, k_nxt, v_nxt, m_nxt

    _stamp_ring_bytes(axis_name, wire, n, int(k.size) + int(v.size),
                      k.dtype.itemsize, n)
    m, l, o, _, _, _ = lax.fori_loop(0, n, body,
                                     (m, l, o, k, v, key_mask))
    denom = l.transpose(0, 2, 1)[..., None]               # (B,S,H,1)
    out = o / jnp.maximum(denom, 1e-30)
    return out.astype(q.dtype)


def _ring_flash_available(q, use_flash: Optional[bool]) -> bool:
    from ..ops.flash_attention import flash_available

    return flash_available(q.shape[1], use_flash)


def _flash_block(q, k, v, mask, causal, use_flash):
    """One ring hop through the Pallas kernel -> (o_f32, lse). Shared by
    the contiguous and striped rings so the decline contract and the
    fp32 cast live in one place."""
    from ..ops.flash_attention import flash_attention_with_lse

    out = flash_attention_with_lse(q, k, v, mask=mask, causal=causal,
                                   use_pallas=use_flash)
    if out is None:  # flash_available() said yes — must not decline
        raise RuntimeError(
            "flash_attention_with_lse declined after flash_available() "
            "approved — the availability predicate and the kernel "
            "wrapper are out of sync")
    o_i, lse_i = out
    return o_i.astype(jnp.float32), lse_i


def _combine_partial(o, lse, o_i, lse_i):
    """Logsumexp-weighted merge of a new normalized partial (o_i, lse_i)
    into the running (o, lse) — the blockwise-softmax combine every ring
    variant shares."""
    lse_new = jnp.logaddexp(lse, lse_i)
    w_old = jnp.exp(lse - lse_new).transpose(0, 2, 1)[..., None]
    w_new = jnp.exp(lse_i - lse_new).transpose(0, 2, 1)[..., None]
    return o * w_old + o_i * w_new, lse_new


def _ring_attention_flash(q, k, v, axis_name: str, causal: bool, mask,
                          use_flash: Optional[bool],
                          wire: str = "none", wire_key=None):
    """Ring steps through the Pallas flash kernel: each block yields a
    normalized partial (o_i, lse_i); blocks combine with
    logaddexp-weighted averaging (both outputs differentiable, so the
    whole ring backprops through the kernels). The key-mask shard
    rotates with its K/V block."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, s, h, d = q.shape
    perm = [(j, (j + 1) % n) for j in range(n)]
    has_mask = mask is not None
    key_mask = (mask.astype(jnp.float32) if has_mask
                else jnp.zeros((b, 0), jnp.float32))

    def block(k_cur, v_cur, m_cur, block_causal: bool):
        return _flash_block(q, k_cur, v_cur,
                            m_cur if has_mask else None, block_causal,
                            use_flash)

    def body(i, carry):
        o, lse, k_cur, v_cur, m_cur = carry
        src = (idx - i) % n
        if causal:
            # Global causality at block granularity: earlier source
            # blocks are fully visible, the diagonal block is causal,
            # later blocks contribute nothing.
            o_i, lse_i = lax.cond(
                src == idx,
                lambda: block(k_cur, v_cur, m_cur, True),
                lambda: lax.cond(
                    src < idx,
                    lambda: block(k_cur, v_cur, m_cur, False),
                    lambda: (jnp.zeros((b, s, h, d), jnp.float32),
                             jnp.full((b, h, s), NEG_INF, jnp.float32))))
        else:
            o_i, lse_i = block(k_cur, v_cur, m_cur, False)
        o, lse = _combine_partial(o, lse, o_i, lse_i)
        k_nxt = _seq_hop(k_cur, axis_name, perm, wire, wire_key, 2 * i)
        v_nxt = _seq_hop(v_cur, axis_name, perm, wire, wire_key,
                         2 * i + 1)
        m_nxt = (lax.ppermute(m_cur, axis_name, perm) if has_mask
                 else m_cur)
        return o, lse, k_nxt, v_nxt, m_nxt

    _stamp_ring_bytes(axis_name, wire, n, int(k.size) + int(v.size),
                      k.dtype.itemsize, n)
    o0 = jnp.zeros((b, s, h, d), jnp.float32)
    lse0 = jnp.full((b, h, s), NEG_INF, jnp.float32)
    o, _, _, _, _ = lax.fori_loop(0, n, body, (o0, lse0, k, v, key_mask))
    return o.astype(q.dtype)


# -- striped attention (balanced causal ring) -------------------------------
#
# Contiguous-block causal ring attention is load-imbalanced: when source
# block src > idx nothing is visible, so low-index devices idle through
# most ring steps while device n-1 does n real block-attends — the ring
# still pays n hops of latency for ~n/2 hops of useful work. Striped
# attention (Brandon et al. 2023, PAPERS.md) fixes this with an
# interleaved layout: device r holds global positions {j*n + r}. Then
# for ANY (idx, src) pair the visible set is triangular over local
# indices — jq > jk always visible, jq == jk visible iff idx >= src,
# jq < jk never — so every device does the same ~s^2/2 work on every
# hop. ~2x wall-clock over contiguous causal at large n.


def stripe_layout(x, n: int):
    """Permute a contiguous global sequence (B, S, ...) into stripe
    order: new position ``r*(S/n) + j`` holds global token ``j*n + r``,
    so a plain contiguous S-axis shard over ``n`` devices hands device
    ``r`` the stripe {j*n + r}. Same shape in, same shape out."""
    b, s = x.shape[:2]
    xs = x.reshape((b, s // n, n) + x.shape[2:])       # [.., j, r, ..]
    return jnp.moveaxis(xs, 2, 1).reshape(x.shape)     # [.., r, j, ..]


def unstripe_layout(x, n: int):
    """Inverse of :func:`stripe_layout` (stripe order -> contiguous)."""
    b, s = x.shape[:2]
    xs = x.reshape((b, n, s // n) + x.shape[2:])       # [.., r, j, ..]
    return jnp.moveaxis(xs, 2, 1).reshape(x.shape)     # [.., j, r, ..]


def striped_positions(s_local: int, axis_name: str = "sp"):
    """(S_local,) global position ids of this device's stripe — pass to
    RoPE/position embeddings (models.gpt rope takes ``positions``)."""
    return jnp.arange(s_local) * lax.axis_size(axis_name) \
        + lax.axis_index(axis_name)


def striped_attention(q, k, v, axis_name: str = "sp",
                      use_flash: Optional[bool] = None,
                      wire: Optional[str] = None,
                      wire_key=None):
    """Causal attention over STRIPE-sharded q/k/v (see stripe_layout).

    q, k, v: (B, S_local, H, D) — this device's stripe. Returns the
    attention output for the local stripe. Causality is over GLOBAL
    positions; for non-causal attention striping buys nothing — use
    ring_attention. K/V hops ride the sequence wire (``wire``; None ->
    :func:`resolve_seq_wire`).
    """
    wire = resolve_seq_wire(wire)
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, s, h, d = q.shape
    perm = [(j, (j + 1) % n) for j in range(n)]
    _stamp_ring_bytes(axis_name, wire, n, int(k.size) + int(v.size),
                      k.dtype.itemsize, n)

    if use_flash is not False and _ring_flash_available(q, use_flash):
        def kernel_block(k_cur, v_cur, strict):
            """causal kernel over local indices; ``strict`` (idx < src)
            excludes the diagonal by rolling K/V one position right and
            masking the wrapped slot 0 — causal over the shifted keys is
            exactly jq >= jk+1 over the originals."""
            if strict:
                k_in = jnp.roll(k_cur, 1, axis=1)
                v_in = jnp.roll(v_cur, 1, axis=1)
                kmask = jnp.ones((b, s), jnp.float32).at[:, 0].set(0.0)
            else:
                k_in, v_in, kmask = k_cur, v_cur, None
            return _flash_block(q, k_in, v_in, kmask, True, use_flash)

        def body(i, carry):
            o, lse, k_cur, v_cur = carry
            src = (idx - i) % n
            o_i, lse_i = lax.cond(
                idx >= src,
                lambda: kernel_block(k_cur, v_cur, False),
                lambda: kernel_block(k_cur, v_cur, True))
            o, lse = _combine_partial(o, lse, o_i, lse_i)
            return (o, lse,
                    _seq_hop(k_cur, axis_name, perm, wire, wire_key,
                             2 * i),
                    _seq_hop(v_cur, axis_name, perm, wire, wire_key,
                             2 * i + 1))

        o0 = jnp.zeros((b, s, h, d), jnp.float32)
        lse0 = jnp.full((b, h, s), NEG_INF, jnp.float32)
        o, _, _, _ = lax.fori_loop(0, n, body, (o0, lse0, k, v))
        return o.astype(q.dtype)

    m = jnp.full((b, h, s), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, s), jnp.float32)
    o = jnp.zeros((b, s, h, d), jnp.float32)
    jq = jnp.arange(s)[:, None]
    jk = jnp.arange(s)[None, :]

    def body(i, carry):
        m, l, o, k_cur, v_cur = carry
        src = (idx - i) % n
        # global causality on stripes: (jq - jk) * n >= src - idx.
        blk = ((jq - jk) * n >= src - idx)[None, None]
        m, l, o = _block_attend(q, k_cur, v_cur, m, l, o, blk)
        return (m, l, o,
                _seq_hop(k_cur, axis_name, perm, wire, wire_key, 2 * i),
                _seq_hop(v_cur, axis_name, perm, wire, wire_key,
                         2 * i + 1))

    m, l, o, _, _ = lax.fori_loop(0, n, body, (m, l, o, k, v))
    denom = l.transpose(0, 2, 1)[..., None]
    out = o / jnp.maximum(denom, 1e-30)
    return out.astype(q.dtype)


def striped_attend_fn(axis_name: str = "sp",
                      wire: Optional[str] = None, wire_key=None):
    """attend_fn adapter for the causal models (models.gpt GPT): striped
    sequence-parallel attention. Pair with ``striped_positions`` for
    RoPE — the stripe's GLOBAL positions must feed the rotary angles."""

    def attend(q, k, v, mask=None):
        if mask is not None:
            raise NotImplementedError(
                "striped attention + key mask: rotate the mask with the "
                "stripes via ring_attention instead")
        return striped_attention(q, k, v, axis_name, wire=wire,
                                 wire_key=wire_key)

    return attend


def ring_attend_fn(axis_name: str = "sp", causal: bool = False,
                   wire: Optional[str] = None, wire_key=None):
    """Adapter producing an ``attend_fn`` for models.bert.Bert (the same
    drop-in hook ulysses_attend_fn provides): sequence-sharded ring
    attention for any model accepting attend_fn."""

    def attend(q, k, v, mask=None):
        # mask: (B, S_local) key mask for this shard; it rotates around
        # the ring with its K/V block.
        return ring_attention(q, k, v, axis_name, causal=causal,
                              mask=mask, wire=wire, wire_key=wire_key)

    return attend


# Single source of truth for the numerics oracle: the flash-attention
# module's reference (a superset — it also takes a key mask). Re-exported
# here because the SP tests historically import it from this module.
from ..ops.flash_attention import reference_attention  # noqa: E402,F401
