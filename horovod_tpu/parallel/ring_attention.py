"""Ring attention — sequence parallelism over the collective-permute ring.

The reference has NO sequence parallelism (SURVEY.md §5 "Long-context:
absent"); its closest primitive is the LOCAL/CROSS split + alltoall. This
module adds the capability the TPU-native way: Q/K/V are sharded along the
sequence dimension across the ``sp`` mesh axis; each device attends its
local Q block against K/V blocks that rotate around the ring via
``lax.ppermute`` (one ICI neighbor hop per step — bandwidth-optimal, and
XLA overlaps the permute with the attention math of the current block).
Softmax is computed online (flash-attention style running max/denominator
in fp32), so the full S×S score matrix never materializes.

Matches the blockwise/ring formulation of Liu et al. (Ring Attention,
2023) — see PAPERS.md.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _block_attend(q, k, v, m, l, o, mask=None):
    """One online-softmax accumulation step.

    q: (B, Sq, H, D); k/v: (B, Sk, H, D); m,l: (B, H, Sq) fp32 running
    max / denominator; o: (B, Sq, H, D) fp32 running numerator.
    """
    d = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    scores = scores / jnp.sqrt(jnp.asarray(d, jnp.float32))
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)
    m_new = jnp.maximum(m, scores.max(axis=-1))
    correction = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    l_new = l * correction + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    o_new = o * correction.transpose(0, 2, 1)[..., None] + \
        pv.astype(jnp.float32)
    return m_new, l_new, o_new


def ring_attention(q, k, v, axis_name: str = "sp",
                   causal: bool = False,
                   mask=None,
                   use_flash: Optional[bool] = None):
    """Attention over sequence-sharded q/k/v.

    Args:
      q, k, v: (B, S_local, H, D) — the local sequence shard on each
        device of the ``axis_name`` ring.
      causal: apply a causal mask over *global* positions.
      mask: optional (B, S_local) key mask for the LOCAL shard (1 =
        attend); it rotates around the ring alongside its K/V block.
      use_flash: run each ring step's block attention through the Pallas
        flash kernel (ops/flash_attention.py) and combine blocks via
        their logsumexp — auto on TPU, jnp blockwise math elsewhere.

    Returns (B, S_local, H, D) attention output for the local Q block.
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, s, h, d = q.shape

    if use_flash is not False and _ring_flash_available(q, use_flash):
        return _ring_attention_flash(q, k, v, axis_name, causal, mask,
                                     use_flash)

    m = jnp.full((b, h, s), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, s), jnp.float32)
    o = jnp.zeros((b, s, h, d), jnp.float32)

    q_pos = idx * s + jnp.arange(s)
    # mask is a TRACE-TIME value: the no-mask path carries no extra ring
    # traffic and skips the where entirely (same zero-cost property the
    # flash path keeps).
    has_mask = mask is not None
    key_mask = (mask.astype(jnp.float32) if has_mask
                else jnp.zeros((b, 0), jnp.float32))

    # Ring: each step, device j hands its current K/V block to j+1, so
    # after i steps device idx holds block (idx - i) mod n.
    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(i, carry):
        m, l, o, k_cur, v_cur, m_cur = carry
        src = (idx - i) % n
        blk = None
        if has_mask:
            blk = m_cur[:, None, None, :] > 0            # (B,1,1,Sk)
        if causal:
            k_pos = src * s + jnp.arange(s)
            cmask = (q_pos[:, None] >= k_pos[None, :])[None, None]
            blk = cmask if blk is None else blk & cmask
        m, l, o = _block_attend(q, k_cur, v_cur, m, l, o, blk)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        m_nxt = (lax.ppermute(m_cur, axis_name, perm) if has_mask
                 else m_cur)
        return m, l, o, k_nxt, v_nxt, m_nxt

    m, l, o, _, _, _ = lax.fori_loop(0, n, body,
                                     (m, l, o, k, v, key_mask))
    denom = l.transpose(0, 2, 1)[..., None]               # (B,S,H,1)
    out = o / jnp.maximum(denom, 1e-30)
    return out.astype(q.dtype)


def _ring_flash_available(q, use_flash: Optional[bool]) -> bool:
    from ..ops.flash_attention import flash_available

    return flash_available(q.shape[1], use_flash)


def _ring_attention_flash(q, k, v, axis_name: str, causal: bool, mask,
                          use_flash: Optional[bool]):
    """Ring steps through the Pallas flash kernel: each block yields a
    normalized partial (o_i, lse_i); blocks combine with
    logaddexp-weighted averaging (both outputs differentiable, so the
    whole ring backprops through the kernels). The key-mask shard
    rotates with its K/V block."""
    from ..ops.flash_attention import flash_attention_with_lse

    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, s, h, d = q.shape
    perm = [(j, (j + 1) % n) for j in range(n)]
    has_mask = mask is not None
    key_mask = (mask.astype(jnp.float32) if has_mask
                else jnp.zeros((b, 0), jnp.float32))

    def block(k_cur, v_cur, m_cur, block_causal: bool):
        out = flash_attention_with_lse(q, k_cur, v_cur,
                                       mask=m_cur if has_mask else None,
                                       causal=block_causal,
                                       use_pallas=use_flash)
        if out is None:  # flash_available() said yes — must not decline
            raise RuntimeError(
                "flash_attention_with_lse declined after "
                "flash_available() approved — the availability "
                "predicate and the kernel wrapper are out of sync")
        o_i, lse_i = out
        return o_i.astype(jnp.float32), lse_i

    def body(i, carry):
        o, lse, k_cur, v_cur, m_cur = carry
        src = (idx - i) % n
        if causal:
            # Global causality at block granularity: earlier source
            # blocks are fully visible, the diagonal block is causal,
            # later blocks contribute nothing.
            o_i, lse_i = lax.cond(
                src == idx,
                lambda: block(k_cur, v_cur, m_cur, True),
                lambda: lax.cond(
                    src < idx,
                    lambda: block(k_cur, v_cur, m_cur, False),
                    lambda: (jnp.zeros((b, s, h, d), jnp.float32),
                             jnp.full((b, h, s), NEG_INF, jnp.float32))))
        else:
            o_i, lse_i = block(k_cur, v_cur, m_cur, False)
        lse_new = jnp.logaddexp(lse, lse_i)
        w_old = jnp.exp(lse - lse_new).transpose(0, 2, 1)[..., None]
        w_new = jnp.exp(lse_i - lse_new).transpose(0, 2, 1)[..., None]
        o = o * w_old + o_i * w_new
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        m_nxt = (lax.ppermute(m_cur, axis_name, perm) if has_mask
                 else m_cur)
        return o, lse_new, k_nxt, v_nxt, m_nxt

    o0 = jnp.zeros((b, s, h, d), jnp.float32)
    lse0 = jnp.full((b, h, s), NEG_INF, jnp.float32)
    o, _, _, _, _ = lax.fori_loop(0, n, body, (o0, lse0, k, v, key_mask))
    return o.astype(q.dtype)


def ring_attend_fn(axis_name: str = "sp", causal: bool = False):
    """Adapter producing an ``attend_fn`` for models.bert.Bert (the same
    drop-in hook ulysses_attend_fn provides): sequence-sharded ring
    attention for any model accepting attend_fn."""

    def attend(q, k, v, mask=None):
        # mask: (B, S_local) key mask for this shard; it rotates around
        # the ring with its K/V block.
        return ring_attention(q, k, v, axis_name, causal=causal,
                              mask=mask)

    return attend


# Single source of truth for the numerics oracle: the flash-attention
# module's reference (a superset — it also takes a key mask). Re-exported
# here because the SP tests historically import it from this module.
from ..ops.flash_attention import reference_attention  # noqa: E402,F401
