"""Expert parallelism — top-k gated MoE with all-to-all dispatch.

The reference exposes alltoall with negotiated uneven splits
(operations.cc:1020-1081) as the primitive "added for such use cases"
(SURVEY.md §2.7 EP); this module provides the actual capability: GShard
style top-2 gating with capacity, einsum-based dispatch/combine (one-hot
matmuls — MXU-friendly, no scatters), and ``lax.all_to_all`` to route
token blocks to the devices holding each expert along the ``ep`` axis.
Static capacity keeps every shape compile-time constant (the XLA analog
of the reference's recv-split negotiation: instead of negotiating sizes at
runtime, overflow tokens are dropped and weighted by the combine tensor).
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def top2_gating(logits, capacity: int):
    """GShard top-2 gating.

    logits: (T, E) router outputs for T local tokens.
    Returns (dispatch (T, E, C) bool-ish, combine (T, E, C) weights,
    aux_loss scalar).
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    g1_idx = jnp.argmax(probs, axis=-1)                       # (T,)
    g1 = jnp.take_along_axis(probs, g1_idx[:, None], -1)[:, 0]
    probs_wo1 = probs * (1.0 - jax.nn.one_hot(g1_idx, e))
    g2_idx = jnp.argmax(probs_wo1, axis=-1)
    g2 = jnp.take_along_axis(probs_wo1, g2_idx[:, None], -1)[:, 0]

    # Load-balancing auxiliary loss (GShard eq. 4 style).
    me = probs.mean(axis=0)
    ce = jax.nn.one_hot(g1_idx, e).mean(axis=0)
    aux = (me * ce).sum() * e

    def positions(idx):
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.int32)      # (T, E)
        pos = jnp.cumsum(onehot, axis=0) - 1                  # pos in expert
        return onehot, (pos * onehot).sum(axis=-1)            # (T,E),(T,)

    oh1, pos1 = positions(g1_idx)
    # Second choice queues behind all first choices.
    count1 = oh1.sum(axis=0)                                  # (E,)
    oh2, pos2_raw = positions(g2_idx)
    pos2 = pos2_raw + jnp.take(count1, g2_idx)

    keep1 = pos1 < capacity
    keep2 = pos2 < capacity
    g1 = g1 * keep1
    g2 = g2 * keep2
    # Renormalize the surviving pair weights to sum to 1 (tokens whose
    # expert overflowed lose that share — the static-capacity analog of
    # dropped sends).
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    def one_dispatch(gate, idx, pos, keep):
        oh_e = jax.nn.one_hot(idx, e)                         # (T, E)
        oh_c = jax.nn.one_hot(pos, capacity)                  # (T, C)
        d = oh_e[:, :, None] * oh_c[:, None, :] * keep[:, None, None]
        return d, d * gate[:, None, None]

    d1, c1 = one_dispatch(g1, g1_idx, pos1, keep1)
    d2, c2 = one_dispatch(g2, g2_idx, pos2, keep2)
    dispatch = jnp.clip(d1 + d2, 0.0, 1.0)
    combine = c1 + c2
    return dispatch, combine, aux


def moe_layer(x, gate_w, expert_fn: Callable, num_experts: int,
              capacity_factor: float = 1.25,
              axis_name: str = "ep"):
    """One MoE layer with experts sharded over the ``ep`` axis.

    x: (T, D) local tokens on each ep device; gate_w: (D, E) router;
    expert_fn(e_idx, tokens (C_local_total, D)) -> same shape, applied to
    the LOCAL experts' token slabs (num_experts/n experts per device).

    Flow (GShard): gate -> dispatch einsum -> all_to_all (tokens to the
    device owning the expert) -> expert MLP -> all_to_all back -> combine.
    """
    n = lax.axis_size(axis_name)
    if num_experts % n != 0:
        raise ValueError(f"{num_experts} experts not divisible by ep={n}")
    e_local = num_experts // n
    t, d = x.shape
    capacity = int(capacity_factor * t * 2 / num_experts) or 1

    logits = x.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    dispatch, combine, aux = top2_gating(logits, capacity)

    # (T,D),(T,E,C) -> (E,C,D): expert-major slabs of dispatched tokens.
    slabs = jnp.einsum("td,tec->ecd", x.astype(jnp.float32),
                       dispatch).astype(x.dtype)
    # Route: each device keeps slabs for its local experts, receives the
    # matching slabs from every peer: (E,C,D) -> (E/n, n*C, D).
    slabs = slabs.reshape(n, e_local, capacity, d)
    routed = lax.all_to_all(slabs, axis_name, split_axis=0, concat_axis=0,
                            tiled=False)                  # (n, e_l, C, D)
    routed = routed.transpose(1, 0, 2, 3).reshape(e_local, n * capacity, d)

    outs = []
    for le in range(e_local):
        outs.append(expert_fn(le, routed[le]))
    expert_out = jnp.stack(outs)                           # (e_l, n*C, D)

    # Inverse route back to the token owners.
    back = expert_out.reshape(e_local, n, capacity, d).transpose(1, 0, 2, 3)
    back = lax.all_to_all(back, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)                     # (n, e_l, C, D)
    back = back.reshape(num_experts, capacity, d)

    y = jnp.einsum("ecd,tec->td", back.astype(jnp.float32), combine)
    return y.astype(x.dtype), aux
