"""Expert parallelism — top-k gated MoE with all-to-all dispatch.

The reference exposes alltoall with negotiated uneven splits
(operations.cc:1020-1081) as the primitive "added for such use cases"
(SURVEY.md §2.7 EP); this module provides the actual capability: GShard
style top-2 gating with capacity, einsum-based dispatch/combine (one-hot
matmuls — MXU-friendly, no scatters), and all-to-all routing of token
blocks to the devices holding each expert. Static capacity keeps every
shape compile-time constant (the XLA analog of the reference's
recv-split negotiation: instead of negotiating sizes at runtime,
overflow tokens are dropped and weighted by the combine tensor).

The dispatch/combine exchange is a first-class hot path (docs/moe.md),
peer to the allreduce stack:

* **wire compression** — ``wire="bf16"/"int8"`` carries the token
  payloads block-scaled on the wire (``collectives.compressed_alltoall``;
  activations, not reduced gradients, so no error feedback is needed —
  the per-element error is bounded by one cast/quantization step).
* **mesh routing** — ``route=`` decomposes the exchange into per-axis
  phases over a ``WirePlan`` (``collectives.mesh_alltoall``), e.g. fp32
  on the fast ICI axis and int8 on the slow DCN hop.
* **overlap pipelining** — ``overlap_chunks=k`` splits the capacity dim
  into ``k`` chunks and chains their exchanges with
  ``optimization_barrier`` (``common/overlap.py``) so the dispatch
  alltoall of chunk ``k+1`` is free to fly while the expert FFN of
  chunk ``k`` computes. Chunking along capacity is a pure reshape —
  numerics are unchanged (``expert_fn`` must therefore be token-wise:
  a map over token rows, like any MLP).
* **load telemetry** — ``return_stats=True`` adds a stats dict
  (dropped token-routes, demanded per-expert load); the host-side
  :func:`record_moe_stats` publishes it as the
  ``hvd_tpu_moe_{dropped_tokens,dropped_frac,expert_load}`` gauges.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..common import metrics as metrics_lib

_METRICS_ON = metrics_lib.enabled()
_M_DROPPED = metrics_lib.gauge(
    "hvd_tpu_moe_dropped_tokens",
    "token-routes dropped by capacity overflow in the most recently "
    "recorded MoE step (global count across the ep world; set by "
    "record_moe_stats from a moe_layer return_stats=True dict)")
_M_DROP_FRAC = metrics_lib.gauge(
    "hvd_tpu_moe_dropped_frac",
    "dropped token-routes as a fraction of all top-2 routes in the most "
    "recently recorded MoE step (the capacity-factor health number; "
    "docs/moe.md runbook)")
_M_LOAD = metrics_lib.gauge(
    "hvd_tpu_moe_expert_load",
    "demanded token-routes per expert (top-2 assignments INCLUDING "
    "dropped ones — the skew signal) in the most recently recorded MoE "
    "step", labels=("expert",))


def top2_gating(logits, capacity: int, noise=None):
    """GShard top-2 gating.

    logits: (T, E) router outputs for T local tokens.
    ``noise`` (optional, same shape) is added to the logits before
    gating — the noisy-gating jitter (Shazeer et al. 2017, GShard's
    input jitter): it decorrelates an untrained router's systematically
    skewed argmax so capacity overflow reflects genuine load, not init
    bias (docs/moe.md runbook).
    Returns (dispatch (T, E, C) bool-ish, combine (T, E, C) weights,
    aux_loss scalar).
    """
    if noise is not None:
        logits = logits + noise
    return _top2_gating_with_demand(logits, capacity)[:3]


def _top2_gating_with_demand(logits, capacity: int):
    """top2_gating plus the per-expert DEMANDED route counts (top-2
    assignments before the capacity cut — derived from the same one-hot
    selections the dispatch uses, so the load gauges can never drift
    from the actual routing)."""
    t, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    g1_idx = jnp.argmax(probs, axis=-1)                       # (T,)
    g1 = jnp.take_along_axis(probs, g1_idx[:, None], -1)[:, 0]
    probs_wo1 = probs * (1.0 - jax.nn.one_hot(g1_idx, e))
    g2_idx = jnp.argmax(probs_wo1, axis=-1)
    g2 = jnp.take_along_axis(probs_wo1, g2_idx[:, None], -1)[:, 0]

    # Load-balancing auxiliary loss (GShard eq. 4 style).
    me = probs.mean(axis=0)
    ce = jax.nn.one_hot(g1_idx, e).mean(axis=0)
    aux = (me * ce).sum() * e

    def positions(idx):
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.int32)      # (T, E)
        pos = jnp.cumsum(onehot, axis=0) - 1                  # pos in expert
        return onehot, (pos * onehot).sum(axis=-1)            # (T,E),(T,)

    oh1, pos1 = positions(g1_idx)
    # Second choice queues behind all first choices.
    count1 = oh1.sum(axis=0)                                  # (E,)
    oh2, pos2_raw = positions(g2_idx)
    pos2 = pos2_raw + jnp.take(count1, g2_idx)

    keep1 = pos1 < capacity
    keep2 = pos2 < capacity
    g1 = g1 * keep1
    g2 = g2 * keep2
    # Renormalize the surviving pair weights to sum to 1 (tokens whose
    # expert overflowed lose that share — the static-capacity analog of
    # dropped sends).
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    def one_dispatch(gate, idx, pos, keep):
        oh_e = jax.nn.one_hot(idx, e)                         # (T, E)
        oh_c = jax.nn.one_hot(pos, capacity)                  # (T, C)
        d = oh_e[:, :, None] * oh_c[:, None, :] * keep[:, None, None]
        return d, d * gate[:, None, None]

    d1, c1 = one_dispatch(g1, g1_idx, pos1, keep1)
    d2, c2 = one_dispatch(g2, g2_idx, pos2, keep2)
    dispatch = jnp.clip(d1 + d2, 0.0, 1.0)
    combine = c1 + c2
    demand = (oh1 + oh2).sum(axis=0).astype(jnp.float32)
    return dispatch, combine, aux, demand


def _resolve_plan(route):
    if route is None:
        return None
    from ..ops.collectives import WirePlan

    return WirePlan.resolve(route)


def ep_size(axis_name: Optional[str] = "ep", route=None) -> int:
    """Expert-parallel world size: the product of the route plan's axis
    sizes when ``route`` is given, else the size of ``axis_name`` (1
    with neither — the local, exchange-free MoE)."""
    plan = _resolve_plan(route)
    if plan is not None:
        n = 1
        for p in plan.phases:
            n *= lax.axis_size(p.axis)
        return n
    if axis_name is None:
        return 1
    return lax.axis_size(axis_name)


def ep_index(axis_name: Optional[str] = "ep", route=None):
    """This rank's expert-parallel index, SLOW-AXIS-MAJOR under a route
    plan (matching ``collectives.mesh_alltoall``'s global order) — the
    index an ``expert_fn`` uses to find its global expert ids."""
    plan = _resolve_plan(route)
    if plan is not None:
        idx = jnp.zeros((), jnp.int32)
        for p in reversed(plan.phases):        # slow axis first
            idx = idx * lax.axis_size(p.axis) + lax.axis_index(p.axis)
        return idx
    if axis_name is None:
        return jnp.zeros((), jnp.int32)
    return lax.axis_index(axis_name)


@jax.custom_vjp
def _chain_barrier(x, token):
    """Differentiable ``optimization_barrier``: the lax primitive has no
    VJP rule (it sits INSIDE the differentiated MoE layer, unlike the
    gradient-side chains in ``common/overlap.py``), so the custom rule
    barriers the cotangents too — the backward walk's exchanges get the
    same issue-order pinning as the forward's. Identity on values both
    ways; numerics untouched."""
    return lax.optimization_barrier((x, token))


def _chain_barrier_fwd(x, token):
    return lax.optimization_barrier((x, token)), None


def _chain_barrier_bwd(_, g):
    return lax.optimization_barrier(g)


_chain_barrier.defvjp(_chain_barrier_fwd, _chain_barrier_bwd)


def _capacity_bounds(capacity: int, chunks: int):
    """Static contiguous split of the capacity dim into ``chunks``
    segments (last may be shorter)."""
    chunks = max(1, min(int(chunks), capacity))
    step = -(-capacity // chunks)
    return [(lo, min(lo + step, capacity))
            for lo in range(0, capacity, step)]


def moe_layer(x, gate_w, expert_fn: Callable, num_experts: int,
              capacity_factor: float = 1.25,
              axis_name: Optional[str] = "ep",
              route=None, wire: str = "none", overlap_chunks: int = 1,
              key=None, use_pallas=None, return_stats: bool = False,
              router_noise_std: float = 0.0,
              quantize_min_bytes: Optional[int] = None):
    """One MoE layer with experts sharded over the expert-parallel world.

    x: (T, D) local tokens on each ep device; gate_w: (D, E) router;
    expert_fn(local_idx, tokens (rows, D)) -> same shape, applied to the
    LOCAL experts' token slabs (num_experts/n experts per device). With
    ``overlap_chunks > 1`` it is called once per capacity chunk, so it
    must be TOKEN-WISE (a pure map over token rows — any MLP is).

    Flow (GShard): gate -> dispatch einsum -> all_to_all (tokens to the
    device owning the expert) -> expert MLP -> all_to_all back ->
    combine. The exchanges ride the wire-compressed / mesh-routed
    alltoall family (module docstring; docs/moe.md):

    - ``axis_name`` — the flat ep axis; ``None`` (and no ``route``)
      selects the local, exchange-free layer (n = 1).
    - ``route`` — a ``WirePlan`` (or spec/name ``WirePlan.resolve``
      accepts): the exchange becomes ``mesh_alltoall`` over the plan's
      axes with PER-AXIS wire formats; the plan's wires win over
      ``wire``, and the ep world is the product of the plan's axes.
    - ``wire`` — flat-axis payload format: ``"none"``/``"bf16"``/
      ``"int8"``, or ``"auto"`` (int8 when the slab crosses the
      ``fusion.assign_alltoall_wire`` size threshold, bf16 below it;
      the threshold is ``quantize_min_bytes`` when given, else the
      configured ``quantize_min_bucket_bytes`` — the same
      HVD_TPU_QUANTIZE_MIN_BYTES knob the eager alltoall consults).
    - ``overlap_chunks`` — capacity-dim pipelining depth (1 = off).
    - ``key`` — stochastic rounding for int8 hops (folded per chunk
      and phase); ``return_stats`` — also return the load/drop stats
      dict for :func:`record_moe_stats`.
    - ``router_noise_std`` — noisy-gating jitter (needs ``key``): adds
      ``std * N(0, 1)`` to the router logits before top-2 selection;
      different ranks draw different noise (the key is folded with the
      ep index), so an untrained router's init bias stops masquerading
      as expert load (docs/moe.md).

    Returns ``(y, aux_loss)`` or ``(y, aux_loss, stats)``.
    """
    from ..ops import collectives as C

    plan = _resolve_plan(route)
    if plan is not None:
        psum_axes: Optional[Tuple[str, ...]] = plan.axis_names
        n = 1
        for p in plan.phases:
            n *= lax.axis_size(p.axis)
    elif axis_name is not None:
        n = lax.axis_size(axis_name)
        psum_axes = (axis_name,) if n > 1 else None
    else:
        n, psum_axes = 1, None
    if num_experts % n != 0:
        raise ValueError(f"{num_experts} experts not divisible by ep={n}")
    e_local = num_experts // n
    t, d = x.shape
    capacity = int(capacity_factor * t * 2 / num_experts) or 1

    if wire == "auto":
        from ..common import fusion as fusion_lib

        qmin = quantize_min_bytes
        if qmin is None:
            # Honor the configured threshold when the runtime is up —
            # the SAME knob the eager alltoall's "auto" consults
            # (HVD_TPU_QUANTIZE_MIN_BYTES); fall back to the module
            # default outside an initialized context.
            try:
                from ..common import basics

                if basics.is_initialized():
                    qmin = basics.context().config \
                        .quantize_min_bucket_bytes
            except Exception:  # noqa: BLE001 — default below
                qmin = None
        slab_bytes = (num_experts * capacity * d
                      * jnp.dtype(x.dtype).itemsize)
        wire = fusion_lib.assign_alltoall_wire(
            slab_bytes, qmin if qmin is not None
            else fusion_lib.A2A_QUANTIZE_MIN_BYTES)

    logits = x.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    if router_noise_std > 0.0 and key is not None:
        nk = jax.random.fold_in(jax.random.fold_in(key, 999),
                                ep_index(axis_name, route))
        logits = logits + router_noise_std * jax.random.normal(
            nk, logits.shape, jnp.float32)
    dispatch, combine, aux, demand = _top2_gating_with_demand(logits,
                                                              capacity)

    def exchange(buf, fold):
        kk = None if key is None else jax.random.fold_in(key, fold)
        if plan is not None:
            return C.mesh_alltoall(buf, plan, key=kk,
                                   use_pallas=use_pallas)
        if n == 1:
            return buf
        return C.compressed_alltoall(buf, axis_name, wire, key=kk,
                                     use_pallas=use_pallas)

    # (T,D),(T,E,C) -> (E,C,D): expert-major slabs of dispatched tokens,
    # viewed as (n, e_local, C, D) destination-major (slow-axis-major
    # global device order under a route plan — mesh_alltoall's order).
    slabs = jnp.einsum("td,tec->ecd", x.astype(jnp.float32),
                       dispatch).astype(x.dtype)
    slabs = slabs.reshape(n, e_local, capacity, d)

    # Dispatch exchanges, capacity-chunked and issue-order chained: the
    # barrier pins alltoall k before k+1 on the shared wire while each
    # chunk's expert compute depends only on its OWN routed slab — the
    # async-collective scheduler may then fly exchange k+1 under FFN k
    # (docs/overlap.md; inert on CPU, numerics unchanged either way).
    bounds = _capacity_bounds(capacity, overlap_chunks)
    routed = []
    token = None
    for ci, (lo, hi) in enumerate(bounds):
        ck = slabs[:, :, lo:hi].reshape(n * e_local * (hi - lo), d)
        if token is not None:
            ck, token = _chain_barrier(ck, token)
        r = exchange(ck, ci)
        routed.append((r, hi - lo))
        token = r

    # Expert FFN per chunk: (n, e_l, ck, D) -> (e_l, n*ck, D) slabs.
    expert_out = []
    for r, ck in routed:
        rr = r.reshape(n, e_local, ck, d).transpose(1, 0, 2, 3)
        rr = rr.reshape(e_local, n * ck, d)
        expert_out.append(jnp.stack(
            [expert_fn(le, rr[le]) for le in range(e_local)]))

    # Inverse route back to the token owners, chained the same way.
    backs = []
    token = None
    for ci, ((_, ck), eo) in enumerate(zip(routed, expert_out)):
        b = eo.reshape(e_local, n, ck, d).transpose(1, 0, 2, 3)
        b = b.reshape(n * e_local * ck, d)
        if token is not None:
            b, token = _chain_barrier(b, token)
        g = exchange(b, 100 + ci)
        backs.append(g.reshape(n, e_local, ck, d))
        token = g
    back = jnp.concatenate(backs, axis=2) if len(backs) > 1 else backs[0]
    back = back.reshape(num_experts, capacity, d)

    y = jnp.einsum("ecd,tec->td", back.astype(jnp.float32), combine)
    y = y.astype(x.dtype)
    if not return_stats:
        return y, aux

    # Load/drop stats (fp32, globally psum-ed over the ep world):
    # demanded load counts top-2 assignments BEFORE the capacity cut —
    # the hot-expert signal, taken from the gating's OWN one-hot
    # selections (noisy jitter included — it decided the routes) so the
    # gauges can never drift from the dispatched routing; kept counts
    # surviving routes.
    demanded = demand
    kept = dispatch.sum()
    routes = jnp.asarray(2.0 * t, jnp.float32)
    if psum_axes is not None:
        demanded = lax.psum(demanded, psum_axes)
        kept = lax.psum(kept, psum_axes)
        routes = lax.psum(routes, psum_axes)
    dropped = jnp.maximum(routes - kept, 0.0)
    stats = {"dropped_tokens": dropped,
             "dropped_frac": dropped / jnp.maximum(routes, 1.0),
             "expert_load": demanded,
             "routed_tokens": routes}
    return y, aux, stats


def record_moe_stats(stats) -> dict:
    """Publish a ``moe_layer(return_stats=True)`` stats dict to the
    Prometheus/podmon surface (host-side, once per observed step):
    ``hvd_tpu_moe_dropped_tokens`` / ``hvd_tpu_moe_dropped_frac``
    gauges plus one ``hvd_tpu_moe_expert_load{expert=}`` gauge per
    expert. Returns the plain-float dict (handy for BENCH/soak
    records)."""
    load = np.asarray(stats["expert_load"], np.float64).reshape(-1)
    out = {"dropped_tokens": float(stats["dropped_tokens"]),
           "dropped_frac": float(stats["dropped_frac"]),
           "expert_load": [float(v) for v in load]}
    if _METRICS_ON:
        _M_DROPPED.set(out["dropped_tokens"])
        _M_DROP_FRAC.set(out["dropped_frac"])
        for e, v in enumerate(load):
            _M_LOAD.labels(expert=str(e)).set(float(v))
    return out


def chaos_skew_gate(gate_w):
    """Chaos site ``moe_skew`` (docs/moe.md): when the installed fault
    plan fires, bias the router weights toward one hot expert —
    ``spec.target`` names the expert column (default 0), ``spec.scale``
    the logit boost (default 10). Host-side, applied to the router
    weight between steps (the ``integrity.chaos_poison`` pattern), so
    the skewed logits flow through the REAL gating/capacity path and
    the drop/load gauges must react. One global load + None check when
    no plan is installed."""
    from ..common import faults as faults_lib

    spec = faults_lib.maybe_moe_skew()
    if spec is None:
        return gate_w
    target = int(spec.target or 0)
    scale = spec.scale if spec.scale else 10.0
    g = jnp.asarray(gate_w)
    return g.at[..., target].add(jnp.asarray(scale, g.dtype))
