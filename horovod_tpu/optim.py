"""DistributedOptimizer — the gradient-averaging wrapper.

Reference equivalents: horovod/tensorflow/__init__.py:465-561
(DistributedOptimizer), :564-629 (DistributedGradientTape),
horovod/torch/optimizer.py:103-207 (per-grad async allreduce hooks), and the
local-gradient-aggregation helpers (tensorflow/gradient_aggregation.py:16)
for ``backward_passes_per_step > 1``.

TPU-native design: the optimizer is an ``optax.GradientTransformation``
wrapper meant to run *inside* the jitted SPMD step function, where the
reference's whole async machinery (hooks, handles, background thread) is
unnecessary — the gradients of every rank are produced by the same traced
program, so the wrapper simply inserts fused allreduces between ``grad()``
and ``update()`` and lets XLA overlap them with remaining backprop compute
(XLA's latency-hiding scheduler plays the role of Horovod's
background-thread overlap).

Also provides ``DistributedGradFn`` (the DistributedGradientTape analog):
wraps ``jax.grad``/``jax.value_and_grad`` results with the same reduction.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from .common import fusion as fusion_lib
from .common import integrity as integrity_lib
from .common import metrics as metrics_lib
from .common.integrity import (current_loss_scale, observe_guard)  # noqa: F401 — re-exported API
from .ops import collectives as C
from .ops.compression import NoneCompressor

# Unified telemetry (docs/metrics.md): host-side step timing. The
# grad/comm/apply split cannot be observed from inside one jitted step
# (XLA owns the schedule) — StepTimer below times phases at dispatch
# boundaries and bridges them into jax.profiler traces; AutotunedStepper
# records the end-to-end step wall time it already measures for tuning.
_METRICS_ON = metrics_lib.enabled()
_M_STEP = metrics_lib.histogram(
    "hvd_tpu_step_seconds",
    "end-to-end training step wall time (AutotunedStepper, blocked)")
_M_PHASE = metrics_lib.histogram(
    "hvd_tpu_step_phase_seconds",
    "per-phase step wall time from StepTimer (grad/comm/apply/...)",
    labels=("phase",))
_M_EF_NORM = metrics_lib.gauge(
    "hvd_tpu_ef_residual_norm",
    "global L2 norm of the error-feedback quantization residual "
    "(observe_ef_residual)")
_M_REBUILDS = metrics_lib.counter(
    "hvd_tpu_autotune_rebuilds_total",
    "step-function rebuilds triggered by autotuner point moves")
_M_ZERO_GATHER = metrics_lib.counter(
    "hvd_tpu_zero_gather_bytes_total",
    "bytes moved by the ZeRO sharded-training collectives, ring-"
    "accounted per device at trace time (docs/zero.md): kind=param "
    "is the stage-3 on-demand parameter all-gather, kind=grad the "
    "gradient reduce-scatter descent, kind=update the stage-1/2 "
    "update all-gather; wire/axis show which hop carried them",
    labels=("kind", "wire", "axis"))
_M_ZERO_RESIDENT = metrics_lib.gauge(
    "hvd_tpu_zero_param_bytes_resident",
    "at-rest parameter bytes resident per rank under the current "
    "ZeRO stage (stage 3 = 1/N bucket shards; stages 0-2 = full "
    "replica) — the memory-model number docs/zero.md derives",
    labels=("stage",))


class StepTimer:
    """Host-side step-phase breakdown — the grad/comm/apply split of
    docs/metrics.md. Each phase records into the
    ``hvd_tpu_step_phase_seconds`` histogram and, when the
    metrics↔timeline bridge is on (``HVD_TPU_METRICS_TRACE=1``), the
    same span is emitted as a ``jax.profiler.TraceAnnotation`` so it
    lines up with the device-side XLA trace.

    Because JAX dispatch is async, a phase only measures real work if
    its outputs are forced before the block exits — use :meth:`timed`
    (which blocks on the result) or block yourself inside ``phase``::

        st = hvd.StepTimer()
        grads = st.timed("grad", grad_fn, params, batch)
        reduced = st.timed("comm", hvd.grouped_allreduce, grads)
        with st.phase("apply"):
            params = optax.apply_updates(params, updates)
            jax.block_until_ready(params)

    Zero-cost when metrics are disabled (every call lands on the no-op
    singleton)."""

    def __init__(self, name: str = "hvd_step"):
        self.name = name

    def phase(self, phase: str):
        """Context manager timing one named phase."""
        return _M_PHASE.labels(phase=phase).time(
            annotation=f"{self.name}/{phase}"
            if metrics_lib.registry().trace_bridge else None)

    def timed(self, phase: str, fn, *args, **kwargs):
        """Run ``fn`` and block until its outputs are ready, recording
        the elapsed wall time under ``phase``."""
        with self.phase(phase):
            out = fn(*args, **kwargs)
            jax.block_until_ready(out)
        return out


def observe_ef_residual(state) -> Optional[float]:
    """Global L2 norm of an error-feedback residual (the ``_EFState`` /
    ``_EFShardState`` carried by the ``int8_ef`` surfaces), published as
    the ``hvd_tpu_ef_residual_norm`` gauge. Host-side — fetches the
    residual leaves, so call it at checkpoint/eval cadence, not every
    step. Walks ``.inner`` wrappers (the integrity ``_GuardedState``,
    the k>1 ``_AggState``) so arming the non-finite guard does not make
    the gauge go dark. Returns the norm, or None if ``state`` carries
    no residual."""
    residual, probe, hops = None, state, 0
    while probe is not None and hops < 8:
        residual = getattr(probe, "residual", None)
        if residual is not None:
            break
        probe = getattr(probe, "inner", None)
        hops += 1
    if residual is None:
        return None
    import numpy as np

    total = 0.0
    for leaf in jax.tree.leaves(residual):
        a = np.asarray(jax.device_get(leaf)).astype(np.float64)
        total += float((a * a).sum())
    norm = float(total ** 0.5)
    _M_EF_NORM.set(norm)
    return norm


def _check_reduce_safe(compression) -> None:
    if not getattr(compression, "reduce_safe", True):
        raise ValueError(
            f"{compression.__name__} is a wire-format compressor (per-block "
            "scales don't commute with summation) and cannot ride the "
            "gradient reduction directly; use a reduce-safe compression "
            "instead — Compression.int8_ef (quantized allreduce with error "
            "feedback, same 4x wire win) or Compression.fp16 / bf16 (cast)")


def _resolve_compression(compression):
    """Accept a Compressor class, a name ("bf16"/"int8_ef"/...), or None
    (=> the configured default, HVD_TPU_COMPRESSION / init(compression=),
    falling back to no compression). Pre-init, the env knob is read
    directly — an optimizer built at module scope before hvd.init()
    must not silently discard HVD_TPU_COMPRESSION (an init(compression=)
    override can only be seen after init, by construction)."""
    from .ops.compression import Compression

    if compression is None:
        from .common import basics

        if basics.is_initialized():
            name = basics.context().config.compression
        else:
            from .common.config import _env

            name = _env("COMPRESSION")
        if name:
            return Compression.by_name(name)
        return NoneCompressor
    if isinstance(compression, str):
        return Compression.by_name(compression)
    return compression


def _resolve_quantize_min_bytes(explicit: Optional[int] = None) -> int:
    if explicit is not None:
        return explicit
    from .common import basics

    if basics.is_initialized():
        return basics.context().config.quantize_min_bucket_bytes
    from .common.config import Config, _env_int

    return _env_int("QUANTIZE_MIN_BYTES", Config.quantize_min_bucket_bytes)


def _resolve_route(route, local_axis: str = "local",
                   cross_axis: str = "cross"):
    """Resolve a route value to a :class:`~.ops.collectives.WirePlan`
    (or None = flat axis). ``None`` consults the configured default
    (``HVD_TPU_ROUTE`` / ``init(route=)``); explicit values — a
    WirePlan, a spec string like ``"local:none,cross:int8"``, or a
    named route (``"flat"``/``"staged"``/``"staged_int8"``) — win."""
    if route is None:
        from .common import basics

        if basics.is_initialized():
            route = basics.context().config.route
        else:
            from .common.config import _env

            route = _env("ROUTE")
        if route is None:
            return None
    return C.WirePlan.resolve(route, local_axis, cross_axis)


def _resolve_parallel(parallel):
    """Coerce a ``parallel=`` value (ParallelSpec / dict / spec string)
    — EXPLICIT-ONLY, deliberately no ``HVD_TPU_PARALLEL`` consult here:
    the spec renames the reduction axes (``hvd`` -> ``dp``) and an env
    knob must never re-route existing call sites' collectives onto
    axes their mesh does not bind (the same contract as ``route=`` on
    the sharded surfaces). ``HVD_TPU_PARALLEL`` / ``init(parallel=)``
    feed the Context's mesh (``hvd.parallel_spec()``) and the tools,
    which pass the spec explicitly."""
    if parallel is None:
        return None
    from .parallel.spec import ParallelSpec

    return ParallelSpec.resolve(parallel)


def _combine_tp(grads, tp_axis):
    """pmean-combine slice gradients over one axis name or a tuple of
    them (tensor_parallel.combine_slice_grads) ahead of the dp
    reduction — ``tp`` reassembles tensor-parallel slices, ``sp``
    averages the per-sequence-shard gradients of replicated params
    (docs/sequence.md): identical math, one combiner. Resolved at
    TRACE time: when an axis is not bound, the model necessarily ran
    unsharded over it in this trace, the grads are already exact, and
    that combine is correctly skipped (the single-device debug
    path)."""
    from .parallel.tensor_parallel import combine_slice_grads

    axes = (tp_axis,) if isinstance(tp_axis, str) else tuple(tp_axis)
    for a in axes:
        if _axes_bound(a):
            grads = combine_slice_grads(grads, a)
    return grads


def _axes_bound(*axes) -> bool:
    """True iff all mesh axis names are bound in the current trace (i.e. we
    are inside shard_map/pmap over them). Probed once, narrowly, so a
    genuine NameError inside user compressors/optimizers still raises."""
    try:
        for a in axes:
            jax.lax.axis_size(a)
        return True
    except NameError:
        return False


def _reduce_tree(grads, op: C.ReduceOp, axis_name: str, compression,
                 fusion_threshold: int, prescale: float = 1.0,
                 postscale: float = 1.0, hierarchical: bool = False,
                 local_axis: str = "local", cross_axis: str = "cross",
                 quantized_cross: bool = False, overlap: bool = False,
                 bucket_order=None, route=None):
    """Fused (bucketed) allreduce of a gradient pytree over the mesh axis.

    ``overlap=True`` selects the latency-hiding schedule
    (common/overlap.py): buckets are planned in readiness order (reverse
    flatten by default, or an explicit ``bucket_order`` permutation from
    ``fusion.measured_order``) and issued through an
    ``optimization_barrier`` chain, so each bucket's collective can run
    while backprop still computes earlier layers' gradients. Scheduling
    only — results are bitwise-identical to ``overlap=False``.

    ``route`` (a :class:`~.ops.collectives.WirePlan`) sends every bucket
    through the topology-aware router (``collectives.mesh_allreduce``):
    per-axis RS/AG phases with per-axis wire dtypes, SUM/AVERAGE/ADASUM
    (docs/topology.md). It supersedes ``hierarchical``/``quantized_cross``
    — those flags are the legacy 2-axis fp32/int8-cross special cases.

    Outside an SPMD region (axis names unbound) the reduction degenerates
    to size-1 reference semantics: no cross-rank sum, but pre/post scaling
    still applies (the reference applies ScaleBuffer regardless of world
    size). Under jit/pjit auto-sharding XLA already inserts the
    cross-device reduction itself — a manual psum there would
    double-reduce.
    """
    if route is not None and not _axes_bound(*route.axis_names) \
            and _axes_bound(axis_name):
        # The program is tracing under the FLAT mesh (rank axis bound,
        # plan axes not) — e.g. an HVD_TPU_ROUTE default reaching a
        # flat-axis step. Reduce over the live axis; the identity
        # (size-1) path below is only for fully-unbound traces, and
        # silently NOT reducing would diverge replicas.
        route = None
    needed_axes = (route.axis_names if route is not None
                   else (local_axis, cross_axis) if hierarchical
                   else (axis_name,))
    bound = _axes_bound(*needed_axes)

    def one(flat):
        w, ctx = compression.compress(flat)
        if route is not None:
            if op not in (C.ReduceOp.SUM, C.ReduceOp.AVERAGE,
                          C.ReduceOp.ADASUM):
                # MIN/MAX/PRODUCT have no staged decomposition (and no
                # wire win to stage for) — reduce jointly over ALL plan
                # axes, which lax accepts as an axis tuple.
                return compression.decompress(
                    C.allreduce(w, op, tuple(route.axis_names),
                                prescale, postscale), ctx)
            # Integer buckets must not ride lossy wires: same axes,
            # native payload (psum of ints is exact on every phase).
            rp = route if jnp.issubdtype(w.dtype, jnp.floating) \
                else route.with_wires("none")
            if op != C.ReduceOp.ADASUM:
                w = C._apply_scale(w, prescale)
            w = C.mesh_allreduce(w, op, rp)
            w = C._apply_scale(w, postscale)
        elif op == C.ReduceOp.ADASUM:
            from .ops import adasum as adasum_lib

            if hierarchical:
                w = adasum_lib.adasum_hierarchical(w, local_axis, cross_axis)
            else:
                w = adasum_lib.adasum_allreduce(w, axis_name)
            w = C._apply_scale(w, postscale)
        elif hierarchical:
            w = C._apply_scale(w, prescale)
            nl = jax.lax.axis_size(local_axis)
            w, n = fusion_lib.pad_to_multiple(w, nl)
            if quantized_cross:
                # EQuARX path: int8 payload on the DCN hop
                # (collectives.quantized_hierarchical_allreduce).
                w = C.quantized_hierarchical_allreduce(
                    w, op, local_axis, cross_axis)
            else:
                w = C.hierarchical_allreduce_staged(w, op, local_axis,
                                                    cross_axis)
            w = jax.lax.slice_in_dim(w, 0, n)
            w = C._apply_scale(w, postscale)
        else:
            w = C.allreduce(w, op, axis_name, prescale, postscale)
        return compression.decompress(w, ctx)

    def identity_with_scales(flat):
        w, ctx = compression.compress(flat)
        w = C._apply_scale(w, prescale)
        w = C._apply_scale(w, postscale)
        return compression.decompress(w, ctx)

    fn = one if bound else identity_with_scales
    if overlap and bound:
        from .common import overlap as overlap_lib

        order = bucket_order if bucket_order is not None \
            else fusion_lib.ORDER_REVERSE
        return overlap_lib.fused_apply_overlapped(grads, fn,
                                                  fusion_threshold,
                                                  order=order)
    return fusion_lib.fused_apply(grads, fn, fusion_threshold)


class _AggState(NamedTuple):
    inner: Any
    acc: Any          # local gradient accumulator
    counter: jnp.ndarray


try:
    # The class needs the optax base at definition time; the rest of
    # this module must keep importing without optax installed.
    import optax as _optax
except Exception:  # pragma: no cover — exercised only without optax
    _optax = None


if _optax is not None:

    class AccumGradientTransformation(_optax.GradientTransformation):
        """The optax pair plus the scan-based accumulation driver the
        factory bound it to (docs/performance.md):
        ``accumulate(loss_fn, has_aux=False)`` returns the microbatched
        ``value_and_grad`` for the bound
        ``accum_steps``/``remat_policy`` — feed its gradients to
        ``update`` ONCE per effective step, so the collective round,
        non-finite guard agreement, and error-feedback advance all run
        once per effective step by construction.

        A module-level SUBCLASS of ``optax.GradientTransformation``
        with defaulted extras (not a wider NamedTuple): the 2-tuple
        shape, ``init, update = tx`` destructuring, isinstance checks,
        pickle/copy, and pytree flatten/unflatten all keep working.
        A pytree unflatten rebuilds ``cls(init, update)`` — the
        accumulation config resets to the ``1``/``"none"`` defaults,
        matching the pre-accumulation return type, which carried
        none."""

        def __new__(cls, init, update, accum_steps: int = 1,
                    remat_policy: str = "none"):
            self = super().__new__(cls, init, update)
            self.accum_steps = accum_steps
            self.remat_policy = remat_policy
            return self

        def accumulate(self, loss_fn: Callable, has_aux: bool = False):
            return accumulate_gradients(loss_fn, self.accum_steps,
                                        self.remat_policy,
                                        has_aux=has_aux)

else:  # pragma: no cover — optax-less installs have no optax surface
    AccumGradientTransformation = None


class _GuardedState(NamedTuple):
    """Optimizer-state wrapper carried when a non-finite policy is
    active (docs/integrity.md): the wrapped surface's state (possibly
    itself an :class:`_EFState` / :class:`_EFShardState`) plus the
    integrity :class:`~.common.integrity.GuardState` — policy code,
    non-finite step count, good-step streak, dynamic loss scale."""

    inner: Any
    guard: integrity_lib.GuardState


# -- error-feedback quantized reduction (compression="int8_ef") -------------

class _EFState(NamedTuple):
    """Optimizer-state wrapper carried by the error-feedback compressors:
    the inner transform's state, the fp32 residual pytree (this rank's
    accumulated quantization error — LOCAL, like the reference's per-rank
    gradient state), and the step counter that seeds the deterministic
    per-step stochastic rounding."""

    inner: Any
    residual: Any
    step: jnp.ndarray


# Base seed for the stochastic-rounding PRNG. The effective key is
# fold_in(fold_in(PRNGKey(_EF_SEED), step), bucket_index): deterministic
# per (step, bucket) — identical across ranks (SPMD traces one program)
# and across reruns, so elastic replays and bitwise-repro debugging hold.
_EF_SEED = 0x5EED


def _zeros_residual(tree):
    return jax.tree.map(
        lambda p: jnp.zeros(jnp.shape(p), jnp.float32), tree)


def _ef_key(step, bucket_index: int):
    return jax.random.fold_in(
        jax.random.fold_in(jax.random.PRNGKey(_EF_SEED), step),
        bucket_index)


def _reduce_tree_ef(grads, residual, step, op: C.ReduceOp, axis_name: str,
                    fusion_threshold: int, prescale: float = 1.0,
                    postscale: float = 1.0, overlap: bool = False,
                    bucket_order=None,
                    quantize_min_bytes: Optional[int] = None,
                    route=None):
    """Fused QUANTIZED allreduce of a gradient pytree with error
    feedback. Returns ``(reduced_tree, new_residual_tree)``.

    Buckets are planned exactly like :func:`_reduce_tree` (same
    threshold; reverse/readiness order under ``overlap``) and then
    stamped with per-bucket wire decisions
    (``fusion.assign_wire_dtypes``): large float buckets go through
    ``collectives.quantized_allreduce`` with this step's corrected
    gradient ``g + residual`` and a per-(step, bucket) stochastic-
    rounding key; their returned local quantization error becomes the
    next residual. Small float buckets ride a bf16 cast (no residual —
    bf16 keeps fp32's exponent range and the cast error is far below the
    int8 rounding floor); integer buckets ride untouched. ``overlap``
    chains the per-bucket collectives in issue order (common/overlap.py)
    exactly like the unquantized path.

    ``route`` (a WirePlan) sends each bucket through the mesh router
    instead of the flat axis: int8-eligible buckets run
    ``collectives.mesh_allreduce`` with the plan's PER-AXIS wires and
    carry its residual; small buckets ride the same axes bf16/native
    (docs/topology.md). With ``op=ADASUM`` the router runs the
    hierarchical Adasum scheme — the error-feedback residual corrects
    the LINEAR fast-axis phases (the local sums the Adasum recursion
    consumes); on a flat (1-phase) axis Adasum has no linear phase, so
    the residual is consumed once and zeroed rather than telescoped.

    Outside an SPMD region the reduction degenerates to size-1 semantics
    (scales applied, residual unchanged) — matching :func:`_reduce_tree`.
    """
    qmin = _resolve_quantize_min_bytes(quantize_min_bytes)
    if route is not None and not _axes_bound(*route.axis_names) \
            and _axes_bound(axis_name):
        route = None  # flat mesh is live — reduce flat (see _reduce_tree)
    bound = _axes_bound(*(route.axis_names if route is not None
                          else (axis_name,)))
    order = (bucket_order if bucket_order is not None
             else (fusion_lib.ORDER_REVERSE if overlap
                   else fusion_lib.ORDER_FLATTEN))
    plan = fusion_lib.plan_fusion(grads, fusion_threshold, order=order)
    plan = fusion_lib.assign_wire_dtypes(plan, qmin)
    g_flats = fusion_lib.fuse(grads, plan)
    r_flats = fusion_lib.fuse(residual, plan)
    reducible = (C.ReduceOp.SUM, C.ReduceOp.AVERAGE, C.ReduceOp.ADASUM)
    adasum = op == C.ReduceOp.ADASUM

    def one(i, g, r):
        wire = plan.wire_dtypes[i]
        if not bound:
            w = C._apply_scale(g, prescale)
            return C._apply_scale(w, postscale), r
        if wire == fusion_lib.WIRE_INT8 and op in reducible:
            corrected = g.astype(jnp.float32) + r
            if not adasum and prescale not in (None, 1.0):
                corrected = corrected * prescale
            if route is not None:
                y, res = C.mesh_allreduce(
                    corrected, op, route, key=_ef_key(step, i),
                    return_residual=True)
            elif adasum:
                # Flat-axis Adasum: quantized distance-doubling exchange
                # (unbiased with the stochastic key); no linear phase, so
                # the consumed residual zeroes instead of telescoping.
                from .ops import adasum as adasum_lib

                y = adasum_lib.adasum_allreduce(
                    corrected, axis_name, wire="int8",
                    key=_ef_key(step, i))
                res = jnp.zeros_like(r)
            else:
                y, res = C.quantized_allreduce(
                    corrected, op, axis_name, key=_ef_key(step, i),
                    return_residual=True)
            if not adasum and prescale not in (None, 1.0):
                # Residual lives in UNSCALED gradient units (it is added
                # to raw grads next step, before this prescale reapplies).
                res = res / prescale
            y = C._apply_scale(y, postscale)
            return y.astype(g.dtype), res
        if wire == fusion_lib.WIRE_BF16 and op in reducible:
            gb = g.astype(jnp.bfloat16)
            if route is not None:
                if not adasum:
                    gb = C._apply_scale(gb, prescale)
                w = C.mesh_allreduce(gb, op, route.with_wires("none"))
                w = C._apply_scale(w, postscale)
            else:
                w = C.allreduce(gb, op, axis_name, prescale, postscale)
            return w.astype(g.dtype), r
        if route is not None and op in reducible:
            gg = g if adasum else C._apply_scale(g, prescale)
            w = C.mesh_allreduce(gg, op, route.with_wires("none"))
            return C._apply_scale(w, postscale), r
        return C.allreduce(g, op, axis_name, prescale, postscale), r

    outs = []
    token = None
    for i, (g, r) in enumerate(zip(g_flats, r_flats)):
        if overlap and bound and token is not None:
            g, token = jax.lax.optimization_barrier((g, token))
        y, res = one(i, g, r)
        outs.append((y, res))
        if overlap and bound:
            token = y
    reduced = fusion_lib.unfuse([y for y, _ in outs], plan)
    new_residual = fusion_lib.unfuse([res for _, res in outs], plan)
    return reduced, new_residual


def _resolve_fusion_threshold(explicit: Optional[int]) -> int:
    """None → the live runtime value (autotuner's current suggestion when
    tuning, else the configured knob); an explicit value always wins."""
    if explicit is not None:
        return explicit
    from .common import basics

    if basics.is_initialized():
        return basics.context().fusion_threshold()
    return 64 * 1024 * 1024


# -- scan-based gradient accumulation (accum_steps=) -------------------------
#
# The MFU lever for batch-starved and memory-bound steps (ROADMAP item 2,
# docs/performance.md "MFU playbook"): instead of paying one dispatch +
# one traced cond per microbatch (the reference-style
# ``backward_passes_per_step`` aggregation above), ONE jitted step scans
# the loss/grad over k microbatches, carrying an fp32 gradient
# accumulator, and pays the collective round, the non-finite guard
# agreement, and the error-feedback state advance exactly once per
# EFFECTIVE step. Activation memory peaks at one microbatch (1/k of the
# fused batch), which is what lets remat + bigger per-chip batches trade
# against each other.

_REMAT_POLICY_NAMES = ("none", "full", "dots", "dots_no_batch")


def resolve_remat_policy(policy: Optional[str] = None):
    """Resolve a remat-policy name to ``(name, wrap, jax_policy)``.

    ``None`` consults the configured default (``HVD_TPU_REMAT_POLICY``
    / ``init(remat_policy=)``). Names map to ``jax.checkpoint``
    policies: ``"none"`` = no remat; ``"full"`` = recompute everything
    in backward (``jax.checkpoint`` default); ``"dots"`` = save matmul
    outputs, recompute elementwise (``dots_saveable``);
    ``"dots_no_batch"`` = save only non-batch-dim matmuls
    (``dots_with_no_batch_dims_saveable`` — the TPU-recommended policy
    for transformer blocks)."""
    if policy is None:
        from .common import basics

        if basics.is_initialized():
            policy = basics.context().config.remat_policy
        else:
            from .common.config import _env

            policy = _env("REMAT_POLICY")
    if policy is None or policy in ("none", "off", ""):
        return "none", False, None
    if policy == "full":
        return "full", True, None
    cp = jax.checkpoint_policies
    if policy == "dots":
        return "dots", True, cp.dots_saveable
    if policy == "dots_no_batch":
        return "dots_no_batch", True, cp.dots_with_no_batch_dims_saveable
    raise ValueError(
        f"unknown remat policy {policy!r}; choose from "
        f"{_REMAT_POLICY_NAMES}")


def _resolve_accum_steps(explicit: Optional[int] = None) -> int:
    """None → the configured default (``HVD_TPU_ACCUM_STEPS`` /
    ``init(accum_steps=)``, falling back to 1); an explicit value always
    wins."""
    if explicit is not None:
        k = int(explicit)
    else:
        from .common import basics

        if basics.is_initialized():
            k = int(basics.context().config.accum_steps)
        else:
            from .common.config import _env_int

            k = _env_int("ACCUM_STEPS", 1)
    if k < 1:
        raise ValueError(f"accum_steps must be >= 1, got {k}")
    return k


def _split_microbatches(batch_args, k: int):
    """Each array leaf of the batch pytrees gains a leading microbatch
    axis: ``(b, ...) -> (k, b//k, ...)``. Raises (naming the leaf shape)
    when a leading dim does not divide."""
    def one(x):
        x = jnp.asarray(x)
        if x.ndim == 0 or x.shape[0] % k:
            raise ValueError(
                f"accum_steps={k} does not divide the leading batch dim "
                f"of a batch leaf with shape {jnp.shape(x)}; every batch "
                "array must carry b = k * microbatch rows")
        return x.reshape((k, x.shape[0] // k) + x.shape[1:])

    return jax.tree.map(one, batch_args)


def accumulate_gradients(loss_fn: Callable,
                         accum_steps: Optional[int] = None,
                         remat_policy: Optional[str] = None,
                         has_aux: bool = False):
    """Scan-based gradient accumulation: wrap a LOSS function into a
    microbatched ``value_and_grad``.

    Returns ``fn(params, *batch) -> (value, grads)`` (or
    ``((value, aux), grads)`` with ``has_aux``): the batch args are
    split into ``accum_steps`` microbatches along their leading dim and
    a ``lax.scan`` runs ``jax.value_and_grad(loss_fn)`` per microbatch,
    accumulating gradients (and the loss) in fp32 — activation memory
    peaks at ONE microbatch instead of the fused batch. The returned
    gradients are the MEAN over microbatches, so a loss that is a mean
    over its batch rows yields gradients equivalent to the fused large
    batch (the accumulation-equivalence contract, tests/test_accum.py).

    ``remat_policy`` wraps the microbatch loss in ``jax.checkpoint``
    (:func:`resolve_remat_policy` names), trading recompute for a
    further activation-memory cut INSIDE each microbatch — the two
    levers tune jointly (docs/performance.md).

    Float ``aux`` leaves are averaged across microbatches (e.g. batch
    stats); integer leaves keep the LAST microbatch's value. There are
    no collectives in here: reduce the returned gradients once per
    effective step (DistributedOptimizer/DistributedGradFn compose this
    for you via their own ``accum_steps=``)."""
    k = _resolve_accum_steps(accum_steps)
    _, wrap, jax_policy = resolve_remat_policy(remat_policy)
    inner = jax.checkpoint(loss_fn, policy=jax_policy) if wrap else loss_fn
    vgrad = jax.value_and_grad(inner, has_aux=has_aux)
    if k == 1:
        return vgrad

    def accum_fn(params, *batch):
        mbs = _split_microbatches(batch, k)
        mb0 = jax.tree.map(lambda x: x[0], mbs)
        # Every microbatch runs through the SAME compiled scan body —
        # unrolling the first iteration would let XLA compile it
        # differently, and ulp-level drift between "identical"
        # microbatches breaks the bitwise state-transition contract
        # (tests/test_accum.py). eval_shape gives the accumulator
        # structure without spending a FLOP.
        shapes = jax.eval_shape(vgrad, params, *mb0)
        out_s, g_s = shapes
        v_s, aux_s = out_s if has_aux else (out_s, None)

        def zeros_acc(t):
            return jax.tree.map(
                lambda s: jnp.zeros(
                    s.shape, jnp.float32
                    if jnp.issubdtype(s.dtype, jnp.floating)
                    else s.dtype), t)

        def acc_add(acc, new):
            return jax.tree.map(
                lambda a, x: a + x.astype(jnp.float32)
                if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)
                else x,  # non-float aux: keep the latest microbatch's
                acc, new)

        carry0 = (zeros_acc(g_s), jnp.zeros((), jnp.float32),
                  zeros_acc(aux_s))

        def body(carry, mb):
            g_acc, v_acc, aux_acc = carry
            out, g = vgrad(params, *mb)
            v, aux = out if has_aux else (out, None)
            return (acc_add(g_acc, g), v_acc + v.astype(jnp.float32),
                    acc_add(aux_acc, aux)), None

        (g_acc, v_acc, aux_acc), _ = jax.lax.scan(body, carry0, mbs)

        def mean_like(acc, template):
            return jax.tree.map(
                lambda a, s: (a / k).astype(s.dtype)
                if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)
                else a, acc, template)

        grads = mean_like(g_acc, g_s)
        value = (v_acc / k).astype(v_s.dtype)
        if has_aux:
            return (value, mean_like(aux_acc, aux_s)), grads
        return value, grads

    return accum_fn


def auto_shard_threshold(explicit: Optional[int] = None) -> int:
    """The weight-update-sharding threshold in bytes
    (``HVD_TPU_AUTO_SHARD_THRESHOLD`` / ``init(auto_shard_threshold_
    bytes=)``, default 256 MiB): replicated params at least this large
    make ZeRO-1's sharded update the default candidate."""
    if explicit is not None:
        return int(explicit)
    from .common import basics

    if basics.is_initialized():
        return int(basics.context().config.auto_shard_threshold_bytes)
    from .common.config import Config, _env_int

    return _env_int("AUTO_SHARD_THRESHOLD",
                    Config.auto_shard_threshold_bytes)


def should_shard_update(params, size: Optional[int] = None,
                        threshold_bytes: Optional[int] = None) -> bool:
    """Heuristic (arXiv:1909.09756, docs/performance.md): True when
    weight-update sharding (ZeRO-1, :class:`ShardedOptimizer`) should
    be the default candidate for this model — the world has more than
    one rank and the replicated params are at least
    :func:`auto_shard_threshold` bytes (the regime where the replicated
    optimizer state + update compute dominate the RS+AG latency the
    sharded path adds). Accepts real arrays or ShapeDtypeStructs."""
    if size is None:
        from .common import basics

        size = basics.context().size() if basics.is_initialized() else 1
    if size <= 1:
        return False
    import numpy as np

    nbytes = 0
    for leaf in jax.tree.leaves(params):
        shape = getattr(leaf, "shape", ())
        dtype = jnp.asarray(leaf).dtype if not hasattr(leaf, "dtype") \
            else leaf.dtype
        nbytes += int(np.prod(shape)) * jnp.dtype(dtype).itemsize
    return nbytes >= auto_shard_threshold(threshold_bytes)


def DistributedOptimizer(optimizer,
                         op: C.ReduceOp = C.ReduceOp.AVERAGE,
                         axis_name: str = "hvd",
                         compression=None,
                         backward_passes_per_step: int = 1,
                         average_aggregated_gradients: bool = True,
                         prescale_factor: float = 1.0,
                         postscale_factor: float = 1.0,
                         fusion_threshold_bytes: Optional[int] = None,
                         hierarchical: bool = False,
                         local_axis: str = "local",
                         cross_axis: str = "cross",
                         quantized_cross: bool = False,
                         overlap: bool = False,
                         bucket_order=None,
                         quantize_min_bucket_bytes: Optional[int] = None,
                         nonfinite_policy: Optional[str] = None,
                         route=None,
                         accum_steps: Optional[int] = None,
                         remat_policy: Optional[str] = None,
                         zero_stage: int = 0,
                         parallel=None):
    """Wrap an optax optimizer so ``update()`` allreduces gradients first.

    Use inside the jitted step function running under
    shard_map/pjit over the rank axis::

        tx = hvd.DistributedOptimizer(optax.sgd(0.1), axis_name="hvd")

    ``backward_passes_per_step`` accumulates k local microbatch gradients
    before one fused allreduce + inner update (reference
    gradient_aggregation.py semantics: allreduce every k-th call, identity
    updates in between). Prefer ``accum_steps`` (below) for new code —
    the scan-based form pays one dispatch per EFFECTIVE step instead of
    one per microbatch.

    ``accum_steps`` (None → ``HVD_TPU_ACCUM_STEPS`` /
    ``init(accum_steps=)``) + ``remat_policy`` select SCAN-BASED
    gradient accumulation (docs/performance.md "MFU playbook"): the
    returned transformation carries an ``accumulate(loss_fn,
    has_aux=False)`` driver (:func:`accumulate_gradients` bound to the
    pinned knobs) that microbatches the loss under ``lax.scan`` —
    activation memory peaks at 1/k of the fused batch, and
    ``remat_policy`` ("full"/"dots"/"dots_no_batch") further remats
    inside each microbatch via ``jax.checkpoint``. Feed its MEAN
    gradient to ``update()`` once per effective step::

        tx = hvd.DistributedOptimizer(optax.adamw(1e-3), accum_steps=4,
                                      remat_policy="dots_no_batch")
        vgrad = tx.accumulate(loss_fn)         # scans 4 microbatches
        loss, grads = vgrad(params, batch)     # batch rows = 4 * mb
        updates, state = tx.update(grads, state, params)

    The collective round, the non-finite guard agreement, and the
    int8_ef error-feedback/stochastic-rounding advance then all run
    exactly ONCE per effective step by construction — accumulation
    composes with ``overlap``/``compression``/``route``/
    ``nonfinite_policy`` unchanged. Mutually exclusive with the legacy
    ``backward_passes_per_step`` aggregation.

    ``quantized_cross`` (requires ``hierarchical``) carries the DCN hop
    of each fused bucket as block-scaled int8 — the EQuARX-style
    quantized allreduce (collectives.quantized_hierarchical_allreduce);
    gradients land within block-absmax rounding error of the exact sum.

    ``overlap=True`` buckets gradients in readiness order and chains the
    per-bucket collectives so they fire while the backward pass is still
    computing (common/overlap.py — the reference's background-thread
    overlap, expressed through XLA scheduling). Composes with
    ``hierarchical``/``quantized_cross`` (each chained bucket runs the
    staged reduction) and reduce-safe ``compression``; same numerics as
    ``overlap=False``. Pair with the latency-hiding XLA flags
    (``init(overlap_xla_flags=True)`` / common/xla_tuning.py) on TPU.
    ``bucket_order`` optionally pins a measured leaf permutation
    (``fusion.measured_order``) instead of the reverse-flatten proxy.

    ``compression`` accepts a Compressor class, a name
    (``"bf16"``/``"int8_ef"``/...), or None — the configured default
    (``HVD_TPU_COMPRESSION`` / ``init(compression=)``). With
    ``compression="int8_ef"`` the reduction runs as a REDUCE-SAFE
    QUANTIZED ALLREDUCE (collectives.quantized_allreduce: int8 payload
    on every hop, ~4x fewer wire bytes) with an ERROR-FEEDBACK residual
    carried in the optimizer state: each step reduces ``grad +
    residual``, and the local quantization error becomes the next
    residual, so training converges like fp32 (docs/compression.md).
    Only fused buckets of at least ``quantize_min_bucket_bytes``
    (default: the HVD_TPU_QUANTIZE_MIN_BYTES knob, 64 KiB) are
    quantized — smaller float buckets ride bf16. Requires a SUM/AVERAGE
    op; composes with ``overlap`` but not with ``hierarchical`` (use
    ``quantized_cross`` for the int8 DCN hop of the staged pipeline).

    ``nonfinite_policy`` (None → ``HVD_TPU_NONFINITE_POLICY`` /
    ``init(nonfinite_policy=)``; docs/integrity.md) arms the
    training-integrity guard: an all-finite flag over the gradients is
    globally agreed via a one-scalar min-allreduce and a jit-safe
    ``lax.cond`` reacts identically on every rank — ``warn`` |
    ``skip_step`` (zero updates, optimizer state AND the int8_ef
    error-feedback residual untouched) | ``zero`` | ``scale_backoff``
    (dynamic loss scaling: multiply your loss by
    ``hvd.current_loss_scale(opt_state)``) | ``abort`` (skip in-trace,
    ``hvd.observe_guard(opt_state)`` raises host-side). The state is
    wrapped in :class:`_GuardedState`; observe with
    ``hvd.observe_guard``.

    ``route`` (None → ``HVD_TPU_ROUTE`` / ``init(route=)``) selects the
    TOPOLOGY-AWARE ROUTER (docs/topology.md): a
    :class:`~.ops.collectives.WirePlan`, a spec string like
    ``"local:none,cross:int8"`` (fast axis first), or a named route
    (``"flat"`` / ``"staged"`` / ``"staged_int8"``). Each fused bucket
    then reduces via per-axis phases with PER-AXIS WIRE DTYPES —
    fp32/bf16 on fast ICI axes, int8 on the slow cross hop — so wire
    cost scales with the slowest link, not the world size. Composes
    with ``compression="int8_ef"`` (the residual rides the linear
    phases), with ``op=hvd.Adasum`` (hierarchical Adasum: fast axes
    averaged, the adaptive recursion runs on shards over the slow axis
    with fast-axis-psum-med scalars), and with ``overlap`` (each
    chained bucket routes independently). Supersedes the legacy
    ``hierarchical``/``quantized_cross`` booleans — passing both
    raises.

    ``parallel`` (EXPLICIT-ONLY — a :class:`~.parallel.spec.
    ParallelSpec`, role dict, or spec string like ``"dp=2,pp=2,tp=2"``;
    docs/pipeline.md) declares HYBRID dp x pp x tp parallelism on one
    mesh: gradients then reduce over the ``dp`` axis ONLY (pipeline
    stages own disjoint params — their activation sends ride the pp
    axis, not the gradient reduction; tensor-parallel slice gradients
    are pmean-combined over ``tp`` first via
    ``tensor_parallel.combine_slice_grads``), and the non-finite guard
    agrees over the ``dp`` axis only (each stage guards its own
    params). Feed it the gradients of
    ``parallel.pipeline.pipeline_accumulate_gradients`` (the 1F1B
    schedule with the same ``(value, grads)`` contract as
    ``accumulate``). Composes with ``compression``/``overlap``/
    ``zero_stage`` (ZeRO shard grids then span the dp axis, so
    stage-2/3 shards live PER PIPELINE STAGE); supersedes
    ``axis_name``/``route`` — passing an explicit route alongside
    raises unless its axes are exactly the spec's dp axes.
    """
    try:
        import optax
    except ImportError as e:  # pragma: no cover
        raise ImportError("DistributedOptimizer requires optax") from e

    pspec = _resolve_parallel(parallel)
    tp_combine_axis = None
    if pspec is not None:
        if hierarchical or quantized_cross:
            raise ValueError(
                "parallel= supersedes the hierarchical/quantized_cross "
                "booleans — wires on the dp reduction come from route= "
                "(a WirePlan over the spec's dp axes)")
        if route is not None:
            rt = C.WirePlan.resolve(route)
            if rt is not None and set(rt.axis_names) != set(
                    pspec.dp_axes):
                raise ValueError(
                    f"route axes {rt.axis_names} must be exactly the "
                    f"parallel spec's dp axes {pspec.dp_axes} — "
                    "gradients reduce over dp only (activation traffic "
                    "rides the pp axis; tp combines via pmean)")
        if not pspec.dp_axes:
            raise ValueError(
                f"parallel spec {pspec.describe()!r} has no dp axis — "
                "with nothing to reduce over, wrap the optimizer "
                "directly (pure pp x tp runs need no "
                "DistributedOptimizer)")
        axis_name = pspec.dp_axes[0]
        if route is None:
            # Reduce through the mesh router over the dp axis (not the
            # flat psum): the router stamps the per-axis byte counters
            # (hvd_tpu_allreduce_bytes_total{axis="dp"}) that prove the
            # schedule's wire mix, and it pins the plan so the
            # HVD_TPU_ROUTE default (which names local/cross axes this
            # mesh does not bind) can never apply.
            route = pspec.grad_route()
        tp_combine_axis = tuple(
            a for a in (pspec.tp_axis, pspec.sp_axis)
            if a is not None) or None

    if zero_stage:
        # The one-line ZeRO surface (docs/zero.md): stage 1 = sharded
        # optimizer state, 2 = + sharded gradient accumulation, 3 =
        # + sharded parameters with gather-on-demand. EXPLICIT-ONLY
        # (no HVD_TPU_ZERO_STAGE consult here): the stage changes the
        # update() call contract — it must run inside the SPMD region
        # and takes params/shards — and an env knob must never break
        # existing call sites; bench/tools read the config knob and
        # pass the stage explicitly.
        if int(zero_stage) not in (1, 2, 3):
            raise ValueError(
                f"zero_stage must be 0 (off), 1, 2 or 3 — got "
                f"{zero_stage!r}")
        if backward_passes_per_step != 1 or hierarchical \
                or quantized_cross:
            raise ValueError(
                "zero_stage composes with accum_steps / route / "
                "compression / nonfinite_policy, not with the legacy "
                "backward_passes_per_step aggregation or the "
                "hierarchical/quantized_cross booleans (express the "
                "staged reduction as a WirePlan route instead)")
        if prescale_factor != 1.0 or postscale_factor != 1.0:
            raise ValueError(
                "pre/postscale_factor are not supported on the ZeRO "
                "sharded surfaces — fold the scale into your loss")
        return ZeroOptimizer(
            optimizer, zero_stage=int(zero_stage),
            axis_name=axis_name, grad_op=op,
            fusion_threshold_bytes=fusion_threshold_bytes,
            compression=compression, nonfinite_policy=nonfinite_policy,
            route=route, accum_steps=accum_steps,
            remat_policy=remat_policy, overlap=True,
            bucket_order=bucket_order, parallel=pspec)

    compression = _resolve_compression(compression)
    _check_reduce_safe(compression)
    ef = getattr(compression, "error_feedback", False)
    route_explicit = route is not None
    route = _resolve_route(route, local_axis, cross_axis)
    if route_explicit and route is not None and (hierarchical
                                                or quantized_cross):
        raise ValueError(
            "route= supersedes the hierarchical/quantized_cross "
            "booleans: express the staged reduction as WirePlan phases "
            "on the mesh router instead (collectives.mesh_allreduce, "
            "docs/topology.md) — e.g. route='staged_int8' or "
            "WirePlan.hierarchical(cross_wire='int8') for the old "
            "hierarchical+quantized_cross pair")
    if not route_explicit and (hierarchical or quantized_cross):
        # Call-site legacy flags beat the HVD_TPU_ROUTE / init(route=)
        # DEFAULT — an env knob must never make existing hierarchical
        # call sites raise (or silently re-route them).
        route = None
    if quantized_cross and (not hierarchical or op not in (
            C.ReduceOp.SUM, C.ReduceOp.AVERAGE)):
        raise ValueError("quantized_cross requires hierarchical=True and "
                         "a SUM/AVERAGE op (the int8 hop rides the "
                         "staged RS->AR->AG pipeline); for Adasum or "
                         "deeper meshes use the router — route= / "
                         "collectives.mesh_allreduce (docs/topology.md)")
    if ef and op not in (C.ReduceOp.SUM, C.ReduceOp.AVERAGE,
                         C.ReduceOp.ADASUM):
        raise ValueError(
            f"compression={compression.__name__} needs a SUM/AVERAGE/"
            "ADASUM op (block-scaled payloads compose with linear "
            "reductions, plus the routed hierarchical Adasum)")
    if ef and hierarchical:
        # Formerly a hard error: int8_ef now composes with the ICI/DCN
        # split THROUGH the mesh router — the per-axis WirePlan carries
        # the slow cross hop as int8 and the error-feedback residual
        # rides the linear phases (docs/topology.md).
        route = C.WirePlan.hierarchical(local_axis, cross_axis,
                                        cross_wire="int8")
        hierarchical = quantized_cross = False

    k = int(backward_passes_per_step)
    accum_k = _resolve_accum_steps(accum_steps)
    # Resolve (and validate) the remat policy ONCE at factory time — a
    # later env-knob change must not re-shape the accumulate driver.
    remat_name, _, _ = resolve_remat_policy(remat_policy)
    if accum_k > 1 and k > 1:
        raise ValueError(
            "accum_steps and backward_passes_per_step are two spellings "
            "of gradient accumulation — pick one (accum_steps is the "
            "scan-based form; backward_passes_per_step the legacy "
            "call-per-microbatch aggregation)")
    fusion_threshold_bytes = _resolve_fusion_threshold(fusion_threshold_bytes)
    quantize_min_bucket_bytes = _resolve_quantize_min_bytes(
        quantize_min_bucket_bytes)
    nonfinite_policy = integrity_lib.resolve_nonfinite_policy(
        nonfinite_policy)
    scale_cfg = integrity_lib.ScaleConfig.from_env()

    def reduce_grads(grads):
        return _reduce_tree(grads, op, axis_name, compression,
                            fusion_threshold_bytes, prescale_factor,
                            postscale_factor, hierarchical, local_axis,
                            cross_axis, quantized_cross, overlap,
                            bucket_order, route)

    # Core transformation: reduce + inner update (+ the error-feedback
    # residual/step state when the compressor declares it). The k>1
    # aggregation below wraps THIS, so backward_passes_per_step composes
    # with error feedback unchanged.
    def core_init(params):
        inner = optimizer.init(params)
        if not ef:
            return inner
        return _EFState(inner=inner, residual=_zeros_residual(params),
                        step=jnp.zeros((), jnp.int32))

    def core_update(grads, state, params=None, **extra):
        if not ef:
            reduced = reduce_grads(grads)
            return optimizer.update(reduced, state, params, **extra)
        reduced, new_res = _reduce_tree_ef(
            grads, state.residual, state.step, op, axis_name,
            fusion_threshold_bytes, prescale_factor, postscale_factor,
            overlap, bucket_order, quantize_min_bucket_bytes, route)
        updates, new_inner = optimizer.update(reduced, state.inner,
                                              params, **extra)
        return updates, _EFState(new_inner, new_res, state.step + 1)

    # Non-finite guard (docs/integrity.md): wraps the WHOLE core —
    # reduction + inner update — in the globally-agreed lax.cond, so a
    # skipped step leaves inner state, EF residual, and EF step counter
    # untouched. The k>1 aggregation below wraps THIS, so each
    # effective (post-accumulation) step is what gets guarded. Under a
    # mesh route the one-scalar agreement runs over the PLAN's axes
    # (the flat rank axis is not bound there); resolved at TRACE time
    # so a defaulted route reaching a flat-axis step still agrees over
    # the live axis (matching _reduce_tree's fallback).
    def _guard_axes():
        if route is not None and _axes_bound(*route.axis_names):
            return tuple(route.axis_names)
        if hierarchical and _axes_bound(local_axis, cross_axis):
            return (local_axis, cross_axis)
        return axis_name

    if nonfinite_policy is None:
        u_init, u_update = core_init, core_update
    else:
        def u_init(params):
            return _GuardedState(
                inner=core_init(params),
                guard=integrity_lib.init_guard_state(nonfinite_policy,
                                                     scale_cfg))

        def u_update(grads, state, params=None, **extra):
            def fn(g, c):
                return core_update(g, c, params, **extra)

            updates, new_inner, new_guard = integrity_lib.guarded_apply(
                nonfinite_policy, fn, grads, state.inner, state.guard,
                _guard_axes(), scale_cfg)
            return updates, _GuardedState(new_inner, new_guard)

    def _finish(init_f, update_f):
        if tp_combine_axis is not None:
            # Tensor/sequence-parallel slice grads reassemble (pmean
            # over tp, then sp) BEFORE everything downstream — the dp
            # reduction, the guard's finite check, and the legacy k>1
            # accumulator all see exact gradients (pmean is linear, so
            # combining ahead of accumulation is equivalent).
            inner_update_f = update_f

            def update_f(grads, state, params=None, **extra):  # noqa: F811
                return inner_update_f(_combine_tp(grads,
                                                  tp_combine_axis),
                                      state, params, **extra)

        return AccumGradientTransformation(
            init_f, update_f, accum_k, remat_name)

    if k <= 1:
        return _finish(u_init, u_update)

    def init_fn(params):
        acc = jax.tree.map(jnp.zeros_like, params)
        return _AggState(inner=u_init(params), acc=acc,
                         counter=jnp.zeros((), jnp.int32))

    def update_fn(grads, state, params=None, **extra):
        acc = jax.tree.map(jnp.add, state.acc, grads)
        counter = state.counter + 1
        do_step = counter >= k

        def take_step(args):
            acc, inner = args
            scale = (1.0 / k) if average_aggregated_gradients else 1.0
            scaled = jax.tree.map(lambda g: g * scale, acc) \
                if scale != 1.0 else acc
            updates, new_inner = u_update(scaled, inner, params,
                                          **extra)
            zeroed = jax.tree.map(jnp.zeros_like, acc)
            return updates, new_inner, zeroed

        def skip_step(args):
            acc, inner = args
            updates = jax.tree.map(jnp.zeros_like, acc)
            return updates, inner, acc

        updates, new_inner, new_acc = jax.lax.cond(
            do_step, take_step, skip_step, (acc, state.inner))
        new_counter = jnp.where(do_step, 0, counter)
        return updates, _AggState(new_inner, new_acc, new_counter)

    return _finish(init_fn, update_fn)


def DistributedGradFn(grad_fn: Callable,
                      op: C.ReduceOp = C.ReduceOp.AVERAGE,
                      axis_name: str = "hvd",
                      compression=None,
                      fusion_threshold_bytes: Optional[int] = None,
                      has_value: bool = False,
                      reduce_value: bool = True,
                      overlap: bool = False,
                      bucket_order=None,
                      quantize_min_bucket_bytes: Optional[int] = None,
                      nonfinite_policy: Optional[str] = None,
                      route=None,
                      accum_steps: Optional[int] = None,
                      remat_policy: Optional[str] = None):
    """DistributedGradientTape analog (reference
    tensorflow/__init__.py:564-629): wraps a function returning gradients
    (e.g. ``jax.grad(loss)``) so the result is allreduced across ranks.

    ``has_value=True`` declares the wrapped function follows the
    ``jax.value_and_grad`` convention ``(value, grads)``; the value is
    additionally averaged across ranks when ``reduce_value``. Explicit flag
    instead of tuple-sniffing so ``jax.grad(loss, argnums=(0, 1))`` (a
    tuple of gradients) is never misclassified.

    ``overlap``/``bucket_order``: readiness-ordered buckets + issue-order
    chaining, as on :func:`DistributedOptimizer` — scheduling only,
    identical numerics.

    ``accum_steps`` (EXPLICIT-ONLY on this surface: it changes how the
    first argument is interpreted, so the ``HVD_TPU_ACCUM_STEPS`` env
    default is deliberately not consulted) selects SCAN-BASED gradient
    accumulation: pass the LOSS function instead of ``jax.grad(loss)``
    — the wrapper owns the grad computation (it must: the microbatch
    scan and the ``remat_policy`` ``jax.checkpoint`` wrap live between
    loss and gradients, :func:`accumulate_gradients`)::

        gfn = hvd.DistributedGradFn(loss_fn, accum_steps=4,
                                    remat_policy="dots", has_value=True)
        (loss, grads) = gfn(params, batch)   # batch rows = 4 * mb

    The batch args are split into k microbatches along their leading
    dim, gradients accumulate in fp32 under ``lax.scan``, and the
    REDUCTION (with overlap / int8_ef error feedback / route / the
    non-finite guard agreement) runs exactly once on the accumulated
    mean — one collective round and one guard agreement per effective
    step. ``has_value=False`` simply drops the (already computed) loss
    from the returns.

    With an error-feedback compression (``"int8_ef"``) the wrapper is
    STATEFUL in the functional style: the wrapped function grows an
    ``ef_state`` keyword and returns ``(result, new_ef_state)`` — thread
    the state through your training loop like optimizer state::

        gfn = hvd.DistributedGradFn(jax.grad(loss), compression="int8_ef")
        ef = gfn.init_ef_state(params)        # zeros residual + step 0
        grads, ef = gfn(params, batch, ef_state=ef)

    ``ef_state=None`` starts from a zero residual (valid, but the
    residual is then discarded each call — quantization error no longer
    cancels across steps; thread the state for fp32-like convergence).

    ``nonfinite_policy`` (docs/integrity.md) arms the non-finite guard:
    the wrapped function grows a ``guard_state`` keyword and APPENDS
    the new guard state to its returns — ``(grads, guard)``, or
    ``(grads, ef_state, guard)`` with an error-feedback compression.
    On a globally-agreed non-finite step the returned gradients are
    zeros and (under ``skip_step``/``scale_backoff``/``abort``) the EF
    residual is NOT updated; gate your own optimizer update on
    ``guard.last_ok`` if zero gradients are not a no-op for it. Seed
    with ``wrapped.init_guard_state()``. EXPLICIT-ONLY on this surface:
    the ``HVD_TPU_NONFINITE_POLICY`` env default is deliberately NOT
    consulted here — the guard changes the wrapped function's return
    arity, and an env knob must never silently break existing call
    sites (the optimizer surfaces, whose state is opaque, do honor it).
    """
    compression = _resolve_compression(compression)
    _check_reduce_safe(compression)
    ef = getattr(compression, "error_feedback", False)
    route = _resolve_route(route)
    accum_k = int(accum_steps) if accum_steps is not None else 1
    if accum_k > 1:
        # grad_fn is the LOSS here; the scan driver produces
        # (value, grads) — has_value only controls the caller-visible
        # return arity below.
        grad_fn = accumulate_gradients(grad_fn, accum_k, remat_policy)
        produces_value = True
    else:
        if accum_k < 1:
            raise ValueError(f"accum_steps must be >= 1, got {accum_k}")
        if remat_policy is not None:
            raise ValueError(
                "remat_policy on DistributedGradFn requires "
                "accum_steps > 1 — remat wraps the LOSS before "
                "value_and_grad, which this surface only owns under "
                "the microbatch scan (use jax.checkpoint on your loss "
                "directly otherwise)")
        produces_value = has_value
    if ef and op not in (C.ReduceOp.SUM, C.ReduceOp.AVERAGE,
                         C.ReduceOp.ADASUM):
        raise ValueError(
            f"compression={compression.__name__} needs a SUM/AVERAGE/"
            "ADASUM op")
    fusion_threshold_bytes = _resolve_fusion_threshold(fusion_threshold_bytes)
    quantize_min_bucket_bytes = _resolve_quantize_min_bytes(
        quantize_min_bucket_bytes)
    nonfinite_policy = integrity_lib.resolve_nonfinite_policy(
        nonfinite_policy) if nonfinite_policy is not None else None
    scale_cfg = integrity_lib.ScaleConfig.from_env()
    def _guard_axes():
        """Resolved at TRACE time: the plan's axes when they are bound,
        else the flat rank axis (a defaulted route must not push the
        guard's agreement onto unbound axes — see _reduce_tree)."""
        if route is not None and _axes_bound(*route.axis_names):
            return tuple(route.axis_names)
        return axis_name

    def reduce_grads(grads):
        return _reduce_tree(grads, op, axis_name, compression,
                            fusion_threshold_bytes, overlap=overlap,
                            bucket_order=bucket_order, route=route)

    def _reduce_value(val):
        if not reduce_value:
            return val
        if route is not None and _axes_bound(*route.axis_names):
            return jax.tree.map(
                lambda v: C.mesh_allreduce(
                    v, C.ReduceOp.AVERAGE, route.with_wires("none")),
                val)
        if _axes_bound(axis_name):
            return jax.tree.map(
                lambda v: C.allreduce(v, C.ReduceOp.AVERAGE, axis_name),
                val)
        return val

    def _guard_or_init(guard_state):
        if guard_state is not None:
            return guard_state
        return integrity_lib.init_guard_state(nonfinite_policy, scale_cfg)

    if ef:
        def wrapped(*args, ef_state=None, guard_state=None, **kwargs):
            out = grad_fn(*args, **kwargs)
            val, grads = out if produces_value else (None, out)
            if ef_state is None:
                residual = _zeros_residual(grads)
                step = jnp.zeros((), jnp.int32)
            else:
                residual, step = ef_state.residual, ef_state.step

            def reduce_ef(g, carry):
                res, stp = carry
                red, new_res = _reduce_tree_ef(
                    g, res, stp, op, axis_name,
                    fusion_threshold_bytes, overlap=overlap,
                    bucket_order=bucket_order,
                    quantize_min_bytes=quantize_min_bucket_bytes,
                    route=route)
                return red, (new_res, stp + 1)

            if nonfinite_policy is None:
                reduced, (new_res, new_step) = reduce_ef(
                    grads, (residual, step))
                new_state = _EFState(inner=None, residual=new_res,
                                     step=new_step)
                if has_value:
                    return (_reduce_value(val), reduced), new_state
                return reduced, new_state
            # Guarded: the cond wraps the whole quantized reduction, so
            # a skipped step leaves residual AND step counter untouched
            # (the error-feedback telescoping stays exact).
            reduced, (new_res, new_step), new_guard = \
                integrity_lib.guarded_apply(
                    nonfinite_policy, reduce_ef, grads, (residual, step),
                    _guard_or_init(guard_state), _guard_axes(),
                    scale_cfg)
            new_state = _EFState(inner=None, residual=new_res,
                                 step=new_step)
            if has_value:
                return (_reduce_value(val), reduced), new_state, new_guard
            return reduced, new_state, new_guard

        wrapped.init_ef_state = lambda grads_template: _EFState(
            inner=None, residual=_zeros_residual(grads_template),
            step=jnp.zeros((), jnp.int32))
        if nonfinite_policy is not None:
            wrapped.init_guard_state = lambda: integrity_lib. \
                init_guard_state(nonfinite_policy, scale_cfg)
        return wrapped

    def wrapped(*args, guard_state=None, **kwargs):
        out = grad_fn(*args, **kwargs)
        if produces_value:
            val, grads = out
        else:
            val, grads = None, out
        if nonfinite_policy is None:
            if has_value:
                return _reduce_value(val), reduce_grads(grads)
            return reduce_grads(grads)
        reduced, _, new_guard = integrity_lib.guarded_apply(
            nonfinite_policy, lambda g, c: (reduce_grads(g), c), grads,
            (), _guard_or_init(guard_state), _guard_axes(), scale_cfg)
        if has_value:
            return (_reduce_value(val), reduced), new_guard
        return reduced, new_guard

    if nonfinite_policy is not None:
        wrapped.init_guard_state = lambda: integrity_lib. \
            init_guard_state(nonfinite_policy, scale_cfg)
    return wrapped


class AutotunedStepper:
    """Drives the runtime Autotuner from real step timings and rebuilds the
    jitted step function whenever the suggested fusion threshold moves.

    This is the in-jit analog of the reference's live ParameterManager
    tuning (parameter_manager.cc: each cycle scores bytes/sec and may
    change the fusion threshold; subsequent cycles fuse differently).
    Under XLA a threshold change means a different bucket plan, i.e. a
    retrace — so the stepper owns the (re)build::

        def build(threshold_bytes):
            tx = hvd.DistributedOptimizer(optax.sgd(0.01),
                                          fusion_threshold_bytes=threshold_bytes)
            ... return jitted_step               # closes over tx
        stepper = hvd.AutotunedStepper(build, grad_bytes=nbytes)
        while training:
            out = stepper(*step_args)

    ``grad_bytes`` is the bytes reduced per step (the score numerator,
    matching the reference's bytes/sec score, parameter_manager.h:42).
    """

    def __init__(self, build_step: Callable[[int], Callable],
                 grad_bytes: int, tuner=None, block: bool = True,
                 controller=None):
        from .common import basics

        if tuner is None:
            tuner = basics.context().autotuner
            if tuner is None:
                raise ValueError(
                    "runtime autotuner not enabled — init(autotune=True) "
                    "or set HVD_TPU_AUTOTUNE=1, or pass tuner= explicitly")
        if controller is None and basics.is_initialized():
            controller = basics.context().controller
        self.tuner = tuner
        self.grad_bytes = int(grad_bytes)
        self.block = block
        self._build = build_step
        # Multi-process: rank 0 alone scores samples and decides; every
        # process adopts the decision at the SAME call index via a
        # synchronous controller exchange — per-process decisions would
        # compile diverged bucket plans and deadlock the collectives
        # (reference: SynchronizeParameters broadcasts rank-0's
        # ParameterManager state, controller.cc:34-48).
        self._controller = controller
        self._period = tuner.warmup + tuner.steps_per_sample
        self._calls = 0
        self._tuner_done = False  # set when rank 0 broadcasts :done
        self._threshold = tuner.current
        # Joint tuning (reference ParameterManager's hierarchical toggle):
        # build_step then takes (threshold, hierarchical). With a
        # tune_overlap tuner the signature widens once more to
        # (threshold, hierarchical, overlap), with tune_compression to
        # (threshold, hierarchical, overlap, compression), and with
        # tune_route to (..., route) — route is the axis-order/
        # reduction-mode candidate ("flat"/"staged"/"staged_int8"/
        # "adasum"; docs/topology.md) — the full point the (re)built
        # step must agree on across ranks.
        self._joint = getattr(tuner, "tune_hierarchical", False)
        self._joint_overlap = getattr(tuner, "tune_overlap", False)
        self._joint_comp = getattr(tuner, "tune_compression", False)
        self._joint_route = getattr(tuner, "tune_route", False)
        # MFU dimensions (docs/performance.md): accumulation microbatch
        # count, remat policy, weight-update sharding. When ANY of them
        # is tuned, build_step receives the whole
        # :class:`~.common.autotune.TunedPoint` instead of the
        # positional cascade — eight positional args would be
        # unreadable at every call site.
        self._joint_accum = getattr(tuner, "tune_accum", False)
        self._joint_remat = getattr(tuner, "tune_remat", False)
        self._joint_shard = getattr(tuner, "tune_shard", False)
        # MoE dispatch-wire axis (docs/moe.md): like the MFU axes it
        # rides the whole-TunedPoint build signature — the build fn
        # threads pt.moe_wire into its moe_layer/MoeMlp construction.
        self._joint_moe_wire = getattr(tuner, "tune_moe_wire", False)
        # Pipeline stage-boundary wire axis (docs/pipeline.md): same
        # whole-TunedPoint contract — the build fn threads pt.pp_wire
        # into its pipeline_accumulate_gradients(wire=) construction.
        self._joint_pp_wire = getattr(tuner, "tune_pp_wire", False)
        self._hier = (tuner.current_hierarchical if self._joint else False)
        self._ovl = (tuner.current_overlap if self._joint_overlap
                     else False)
        self._comp = (tuner.current_compression if self._joint_comp
                      else "none")
        self._route = (tuner.current_route if self._joint_route
                       else "flat")
        self._accum = (tuner.current_accum if self._joint_accum else 1)
        self._remat = (tuner.current_remat if self._joint_remat
                       else "none")
        self._shard = (tuner.current_shard if self._joint_shard
                       else 0)  # ZeRO stage, 0 = replicated
        self._moe_wire = (tuner.current_moe_wire
                          if self._joint_moe_wire else "none")
        self._pp_wire = (tuner.current_pp_wire
                         if self._joint_pp_wire else "none")
        self._step = self._rebuild()
        self.rebuilds = 0
        self._step_count = 0  # metrics/profiler step numbering

    @property
    def _mfu_joint(self) -> bool:
        return (self._joint_accum or self._joint_remat
                or self._joint_shard or self._joint_moe_wire
                or self._joint_pp_wire)

    def _rebuild(self):
        if self._mfu_joint:
            from .common.autotune import TunedPoint

            return self._build(TunedPoint(
                threshold=self._threshold, hierarchical=self._hier,
                overlap=self._ovl, compression=self._comp,
                route=self._route, accum=self._accum, remat=self._remat,
                shard=self._shard, moe_wire=self._moe_wire,
                pp_wire=self._pp_wire))
        if self._joint_route:
            return self._build(self._threshold, self._hier, self._ovl,
                               self._comp, self._route)
        if self._joint_comp:
            return self._build(self._threshold, self._hier, self._ovl,
                               self._comp)
        if self._joint_overlap:
            return self._build(self._threshold, self._hier, self._ovl)
        if self._joint:
            return self._build(self._threshold, self._hier)
        return self._build(self._threshold)

    @property
    def fusion_threshold(self) -> int:
        return self._threshold

    @property
    def hierarchical(self) -> bool:
        return self._hier

    @property
    def overlap(self) -> bool:
        return self._ovl

    @property
    def compression(self) -> str:
        return self._comp

    @property
    def route(self) -> str:
        return self._route

    @property
    def accum(self) -> int:
        return self._accum

    @property
    def remat(self) -> str:
        return self._remat

    @property
    def shard(self) -> int:
        """The tuned ZeRO stage (0 = replicated; docs/zero.md)."""
        return self._shard

    @property
    def moe_wire(self) -> str:
        return self._moe_wire

    @property
    def pp_wire(self) -> str:
        return self._pp_wire

    def __call__(self, *args, **kwargs):
        import time

        self._step_count += 1
        t0 = time.perf_counter()
        # metrics<->timeline bridge: a StepTraceAnnotation per step when
        # HVD_TPU_METRICS_TRACE=1, so device-side traces group by step.
        with metrics_lib.step_annotation(self._step_count):
            out = self._step(*args, **kwargs)
            if self.block:
                jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        if _METRICS_ON:
            _M_STEP.observe(dt)
        c = self._controller
        if c is None or c.size == 1:
            pt = self.tuner.feed_full(self.grad_bytes, dt)
            new = pt.threshold
            new_h = pt.hierarchical if self._joint else self._hier
            new_o = pt.overlap if self._joint_overlap else self._ovl
            new_c = pt.compression if self._joint_comp else self._comp
            new_r = pt.route if self._joint_route else self._route
            new_a = pt.accum if self._joint_accum else self._accum
            new_m = pt.remat if self._joint_remat else self._remat
            new_s = pt.shard if self._joint_shard else self._shard
            new_w = pt.moe_wire if self._joint_moe_wire \
                else self._moe_wire
            new_pw = pt.pp_wire if self._joint_pp_wire \
                else self._pp_wire
        else:
            if c.rank == 0:
                self.tuner.record(self.grad_bytes, dt)
            self._calls += 1
            (new, new_h, new_o, new_c, new_r, new_a, new_m, new_s,
             new_w, new_pw) = (
                self._threshold, self._hier, self._ovl, self._comp,
                self._route, self._accum, self._remat, self._shard,
                self._moe_wire, self._pp_wire)
            if self._calls % self._period == 0 and not self._tuner_done:
                # Sample boundary — same call index on every process
                # (SPMD lockstep), so the exchange is synchronous. After
                # rank 0 broadcasts convergence (:done) the rounds stop —
                # no point paying a KV round per period forever.
                if c.rank == 0 and self.tuner.ready():
                    self.tuner.suggest()
                cur = self.tuner.current_full  # atomic
                mine = (f"{cur.threshold}"
                        f"|{int(cur.hierarchical) if self._joint else 0}"
                        f"|{int(cur.overlap) if self._joint_overlap else 0}"
                        f"|{cur.compression if self._joint_comp else 'none'}"
                        f"|{cur.route if self._joint_route else 'flat'}"
                        f"|{cur.accum if self._joint_accum else 1}"
                        f"|{cur.remat if self._joint_remat else 'none'}"
                        f"|{int(cur.shard) if self._joint_shard else 0}"
                        f"|{cur.moe_wire if self._joint_moe_wire else 'none'}"
                        f"|{cur.pp_wire if self._joint_pp_wire else 'none'}"
                        + (":done" if c.rank == 0 and self.tuner.done
                           else ""))
                vals = c.exchange("autotune_threshold", mine)
                v0 = vals[0]  # rank 0's decision wins
                if v0.endswith(":done"):
                    self._tuner_done = True
                    v0 = v0[:-5]
                (t_str, h_str, o_str, c_str, r_str, a_str, m_str,
                 s_str, w_str, pw_str) = v0.split("|")
                new = int(t_str)
                new_h = bool(int(h_str)) if self._joint else self._hier
                new_o = bool(int(o_str)) if self._joint_overlap \
                    else self._ovl
                new_c = c_str if self._joint_comp else self._comp
                new_r = r_str if self._joint_route else self._route
                new_a = int(a_str) if self._joint_accum else self._accum
                new_m = m_str if self._joint_remat else self._remat
                new_s = int(s_str) if self._joint_shard \
                    else self._shard
                new_w = w_str if self._joint_moe_wire \
                    else self._moe_wire
                new_pw = pw_str if self._joint_pp_wire \
                    else self._pp_wire
        if (new != self._threshold or new_h != self._hier
                or new_o != self._ovl or new_c != self._comp
                or new_r != self._route or new_a != self._accum
                or new_m != self._remat or new_s != self._shard
                or new_w != self._moe_wire or new_pw != self._pp_wire):
            (self._threshold, self._hier, self._ovl, self._comp,
             self._route, self._accum, self._remat, self._shard,
             self._moe_wire, self._pp_wire) = (
                new, new_h, new_o, new_c, new_r, new_a, new_m, new_s,
                new_w, new_pw)
            self._step = self._rebuild()
            self.rebuilds += 1
            _M_REBUILDS.inc()
        return out


def broadcast_parameters(params, root_rank: int = 0,
                         axis_name: str = "hvd"):
    """Broadcast a parameter pytree from root to all ranks — for use inside
    the jitted init path (reference: torch/functions.py:30
    broadcast_parameters / tensorflow broadcast_variables)."""
    return jax.tree.map(
        lambda p: C.broadcast(p, root_rank, axis_name), params)


# -- ZeRO-1 sharded optimizer state (beyond the reference) ------------------
#
# The reference replicates optimizer state on every rank (its
# DistributedOptimizer wraps a local optimizer; state is per-rank,
# memory = full). On TPU the idiomatic win is to SHARD the state over
# the rank axis: reduce-scatter the gradients, update only this rank's
# 1/n slice of each parameter with the inner optax transform, and
# all-gather the resulting updates — optimizer memory drops to 1/n (the
# ZeRO-1 / Megatron "distributed optimizer" recipe) while the wire cost
# stays the allreduce-equivalent RS+AG pair.
#
# Works for ELEMENTWISE inner transforms (sgd/momentum/adam/adamw/...).
# Transforms that couple elements across the tree (global-norm clipping)
# would compute shard-local statistics — compose those OUTSIDE.

def _sharded_state_specs(inner, plan, axes):
    """PartitionSpecs for an inner transform's state over bucket shards:
    vector leaves P(axes) — a single axis name, or the plan's axis
    tuple under a route (fast-major) — scalar leaves (step counters)
    replicated. A length-1 probe per bucket suffices — only leaf rank
    matters."""
    from jax.sharding import PartitionSpec as P

    probe = [jax.ShapeDtypeStruct((1,), b.dtype) for b in plan.buckets]
    shapes = jax.eval_shape(inner.init, probe)
    return jax.tree.map(
        lambda s: P(axes) if s.ndim else P(), shapes)


def _gather_sharded_state(inner, plan, state, axis_name: str):
    """Sharded inner state -> WORLD-SIZE-INDEPENDENT full state: every
    vector (bucket-shard) leaf all-gathers and drops the shard-split
    padding; scalar leaves pass through. The inverse of
    :func:`_reshard_state` — together they carry ZeRO-1/FSDP state
    across an elastic WORLD-SIZE CHANGE, where the 1/n shard shapes
    (and their pad-to-multiple) differ between the old and new worlds
    so a sharded checkpoint cannot be restored directly."""
    full_probe = [jax.ShapeDtypeStruct((b.total_elems,), b.dtype)
                  for b in plan.buckets]
    full_shapes = jax.eval_shape(inner.init, full_probe)

    def one(leaf, shp):
        if shp.ndim:
            return C.allgather(leaf, axis_name)[:shp.shape[0]]
        return leaf

    return jax.tree.map(one, state, full_shapes)


def _gather_sharded_state_routed(inner, plan, state, route):
    """Mesh analog of :func:`_gather_sharded_state`: vector (bucket-
    shard) leaves all-gather over the plan in REVERSE with wires forced
    native — state carry must be lossless — and drop the grid padding;
    scalar leaves pass through. Serves both the ZeRO-1 and FSDP routed
    gathers (one derivation to maintain)."""
    exact = route.reversed().with_wires("none")
    full_probe = [jax.ShapeDtypeStruct((b.total_elems,), b.dtype)
                  for b in plan.buckets]
    full_shapes = jax.eval_shape(inner.init, full_probe)
    return jax.tree.map(
        lambda leaf, shp: (C.mesh_allgather(leaf, exact)[:shp.shape[0]]
                           if shp.ndim else leaf),
        state, full_shapes)


def _reshard_state(state_full, axis_name: str):
    """Full (gathered) inner state -> this world's shards: vector
    leaves re-split 1/n under the CURRENTLY BOUND axis (whatever its
    size), scalars pass through."""
    return jax.tree.map(
        lambda v: _shard_flat(v, axis_name) if v.ndim else v,
        state_full)


def _require_axis(axis_name: str, what: str) -> None:
    if not _axes_bound(axis_name):
        raise ValueError(
            f"{what} must run inside the jitted SPMD region (shard_map/"
            f"pjit binding axis {axis_name!r}) — the shard shapes and "
            f"slices depend on the bound axis. Wrap the call in your "
            f"spmd_step (see ShardedOptimizer docstring).")


def _shard_flat(flat, axis_name: str, align: int = 1):
    """(1-D bucket) -> this rank's padded 1/n slice. ``align`` rounds the
    per-rank chunk up to a multiple (the quantized RS path needs whole
    32x128 int8 blocks per chunk, align=4096); align=1 is the historical
    layout and MUST stay the default — sharded state is positionally
    indexed by these shapes."""
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    # pad-to-multiple-of(n*align) == per-rank chunks of ceil-aligned
    # size: ceil(ceil(L/n)/a)*a == ceil(L/(n*a))*a.
    flat, _ = fusion_lib.pad_to_multiple(flat, n * align)
    chunk = flat.shape[0] // n
    return jax.lax.dynamic_slice_in_dim(flat, idx * chunk, chunk)


# -- mesh-routed sharding (route= on the ZeRO-1/FSDP surfaces) ---------------
#
# With a WirePlan the shard grid spans ALL plan axes (N = prod of axis
# sizes) and chunk ownership is fast-axis-MAJOR — exactly the layout
# collectives.mesh_reducescatter's descent produces, so the gradient RS
# can ride the staged per-axis wires (int8 on the slow hop) and the
# update all-gather inverts it with plan.reversed() (docs/topology.md).

def _route_total(route) -> int:
    n = 1
    for a in route.axis_names:
        n *= jax.lax.axis_size(a)
    return n


def _route_align(compression, route) -> int:
    """Per-rank chunk alignment: whole 32x128 int8 blocks whenever ANY
    hop is quantized — by the error-feedback compression or by an int8
    wire on the plan itself (a stateless staged_int8 route quantizes the
    RS the same way)."""
    from .ops.collectives import _Q_BLOCK

    ef = getattr(compression, "error_feedback", False)
    if ef or (route is not None and "int8" in route.wires):
        return _Q_BLOCK
    return 1


def _mesh_shard_flat(flat, route, align: int = 1):
    """(1-D bucket) -> this rank's padded 1/N mesh slice, N = prod of
    the plan's axis sizes, fast-axis-major chunk ownership (the static
    twin of mesh_reducescatter's descent: each phase keeps this rank's
    chunk of the previous phase's chunk)."""
    N = _route_total(route)
    flat, _ = fusion_lib.pad_to_multiple(flat, N * align)
    for a in route.axis_names:
        n = jax.lax.axis_size(a)
        idx = jax.lax.axis_index(a)
        chunk = flat.shape[0] // n
        flat = jax.lax.dynamic_slice_in_dim(flat, idx * chunk, chunk)
    return flat


def _sharded_route(route, axis_name: str):
    """Resolve + trace-time fallback for the sharded surfaces.

    EXPLICIT-ONLY: unlike the reduction surfaces, ``route=None`` does
    NOT consult the ``HVD_TPU_ROUTE`` / ``init(route=)`` default here —
    the route decides the sharded STATE LAYOUT (and the PartitionSpecs
    built OUTSIDE any trace, where no fallback can be probed), and an
    env knob must never change a state layout out from under a
    flat-world program. An explicit route traced under the flat mesh
    still falls back to the flat axis (safety net — same contract as
    _reduce_tree)."""
    route = C.WirePlan.resolve(route)
    if route is not None and not _axes_bound(*route.axis_names) \
            and _axes_bound(axis_name):
        return None
    return route


class _EFShardState(NamedTuple):
    """ZeRO-1 (sharded_update) analog of :class:`_EFState`: the inner
    state over bucket shards, plus this rank's full-length fp32
    quantization residual per bucket (padded to the quantized chunk
    grid) and the stochastic-rounding step counter."""

    inner: Any
    residual: Any            # list of (n*chunk,) fp32 arrays per bucket
    step: jnp.ndarray


def _qpad_len(total_elems: int, n: int) -> int:
    """Padded bucket length on the quantized-RS chunk grid — the static
    twin of ``_shard_flat(..., align=_Q_BLOCK)``'s padding."""
    from .ops.collectives import _Q_BLOCK

    grid = n * _Q_BLOCK
    return -(-total_elems // grid) * grid


def sharded_init(tx, params, axis_name: str = "hvd",
                 fusion_threshold_bytes: Optional[int] = None,
                 compression=None, nonfinite_policy: Optional[str] = None,
                 route=None):
    """Inner-optimizer state over FUSED-BUCKET SHARDS — call inside the
    same shard_map/jit region as :func:`sharded_update` (the shard
    shapes depend on the bound axis). State structure = the inner
    transform's state over a list of per-bucket shard arrays.

    With ``compression="int8_ef"`` the gradient reduce-scatter runs
    quantized (collectives.quantized_reducescatter) and the state gains
    the error-feedback residual + step counter (:class:`_EFShardState`);
    shard chunks align to the 4096-element int8 block grid, so a state
    built with compression can only be consumed by an update using the
    SAME compression (and vice versa). ``nonfinite_policy`` likewise
    wraps the state in :class:`_GuardedState` (docs/integrity.md) —
    init and update must agree on it.

    ``route`` (EXPLICIT-ONLY — the ``HVD_TPU_ROUTE`` env default
    applies to the reduction surfaces, never to a sharded state
    layout) shards over ALL the WirePlan's mesh axes (fast-axis-major,
    1/prod(sizes) per rank — docs/topology.md): the gradient
    reduce-scatter then descends the staged per-axis wires instead of
    the flat axis. Init, update, gather and reshard must all agree on
    the route — it decides the shard grid."""
    route = _sharded_route(route, axis_name)
    if route is not None:
        for a in route.axis_names:
            _require_axis(a, "sharded_init(route=)")
    else:
        _require_axis(axis_name, "sharded_init")
    compression = _resolve_compression(compression)
    _check_reduce_safe(compression)
    ef = getattr(compression, "error_feedback", False)
    nonfinite_policy = integrity_lib.resolve_nonfinite_policy(
        nonfinite_policy)
    threshold = _resolve_fusion_threshold(fusion_threshold_bytes)
    plan = fusion_lib.plan_fusion(params, threshold)
    flats = fusion_lib.fuse(params, plan)
    from .ops.collectives import _Q_BLOCK

    if route is not None:
        align = _route_align(compression, route)
        n = _route_total(route)
        inner = tx.init([_mesh_shard_flat(f, route, align)
                         for f in flats])
    else:
        align = _Q_BLOCK if ef else 1
        n = jax.lax.axis_size(axis_name)
        inner = tx.init([_shard_flat(f, axis_name, align)
                         for f in flats])
    if ef:
        residual = [jnp.zeros((_qpad_len(b.total_elems, n),), jnp.float32)
                    for b in plan.buckets]
        inner = _EFShardState(inner=inner, residual=residual,
                              step=jnp.zeros((), jnp.int32))
    if nonfinite_policy is None:
        return inner
    return _GuardedState(
        inner=inner,
        guard=integrity_lib.init_guard_state(nonfinite_policy))


def sharded_update(tx, grads, state, params, axis_name: str = "hvd",
                   grad_op: C.ReduceOp = C.ReduceOp.AVERAGE,
                   fusion_threshold_bytes: Optional[int] = None,
                   compression=None,
                   nonfinite_policy: Optional[str] = None,
                   route=None, **extra):
    """ZeRO-1 step over fused buckets: RS(bucket grads) -> inner update
    on this rank's shards -> AG(bucket updates). A few large collectives
    instead of one pair per leaf (same bucketing as the replicated
    path). Returns ``(updates, new_state)`` with ``updates`` shaped like
    ``params`` (apply with ``optax.apply_updates``).

    ``compression="int8_ef"`` (state from ``sharded_init`` with the same
    compression) carries the gradient reduce-scatter — the hop that
    moves (n-1)/n of every gradient byte — as block-scaled int8 with
    stochastic rounding, folding each step's quantization error into the
    carried residual. The update all-gather stays in the params' dtype:
    updates are small relative to gradients' dynamic range and have no
    residual state to absorb a second rounding.

    ``route`` (state from a ``sharded_init`` with the SAME route) runs
    the gradient reduce-scatter as the staged per-axis descent
    (``collectives.mesh_reducescatter``) and the update all-gather as
    the inverse ascent — each hop in its axis's wire format, so a
    ``staged_int8`` plan puts int8 only where the slow bytes are
    (docs/topology.md). With ``int8_ef`` the descent's quantization
    error feeds the carried residual (the ``mesh_reducescatter``
    Σ-over-ranks contract) and the update ascent stays in the params'
    dtype, exactly like the flat path."""
    if grad_op not in (C.ReduceOp.SUM, C.ReduceOp.AVERAGE):
        raise ValueError("sharded_update supports SUM/AVERAGE")
    route = _sharded_route(route, axis_name)
    if route is not None:
        for a in route.axis_names:
            _require_axis(a, "sharded_update(route=)")
    else:
        _require_axis(axis_name, "sharded_update")
    compression = _resolve_compression(compression)
    ef = getattr(compression, "error_feedback", False)
    nonfinite_policy = integrity_lib.resolve_nonfinite_policy(
        nonfinite_policy)
    guarded = isinstance(state, _GuardedState)
    if (nonfinite_policy is not None) != guarded:
        raise ValueError(
            "sharded_update nonfinite_policy= must match the "
            "sharded_init that built this state: policy="
            f"{nonfinite_policy}, state "
            f"{'carries' if guarded else 'lacks'} a guard")
    inner_state = state.inner if guarded else state
    if ef != isinstance(inner_state, _EFShardState):
        raise ValueError(
            "sharded_update compression= must match the sharded_init that "
            "built this state (error-feedback state and shard alignment "
            f"differ): compression={compression.__name__}, state "
            f"{'has' if isinstance(inner_state, _EFShardState) else 'lacks'} "
            "an error-feedback residual")
    n = (_route_total(route) if route is not None
         else jax.lax.axis_size(axis_name))
    threshold = _resolve_fusion_threshold(fusion_threshold_bytes)
    # Plan over PARAMS (grads share the treedef): the state was built
    # over the params plan, and a grad leaf cast to another dtype must
    # not change the bucket structure out from under the carried state.
    plan = fusion_lib.plan_fusion(params, threshold)
    p_flats = fusion_lib.fuse(params, plan)

    def core(g, st):
        """RS -> shard-local inner update -> AG; returns
        (updates_tree ≅ grads, new_inner_state)."""
        g_flats = fusion_lib.fuse(
            jax.tree.map(lambda gg, p: gg.astype(p.dtype), g, params),
            plan)
        if not ef:
            if route is not None:
                align = _route_align(compression, route)

                def rs(f):
                    padded, _ = fusion_lib.pad_to_multiple(f, n * align)
                    return C.mesh_reducescatter(padded, grad_op, route)

                g_shards = [rs(f) for f in g_flats]
                p_shards = [_mesh_shard_flat(f, route, align)
                            for f in p_flats]
                u_shards, new_st = tx.update(g_shards, st, p_shards,
                                             **extra)
                # Ascent inverts the fast-major descent: slow axis
                # first, each hop in its axis's wire format (stateless —
                # same bounded-error contract as mesh_allreduce).
                u_flats = [C.mesh_allgather(u, route.reversed())
                           [:f.shape[0]]
                           for u, f in zip(u_shards, g_flats)]
                return fusion_lib.unfuse(u_flats, plan), new_st

            def rs(f):
                padded, _ = fusion_lib.pad_to_multiple(f, n)
                return C.reducescatter(padded, grad_op, axis_name)

            g_shards = [rs(f) for f in g_flats]
            p_shards = [_shard_flat(f, axis_name) for f in p_flats]
            u_shards, new_st = tx.update(g_shards, st, p_shards, **extra)
            u_flats = [C.allgather(u, axis_name)[:f.shape[0]]
                       for u, f in zip(u_shards, g_flats)]
            return fusion_lib.unfuse(u_flats, plan), new_st

        from .ops.collectives import _Q_BLOCK

        g_shards, new_residual = [], []
        for i, (f, res) in enumerate(zip(g_flats, st.residual)):
            pad = res.shape[0] - f.shape[0]
            corrected = jnp.pad(f.astype(jnp.float32), (0, pad)) + res
            if route is not None:
                shard, r = C.mesh_reducescatter(
                    corrected, grad_op, route,
                    key=_ef_key(st.step, i), return_residual=True)
            else:
                shard, r = C.quantized_reducescatter(
                    corrected, grad_op, axis_name,
                    key=_ef_key(st.step, i), return_residual=True)
            g_shards.append(shard.astype(f.dtype))
            new_residual.append(r)
        if route is not None:
            p_shards = [_mesh_shard_flat(f, route, _Q_BLOCK)
                        for f in p_flats]
            # Update ascent stays in the params' dtype (wires
            # downgraded): updates have no residual state to absorb a
            # second rounding — the flat int8_ef contract.
            u_gather = route.reversed().with_wires("none")
        else:
            p_shards = [_shard_flat(f, axis_name, _Q_BLOCK)
                        for f in p_flats]
            u_gather = None
        u_shards, new_inner = tx.update(g_shards, st.inner, p_shards,
                                        **extra)
        u_flats = [(C.mesh_allgather(u, u_gather)
                    if u_gather is not None
                    else C.allgather(u, axis_name))[:f.shape[0]]
                   for u, f in zip(u_shards, g_flats)]
        new_st = _EFShardState(inner=new_inner, residual=new_residual,
                               step=st.step + 1)
        return fusion_lib.unfuse(u_flats, plan), new_st

    if not guarded:
        return core(grads, inner_state)
    # Guarded (docs/integrity.md): the cond wraps RS + update + AG, so
    # a skipped step leaves shards, EF residual and step untouched.
    # Under a route the one-scalar agreement runs over the PLAN's axes
    # (every mesh rank must take the same branch).
    updates, new_inner, new_guard = integrity_lib.guarded_apply(
        nonfinite_policy, core, grads, inner_state, state.guard,
        tuple(route.axis_names) if route is not None else axis_name)
    return updates, _GuardedState(new_inner, new_guard)


class ShardedOptimizer:
    """Object wrapper over :func:`sharded_init`/:func:`sharded_update`
    mirroring the optax GradientTransformation shape::

        tx = hvd.ShardedOptimizer(optax.adamw(1e-3), axis_name=ax)
        # inside the jitted step (axis bound):
        state = tx.init(params)                  # 1/n-sized state
        updates, state = tx.update(grads, state, params)
    """

    def __init__(self, inner, axis_name: str = "hvd",
                 grad_op: C.ReduceOp = C.ReduceOp.AVERAGE,
                 fusion_threshold_bytes: Optional[int] = None,
                 compression=None, nonfinite_policy: Optional[str] = None,
                 route=None, accum_steps: Optional[int] = None,
                 remat_policy: Optional[str] = None):
        self.inner = inner
        self.axis_name = axis_name
        self.grad_op = grad_op
        # Scan-based accumulation (docs/performance.md): pinned once
        # like the threshold; consumed by accumulate() — update() runs
        # once per EFFECTIVE step either way, so the RS+AG pair, the
        # guard agreement, and the EF advance stay once-per-step.
        self.accum_steps = _resolve_accum_steps(accum_steps)
        self.remat_policy = resolve_remat_policy(remat_policy)[0]
        # Pinned ONCE (like the DistributedOptimizer factory): the state
        # layout is one shard per bucket, so a live autotuner moving the
        # threshold between traces must not replan the buckets out from
        # under the carried state. Same for the compression: it decides
        # the shard alignment and the state structure (_EFShardState).
        # And the non-finite policy: it decides whether the state is
        # _GuardedState-wrapped (docs/integrity.md). And the route: it
        # decides the SHARD GRID (1/prod(mesh sizes), fast-axis-major)
        # — docs/topology.md.
        self.fusion_threshold_bytes = _resolve_fusion_threshold(
            fusion_threshold_bytes)
        self.compression = _resolve_compression(compression)
        _check_reduce_safe(self.compression)
        self._ef = getattr(self.compression, "error_feedback", False)
        self.nonfinite_policy = integrity_lib.resolve_nonfinite_policy(
            nonfinite_policy)
        # Explicit-only (no HVD_TPU_ROUTE default): the route decides
        # the state layout AND the state_specs built outside any trace.
        self.route = C.WirePlan.resolve(route)

    def _live_route(self):
        """The pinned route with the trace-time flat-mesh fallback
        applied (a defaulted route under the flat mesh must not change
        the shard grid — same contract as the reduction surfaces)."""
        return _sharded_route(self.route, self.axis_name)

    def accumulate(self, loss_fn, has_aux: bool = False):
        """The scan-based microbatch ``value_and_grad`` for the pinned
        ``accum_steps``/``remat_policy`` (:func:`accumulate_gradients`)
        — feed its mean gradient to :meth:`update` once per effective
        step."""
        return accumulate_gradients(loss_fn, self.accum_steps,
                                    self.remat_policy, has_aux=has_aux)

    def init(self, params):
        return sharded_init(self.inner, params, self.axis_name,
                            self.fusion_threshold_bytes,
                            compression=self.compression,
                            nonfinite_policy=self.nonfinite_policy,
                            route=self.route)

    def update(self, grads, state, params=None, **extra):
        if params is None:
            raise ValueError("ShardedOptimizer.update requires params "
                             "(the shard slices come from them)")
        return sharded_update(self.inner, grads, state, params,
                              self.axis_name, self.grad_op,
                              self.fusion_threshold_bytes,
                              compression=self.compression,
                              nonfinite_policy=self.nonfinite_policy,
                              route=self.route,
                              **extra)

    def state_specs(self, params):
        """PartitionSpecs for carrying the sharded state through
        shard_map: vector leaves are P(axis) (each rank owns its slice;
        the global array is the shard concatenation), scalar leaves
        (step counters) replicate. The probe uses the same fusion plan
        as init/update so the state STRUCTURE (one shard per bucket)
        matches — callable before init(). With an error-feedback
        compression the residual leaves are per-rank LOCAL (each rank's
        own quantization error), carried as P(axis) shards of the
        rank-stacked global view; the step counter replicates. Under a
        route the shard dim spans ALL plan axes fast-axis-major —
        ``P((fast, ..., slow))``."""
        from jax.sharding import PartitionSpec as P

        axes = (tuple(self.route.axis_names) if self.route is not None
                else self.axis_name)
        threshold = _resolve_fusion_threshold(self.fusion_threshold_bytes)
        plan = fusion_lib.plan_fusion(params, threshold)
        inner_specs = _sharded_state_specs(self.inner, plan, axes)
        if self._ef:
            inner_specs = _EFShardState(
                inner=inner_specs,
                residual=[P(axes)] * len(plan.buckets),
                step=P())
        if self.nonfinite_policy is None:
            return inner_specs
        # Guard scalars are globally agreed -> replicated.
        return _GuardedState(inner=inner_specs,
                             guard=integrity_lib.guard_state_specs())

    def gather_state(self, state, params):
        """Sharded state -> world-size-independent full state (inside
        the OLD world's SPMD region) — checkpoint this across an
        elastic resize; restore with :meth:`reshard_state` in the new
        world.

        The layout is still FUSION-PLAN-dependent: the new world's
        optimizer must resolve the SAME fusion threshold (pass
        ``fusion_threshold_bytes`` explicitly in elastic jobs — a
        live autotuner or changed env knob in the restarted process
        would re-bucket and silently misalign the per-bucket mu/nu
        vectors).

        Error-feedback states carry the residual across the resize as
        its PSUM: Σ_r residual_r is the total pending correction and is
        world-size-independent; :meth:`reshard_state` hands it to the
        new world's rank 0 (zeros elsewhere) — the next reduction sums
        residuals across ranks anyway, so placement is arbitrary.

        Routed states gather/psum over ALL the plan's axes (wires
        forced native — state carry must be exact); the gathered form
        is identical to the flat one, so a checkpoint written under a
        route restores into a flat world and vice versa (the residual's
        psum is grid-padding-independent: pads carry zeros)."""
        route = self._live_route()
        if route is not None:
            for a in route.axis_names:
                _require_axis(a, "ShardedOptimizer.gather_state")
        else:
            _require_axis(self.axis_name, "ShardedOptimizer.gather_state")
        threshold = _resolve_fusion_threshold(self.fusion_threshold_bytes)
        plan = fusion_lib.plan_fusion(params, threshold)
        guard = state.guard if isinstance(state, _GuardedState) else None
        if guard is not None:
            state = state.inner
        if route is not None:
            axes = tuple(route.axis_names)
            if not self._ef:
                full = _gather_sharded_state_routed(self.inner, plan,
                                                    state, route)
            else:
                inner_full = _gather_sharded_state_routed(
                    self.inner, plan, state.inner, route)
                residual_full = [
                    jax.lax.psum(r, axes)[:b.total_elems]
                    for r, b in zip(state.residual, plan.buckets)]
                full = _EFShardState(inner=inner_full,
                                     residual=residual_full,
                                     step=state.step)
        elif not self._ef:
            full = _gather_sharded_state(self.inner, plan, state,
                                         self.axis_name)
        else:
            inner_full = _gather_sharded_state(self.inner, plan,
                                               state.inner,
                                               self.axis_name)
            residual_full = [
                jax.lax.psum(r, self.axis_name)[:b.total_elems]
                for r, b in zip(state.residual, plan.buckets)]
            full = _EFShardState(inner=inner_full, residual=residual_full,
                                 step=state.step)
        if guard is None:
            return full
        # Guard scalars are replicated/world-size-independent — carried
        # across the resize verbatim.
        return _GuardedState(inner=full, guard=guard)

    def reshard_state(self, state_full):
        """Full (gathered) state -> this world's shards (inside the
        NEW world's SPMD region, whatever its size — or its ROUTE: a
        flat checkpoint reshards onto a mesh-routed world and back)."""
        route = self._live_route()
        if route is not None:
            for a in route.axis_names:
                _require_axis(a, "ShardedOptimizer.reshard_state")
        else:
            _require_axis(self.axis_name, "ShardedOptimizer.reshard_state")
        guard = state_full.guard \
            if isinstance(state_full, _GuardedState) else None
        if guard is not None:
            state_full = state_full.inner
        from .ops.collectives import _Q_BLOCK

        if route is not None:
            align = _route_align(self.compression, route)
            n = _route_total(route)
            # "Am I mesh rank 0" = every plan axis index is 0.
            me0 = jnp.asarray(True)
            for a in route.axis_names:
                me0 = jnp.logical_and(me0, jax.lax.axis_index(a) == 0)

            def shard_leaf(v):
                return _mesh_shard_flat(v, route, align) if v.ndim else v
        else:
            align = _Q_BLOCK
            n = jax.lax.axis_size(self.axis_name)
            me0 = jax.lax.axis_index(self.axis_name) == 0

            def shard_leaf(v):
                return (_shard_flat(v, self.axis_name, align)
                        if v.ndim else v)

        if not self._ef:
            if route is None:
                sharded = _reshard_state(state_full, self.axis_name)
            else:
                sharded = jax.tree.map(shard_leaf, state_full)
            return sharded if guard is None else \
                _GuardedState(inner=sharded, guard=guard)
        inner = jax.tree.map(shard_leaf, state_full.inner)
        residual = []
        for r in state_full.residual:
            pad = _qpad_len(r.shape[0], n) - r.shape[0]
            r = jnp.pad(r, (0, pad))
            residual.append(jnp.where(me0, r, jnp.zeros_like(r)))
        sharded = _EFShardState(inner=inner, residual=residual,
                                step=state_full.step)
        return sharded if guard is None else \
            _GuardedState(inner=sharded, guard=guard)


# -- FSDP / ZeRO-3: fully-sharded parameters (beyond the reference) ---------
#
# ZeRO-1 (above) shards the OPTIMIZER STATE; FSDP additionally keeps the
# PARAMETERS at rest as 1/n bucket shards. Per step: all-gather shards ->
# full params for compute, reduce-scatter grads -> shard-local inner
# update -> new shards. At-rest memory for params + Adam state drops to
# 1/n; the transient peak is full params + activations during the step
# (fusion-bucket granularity — XLA's scheduler overlaps the per-bucket
# allgathers with the first layers' compute the same way it overlaps the
# grad reduction with backprop). Wire cost per step: AG(params) +
# RS(grads) — the same bytes as ZeRO-1's RS+AG pair plus the param
# gather that replicated storage gets for free.

class FSDPOptimizer:
    """Fully-sharded (ZeRO-3-style) training helper over fused buckets::

        tx = hvd.FSDPOptimizer(optax.adamw(1e-3), axis_name=ax)
        # inside the jitted SPMD region (axis bound):
        shards = tx.shard_params(params)    # full -> 1/n bucket shards
        state  = tx.init(shards)            # inner state on shards (1/n)
        # each step:
        full   = tx.gather_params(shards)   # AG per bucket -> pytree
        loss, grads = jax.value_and_grad(loss_fn)(full, batch)
        shards, state = tx.update(grads, state, shards)  # RS + update

    Carry ``shards``/``state`` through shard_map with
    :meth:`shard_specs` / :meth:`state_specs` (leaves are P(axis)).
    Elementwise inner transforms only — same contract as
    :class:`ShardedOptimizer`."""

    def __init__(self, inner, axis_name: str = "hvd",
                 grad_op: C.ReduceOp = C.ReduceOp.AVERAGE,
                 fusion_threshold_bytes: Optional[int] = None,
                 route=None):
        if grad_op not in (C.ReduceOp.SUM, C.ReduceOp.AVERAGE):
            raise ValueError("FSDPOptimizer supports SUM/AVERAGE")
        self.inner = inner
        self.axis_name = axis_name
        self.grad_op = grad_op
        self.fusion_threshold_bytes = _resolve_fusion_threshold(
            fusion_threshold_bytes)
        # Route (docs/topology.md): params at rest shard over ALL plan
        # axes (fast-axis-major); the per-step param all-gather ascends
        # and the grad reduce-scatter descends the staged per-axis
        # wires. Pinned like the threshold — it decides the shard grid.
        # Explicit-only: the HVD_TPU_ROUTE default never reshapes a
        # sharded state layout (shard_specs are built outside traces).
        self.route = C.WirePlan.resolve(route)
        self._plan = None
        self._flat_lens = None
        self._sig = None

    def _live_route(self):
        return _sharded_route(self.route, self.axis_name)

    def _require_route_axes(self, route, what: str) -> None:
        if route is not None:
            for a in route.axis_names:
                _require_axis(a, what)
        else:
            _require_axis(self.axis_name, what)

    def bind(self, params_template):
        """Pin the bucket plan from a params pytree (real arrays or
        ShapeDtypeStructs). Called implicitly by shard_params; explicit
        bind() lets gather/update trace in a separate jit region.

        The instance is stateful: the first bind pins the tree
        structure, and a later bind with a STRUCTURALLY DIFFERENT
        template raises — silently replacing the plan would misalign
        any shards already produced under the old one. Use unbind() (or
        a fresh instance) to retarget deliberately."""
        sig = (str(jax.tree.structure(params_template)),
               tuple((tuple(x.shape), str(x.dtype))
                     for x in jax.tree.leaves(params_template)))
        if self._sig is not None and sig != self._sig:
            raise ValueError(
                "FSDPOptimizer is already bound to a different param "
                "tree (structure or leaf shapes changed); shards from "
                "the old plan would silently misalign. Use a fresh "
                "FSDPOptimizer per param tree, or call unbind() first")
        self._sig = sig
        self._plan = fusion_lib.plan_fusion(params_template,
                                            self.fusion_threshold_bytes)
        self._flat_lens = [b.total_elems for b in self._plan.buckets]
        return self

    def unbind(self):
        """Drop the bound plan so the instance can be re-bound to a new
        param tree (any shards/state from the old plan become invalid)."""
        self._plan = self._flat_lens = self._sig = None
        return self

    def _require_bound(self, what: str):
        if self._plan is None:
            raise ValueError(
                f"{what} needs the bucket plan — call shard_params "
                f"(or bind(params_template)) first")

    def _check_shards(self, shards, what: str):
        if len(shards) != len(self._flat_lens):
            raise ValueError(
                f"{what}: got {len(shards)} bucket shards but the bound "
                f"plan has {len(self._flat_lens)} buckets — these shards "
                f"come from a different plan/template")

    def shard_params(self, params):
        """Full params -> list of this rank's 1/n bucket shards (1/N
        over all plan axes under a route)."""
        route = self._live_route()
        self._require_route_axes(route, "FSDPOptimizer.shard_params")
        self.bind(params)
        flats = fusion_lib.fuse(params, self._plan)
        if route is not None:
            align = _route_align(NoneCompressor, route)
            return [_mesh_shard_flat(f, route, align) for f in flats]
        return [_shard_flat(f, self.axis_name) for f in flats]

    def gather_params(self, shards):
        """Bucket shards -> full params pytree (one all-gather per
        bucket; padding from the shard split sliced back off). Under a
        route the gather ascends the plan in reverse, each hop in its
        axis's wire format — a staged_int8 plan moves the slow-axis
        param bytes as block-scaled int8 (stateless, bounded like
        mesh_allreduce's ascent)."""
        self._require_bound("gather_params")
        self._check_shards(shards, "gather_params")
        route = self._live_route()
        self._require_route_axes(route, "FSDPOptimizer.gather_params")
        if route is not None:
            inv = route.reversed()
            flats = [C.mesh_allgather(s, inv)[:length]
                     for s, length in zip(shards, self._flat_lens)]
        else:
            flats = [C.allgather(s, self.axis_name)[:length]
                     for s, length in zip(shards, self._flat_lens)]
        return fusion_lib.unfuse(flats, self._plan)

    def init(self, shards):
        return self.inner.init(shards)

    def update(self, grads, state, shards, **extra):
        """RS(full grads) -> inner update on this rank's shards ->
        apply. Returns (new_shards, new_state). Under a route the RS
        descends the staged per-axis wires (docs/topology.md)."""
        self._require_bound("update")
        self._check_shards(shards, "update")
        route = self._live_route()
        self._require_route_axes(route, "FSDPOptimizer.update")
        g_flats = fusion_lib.fuse(grads, self._plan)

        if route is not None:
            n = _route_total(route)
            align = _route_align(NoneCompressor, route)

            def rs(f):
                padded, _ = fusion_lib.pad_to_multiple(f, n * align)
                return C.mesh_reducescatter(padded, self.grad_op, route)
        else:
            n = jax.lax.axis_size(self.axis_name)

            def rs(f):
                padded, _ = fusion_lib.pad_to_multiple(f, n)
                return C.reducescatter(padded, self.grad_op,
                                       self.axis_name)

        g_shards = [rs(f).astype(s.dtype)
                    for f, s in zip(g_flats, shards)]
        u_shards, new_state = self.inner.update(g_shards, state, shards,
                                                **extra)
        new_shards = [(s + u).astype(s.dtype)
                      for s, u in zip(shards, u_shards)]
        return new_shards, new_state

    def shard_specs(self, params_template):
        """P(axis) per bucket shard — for carrying shards through
        shard_map (P((fast, ..., slow)) over all plan axes under a
        route). Binds the plan from the template."""
        from jax.sharding import PartitionSpec as P

        self.bind(params_template)
        axes = (tuple(self.route.axis_names) if self.route is not None
                else self.axis_name)
        return [P(axes)] * len(self._flat_lens)

    def state_specs(self, params_template):
        """Specs for the inner state over bucket shards (vector leaves
        P(axis) — or the plan's axis tuple under a route; scalars
        replicated)."""
        self.bind(params_template)
        axes = (tuple(self.route.axis_names) if self.route is not None
                else self.axis_name)
        return _sharded_state_specs(self.inner, self._plan, axes)

    def gather_state(self, state):
        """Sharded state -> world-size-independent full state (inside
        the OLD world's SPMD region); pair with :meth:`reshard_state`
        (and gather_params/shard_params for the params themselves) to
        carry FSDP training across an elastic resize.

        Same caveat as ShardedOptimizer.gather_state: the layout is
        fusion-plan-dependent — pin ``fusion_threshold_bytes``
        explicitly across the resize so the new world re-buckets
        identically."""
        self._require_bound("gather_state")
        route = self._live_route()
        self._require_route_axes(route, "FSDPOptimizer.gather_state")
        if route is None:
            return _gather_sharded_state(self.inner, self._plan, state,
                                         self.axis_name)
        return _gather_sharded_state_routed(self.inner, self._plan,
                                            state, route)

    def reshard_state(self, state_full):
        """Full (gathered) state -> this world's 1/n shards (inside the
        NEW world's SPMD region, whatever its size or route)."""
        route = self._live_route()
        self._require_route_axes(route, "FSDPOptimizer.reshard_state")
        if route is None:
            return _reshard_state(state_full, self.axis_name)
        align = _route_align(NoneCompressor, route)
        return jax.tree.map(
            lambda v: (_mesh_shard_flat(v, route, align)
                       if v.ndim else v),
            state_full)


# -- ZeRO-2/3: gradient- and parameter-sharded training (docs/zero.md) -------
#
# ZeRO-1 (ShardedOptimizer, above) shards the OPTIMIZER STATE over the
# rank axis (or the WirePlan grid). ZeRO-2 additionally keeps the
# GRADIENT accumulator as 1/N shards: each microbatch's gradients are
# reduce-scattered straight into the owner's shard, so no full-size
# accumulated gradient ever materializes. ZeRO-3 additionally keeps the
# PARAMETERS at rest as 1/N bucket shards, all-gathered ON DEMAND per
# readiness-ordered bucket for the step's compute and freed after use
# (XLA liveness): the gather chain pins bucket order with the
# optimization-barrier pattern (common/overlap.py, parallel/moe.py), so
# the async-collective scheduler may prefetch bucket k+1's params under
# bucket k's compute. Overlap bucketing's readiness order IS the gather
# schedule — forward (flatten) order for the param gathers, reverse for
# the gradient reduce-scatters.
#
# Wire model per effective step (docs/zero.md): stage 1/2 pay
# RS(grads) + AG(updates); stage 3 pays AG(params) + RS(grads) — the
# same ring bytes, with the update AG traded for the on-demand param
# gather. All hops ride the route's per-axis wires; int8_ef keeps its
# Σ-residual contract on the quantized descent (mesh_reducescatter).

def _zero_count_bytes(kind: str, nelems: int, itemsize: int, route,
                      axis_name: str, wire: Optional[str] = None) -> None:
    """Trace-time ring accounting of one sharded-collective descent or
    ascent into ``hvd_tpu_zero_gather_bytes_total``: ``(n-1)/n`` of the
    live buffer per device per axis, each hop priced at its wire format
    (``collectives.mesh_wire_cost``'s recipe). Axis sizes are trace-time
    constants, so the increments are static per compile. ``wire``
    overrides the flat-axis payload name (the quantized flat RS)."""
    if not _METRICS_ON:
        return
    length = float(nelems)
    if route is None:
        if not _axes_bound(axis_name):
            return
        n = jax.lax.axis_size(axis_name)
        w = wire or "none"
        _M_ZERO_GATHER.labels(kind=kind, wire=w, axis=axis_name).inc(
            (n - 1) / n * length * C._wire_elem_bytes(w, itemsize))
        return
    if not _axes_bound(*route.axis_names):
        return
    for p in route.phases:
        n = jax.lax.axis_size(p.axis)
        w = wire or p.wire
        _M_ZERO_GATHER.labels(kind=kind, wire=w, axis=p.axis).inc(
            (n - 1) / n * length * C._wire_elem_bytes(w, itemsize))
        length /= n


def _is_shard_grads(grads, like=None) -> bool:
    """True when ``grads`` is a list/tuple of 1-D bucket-shard arrays
    (the output of the ZeRO-2/3 shard accumulators) rather than a
    params-shaped pytree. ``like`` (params, or the stage-3 shard list)
    disambiguates the pathological case where the params tree is
    ITSELF a flat list of 1-D vectors: a tree with ``like``'s
    structure AND leaf shapes is a full-gradient tree, never shards —
    while stage-3 shard grads must match the shard list's shapes
    exactly."""
    if not isinstance(grads, (list, tuple)) or not grads:
        return False
    if not all(getattr(jnp.asarray(g), "ndim", None) == 1
               for g in grads):
        return False
    if like is None:
        return True
    g_shapes = [tuple(jnp.shape(g)) for g in grads]
    if isinstance(like, (list, tuple)) and like \
            and all(getattr(jnp.asarray(s), "ndim", None) == 1
                    for s in like):
        # Stage-3 form: ``like`` is the param-shard list — shard grads
        # mirror it one-to-one.
        return g_shapes == [tuple(jnp.shape(s)) for s in like]
    if jax.tree.structure(grads) != jax.tree.structure(like):
        return True
    return g_shapes != [tuple(jnp.shape(p))
                        for p in jax.tree.leaves(like)]


class ZeroOptimizer:
    """One surface over the ZeRO stages (docs/zero.md)::

        tx = hvd.DistributedOptimizer(optax.adamw(1e-3), zero_stage=3,
                                      axis_name=ax)           # == this
        tx = hvd.ZeroOptimizer(optax.adamw(1e-3), zero_stage=3,
                               axis_name=ax)

    Stage semantics (all inside the jitted SPMD region — the shard
    shapes come from the bound axes):

    * ``zero_stage=1`` — optimizer state sharded; full grads in,
      RS -> shard update -> AG(updates) out. Exactly
      :class:`ShardedOptimizer` (delegated; same state layout,
      checkpoint-compatible).
    * ``zero_stage=2`` — plus gradient sharding: :meth:`accumulate`
      carries a 1/N-shard fp32 accumulator (reduce-scatter per
      microbatch, exact native wires), and :meth:`update` accepts the
      resulting shard-gradient list directly (no RS inside). Full-grad
      ``update()`` calls keep stage-1 semantics, so the two stages are
      state-compatible.
    * ``zero_stage=3`` — plus parameter sharding: params live as 1/N
      fast-major bucket shards (:meth:`shard_params`), are gathered on
      demand (:meth:`gather_params` — per-bucket all-gathers chained in
      readiness order so bucket k+1's gather can fly under bucket k's
      compute), and :meth:`update` returns NEW SHARDS (the update never
      all-gathers; the next step's param gather is the inverse hop).

    Composition contracts:

    * ``route=`` (explicit-only, like every sharded surface): the shard
      grid spans ALL plan axes fast-major and every RS/AG hop rides the
      plan's per-axis wires (int8 on the slow hop under
      ``staged_int8``).
    * ``compression="int8_ef"``: the quantized gradient descent keeps
      the Σ-over-ranks residual contract (``mesh_reducescatter``); the
      residual advances once per quantized descent — under the stage-2/3
      shard accumulator the per-microbatch RS is EXACT (native wires),
      so the EF residual advances only on full-grad ``update()`` calls
      (accum_steps=1) and never drifts silently.
    * ``nonfinite_policy``: one globally-agreed flag over the plan's
      axes; a skipped step leaves shards, inner state, EF residual and
      step counter untouched (stage 3 adds zeros to the param shards).
    * ``accum_steps``/``remat_policy``: :meth:`accumulate` gathers
      params ONCE per effective step (stage 3) and accumulates
      shard-sized gradients (stages 2/3) — the gather count is
      trace-verified (tests/test_zero.py).

    Elementwise inner transforms only — the ShardedOptimizer contract.
    """

    def __init__(self, inner, zero_stage: int = 2,
                 axis_name: str = "hvd",
                 grad_op: C.ReduceOp = C.ReduceOp.AVERAGE,
                 fusion_threshold_bytes: Optional[int] = None,
                 compression=None,
                 nonfinite_policy: Optional[str] = None,
                 route=None, accum_steps: Optional[int] = None,
                 remat_policy: Optional[str] = None,
                 overlap: bool = True, bucket_order=None,
                 parallel=None):
        stage = int(zero_stage)
        if stage not in (1, 2, 3):
            raise ValueError(
                f"zero_stage must be 1, 2 or 3, got {zero_stage!r} "
                "(0/off = the replicated DistributedOptimizer)")
        if grad_op not in (C.ReduceOp.SUM, C.ReduceOp.AVERAGE):
            raise ValueError("ZeroOptimizer supports SUM/AVERAGE")
        # Hybrid parallelism (docs/pipeline.md): the spec pins the shard
        # grid + reduction to the dp axis ONLY, so ZeRO shards live PER
        # PIPELINE STAGE (each pp/tp coordinate forms its own dp shard
        # group) and the guard agrees over dp only; tp slice grads are
        # pmean-combined before every reduce-scatter.
        self._tp_axis = None
        pspec = _resolve_parallel(parallel)
        if pspec is not None:
            if not pspec.dp_axes:
                raise ValueError(
                    f"parallel spec {pspec.describe()!r} has no dp axis "
                    "— ZeRO shards gradient/optimizer/param state over "
                    "the data-parallel replicas; a pure pp x tp spec "
                    "has nothing to shard over")
            if route is not None:
                rt = C.WirePlan.resolve(route)
                if rt is not None and set(rt.axis_names) != set(
                        pspec.dp_axes):
                    raise ValueError(
                        f"route axes {rt.axis_names} must be exactly "
                        f"the parallel spec's dp axes {pspec.dp_axes}")
            else:
                # Shard and reduce through the mesh router over the dp
                # axis (mirroring DistributedOptimizer's parallel=
                # default): the router stamps the per-axis byte
                # counters (hvd_tpu_zero_gather_bytes_total /
                # hvd_tpu_allreduce_bytes_total axis="dp") that prove
                # the hybrid schedule's wire mix.
                route = pspec.grad_route()
            axis_name = pspec.dp_axes[0]
            self._tp_axis = tuple(
                a for a in (pspec.tp_axis, pspec.sp_axis)
                if a is not None) or None
        self.zero_stage = stage
        self.inner = inner
        self.axis_name = axis_name
        self.grad_op = grad_op
        self.fusion_threshold_bytes = _resolve_fusion_threshold(
            fusion_threshold_bytes)
        self.compression = _resolve_compression(compression)
        _check_reduce_safe(self.compression)
        self._ef = getattr(self.compression, "error_feedback", False)
        self.nonfinite_policy = integrity_lib.resolve_nonfinite_policy(
            nonfinite_policy)
        # Explicit-only (no HVD_TPU_ROUTE default): the route decides
        # the shard grid and the PartitionSpecs built outside traces.
        self.route = C.WirePlan.resolve(route)
        self.accum_steps = _resolve_accum_steps(accum_steps)
        self.remat_policy = resolve_remat_policy(remat_policy)[0]
        self.overlap = bool(overlap)
        self.bucket_order = bucket_order
        # Stages 1/2 ride the ZeRO-1 substrate unchanged: same state
        # layout, EF/guard wrapping, gather/reshard — checkpoint- and
        # elastic-compatible by construction.
        self._z1 = ShardedOptimizer(
            inner, axis_name=axis_name, grad_op=grad_op,
            fusion_threshold_bytes=self.fusion_threshold_bytes,
            compression=self.compression,
            nonfinite_policy=self.nonfinite_policy, route=self.route,
            accum_steps=self.accum_steps,
            remat_policy=self.remat_policy)
        # Stage-3 bound plan (the FSDPOptimizer binding contract).
        self._plan = None
        self._flat_lens = None
        self._sig = None

    # -- shared plumbing -----------------------------------------------------

    def _live_route(self):
        return _sharded_route(self.route, self.axis_name)

    def _maybe_combine_tp(self, grads):
        """Reassemble tensor/sequence-parallel slice gradients (pmean
        over tp, then sp) before a full-gradient tree enters any
        reduce-scatter — no-op without a parallel spec, or when an axis
        is unbound in this trace (the model then ran unsharded over it
        and grads are exact)."""
        if self._tp_axis is None:
            return grads
        return _combine_tp(grads, self._tp_axis)

    def _require_route_axes(self, route, what: str) -> None:
        if route is not None:
            for a in route.axis_names:
                _require_axis(a, what)
        else:
            _require_axis(self.axis_name, what)

    def _axes(self, route):
        return tuple(route.axis_names) if route is not None \
            else self.axis_name

    def _n(self, route) -> int:
        return (_route_total(route) if route is not None
                else jax.lax.axis_size(self.axis_name))

    def _plan_z12(self, params):
        """Stages 1/2 plan the buckets from the live params each call
        (the sharded_update contract — state carries one shard per
        bucket of THIS plan)."""
        return fusion_lib.plan_fusion(params, self.fusion_threshold_bytes)

    # -- stage-3 plan binding (the FSDPOptimizer contract) -------------------

    def bind(self, params_template):
        """Pin the stage-3 bucket plan from a params pytree (arrays or
        ShapeDtypeStructs). A later bind with a structurally different
        template raises — shards from the old plan would silently
        misalign; unbind() (or a fresh instance) retargets."""
        sig = (str(jax.tree.structure(params_template)),
               tuple((tuple(x.shape), str(x.dtype))
                     for x in jax.tree.leaves(params_template)))
        if self._sig is not None and sig != self._sig:
            raise ValueError(
                "ZeroOptimizer is already bound to a different param "
                "tree (structure or leaf shapes changed); use a fresh "
                "instance per param tree, or call unbind() first")
        self._sig = sig
        order = (self.bucket_order if self.bucket_order is not None
                 else fusion_lib.ORDER_FLATTEN)
        self._plan = fusion_lib.plan_fusion(
            params_template, self.fusion_threshold_bytes, order=order)
        self._flat_lens = [b.total_elems for b in self._plan.buckets]
        return self

    def unbind(self):
        self._plan = self._flat_lens = self._sig = None
        return self

    def _require_bound(self, what: str):
        if self._plan is None:
            raise ValueError(
                f"{what} needs the stage-3 bucket plan — call "
                f"shard_params (or bind(params_template)) first")

    def _check_shards(self, shards, what: str):
        if len(shards) != len(self._flat_lens):
            raise ValueError(
                f"{what}: got {len(shards)} bucket shards but the bound "
                f"plan has {len(self._flat_lens)} buckets — these "
                f"shards come from a different plan/template")

    # -- stage-3 parameter residency -----------------------------------------

    def shard_params(self, params):
        """Full params -> this rank's 1/N bucket shards (stage 3; the
        at-rest layout — fast-axis-major over all plan axes under a
        route). Publishes the per-rank resident-byte gauge."""
        if self.zero_stage < 3:
            raise ValueError(
                "shard_params is the stage-3 surface (params stay "
                f"replicated under zero_stage={self.zero_stage})")
        route = self._live_route()
        self._require_route_axes(route, "ZeroOptimizer.shard_params")
        self.bind(params)
        flats = fusion_lib.fuse(params, self._plan)
        align = _route_align(self.compression, route)
        if route is not None:
            shards = [_mesh_shard_flat(f, route, align) for f in flats]
        else:
            shards = [_shard_flat(f, self.axis_name, align)
                      for f in flats]
        if _METRICS_ON:
            resident = sum(int(s.shape[0]) * jnp.dtype(s.dtype).itemsize
                           for s in shards)
            _M_ZERO_RESIDENT.labels(stage="3").set(resident)
        return shards

    def gather_params(self, shards):
        """Bucket shards -> full params pytree: ONE all-gather per
        readiness-ordered bucket, chained through an
        ``optimization_barrier`` so the issue order is pinned (bucket
        k+1's gather may then fly under bucket k's compute — the
        prefetch schedule; inert on CPU, numerics unchanged). Under a
        route the gather ascends the plan in reverse, each hop in its
        axis's wire format, and the moved bytes land in
        ``hvd_tpu_zero_gather_bytes_total{kind="param"}``."""
        self._require_bound("gather_params")
        self._check_shards(shards, "gather_params")
        route = self._live_route()
        self._require_route_axes(route, "ZeroOptimizer.gather_params")
        if route is not None:
            inv = route.reversed()

            def ag(s):
                return C.mesh_allgather(s, inv)
        else:
            def ag(s):
                return C.allgather(s, self.axis_name)

        if self.overlap:
            from .common import overlap as overlap_lib

            outs = overlap_lib.chain_issue_order(shards, ag)
        else:
            outs = [ag(s) for s in shards]
        flats = [o[:length]
                 for o, length in zip(outs, self._flat_lens)]
        for b in self._plan.buckets:
            _zero_count_bytes("param", b.total_elems,
                              jnp.dtype(b.dtype).itemsize, route,
                              self.axis_name)
        return fusion_lib.unfuse(flats, self._plan)

    def shard_specs(self, params_template):
        """P(axes) per stage-3 bucket shard, for carrying the shards
        through shard_map. Binds the plan."""
        from jax.sharding import PartitionSpec as P

        self.bind(params_template)
        axes = (tuple(self.route.axis_names) if self.route is not None
                else self.axis_name)
        return [P(axes)] * len(self._flat_lens)

    # -- state ---------------------------------------------------------------

    def init(self, params_or_shards):
        """Stage 1/2: ``init(params)`` (sharded_init). Stage 3:
        ``init(shards)`` — inner state over the param shards, plus the
        EF residual / guard wrappers when configured."""
        if self.zero_stage < 3:
            return self._z1.init(params_or_shards)
        shards = params_or_shards
        self._require_bound("ZeroOptimizer.init")
        self._check_shards(shards, "init")
        inner = self.inner.init(list(shards))
        if self._ef:
            n = self._n(self._live_route())
            residual = [jnp.zeros((_qpad_len(b.total_elems, n),),
                                  jnp.float32)
                        for b in self._plan.buckets]
            inner = _EFShardState(inner=inner, residual=residual,
                                  step=jnp.zeros((), jnp.int32))
        if self.nonfinite_policy is None:
            return inner
        return _GuardedState(
            inner=inner,
            guard=integrity_lib.init_guard_state(self.nonfinite_policy))

    def state_specs(self, params_template):
        if self.zero_stage < 3:
            return self._z1.state_specs(params_template)
        from jax.sharding import PartitionSpec as P

        self.bind(params_template)
        axes = (tuple(self.route.axis_names) if self.route is not None
                else self.axis_name)
        inner_specs = _sharded_state_specs(self.inner, self._plan, axes)
        if self._ef:
            inner_specs = _EFShardState(
                inner=inner_specs,
                residual=[P(axes)] * len(self._plan.buckets),
                step=P())
        if self.nonfinite_policy is None:
            return inner_specs
        return _GuardedState(inner=inner_specs,
                             guard=integrity_lib.guard_state_specs())

    # -- the exact (native-wire) shard reduce-scatter ------------------------

    def _rs_exact(self, f, route, n, align):
        padded, _ = fusion_lib.pad_to_multiple(f, n * align)
        if route is not None:
            return C.mesh_reducescatter(padded, self.grad_op,
                                        route.with_wires("none"))
        return C.reducescatter(padded, self.grad_op, self.axis_name)

    def _rs_tree_exact(self, grads, params_like, plan, route, n, align):
        """Full gradient pytree -> fp32 bucket shards via the EXACT
        reduce-scatter descent (native wires on every hop — the shard
        accumulator must sum losslessly across microbatches), chained
        in REVERSE (backward-readiness) order under ``overlap``."""
        g_flats = fusion_lib.fuse(
            jax.tree.map(lambda g, p: g.astype(p.dtype), grads,
                         params_like), plan)
        outs: list = [None] * len(g_flats)
        token = None
        order = (range(len(g_flats) - 1, -1, -1) if self.overlap
                 else range(len(g_flats)))
        for i in order:
            f = g_flats[i]
            if self.overlap and token is not None:
                f, token = jax.lax.optimization_barrier((f, token))
            s = self._rs_exact(f, route, n, align)
            outs[i] = s.astype(jnp.float32)
            token = s
        for b in plan.buckets:
            _zero_count_bytes("grad", b.total_elems,
                              jnp.dtype(b.dtype).itemsize, route,
                              self.axis_name, wire="none")
        return outs

    # -- update --------------------------------------------------------------

    def update(self, grads, state, params=None, **extra):
        """Stage 1/2 with a params-shaped ``grads``: stage-1 semantics
        (sharded_update — RS inside, EF descent quantized, full updates
        out). Stage 1/2 with a SHARD-GRADIENT list (from
        :meth:`accumulate` / :meth:`reduce_grads`): shard-local inner
        update + AG(updates) — no second reduction. Stage 3:
        ``update(grads, state, shards) -> (new_shards, new_state)``."""
        if self.zero_stage < 3:
            if _is_shard_grads(grads, like=params):
                return self._update_from_shards_z12(grads, state, params,
                                                    **extra)
            return self._z1.update(self._maybe_combine_tp(grads), state,
                                   params, **extra)
        if not _is_shard_grads(grads, like=list(params)
                               if params is not None else None):
            grads = self._maybe_combine_tp(grads)
        return self._update_z3(grads, state, params, **extra)

    def reduce_grads(self, grads, params):
        """Full gradient pytree -> fp32 bucket-shard list via the exact
        reduce-scatter (the ZeRO-2 descent without accumulation); feed
        to :meth:`update`. One RS round, no full-gradient copy beyond
        backprop's own transient output."""
        route = self._live_route()
        self._require_route_axes(route, "ZeroOptimizer.reduce_grads")
        n = self._n(route)
        align = _route_align(self.compression, route)
        plan = (self._plan if self.zero_stage >= 3
                else self._plan_z12(params))
        if self.zero_stage >= 3:
            self._require_bound("reduce_grads")
        return self._rs_tree_exact(self._maybe_combine_tp(grads),
                                   params, plan, route, n, align)

    def _update_from_shards_z12(self, g_shards, state, params, **extra):
        if params is None:
            raise ValueError("ZeroOptimizer.update requires params")
        route = self._live_route()
        self._require_route_axes(route, "ZeroOptimizer.update")
        axes = self._axes(route)
        guarded = isinstance(state, _GuardedState)
        if (self.nonfinite_policy is not None) != guarded:
            raise ValueError(
                "ZeroOptimizer.update nonfinite_policy must match the "
                "init that built this state")
        inner_state = state.inner if guarded else state
        if self._ef != isinstance(inner_state, _EFShardState):
            raise ValueError(
                "ZeroOptimizer.update compression= must match the init "
                "that built this state (EF state/shard alignment)")
        plan = self._plan_z12(params)
        if len(g_shards) != len(plan.buckets):
            raise ValueError(
                f"got {len(g_shards)} gradient shards for a plan of "
                f"{len(plan.buckets)} buckets")
        align = _route_align(self.compression, route)
        p_flats = fusion_lib.fuse(params, plan)
        if route is not None:
            p_shards = [_mesh_shard_flat(f, route, align)
                        for f in p_flats]
            u_gather = route.reversed().with_wires("none")
        else:
            p_shards = [_shard_flat(f, self.axis_name, align)
                        for f in p_flats]
            u_gather = None

        def core(gs, st):
            ist = st.inner if self._ef else st
            gs = [g.astype(p.dtype) for g, p in zip(gs, p_shards)]
            u_shards, new_inner = self.inner.update(gs, ist, p_shards,
                                                    **extra)
            u_shards = [u.astype(jnp.float32) for u in u_shards]
            if self._ef:
                # No quantized hop ran: residual and step carry over
                # untouched (the EF telescope only advances on a lossy
                # descent).
                new_st = _EFShardState(inner=new_inner,
                                       residual=st.residual,
                                       step=st.step)
            else:
                new_st = new_inner
            return u_shards, new_st

        if not guarded:
            u_shards, new_inner = core(g_shards, inner_state)
            new_guard = None
        else:
            u_shards, new_inner, new_guard = integrity_lib.guarded_apply(
                self.nonfinite_policy, core, list(g_shards), inner_state,
                state.guard, axes)
        # Update all-gather OUTSIDE the guard: a skipped step gathers
        # zeros (harmless), and the guard's skip branch stays
        # structure-matched to the shard gradients.
        u_flats = [(C.mesh_allgather(u, u_gather)
                    if u_gather is not None
                    else C.allgather(u, self.axis_name))[:f.shape[0]]
                   .astype(f.dtype)
                   for u, f in zip(u_shards, p_flats)]
        for b in plan.buckets:
            _zero_count_bytes("update", b.total_elems,
                              jnp.dtype(b.dtype).itemsize, route,
                              self.axis_name, wire="none")
        updates = fusion_lib.unfuse(u_flats, plan)
        if new_guard is None:
            return updates, new_inner
        return updates, _GuardedState(new_inner, new_guard)

    def _update_z3(self, grads, state, shards, **extra):
        if shards is None:
            raise ValueError(
                "stage-3 update requires the param shards as the third "
                "argument: update(grads, state, shards)")
        self._require_bound("update")
        self._check_shards(shards, "update")
        route = self._live_route()
        self._require_route_axes(route, "ZeroOptimizer.update")
        axes = self._axes(route)
        guarded = isinstance(state, _GuardedState)
        if (self.nonfinite_policy is not None) != guarded:
            raise ValueError(
                "ZeroOptimizer.update nonfinite_policy must match the "
                "init that built this state")
        inner_state = state.inner if guarded else state
        if self._ef != isinstance(inner_state, _EFShardState):
            raise ValueError(
                "ZeroOptimizer.update compression= must match the init "
                "that built this state (EF state/shard alignment)")
        n = self._n(route)
        align = _route_align(self.compression, route)
        plan = self._plan
        from_shards = _is_shard_grads(grads, like=list(shards))

        def core(g, st):
            """-> (u_shards ≅ param shards, new inner state). The whole
            descent — EF residual advance included — sits inside the
            guard's cond."""
            if from_shards:
                g_shards = [gg.astype(s.dtype)
                            for gg, s in zip(g, shards)]
                new_res, new_step = ((st.residual, st.step)
                                     if self._ef else (None, None))
            elif not self._ef:
                g_flats = fusion_lib.fuse(g, plan)
                g_shards = []
                for f, s in zip(g_flats, shards):
                    padded, _ = fusion_lib.pad_to_multiple(
                        f.astype(s.dtype), n * align)
                    if route is not None:
                        # The descent rides the PLAN's wires (int8 on
                        # the slow hop under staged_int8 — stateless,
                        # bounded, the FSDP contract).
                        g_shards.append(C.mesh_reducescatter(
                            padded, self.grad_op, route))
                    else:
                        g_shards.append(C.reducescatter(
                            padded, self.grad_op, self.axis_name))
                for b in plan.buckets:
                    _zero_count_bytes("grad", b.total_elems,
                                      jnp.dtype(b.dtype).itemsize,
                                      route, self.axis_name)
                new_res = new_step = None
            else:
                # Quantized descent with error feedback: corrected
                # gradient g + residual rides the int8 wires; the local
                # rounding error becomes the next residual
                # (Σ-over-ranks contract, mesh_reducescatter).
                g_flats = fusion_lib.fuse(g, plan)
                g_shards, new_res = [], []
                for i, (f, res) in enumerate(zip(g_flats, st.residual)):
                    pad = res.shape[0] - f.shape[0]
                    corrected = jnp.pad(f.astype(jnp.float32),
                                        (0, pad)) + res
                    if route is not None:
                        shard, r = C.mesh_reducescatter(
                            corrected, self.grad_op, route,
                            key=_ef_key(st.step, i),
                            return_residual=True)
                    else:
                        shard, r = C.quantized_reducescatter(
                            corrected, self.grad_op, self.axis_name,
                            key=_ef_key(st.step, i),
                            return_residual=True)
                    g_shards.append(shard.astype(shards[i].dtype))
                    new_res.append(r)
                for b in plan.buckets:
                    _zero_count_bytes("grad", b.total_elems,
                                      jnp.dtype(b.dtype).itemsize,
                                      route, self.axis_name,
                                      wire=None if route is not None
                                      else "int8")
                new_step = st.step + 1
            ist = st.inner if self._ef else st
            u_shards, new_inner = self.inner.update(g_shards, ist,
                                                    list(shards),
                                                    **extra)
            u_shards = [u.astype(s.dtype)
                        for u, s in zip(u_shards, shards)]
            if self._ef:
                new_st = _EFShardState(inner=new_inner,
                                       residual=new_res, step=new_step)
            else:
                new_st = new_inner
            return u_shards, new_st

        if not guarded:
            u_shards, new_inner = core(grads, inner_state)
            new_guard = None
        else:
            u_shards, new_inner, new_guard = integrity_lib.guarded_apply(
                self.nonfinite_policy, core,
                list(grads) if from_shards else grads, inner_state,
                state.guard, axes, skip_like=list(shards))
        new_shards = [(s + u).astype(s.dtype)
                      for s, u in zip(shards, u_shards)]
        if new_guard is None:
            return new_shards, new_inner
        return new_shards, _GuardedState(new_inner, new_guard)

    # -- scan-based shard accumulation ---------------------------------------

    def accumulate(self, loss_fn: Callable, has_aux: bool = False):
        """The microbatched ``value_and_grad`` for the pinned
        ``accum_steps``/``remat_policy``. Stage 1 delegates to the
        full-accumulator scan (:func:`accumulate_gradients`). Stages
        2/3 return ``fn(params_or_shards, *batch) -> (value,
        shard_grads)``: the carried accumulator is the 1/N gradient
        SHARD list — each microbatch's full gradients exist only
        transiently inside its own backward before the exact
        reduce-scatter folds them into the owner's shard. Stage 3
        gathers the params ONCE per effective step, outside the scan
        (trace-count-verified, tests/test_zero.py), so k microbatches
        share one chained param gather."""
        if self.zero_stage == 1:
            return self._z1.accumulate(loss_fn, has_aux=has_aux)
        k = self.accum_steps
        _, wrap, jax_policy = resolve_remat_policy(self.remat_policy)
        inner_loss = jax.checkpoint(loss_fn, policy=jax_policy) \
            if wrap else loss_fn
        vgrad = jax.value_and_grad(inner_loss, has_aux=has_aux)
        stage3 = self.zero_stage >= 3

        def fn(params_or_shards, *batch):
            route = self._live_route()
            n = self._n(route)
            align = _route_align(self.compression, route)
            if stage3:
                self._require_bound("ZeroOptimizer.accumulate")
                plan = self._plan
                full = self.gather_params(params_or_shards)
            else:
                full = params_or_shards
                plan = self._plan_z12(full)

            def rs(g):
                return self._rs_tree_exact(self._maybe_combine_tp(g),
                                           full, plan, route, n, align)

            if k == 1:
                out, g = vgrad(full, *batch)
                return out, rs(g)

            mbs = _split_microbatches(batch, k)
            mb0 = jax.tree.map(lambda x: x[0], mbs)
            shapes = jax.eval_shape(vgrad, full, *mb0)
            out_s, _g_s = shapes
            v_s, aux_s = out_s if has_aux else (out_s, None)

            def zeros_acc(t):
                return jax.tree.map(
                    lambda s: jnp.zeros(
                        s.shape, jnp.float32
                        if jnp.issubdtype(s.dtype, jnp.floating)
                        else s.dtype), t)

            def acc_add(acc, new):
                return jax.tree.map(
                    lambda a, x: a + x.astype(jnp.float32)
                    if jnp.issubdtype(jnp.asarray(a).dtype,
                                      jnp.floating)
                    else x, acc, new)

            def chunk_len(total_elems: int) -> int:
                grid = n * align
                return (-(-total_elems // grid) * grid) // n

            g_acc0 = [jnp.zeros((chunk_len(b.total_elems),),
                                jnp.float32) for b in plan.buckets]
            carry0 = (g_acc0, jnp.zeros((), jnp.float32),
                      zeros_acc(aux_s))

            def body(carry, mb):
                g_acc, v_acc, aux_acc = carry
                out, g = vgrad(full, *mb)
                v, aux = out if has_aux else (out, None)
                g_sh = rs(g)
                g_acc = [a + s for a, s in zip(g_acc, g_sh)]
                return (g_acc, v_acc + v.astype(jnp.float32),
                        acc_add(aux_acc, aux)), None

            (g_acc, v_acc, aux_acc), _ = jax.lax.scan(body, carry0,
                                                      mbs)
            g_shards = [a / k for a in g_acc]
            value = (v_acc / k).astype(v_s.dtype)
            if has_aux:
                aux = jax.tree.map(
                    lambda a, s: (a / k).astype(s.dtype)
                    if jnp.issubdtype(jnp.asarray(a).dtype,
                                      jnp.floating)
                    else a, aux_acc, aux_s)
                return (value, aux), g_shards
            return value, g_shards

        return fn

    # -- elastic resize ------------------------------------------------------

    def gather_state(self, state, params=None):
        """Sharded state -> world-size-independent full state (inside
        the OLD world's SPMD region). Stage 3 needs no ``params`` (the
        bound plan carries the bucket layout); the param SHARDS
        themselves travel via :meth:`gather_params` /
        :meth:`shard_params`. EF residuals carry as their psum (the
        world-size-independent pending correction; the new world's
        mesh-rank 0 receives it)."""
        if self.zero_stage < 3:
            return self._z1.gather_state(state, params)
        self._require_bound("gather_state")
        route = self._live_route()
        self._require_route_axes(route, "ZeroOptimizer.gather_state")
        guard = state.guard if isinstance(state, _GuardedState) else None
        if guard is not None:
            state = state.inner
        inner = state.inner if self._ef else state
        if route is not None:
            inner_full = _gather_sharded_state_routed(
                self.inner, self._plan, inner, route)
        else:
            inner_full = _gather_sharded_state(
                self.inner, self._plan, inner, self.axis_name)
        if self._ef:
            axes = self._axes(route)
            residual_full = [
                jax.lax.psum(r, axes)[:b.total_elems]
                for r, b in zip(state.residual, self._plan.buckets)]
            full = _EFShardState(inner=inner_full,
                                 residual=residual_full,
                                 step=state.step)
        else:
            full = inner_full
        return full if guard is None else _GuardedState(inner=full,
                                                        guard=guard)

    def reshard_state(self, state_full):
        """Full (gathered) state -> this world's shards (inside the NEW
        world's SPMD region, whatever its size or route). This is the
        gather-then-reshard leg of the elastic journey; when only the
        SHARD GRID changed (an elastic respec — docs/elastic.md
        "hybrid worlds") ``checkpoint.restore_sharded`` remaps the
        saved pieces directly instead, with no gather at all."""
        if self.zero_stage < 3:
            return self._z1.reshard_state(state_full)
        self._require_bound("reshard_state")
        route = self._live_route()
        self._require_route_axes(route, "ZeroOptimizer.reshard_state")
        guard = state_full.guard \
            if isinstance(state_full, _GuardedState) else None
        if guard is not None:
            state_full = state_full.inner
        align = _route_align(self.compression, route)
        n = self._n(route)
        if route is not None:
            me0 = jnp.asarray(True)
            for a in route.axis_names:
                me0 = jnp.logical_and(me0, jax.lax.axis_index(a) == 0)

            def shard_leaf(v):
                return _mesh_shard_flat(v, route, align) if v.ndim \
                    else v
        else:
            me0 = jax.lax.axis_index(self.axis_name) == 0

            def shard_leaf(v):
                return (_shard_flat(v, self.axis_name, align)
                        if v.ndim else v)

        if not self._ef:
            sharded = jax.tree.map(shard_leaf, state_full)
            return sharded if guard is None else \
                _GuardedState(inner=sharded, guard=guard)
        inner = jax.tree.map(shard_leaf, state_full.inner)
        residual = []
        for r in state_full.residual:
            pad = _qpad_len(r.shape[0], n) - r.shape[0]
            r = jnp.pad(r, (0, pad))
            residual.append(jnp.where(me0, r, jnp.zeros_like(r)))
        sharded = _EFShardState(inner=inner, residual=residual,
                                step=state_full.step)
        return sharded if guard is None else \
            _GuardedState(inner=sharded, guard=guard)
