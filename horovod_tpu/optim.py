"""DistributedOptimizer — the gradient-averaging wrapper.

Reference equivalents: horovod/tensorflow/__init__.py:465-561
(DistributedOptimizer), :564-629 (DistributedGradientTape),
horovod/torch/optimizer.py:103-207 (per-grad async allreduce hooks), and the
local-gradient-aggregation helpers (tensorflow/gradient_aggregation.py:16)
for ``backward_passes_per_step > 1``.

TPU-native design: the optimizer is an ``optax.GradientTransformation``
wrapper meant to run *inside* the jitted SPMD step function, where the
reference's whole async machinery (hooks, handles, background thread) is
unnecessary — the gradients of every rank are produced by the same traced
program, so the wrapper simply inserts fused allreduces between ``grad()``
and ``update()`` and lets XLA overlap them with remaining backprop compute
(XLA's latency-hiding scheduler plays the role of Horovod's
background-thread overlap).

Also provides ``DistributedGradFn`` (the DistributedGradientTape analog):
wraps ``jax.grad``/``jax.value_and_grad`` results with the same reduction.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from .common import fusion as fusion_lib
from .ops import collectives as C
from .ops.compression import NoneCompressor


def _check_reduce_safe(compression) -> None:
    if not getattr(compression, "reduce_safe", True):
        raise ValueError(
            f"{compression.__name__} is a wire-format compressor (per-block "
            "scales don't commute with summation); use Compression.fp16 / "
            "bf16 for gradient reduction")


def _axes_bound(*axes) -> bool:
    """True iff all mesh axis names are bound in the current trace (i.e. we
    are inside shard_map/pmap over them). Probed once, narrowly, so a
    genuine NameError inside user compressors/optimizers still raises."""
    try:
        for a in axes:
            jax.lax.axis_size(a)
        return True
    except NameError:
        return False


def _reduce_tree(grads, op: C.ReduceOp, axis_name: str, compression,
                 fusion_threshold: int, prescale: float = 1.0,
                 postscale: float = 1.0, hierarchical: bool = False,
                 local_axis: str = "local", cross_axis: str = "cross",
                 quantized_cross: bool = False, overlap: bool = False,
                 bucket_order=None):
    """Fused (bucketed) allreduce of a gradient pytree over the mesh axis.

    ``overlap=True`` selects the latency-hiding schedule
    (common/overlap.py): buckets are planned in readiness order (reverse
    flatten by default, or an explicit ``bucket_order`` permutation from
    ``fusion.measured_order``) and issued through an
    ``optimization_barrier`` chain, so each bucket's collective can run
    while backprop still computes earlier layers' gradients. Scheduling
    only — results are bitwise-identical to ``overlap=False``.

    Outside an SPMD region (axis names unbound) the reduction degenerates
    to size-1 reference semantics: no cross-rank sum, but pre/post scaling
    still applies (the reference applies ScaleBuffer regardless of world
    size). Under jit/pjit auto-sharding XLA already inserts the
    cross-device reduction itself — a manual psum there would
    double-reduce.
    """
    needed_axes = ((local_axis, cross_axis) if hierarchical
                   else (axis_name,))
    bound = _axes_bound(*needed_axes)

    def one(flat):
        w, ctx = compression.compress(flat)
        if op == C.ReduceOp.ADASUM:
            from .ops import adasum as adasum_lib

            if hierarchical:
                w = adasum_lib.adasum_hierarchical(w, local_axis, cross_axis)
            else:
                w = adasum_lib.adasum_allreduce(w, axis_name)
            w = C._apply_scale(w, postscale)
        elif hierarchical:
            w = C._apply_scale(w, prescale)
            nl = jax.lax.axis_size(local_axis)
            w, n = fusion_lib.pad_to_multiple(w, nl)
            if quantized_cross:
                # EQuARX path: int8 payload on the DCN hop
                # (collectives.quantized_hierarchical_allreduce).
                w = C.quantized_hierarchical_allreduce(
                    w, op, local_axis, cross_axis)
            else:
                w = C.hierarchical_allreduce_staged(w, op, local_axis,
                                                    cross_axis)
            w = jax.lax.slice_in_dim(w, 0, n)
            w = C._apply_scale(w, postscale)
        else:
            w = C.allreduce(w, op, axis_name, prescale, postscale)
        return compression.decompress(w, ctx)

    def identity_with_scales(flat):
        w, ctx = compression.compress(flat)
        w = C._apply_scale(w, prescale)
        w = C._apply_scale(w, postscale)
        return compression.decompress(w, ctx)

    fn = one if bound else identity_with_scales
    if overlap and bound:
        from .common import overlap as overlap_lib

        order = bucket_order if bucket_order is not None \
            else fusion_lib.ORDER_REVERSE
        return overlap_lib.fused_apply_overlapped(grads, fn,
                                                  fusion_threshold,
                                                  order=order)
    return fusion_lib.fused_apply(grads, fn, fusion_threshold)


class _AggState(NamedTuple):
    inner: Any
    acc: Any          # local gradient accumulator
    counter: jnp.ndarray


def _resolve_fusion_threshold(explicit: Optional[int]) -> int:
    """None → the live runtime value (autotuner's current suggestion when
    tuning, else the configured knob); an explicit value always wins."""
    if explicit is not None:
        return explicit
    from .common import basics

    if basics.is_initialized():
        return basics.context().fusion_threshold()
    return 64 * 1024 * 1024


def DistributedOptimizer(optimizer,
                         op: C.ReduceOp = C.ReduceOp.AVERAGE,
                         axis_name: str = "hvd",
                         compression=NoneCompressor,
                         backward_passes_per_step: int = 1,
                         average_aggregated_gradients: bool = True,
                         prescale_factor: float = 1.0,
                         postscale_factor: float = 1.0,
                         fusion_threshold_bytes: Optional[int] = None,
                         hierarchical: bool = False,
                         local_axis: str = "local",
                         cross_axis: str = "cross",
                         quantized_cross: bool = False,
                         overlap: bool = False,
                         bucket_order=None):
    """Wrap an optax optimizer so ``update()`` allreduces gradients first.

    Use inside the jitted step function running under
    shard_map/pjit over the rank axis::

        tx = hvd.DistributedOptimizer(optax.sgd(0.1), axis_name="hvd")

    ``backward_passes_per_step`` accumulates k local microbatch gradients
    before one fused allreduce + inner update (reference
    gradient_aggregation.py semantics: allreduce every k-th call, identity
    updates in between).

    ``quantized_cross`` (requires ``hierarchical``) carries the DCN hop
    of each fused bucket as block-scaled int8 — the EQuARX-style
    quantized allreduce (collectives.quantized_hierarchical_allreduce);
    gradients land within block-absmax rounding error of the exact sum.

    ``overlap=True`` buckets gradients in readiness order and chains the
    per-bucket collectives so they fire while the backward pass is still
    computing (common/overlap.py — the reference's background-thread
    overlap, expressed through XLA scheduling). Composes with
    ``hierarchical``/``quantized_cross`` (each chained bucket runs the
    staged reduction) and reduce-safe ``compression``; same numerics as
    ``overlap=False``. Pair with the latency-hiding XLA flags
    (``init(overlap_xla_flags=True)`` / common/xla_tuning.py) on TPU.
    ``bucket_order`` optionally pins a measured leaf permutation
    (``fusion.measured_order``) instead of the reverse-flatten proxy.
    """
    try:
        import optax
    except ImportError as e:  # pragma: no cover
        raise ImportError("DistributedOptimizer requires optax") from e

    _check_reduce_safe(compression)
    if quantized_cross and (not hierarchical or op not in (
            C.ReduceOp.SUM, C.ReduceOp.AVERAGE)):
        raise ValueError("quantized_cross requires hierarchical=True and "
                         "a SUM/AVERAGE op (the int8 hop rides the "
                         "staged RS->AR->AG pipeline)")

    k = int(backward_passes_per_step)
    fusion_threshold_bytes = _resolve_fusion_threshold(fusion_threshold_bytes)

    def reduce_grads(grads):
        return _reduce_tree(grads, op, axis_name, compression,
                            fusion_threshold_bytes, prescale_factor,
                            postscale_factor, hierarchical, local_axis,
                            cross_axis, quantized_cross, overlap,
                            bucket_order)

    if k <= 1:
        def init_fn(params):
            return optimizer.init(params)

        def update_fn(grads, state, params=None, **extra):
            reduced = reduce_grads(grads)
            return optimizer.update(reduced, state, params, **extra)

        return optax.GradientTransformation(init_fn, update_fn)

    def init_fn(params):
        acc = jax.tree.map(jnp.zeros_like, params)
        return _AggState(inner=optimizer.init(params), acc=acc,
                         counter=jnp.zeros((), jnp.int32))

    def update_fn(grads, state, params=None, **extra):
        acc = jax.tree.map(jnp.add, state.acc, grads)
        counter = state.counter + 1
        do_step = counter >= k

        def take_step(args):
            acc, inner = args
            scale = (1.0 / k) if average_aggregated_gradients else 1.0
            scaled = jax.tree.map(lambda g: g * scale, acc) \
                if scale != 1.0 else acc
            reduced = reduce_grads(scaled)
            updates, new_inner = optimizer.update(reduced, inner, params,
                                                  **extra)
            zeroed = jax.tree.map(jnp.zeros_like, acc)
            return updates, new_inner, zeroed

        def skip_step(args):
            acc, inner = args
            updates = jax.tree.map(jnp.zeros_like, acc)
            return updates, inner, acc

        updates, new_inner, new_acc = jax.lax.cond(
            do_step, take_step, skip_step, (acc, state.inner))
        new_counter = jnp.where(do_step, 0, counter)
        return updates, _AggState(new_inner, new_acc, new_counter)

    return optax.GradientTransformation(init_fn, update_fn)


def DistributedGradFn(grad_fn: Callable,
                      op: C.ReduceOp = C.ReduceOp.AVERAGE,
                      axis_name: str = "hvd",
                      compression=NoneCompressor,
                      fusion_threshold_bytes: Optional[int] = None,
                      has_value: bool = False,
                      reduce_value: bool = True,
                      overlap: bool = False,
                      bucket_order=None):
    """DistributedGradientTape analog (reference
    tensorflow/__init__.py:564-629): wraps a function returning gradients
    (e.g. ``jax.grad(loss)``) so the result is allreduced across ranks.

    ``has_value=True`` declares the wrapped function follows the
    ``jax.value_and_grad`` convention ``(value, grads)``; the value is
    additionally averaged across ranks when ``reduce_value``. Explicit flag
    instead of tuple-sniffing so ``jax.grad(loss, argnums=(0, 1))`` (a
    tuple of gradients) is never misclassified.

    ``overlap``/``bucket_order``: readiness-ordered buckets + issue-order
    chaining, as on :func:`DistributedOptimizer` — scheduling only,
    identical numerics.
    """
    _check_reduce_safe(compression)
    fusion_threshold_bytes = _resolve_fusion_threshold(fusion_threshold_bytes)

    def reduce_grads(grads):
        return _reduce_tree(grads, op, axis_name, compression,
                            fusion_threshold_bytes, overlap=overlap,
                            bucket_order=bucket_order)

    def wrapped(*args, **kwargs):
        out = grad_fn(*args, **kwargs)
        if has_value:
            val, grads = out
            grads = reduce_grads(grads)
            if reduce_value and _axes_bound(axis_name):
                val = jax.tree.map(
                    lambda v: C.allreduce(v, C.ReduceOp.AVERAGE, axis_name),
                    val)
            return val, grads
        return reduce_grads(out)

    return wrapped


class AutotunedStepper:
    """Drives the runtime Autotuner from real step timings and rebuilds the
    jitted step function whenever the suggested fusion threshold moves.

    This is the in-jit analog of the reference's live ParameterManager
    tuning (parameter_manager.cc: each cycle scores bytes/sec and may
    change the fusion threshold; subsequent cycles fuse differently).
    Under XLA a threshold change means a different bucket plan, i.e. a
    retrace — so the stepper owns the (re)build::

        def build(threshold_bytes):
            tx = hvd.DistributedOptimizer(optax.sgd(0.01),
                                          fusion_threshold_bytes=threshold_bytes)
            ... return jitted_step               # closes over tx
        stepper = hvd.AutotunedStepper(build, grad_bytes=nbytes)
        while training:
            out = stepper(*step_args)

    ``grad_bytes`` is the bytes reduced per step (the score numerator,
    matching the reference's bytes/sec score, parameter_manager.h:42).
    """

    def __init__(self, build_step: Callable[[int], Callable],
                 grad_bytes: int, tuner=None, block: bool = True,
                 controller=None):
        from .common import basics

        if tuner is None:
            tuner = basics.context().autotuner
            if tuner is None:
                raise ValueError(
                    "runtime autotuner not enabled — init(autotune=True) "
                    "or set HVD_TPU_AUTOTUNE=1, or pass tuner= explicitly")
        if controller is None and basics.is_initialized():
            controller = basics.context().controller
        self.tuner = tuner
        self.grad_bytes = int(grad_bytes)
        self.block = block
        self._build = build_step
        # Multi-process: rank 0 alone scores samples and decides; every
        # process adopts the decision at the SAME call index via a
        # synchronous controller exchange — per-process decisions would
        # compile diverged bucket plans and deadlock the collectives
        # (reference: SynchronizeParameters broadcasts rank-0's
        # ParameterManager state, controller.cc:34-48).
        self._controller = controller
        self._period = tuner.warmup + tuner.steps_per_sample
        self._calls = 0
        self._tuner_done = False  # set when rank 0 broadcasts :done
        self._threshold = tuner.current
        # Joint tuning (reference ParameterManager's hierarchical toggle):
        # build_step then takes (threshold, hierarchical). With a
        # tune_overlap tuner the signature widens once more to
        # (threshold, hierarchical, overlap) — the full triple the
        # (re)built step must agree on across ranks.
        self._joint = getattr(tuner, "tune_hierarchical", False)
        self._joint_overlap = getattr(tuner, "tune_overlap", False)
        self._hier = (tuner.current_hierarchical if self._joint else False)
        self._ovl = (tuner.current_overlap if self._joint_overlap
                     else False)
        self._step = self._rebuild()
        self.rebuilds = 0

    def _rebuild(self):
        if self._joint_overlap:
            return self._build(self._threshold, self._hier, self._ovl)
        if self._joint:
            return self._build(self._threshold, self._hier)
        return self._build(self._threshold)

    @property
    def fusion_threshold(self) -> int:
        return self._threshold

    @property
    def hierarchical(self) -> bool:
        return self._hier

    @property
    def overlap(self) -> bool:
        return self._ovl

    def __call__(self, *args, **kwargs):
        import time

        t0 = time.perf_counter()
        out = self._step(*args, **kwargs)
        if self.block:
            jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        c = self._controller
        if c is None or c.size == 1:
            new, tuner_h, tuner_o = self.tuner.feed_triple(
                self.grad_bytes, dt)
            new_h = tuner_h if self._joint else self._hier
            new_o = tuner_o if self._joint_overlap else self._ovl
        else:
            if c.rank == 0:
                self.tuner.record(self.grad_bytes, dt)
            self._calls += 1
            new, new_h, new_o = self._threshold, self._hier, self._ovl
            if self._calls % self._period == 0 and not self._tuner_done:
                # Sample boundary — same call index on every process
                # (SPMD lockstep), so the exchange is synchronous. After
                # rank 0 broadcasts convergence (:done) the rounds stop —
                # no point paying a KV round per period forever.
                if c.rank == 0 and self.tuner.ready():
                    self.tuner.suggest()
                cur_t, cur_h, cur_o = self.tuner.current_triple  # atomic
                mine = (f"{cur_t}|{int(cur_h) if self._joint else 0}"
                        f"|{int(cur_o) if self._joint_overlap else 0}"
                        + (":done" if c.rank == 0 and self.tuner.done
                           else ""))
                vals = c.exchange("autotune_threshold", mine)
                v0 = vals[0]  # rank 0's decision wins
                if v0.endswith(":done"):
                    self._tuner_done = True
                    v0 = v0[:-5]
                t_str, h_str, o_str = v0.split("|")
                new = int(t_str)
                new_h = bool(int(h_str)) if self._joint else self._hier
                new_o = bool(int(o_str)) if self._joint_overlap \
                    else self._ovl
        if (new != self._threshold or new_h != self._hier
                or new_o != self._ovl):
            self._threshold, self._hier, self._ovl = new, new_h, new_o
            self._step = self._rebuild()
            self.rebuilds += 1
        return out


def broadcast_parameters(params, root_rank: int = 0,
                         axis_name: str = "hvd"):
    """Broadcast a parameter pytree from root to all ranks — for use inside
    the jitted init path (reference: torch/functions.py:30
    broadcast_parameters / tensorflow broadcast_variables)."""
    return jax.tree.map(
        lambda p: C.broadcast(p, root_rank, axis_name), params)


# -- ZeRO-1 sharded optimizer state (beyond the reference) ------------------
#
# The reference replicates optimizer state on every rank (its
# DistributedOptimizer wraps a local optimizer; state is per-rank,
# memory = full). On TPU the idiomatic win is to SHARD the state over
# the rank axis: reduce-scatter the gradients, update only this rank's
# 1/n slice of each parameter with the inner optax transform, and
# all-gather the resulting updates — optimizer memory drops to 1/n (the
# ZeRO-1 / Megatron "distributed optimizer" recipe) while the wire cost
# stays the allreduce-equivalent RS+AG pair.
#
# Works for ELEMENTWISE inner transforms (sgd/momentum/adam/adamw/...).
# Transforms that couple elements across the tree (global-norm clipping)
# would compute shard-local statistics — compose those OUTSIDE.

def _sharded_state_specs(inner, plan, axis_name: str):
    """PartitionSpecs for an inner transform's state over bucket shards:
    vector leaves P(axis), scalar leaves (step counters) replicated. A
    length-1 probe per bucket suffices — only leaf rank matters."""
    from jax.sharding import PartitionSpec as P

    probe = [jax.ShapeDtypeStruct((1,), b.dtype) for b in plan.buckets]
    shapes = jax.eval_shape(inner.init, probe)
    return jax.tree.map(
        lambda s: P(axis_name) if s.ndim else P(), shapes)


def _gather_sharded_state(inner, plan, state, axis_name: str):
    """Sharded inner state -> WORLD-SIZE-INDEPENDENT full state: every
    vector (bucket-shard) leaf all-gathers and drops the shard-split
    padding; scalar leaves pass through. The inverse of
    :func:`_reshard_state` — together they carry ZeRO-1/FSDP state
    across an elastic WORLD-SIZE CHANGE, where the 1/n shard shapes
    (and their pad-to-multiple) differ between the old and new worlds
    so a sharded checkpoint cannot be restored directly."""
    full_probe = [jax.ShapeDtypeStruct((b.total_elems,), b.dtype)
                  for b in plan.buckets]
    full_shapes = jax.eval_shape(inner.init, full_probe)

    def one(leaf, shp):
        if shp.ndim:
            return C.allgather(leaf, axis_name)[:shp.shape[0]]
        return leaf

    return jax.tree.map(one, state, full_shapes)


def _reshard_state(state_full, axis_name: str):
    """Full (gathered) inner state -> this world's shards: vector
    leaves re-split 1/n under the CURRENTLY BOUND axis (whatever its
    size), scalars pass through."""
    return jax.tree.map(
        lambda v: _shard_flat(v, axis_name) if v.ndim else v,
        state_full)


def _require_axis(axis_name: str, what: str) -> None:
    if not _axes_bound(axis_name):
        raise ValueError(
            f"{what} must run inside the jitted SPMD region (shard_map/"
            f"pjit binding axis {axis_name!r}) — the shard shapes and "
            f"slices depend on the bound axis. Wrap the call in your "
            f"spmd_step (see ShardedOptimizer docstring).")


def _shard_flat(flat, axis_name: str):
    """(1-D bucket) -> this rank's padded 1/n slice."""
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    flat, _ = fusion_lib.pad_to_multiple(flat, n)
    chunk = flat.shape[0] // n
    return jax.lax.dynamic_slice_in_dim(flat, idx * chunk, chunk)


def sharded_init(tx, params, axis_name: str = "hvd",
                 fusion_threshold_bytes: Optional[int] = None):
    """Inner-optimizer state over FUSED-BUCKET SHARDS — call inside the
    same shard_map/jit region as :func:`sharded_update` (the shard
    shapes depend on the bound axis). State structure = the inner
    transform's state over a list of per-bucket shard arrays."""
    _require_axis(axis_name, "sharded_init")
    threshold = _resolve_fusion_threshold(fusion_threshold_bytes)
    plan = fusion_lib.plan_fusion(params, threshold)
    flats = fusion_lib.fuse(params, plan)
    return tx.init([_shard_flat(f, axis_name) for f in flats])


def sharded_update(tx, grads, state, params, axis_name: str = "hvd",
                   grad_op: C.ReduceOp = C.ReduceOp.AVERAGE,
                   fusion_threshold_bytes: Optional[int] = None,
                   **extra):
    """ZeRO-1 step over fused buckets: RS(bucket grads) -> inner update
    on this rank's shards -> AG(bucket updates). A few large collectives
    instead of one pair per leaf (same bucketing as the replicated
    path). Returns ``(updates, new_state)`` with ``updates`` shaped like
    ``params`` (apply with ``optax.apply_updates``)."""
    if grad_op not in (C.ReduceOp.SUM, C.ReduceOp.AVERAGE):
        raise ValueError("sharded_update supports SUM/AVERAGE")
    _require_axis(axis_name, "sharded_update")
    n = jax.lax.axis_size(axis_name)
    threshold = _resolve_fusion_threshold(fusion_threshold_bytes)
    # Plan over PARAMS (grads share the treedef): the state was built
    # over the params plan, and a grad leaf cast to another dtype must
    # not change the bucket structure out from under the carried state.
    plan = fusion_lib.plan_fusion(params, threshold)
    g_flats = fusion_lib.fuse(
        jax.tree.map(lambda g, p: g.astype(p.dtype), grads, params),
        plan)
    p_flats = fusion_lib.fuse(params, plan)

    def rs(f):
        padded, _ = fusion_lib.pad_to_multiple(f, n)
        return C.reducescatter(padded, grad_op, axis_name)

    g_shards = [rs(f) for f in g_flats]
    p_shards = [_shard_flat(f, axis_name) for f in p_flats]
    u_shards, new_state = tx.update(g_shards, state, p_shards, **extra)
    u_flats = [C.allgather(u, axis_name)[:f.shape[0]]
               for u, f in zip(u_shards, g_flats)]
    return fusion_lib.unfuse(u_flats, plan), new_state


class ShardedOptimizer:
    """Object wrapper over :func:`sharded_init`/:func:`sharded_update`
    mirroring the optax GradientTransformation shape::

        tx = hvd.ShardedOptimizer(optax.adamw(1e-3), axis_name=ax)
        # inside the jitted step (axis bound):
        state = tx.init(params)                  # 1/n-sized state
        updates, state = tx.update(grads, state, params)
    """

    def __init__(self, inner, axis_name: str = "hvd",
                 grad_op: C.ReduceOp = C.ReduceOp.AVERAGE,
                 fusion_threshold_bytes: Optional[int] = None):
        self.inner = inner
        self.axis_name = axis_name
        self.grad_op = grad_op
        # Pinned ONCE (like the DistributedOptimizer factory): the state
        # layout is one shard per bucket, so a live autotuner moving the
        # threshold between traces must not replan the buckets out from
        # under the carried state.
        self.fusion_threshold_bytes = _resolve_fusion_threshold(
            fusion_threshold_bytes)

    def init(self, params):
        return sharded_init(self.inner, params, self.axis_name,
                            self.fusion_threshold_bytes)

    def update(self, grads, state, params=None, **extra):
        if params is None:
            raise ValueError("ShardedOptimizer.update requires params "
                             "(the shard slices come from them)")
        return sharded_update(self.inner, grads, state, params,
                              self.axis_name, self.grad_op,
                              self.fusion_threshold_bytes, **extra)

    def state_specs(self, params):
        """PartitionSpecs for carrying the sharded state through
        shard_map: vector leaves are P(axis) (each rank owns its slice;
        the global array is the shard concatenation), scalar leaves
        (step counters) replicate. The probe uses the same fusion plan
        as init/update so the state STRUCTURE (one shard per bucket)
        matches — callable before init()."""
        threshold = _resolve_fusion_threshold(self.fusion_threshold_bytes)
        plan = fusion_lib.plan_fusion(params, threshold)
        return _sharded_state_specs(self.inner, plan, self.axis_name)

    def gather_state(self, state, params):
        """Sharded state -> world-size-independent full state (inside
        the OLD world's SPMD region) — checkpoint this across an
        elastic resize; restore with :meth:`reshard_state` in the new
        world.

        The layout is still FUSION-PLAN-dependent: the new world's
        optimizer must resolve the SAME fusion threshold (pass
        ``fusion_threshold_bytes`` explicitly in elastic jobs — a
        live autotuner or changed env knob in the restarted process
        would re-bucket and silently misalign the per-bucket mu/nu
        vectors)."""
        _require_axis(self.axis_name, "ShardedOptimizer.gather_state")
        threshold = _resolve_fusion_threshold(self.fusion_threshold_bytes)
        plan = fusion_lib.plan_fusion(params, threshold)
        return _gather_sharded_state(self.inner, plan, state,
                                     self.axis_name)

    def reshard_state(self, state_full):
        """Full (gathered) state -> this world's 1/n shards (inside the
        NEW world's SPMD region, whatever its size)."""
        _require_axis(self.axis_name, "ShardedOptimizer.reshard_state")
        return _reshard_state(state_full, self.axis_name)


# -- FSDP / ZeRO-3: fully-sharded parameters (beyond the reference) ---------
#
# ZeRO-1 (above) shards the OPTIMIZER STATE; FSDP additionally keeps the
# PARAMETERS at rest as 1/n bucket shards. Per step: all-gather shards ->
# full params for compute, reduce-scatter grads -> shard-local inner
# update -> new shards. At-rest memory for params + Adam state drops to
# 1/n; the transient peak is full params + activations during the step
# (fusion-bucket granularity — XLA's scheduler overlaps the per-bucket
# allgathers with the first layers' compute the same way it overlaps the
# grad reduction with backprop). Wire cost per step: AG(params) +
# RS(grads) — the same bytes as ZeRO-1's RS+AG pair plus the param
# gather that replicated storage gets for free.

class FSDPOptimizer:
    """Fully-sharded (ZeRO-3-style) training helper over fused buckets::

        tx = hvd.FSDPOptimizer(optax.adamw(1e-3), axis_name=ax)
        # inside the jitted SPMD region (axis bound):
        shards = tx.shard_params(params)    # full -> 1/n bucket shards
        state  = tx.init(shards)            # inner state on shards (1/n)
        # each step:
        full   = tx.gather_params(shards)   # AG per bucket -> pytree
        loss, grads = jax.value_and_grad(loss_fn)(full, batch)
        shards, state = tx.update(grads, state, shards)  # RS + update

    Carry ``shards``/``state`` through shard_map with
    :meth:`shard_specs` / :meth:`state_specs` (leaves are P(axis)).
    Elementwise inner transforms only — same contract as
    :class:`ShardedOptimizer`."""

    def __init__(self, inner, axis_name: str = "hvd",
                 grad_op: C.ReduceOp = C.ReduceOp.AVERAGE,
                 fusion_threshold_bytes: Optional[int] = None):
        if grad_op not in (C.ReduceOp.SUM, C.ReduceOp.AVERAGE):
            raise ValueError("FSDPOptimizer supports SUM/AVERAGE")
        self.inner = inner
        self.axis_name = axis_name
        self.grad_op = grad_op
        self.fusion_threshold_bytes = _resolve_fusion_threshold(
            fusion_threshold_bytes)
        self._plan = None
        self._flat_lens = None
        self._sig = None

    def bind(self, params_template):
        """Pin the bucket plan from a params pytree (real arrays or
        ShapeDtypeStructs). Called implicitly by shard_params; explicit
        bind() lets gather/update trace in a separate jit region.

        The instance is stateful: the first bind pins the tree
        structure, and a later bind with a STRUCTURALLY DIFFERENT
        template raises — silently replacing the plan would misalign
        any shards already produced under the old one. Use unbind() (or
        a fresh instance) to retarget deliberately."""
        sig = (str(jax.tree.structure(params_template)),
               tuple((tuple(x.shape), str(x.dtype))
                     for x in jax.tree.leaves(params_template)))
        if self._sig is not None and sig != self._sig:
            raise ValueError(
                "FSDPOptimizer is already bound to a different param "
                "tree (structure or leaf shapes changed); shards from "
                "the old plan would silently misalign. Use a fresh "
                "FSDPOptimizer per param tree, or call unbind() first")
        self._sig = sig
        self._plan = fusion_lib.plan_fusion(params_template,
                                            self.fusion_threshold_bytes)
        self._flat_lens = [b.total_elems for b in self._plan.buckets]
        return self

    def unbind(self):
        """Drop the bound plan so the instance can be re-bound to a new
        param tree (any shards/state from the old plan become invalid)."""
        self._plan = self._flat_lens = self._sig = None
        return self

    def _require_bound(self, what: str):
        if self._plan is None:
            raise ValueError(
                f"{what} needs the bucket plan — call shard_params "
                f"(or bind(params_template)) first")

    def _check_shards(self, shards, what: str):
        if len(shards) != len(self._flat_lens):
            raise ValueError(
                f"{what}: got {len(shards)} bucket shards but the bound "
                f"plan has {len(self._flat_lens)} buckets — these shards "
                f"come from a different plan/template")

    def shard_params(self, params):
        """Full params -> list of this rank's 1/n bucket shards."""
        _require_axis(self.axis_name, "FSDPOptimizer.shard_params")
        self.bind(params)
        flats = fusion_lib.fuse(params, self._plan)
        return [_shard_flat(f, self.axis_name) for f in flats]

    def gather_params(self, shards):
        """Bucket shards -> full params pytree (one all-gather per
        bucket; padding from the shard split sliced back off)."""
        self._require_bound("gather_params")
        self._check_shards(shards, "gather_params")
        _require_axis(self.axis_name, "FSDPOptimizer.gather_params")
        flats = [C.allgather(s, self.axis_name)[:length]
                 for s, length in zip(shards, self._flat_lens)]
        return fusion_lib.unfuse(flats, self._plan)

    def init(self, shards):
        return self.inner.init(shards)

    def update(self, grads, state, shards, **extra):
        """RS(full grads) -> inner update on this rank's shards ->
        apply. Returns (new_shards, new_state)."""
        self._require_bound("update")
        self._check_shards(shards, "update")
        _require_axis(self.axis_name, "FSDPOptimizer.update")
        n = jax.lax.axis_size(self.axis_name)
        g_flats = fusion_lib.fuse(grads, self._plan)

        def rs(f):
            padded, _ = fusion_lib.pad_to_multiple(f, n)
            return C.reducescatter(padded, self.grad_op, self.axis_name)

        g_shards = [rs(f).astype(s.dtype)
                    for f, s in zip(g_flats, shards)]
        u_shards, new_state = self.inner.update(g_shards, state, shards,
                                                **extra)
        new_shards = [(s + u).astype(s.dtype)
                      for s, u in zip(shards, u_shards)]
        return new_shards, new_state

    def shard_specs(self, params_template):
        """P(axis) per bucket shard — for carrying shards through
        shard_map. Binds the plan from the template."""
        from jax.sharding import PartitionSpec as P

        self.bind(params_template)
        return [P(self.axis_name)] * len(self._flat_lens)

    def state_specs(self, params_template):
        """Specs for the inner state over bucket shards (vector leaves
        P(axis), scalars replicated)."""
        self.bind(params_template)
        return _sharded_state_specs(self.inner, self._plan,
                                    self.axis_name)

    def gather_state(self, state):
        """Sharded state -> world-size-independent full state (inside
        the OLD world's SPMD region); pair with :meth:`reshard_state`
        (and gather_params/shard_params for the params themselves) to
        carry FSDP training across an elastic resize.

        Same caveat as ShardedOptimizer.gather_state: the layout is
        fusion-plan-dependent — pin ``fusion_threshold_bytes``
        explicitly across the resize so the new world re-buckets
        identically."""
        self._require_bound("gather_state")
        _require_axis(self.axis_name, "FSDPOptimizer.gather_state")
        return _gather_sharded_state(self.inner, self._plan, state,
                                     self.axis_name)

    def reshard_state(self, state_full):
        """Full (gathered) state -> this world's 1/n shards (inside the
        NEW world's SPMD region, whatever its size)."""
        _require_axis(self.axis_name, "FSDPOptimizer.reshard_state")
        return _reshard_state(state_full, self.axis_name)
