"""A process-backed, API-faithful stand-in for the slice of the ``ray``
API that :mod:`horovod_tpu.ray` uses — ray itself is not installable in
the CI image (no network), and a thread-based mock could not host real
collectives.

Fidelity choices that matter for the adapter tests:

* **Actors are real OS processes** (``multiprocessing`` spawn context),
  like Ray's — so ``RayExecutor`` workers can set slot env vars, build
  a genuine multi-process ``jax.distributed`` world, and run REAL
  collectives through the engine, exactly as they would on a Ray
  cluster.
* **Method calls are async**: ``handle.method.remote(...)`` returns an
  ObjectRef immediately; per-actor dispatch threads keep all actors
  concurrent (sequential dispatch would deadlock SPMD collectives).
* **cloudpickle on the wire**, like Ray, so closures and lambdas pass.

Covered API: ``init/is_initialized/shutdown``, ``remote(cls)`` (+
``.options()``), actor ``.remote()`` construction, method
``.remote()``, ``get(ref|list, timeout=)``, ``kill(handle)``,
``nodes()``. Reference for the adapter under test: ray/runner.py.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import queue
import socket
import threading
import traceback
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

_mp = mp.get_context("spawn")
_STATE: Dict[str, Any] = {"initialized": False, "actors": []}


def init(*args, **kwargs) -> None:
    _STATE["initialized"] = True


def is_initialized() -> bool:
    return bool(_STATE["initialized"])


def shutdown() -> None:
    for actor in list(_STATE["actors"]):
        actor._terminate()
    _STATE["actors"] = []
    _STATE["initialized"] = False


def nodes() -> List[Dict[str, Any]]:
    if _STATE.get("nodes") is not None:
        return [dict(n) for n in _STATE["nodes"]]
    return [{
        "Alive": True,
        "NodeManagerHostname": socket.gethostname(),
        "NodeManagerAddress": "127.0.0.1",
        "Resources": {"CPU": float(os.cpu_count() or 1)},
    }]


# -- dynamic cluster membership (test hooks, not ray API) -------------------
#
# RayHostDiscovery reads ray.nodes() on every elastic discovery poll;
# these hooks let tests script node arrival/loss (the autoscaling and
# node-death scenarios the reference's ElasticRayExecutor rides Ray
# for) without a real cluster.

def _set_nodes(hostnames_to_cpus: Dict[str, float]) -> None:
    _STATE["nodes"] = [{
        "Alive": True,
        "NodeManagerHostname": h,
        "NodeManagerAddress": "127.0.0.1",
        "Resources": {"CPU": float(c)},
    } for h, c in hostnames_to_cpus.items()]


def _remove_node(hostname: str) -> None:
    """Simulate node loss: the node drops from ray.nodes() (Ray also
    reports dead nodes with Alive=False for a while — model both)."""
    kept = []
    for n in _STATE.get("nodes") or []:
        if n["NodeManagerHostname"] == hostname:
            dead = dict(n)
            dead["Alive"] = False
            kept.append(dead)
        else:
            kept.append(n)
    _STATE["nodes"] = kept


def _reset_nodes() -> None:
    _STATE["nodes"] = None


def _actor_main(conn, cls_blob: bytes) -> None:
    """Child process: build the instance, serve method calls forever."""
    import cloudpickle

    cls, args, kwargs = cloudpickle.loads(cls_blob)
    try:
        instance = cls(*args, **kwargs)
        conn.send_bytes(cloudpickle.dumps(("ok", None)))
    except BaseException:
        conn.send_bytes(cloudpickle.dumps(
            ("error", traceback.format_exc())))
        return
    while True:
        try:
            blob = conn.recv_bytes()
        except EOFError:
            return
        msg = cloudpickle.loads(blob)
        if msg[0] == "stop":
            return
        _, method, args, kwargs = msg
        try:
            reply = ("ok", getattr(instance, method)(*args, **kwargs))
        except BaseException:
            reply = ("error", traceback.format_exc())
        conn.send_bytes(cloudpickle.dumps(reply))


class ObjectRef:
    def __init__(self, future: Future):
        self._future = future


class _RemoteMethod:
    def __init__(self, actor: "ActorHandle", name: str):
        self._actor, self._name = actor, name

    def remote(self, *args, **kwargs) -> ObjectRef:
        return self._actor._call(self._name, args, kwargs)


class ActorHandle:
    """One spawned process + a dispatch thread serializing its calls."""

    def __init__(self, cls: type, args: tuple, kwargs: dict):
        import cloudpickle

        parent, child = _mp.Pipe()
        self._conn = parent
        self._proc = _mp.Process(
            target=_actor_main,
            args=(child, cloudpickle.dumps((cls, args, kwargs))),
            daemon=True)
        self._proc.start()
        child.close()
        status, detail = cloudpickle.loads(self._conn.recv_bytes())
        if status != "ok":
            self._proc.join(timeout=5)
            raise RuntimeError(f"actor constructor failed:\n{detail}")
        self._queue: "queue.Queue" = queue.Queue()
        self._alive = True
        self._thread = threading.Thread(target=self._dispatch_loop,
                                        daemon=True)
        self._thread.start()
        _STATE["actors"].append(self)

    def _dispatch_loop(self) -> None:
        import cloudpickle

        while True:
            item = self._queue.get()
            if item is None:
                return
            method, args, kwargs, future = item
            try:
                self._conn.send_bytes(
                    cloudpickle.dumps(("call", method, args, kwargs)))
                status, value = cloudpickle.loads(self._conn.recv_bytes())
            except (EOFError, OSError) as e:
                future.set_exception(
                    RuntimeError(f"actor died: {e!r}"))
                continue
            if status == "ok":
                future.set_result(value)
            else:
                future.set_exception(RayTaskError(value))

    def _call(self, method: str, args: tuple, kwargs: dict) -> ObjectRef:
        if not self._alive:
            raise RuntimeError("actor has been killed")
        future: Future = Future()
        self._queue.put((method, args, kwargs, future))
        return ObjectRef(future)

    def _terminate(self) -> None:
        if not self._alive:
            return
        self._alive = False
        self._queue.put(None)
        try:
            import cloudpickle

            self._conn.send_bytes(cloudpickle.dumps(("stop",)))
        except (OSError, ValueError):
            pass
        self._proc.join(timeout=10)
        if self._proc.is_alive():
            self._proc.kill()
            self._proc.join(timeout=5)
        self._conn.close()

    def __getattr__(self, name: str) -> _RemoteMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return _RemoteMethod(self, name)


class RayTaskError(RuntimeError):
    """Remote traceback carrier (ray.exceptions.RayTaskError analog)."""


class _RemoteClass:
    def __init__(self, cls: type, options: Optional[dict] = None):
        self._cls = cls
        self._options = dict(options or {})

    def options(self, **opts) -> "_RemoteClass":
        return _RemoteClass(self._cls, {**self._options, **opts})

    def remote(self, *args, **kwargs) -> ActorHandle:
        if not _STATE["initialized"]:
            raise RuntimeError("ray.init() has not been called")
        return ActorHandle(self._cls, args, kwargs)


def remote(cls=None, **opts):
    if cls is None:  # @ray.remote(num_cpus=...) decorator form
        return lambda c: _RemoteClass(c, opts)
    return _RemoteClass(cls)


def get(refs, timeout: Optional[float] = None):
    if isinstance(refs, ObjectRef):
        return refs._future.result(timeout=timeout)
    return [r._future.result(timeout=timeout) for r in refs]


def kill(actor: ActorHandle) -> None:
    actor._terminate()
