"""A process-backed, API-faithful stand-in for the slice of the pyspark
API that :mod:`horovod_tpu.spark` uses — pyspark is not installable in
this image (no pip index), and the adapters must still be driven
end-to-end (VERDICT r4 #3: the mapper path had only ever run its
protocol side).

Fidelity choices that matter:

* **Tasks are real OS processes** (``multiprocessing`` spawn context),
  like Spark executor tasks — so mappers can mutate ``os.environ``,
  spawn worker subprocesses (the elastic task pool), and be KILLED to
  simulate executor loss.
* **cloudpickle on the wire** for the partition mapper chain, like
  pyspark's closure serializer.
* ``collect()`` blocks until every task finishes, returns results in
  partition order, and raises if a task died without producing its
  partition — matching a failed Spark job surfacing in collect.

Covered API: ``SparkContext(defaultParallelism)``, ``getConf().get``,
``parallelize(seq, numSlices)``, ``RDD.mapPartitionsWithIndex``,
``RDD.collect``, ``setJobGroup``, ``cancelJobGroup``. Extra test hooks:
``task_processes`` (index -> live Process) and ``kill_task(index)``.
"""

from __future__ import annotations

import multiprocessing as mp
import queue as queue_lib
from typing import Any, Callable, Dict, List, Optional

_mp = mp.get_context("spawn")


def _task_main(out_queue, index: int, chain_blob: bytes,
               items: List[Any]) -> None:
    import cloudpickle

    chain = cloudpickle.loads(chain_blob)
    data = iter(items)
    for f in chain:
        data = f(index, data)
    out_queue.put((index, list(data)))


def elastic_probe_fn():
    """Worker fn for dryrun/smoke legs — lives here (not in the caller's
    __main__) so elastic workers can unpickle it by module reference."""
    import os

    from horovod_tpu.common.config import runtime_env

    return (int(runtime_env("PROC_ID", required=True)),
            int(runtime_env("NUM_PROC", required=True)),
            runtime_env("COORDINATOR", required=True))


class FakeSparkConf:
    def __init__(self, values: Optional[Dict[str, str]] = None):
        self._values = dict(values or {})

    def get(self, key: str, default: Optional[str] = None):
        return self._values.get(key, default)


class FakeRDD:
    def __init__(self, ctx: "FakeSparkContext",
                 partitions: List[List[Any]],
                 chain: Optional[List[Callable]] = None):
        self._ctx = ctx
        self._partitions = partitions
        self._chain = list(chain or [])

    def mapPartitionsWithIndex(self, f: Callable) -> "FakeRDD":
        return FakeRDD(self._ctx, self._partitions, self._chain + [f])

    def collect(self) -> List[Any]:
        import cloudpickle

        blob = cloudpickle.dumps(self._chain)
        out_queue = _mp.Queue()
        procs: Dict[int, Any] = {}
        pending = list(enumerate(self._partitions))

        def _schedule():
            # Spark's scheduler model: at most `cap` concurrent tasks;
            # the rest wait for a free slot (this is what starves a
            # too-large pool and trips the registration barrier). The
            # cap is re-read each pass so tests can grow the "cluster"
            # mid-job (dynamic allocation adding executors).
            cap = self._ctx.max_concurrent_tasks or len(self._partitions)
            while pending and \
                    sum(p.is_alive() for p in procs.values()) < cap:
                i, part = pending.pop(0)
                p = _mp.Process(target=_task_main,
                                args=(out_queue, i, blob, part),
                                daemon=True)
                p.start()
                procs[i] = p
                self._ctx.task_processes[i] = p

        _schedule()
        results: Dict[int, List[Any]] = {}
        while len(results) < len(self._partitions):
            _schedule()
            try:
                i, values = out_queue.get(timeout=0.5)
                results[i] = values
                continue
            except queue_lib.Empty:
                pass
            if self._ctx._cancelled:
                for p in procs.values():
                    if p.is_alive():
                        p.terminate()
                raise RuntimeError("job group cancelled")
            dead = [i for i, p in procs.items()
                    if not p.is_alive() and i not in results]
            if dead:
                # Drain any results that raced the liveness check.
                try:
                    while True:
                        i, values = out_queue.get_nowait()
                        results[i] = values
                except queue_lib.Empty:
                    pass
                dead = [i for i in dead if i not in results]
                if dead:
                    raise RuntimeError(
                        f"Spark tasks {sorted(dead)} died without "
                        f"producing their partitions (executor lost)")
        for p in procs.values():
            p.join(timeout=5)
        return [v for i in sorted(results) for v in results[i]]


class FakeSparkContext:
    """Drop-in for the SparkContext surface horovod_tpu.spark touches."""

    def __init__(self, default_parallelism: int = 2,
                 conf: Optional[Dict[str, str]] = None,
                 max_concurrent_tasks: Optional[int] = None):
        self.defaultParallelism = default_parallelism
        self._conf = FakeSparkConf(
            {"spark.driver.host": "127.0.0.1", **(conf or {})})
        self._cancelled = False
        self.job_groups: List[str] = []
        self.task_processes: Dict[int, Any] = {}
        self.max_concurrent_tasks = max_concurrent_tasks

    def getConf(self) -> FakeSparkConf:
        return self._conf

    def parallelize(self, seq, numSlices: int = None) -> FakeRDD:
        items = list(seq)
        n = numSlices or self.defaultParallelism
        # Spark's range partitioning: contiguous, balanced slices.
        base, extra = divmod(len(items), n)
        partitions, start = [], 0
        for i in range(n):
            size = base + (1 if i < extra else 0)
            partitions.append(items[start:start + size])
            start += size
        return FakeRDD(self, partitions)

    def setJobGroup(self, group: str, description: str = "",
                    interruptOnCancel: bool = False) -> None:
        self.job_groups.append(group)

    def cancelJobGroup(self, group: str) -> None:
        self._cancelled = True
        for p in self.task_processes.values():
            if p.is_alive():
                p.terminate()

    # -- test hooks (not pyspark API) -----------------------------------

    def kill_task(self, index: int) -> None:
        """SIGKILL a live task process — the executor-loss injection."""
        p = self.task_processes.get(index)
        if p is not None and p.is_alive():
            p.kill()
            p.join(timeout=5)
