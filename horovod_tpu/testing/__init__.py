"""Test substrates: API-faithful stand-ins for cluster schedulers that
are not installable in the CI image (ray, pyspark). Production code
never imports these; tests install them into ``sys.modules`` to
exercise the real adapters."""
