"""``hvd.elastic`` namespace — reference horovod/torch/elastic,
horovod/tensorflow/elastic.py public surface (State/ObjectState + run
wrapper), re-exported from the framework-agnostic core."""

from .common.elastic import (  # noqa: F401
    JaxState, ObjectState, State, run)
from .checkpoint import restore_state, save_state  # noqa: F401
