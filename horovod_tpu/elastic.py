"""``hvd.elastic`` namespace — reference horovod/torch/elastic,
horovod/tensorflow/elastic.py public surface (State/ObjectState + run
wrapper), re-exported from the framework-agnostic core, plus the
TPU-native preemption-aware checkpointing hooks (SIGTERM latch honored
at ``state.commit()``)."""

from .common.elastic import (  # noqa: F401
    HOSTS_UPDATED_EXIT_CODE, PEER_FAILURE_EXIT_CODE, JaxState, ObjectState,
    State, install_preemption_handler, on_preemption,
    preemption_requested, run)
from .common.faults import recovery_stats  # noqa: F401
from .checkpoint import restore_state, save_state  # noqa: F401
