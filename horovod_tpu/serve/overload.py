"""Overload control for the serve plane: multi-tenant SLO classes,
deadline-aware admission, and the brownout degradation ladder
(docs/serve.md "Overload & tenancy").

Horovod's core robustness idea — degrade deterministically instead of
failing (the join op / elastic shrink) — applied to serving. Three
mechanisms, each data-driven off :class:`~.controller.SLOPolicy`:

* **SLO classes** — ``latency`` / ``throughput`` / ``batch`` tenancy
  tiers. Each class carries a priority (strict across classes), a
  default deadline, and a retry budget (shed / re-route attempts are
  self-limiting so retries cannot amplify an overload). The class
  table is pure data: :func:`classes_from_policy` materializes it from
  the policy's per-class scalar fields.
* **Deadline-aware admission** — :func:`admission_estimate` prices a
  request from the controller's windowed per-phase percentiles
  (queue-wait + TTFT residual + ``max_new_tokens`` x TPOT); the
  cluster SHEDS requests that cannot feasibly meet their deadline
  *before* spending prefill on them
  (``hvd_tpu_serve_shed_total{slo_class,reason}``).
* **Brownout ladder** — :class:`BrownoutLadder`, a deterministic
  hysteresis-gated state machine over :data:`BROWNOUT_RUNGS`. Under
  sustained queue pressure the cluster climbs one rung per controller
  tick (disable speculative decode -> clamp throughput-tier
  ``max_new_tokens`` -> shed the batch tier -> reject non-latency
  admission) and descends the same way once pressure clears. Every
  transition is a ``brownout`` line in the serve decision log — the
  same ``{"seq", "action", "target", "reason"}`` contract as
  autoscale/respec, byte-identical under seeded ``--repeat`` runs.

No wall-clock reads, no RNG: every transition is a pure function of
(policy, observed queue depth, tick count), which is what lets the
chaos soak byte-compare decision sequences across repeats.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

from ..common import metrics as metrics_lib
from ..common.config import runtime_env

#: Tenancy tiers, priority order (first = most protected).
SLO_CLASSES = ("latency", "throughput", "batch")

#: The degradation ladder, mildest rung first. Level N means rungs
#: [0, N) are active; the ladder moves at most ONE rung per controller
#: tick in either direction (hysteresis-gated), so decision logs stay
#: byte-identical under seeded repeats.
BROWNOUT_RUNGS = ("spec_off", "clamp_tokens", "shed_batch",
                  "reject_admission")

_M_SHED = metrics_lib.counter(
    "hvd_tpu_serve_shed_total",
    "requests shed by overload control before spending prefill, by "
    "SLO class and reason (deadline = infeasible at admission, "
    "brownout = ladder shed the tier, retry_budget = re-route budget "
    "exhausted) — docs/serve.md 'Overload & tenancy'",
    labels=("slo_class", "reason"))
_M_BROWNOUT_LEVEL = metrics_lib.gauge(
    "hvd_tpu_serve_brownout_level",
    "current brownout ladder level (0 = off; level N = the first N "
    "rungs of spec_off -> clamp_tokens -> shed_batch -> "
    "reject_admission are active)")


@dataclasses.dataclass(frozen=True)
class SLOClass:
    """One tenancy tier, materialized from the policy's scalar fields.

    ``priority`` orders classes strictly (lower = served first);
    ``deadline_s`` is the class default stamped onto requests that
    arrive without one (0 = none); ``retry_budget`` bounds how many
    re-route / re-prefill attempts a request of this class may burn
    before it is shed (``retry_budget`` reroutes are allowed; the
    next one sheds)."""

    name: str
    priority: int
    deadline_s: float
    retry_budget: int


def classes_from_policy(policy) -> Dict[str, SLOClass]:
    """The class table as data: one :class:`SLOClass` per tier from
    the policy's ``<class>_deadline_s`` / ``<class>_priority`` /
    ``<class>_retry_budget`` scalar fields."""
    return {
        name: SLOClass(
            name=name,
            priority=int(getattr(policy, f"{name}_priority")),
            deadline_s=float(getattr(policy, f"{name}_deadline_s")),
            retry_budget=int(getattr(policy, f"{name}_retry_budget")))
        for name in SLO_CLASSES
    }


def class_priorities(policy) -> Dict[str, int]:
    """name -> priority, the strict cross-class order the class-aware
    ``RequestQueue`` sorts by (unclassed requests rank as priority 0,
    i.e. with the latency tier — legacy traffic is never starved by
    classed traffic)."""
    return {name: cls.priority
            for name, cls in classes_from_policy(policy).items()}


def record_shed(slo_class: str, reason: str) -> None:
    """One shed, attributed (docs/metrics.md)."""
    _M_SHED.labels(slo_class=slo_class or "latency", reason=reason).inc()


def admission_estimate(controller,
                       max_new_tokens: int) -> Optional[float]:
    """Estimated request completion latency (virtual seconds) from the
    controller's windowed per-phase p99s: queue-wait + TTFT residual
    (prefill cost net of the queue wait already inside TTFT) +
    ``max_new_tokens`` x TPOT. ``None`` until the window has evidence
    for both TTFT and TPOT — with no evidence the gate admits (the
    first requests of a run must never be shed by an empty window)."""
    ttft = controller.windowed_ttft_p99()
    tpot = controller.windowed_tpot_p99()
    if ttft is None or tpot is None:
        return None
    qwait = controller.windowed_queue_wait_p99() or 0.0
    prefill = max(0.0, ttft - qwait)
    return qwait + prefill + max(0, int(max_new_tokens)) * tpot


class BrownoutLadder:
    """Deterministic, hysteresis-gated degradation state machine.

    ``tick(queue_depth)`` is called once per controller tick. Depth at
    or above ``brownout_enter_depth`` for ``brownout_enter_ticks``
    consecutive ticks climbs ONE rung; depth at or below
    ``brownout_exit_depth`` for ``brownout_exit_ticks`` consecutive
    ticks descends one. Anything in between resets both streaks (the
    hysteresis band). Returns ``(level, rung, direction)`` on a
    transition, ``None`` otherwise — the controller turns transitions
    into ``brownout`` decision-log lines.

    ``HVD_TPU_SERVE_BROWNOUT`` (docs/serve.md) pins the level for
    operator override — the runbook's "force the ladder" lever; the
    pin also moves one rung per tick so the decision log still reads
    as a sequence."""

    def __init__(self, policy):
        self.policy = policy
        self.level = 0
        self.max_level = 0
        self._hot = 0
        self._cool = 0

    def active(self, rung: str) -> bool:
        """Is ``rung`` (a :data:`BROWNOUT_RUNGS` name) in effect?"""
        return self.level > BROWNOUT_RUNGS.index(rung)

    def rung_name(self) -> str:
        """The deepest active rung ('' at level 0)."""
        return BROWNOUT_RUNGS[self.level - 1] if self.level else ""

    def _pinned(self) -> Optional[int]:
        raw = runtime_env("SERVE_BROWNOUT", "")
        if raw is None or raw == "":
            return None
        try:
            return max(0, min(len(BROWNOUT_RUNGS), int(raw)))
        except ValueError:
            return None

    def tick(self, queue_depth: int
             ) -> Optional[Tuple[int, str, str]]:
        p = self.policy
        pin = self._pinned()
        if pin is not None:
            if pin > self.level:
                return self._climb("pinned")
            if pin < self.level:
                return self._descend("pinned")
            return None
        enter = int(p.brownout_enter_depth)
        if enter <= 0:
            return None  # ladder disabled
        exit_d = int(p.brownout_exit_depth)
        if queue_depth >= enter:
            self._hot += 1
            self._cool = 0
            if self._hot >= int(p.brownout_enter_ticks) \
                    and self.level < len(BROWNOUT_RUNGS):
                self._hot = 0
                return self._climb(f"queue_depth={queue_depth}")
        elif queue_depth <= exit_d:
            self._cool += 1
            self._hot = 0
            if self._cool >= int(p.brownout_exit_ticks) \
                    and self.level > 0:
                self._cool = 0
                return self._descend(f"queue_depth={queue_depth}")
        else:
            # Hysteresis band: neither streak accumulates.
            self._hot = 0
            self._cool = 0
        return None

    def _climb(self, why: str) -> Tuple[int, str, str]:
        self.level += 1
        self.max_level = max(self.max_level, self.level)
        _M_BROWNOUT_LEVEL.set(self.level)
        return (self.level, BROWNOUT_RUNGS[self.level - 1],
                f"enter:{why}")

    def _descend(self, why: str) -> Tuple[int, str, str]:
        rung = BROWNOUT_RUNGS[self.level - 1]
        self.level -= 1
        _M_BROWNOUT_LEVEL.set(self.level)
        return (self.level, rung, f"exit:{why}")
