"""Request admission plane: the ``DeviceInfeed`` background-feed
pattern generalized to inference requests (docs/serve.md).

``DeviceInfeed`` (data.py) keeps a bounded queue of ready batches ahead
of a consumer and measures the consumer's wait; a serving replica needs
the same shape with requests instead of batches — a bounded FIFO the
router feeds asynchronously, the batcher drains into free decode slots,
and telemetry measures (queue depth, time-in-queue, deadline misses).
Unlike the infeed the queue must also run BACKWARD: a draining replica
re-routes its unstarted requests to peers (``drain()``), which is why
admission hands out ``Request`` objects rather than opaque batches.

Deterministic by construction: FIFO order, integer virtual-time stamps,
no wall-clock reads — the chaos soak's byte-identity contract
(docs/serve.md) starts here.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import deque
from typing import List, Optional, Tuple

from ..common import metrics as metrics_lib
from . import tracing

_M_QUEUE_DEPTH = metrics_lib.gauge(
    "hvd_tpu_serve_queue_depth",
    "requests queued ahead of the decode slots, summed over this "
    "process's replicas")
_M_LATENCY = metrics_lib.histogram(
    "hvd_tpu_serve_latency_seconds",
    "end-to-end request latency: arrival -> last generated token "
    "(virtual time in simulation, wall time live)")
_M_DEADLINE_MISSES = metrics_lib.counter(
    "hvd_tpu_serve_deadline_misses_total",
    "requests that completed after their deadline (deadline_s from "
    "arrival; 0 = no deadline)")


@dataclasses.dataclass
class Request:
    """One inference request. ``arrival_t`` is stamped by the traffic
    source (virtual seconds); ``deadline_s`` is the per-request latency
    budget from arrival (0 = none) the batcher tracks."""

    rid: int
    prompt: Tuple[int, ...]
    max_new_tokens: int
    arrival_t: float = 0.0
    deadline_s: float = 0.0
    # Sampling lane (docs/serve.md): temperature 0 = greedy argmax
    # (the historical default — byte-identical to pre-sampling
    # engines); > 0 samples from softmax(logits / temperature) under a
    # per-request PRNG lane seeded by (sample_seed, rid, position) —
    # deterministic per request regardless of batching, slot
    # assignment, or mid-sequence migration, so the seeded-repeat
    # event-digest contract keeps holding.
    temperature: float = 0.0
    sample_seed: int = 0
    # Filled at completion.
    tokens: Tuple[int, ...] = ()
    finish_t: Optional[float] = None
    replica: Optional[str] = None
    reroutes: int = 0
    migrations: int = 0
    # Per-phase timeline (virtual seconds). ``admit_t`` is stamped by
    # ``RequestQueue.take`` at every admission (a re-admission after a
    # kill or reroute overwrites it — the phases below describe the
    # attempt that completed); ``first_token_t`` by the prefill that
    # emitted token 0.
    admit_t: Optional[float] = None
    first_token_t: Optional[float] = None

    @property
    def latency_s(self) -> Optional[float]:
        if self.finish_t is None:
            return None
        return self.finish_t - self.arrival_t

    @property
    def queue_wait_s(self) -> Optional[float]:
        """Arrival -> (last) admission onto a replica's decode slots."""
        if self.admit_t is None:
            return None
        return self.admit_t - self.arrival_t

    @property
    def ttft_s(self) -> Optional[float]:
        """Time to first token: arrival -> prefill emits token 0."""
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.arrival_t

    @property
    def tpot_s(self) -> Optional[float]:
        """Time per output token after the first (decode cadence)."""
        if (self.finish_t is None or self.first_token_t is None
                or len(self.tokens) < 2):
            return None
        return ((self.finish_t - self.first_token_t)
                / (len(self.tokens) - 1))

    @property
    def deadline_missed(self) -> bool:
        return bool(self.deadline_s > 0 and self.latency_s is not None
                    and self.latency_s > self.deadline_s)


class RequestQueue:
    """Bounded FIFO between the router and one replica's batcher.

    ``submit`` enqueues (router side, any thread); ``take(n, now)``
    dequeues up to n for admission (batcher side) and records each
    request's time-in-queue; ``drain()`` empties the queue for
    re-routing — the unstarted half of a graceful drain. Thread-safe;
    iteration order is strict FIFO so a seeded run replays exactly."""

    def __init__(self, maxsize: int = 0):
        self._q: deque = deque()
        self._maxsize = int(maxsize)
        self._lock = threading.Lock()
        self.submitted = 0
        self.rejected = 0
        # Stamped by the owning batcher so admission telemetry carries
        # the replica identity (standalone queues default to "mixed").
        self.role = "mixed"
        self.replica = ""

    def submit(self, req: Request) -> bool:
        """Enqueue; False when the queue is at maxsize (the router
        should pick another replica or shed load loudly)."""
        with self._lock:
            if self._maxsize and len(self._q) >= self._maxsize:
                self.rejected += 1
                return False
            self._q.append(req)
            self.submitted += 1
            _M_QUEUE_DEPTH.inc()
            return True

    def take(self, n: int, now: float = 0.0) -> List[Request]:
        """Dequeue up to ``n`` requests for admission at virtual time
        ``now``: stamps ``admit_t`` on each request and records its
        time-in-queue (the queue-wait histogram + a ``queue`` span)."""
        out: List[Request] = []
        with self._lock:
            while self._q and len(out) < int(n):
                out.append(self._q.popleft())
            _M_QUEUE_DEPTH.dec(len(out))
        if out:
            tr = tracing.tracer()
            for req in out:
                req.admit_t = now
                if tr.enabled:
                    tr.queue_admit(req, self.replica, now)
        return out

    def requeue_front(self, reqs: List[Request]) -> None:
        """Put re-routed requests BACK at the head (they already waited
        elsewhere; FIFO fairness follows arrival, not re-route time)."""
        with self._lock:
            for req in reversed(reqs):
                self._q.appendleft(req)
            _M_QUEUE_DEPTH.inc(len(reqs))

    def insert_by_arrival(self, req: Request) -> None:
        """Re-insert a re-routed / fallback-re-prefill request at its
        ARRIVAL position: the deadline clock runs from ``arrival_t``
        and never restarts, so a request that already waited (and then
        lost its slot to a kill, drain, or failed warm handoff) must
        not also wait behind requests that arrived after it. Bypasses
        ``maxsize`` — this is work the cluster already admitted once;
        shedding it here would drop a request, and the drain runbook's
        contract is zero drops (docs/serve.md)."""
        key = (req.arrival_t, req.rid)
        with self._lock:
            idx = len(self._q)
            for i, queued in enumerate(self._q):
                if (queued.arrival_t, queued.rid) > key:
                    idx = i
                    break
            self._q.insert(idx, req)
            _M_QUEUE_DEPTH.inc()

    def drain(self) -> List[Request]:
        """Empty the queue for re-routing (graceful-drain step 1)."""
        with self._lock:
            out = list(self._q)
            self._q.clear()
            _M_QUEUE_DEPTH.dec(len(out))
        return out

    def depth(self) -> int:
        with self._lock:
            return len(self._q)

    def __len__(self) -> int:
        return self.depth()


def record_completion(req: Request) -> None:
    """Completion telemetry shared by every retire/finish path: latency
    histogram + deadline-miss counter (one definition of 'done')."""
    lat = req.latency_s
    if lat is not None:
        _M_LATENCY.observe(lat)
    if req.deadline_missed:
        _M_DEADLINE_MISSES.inc()
