"""Request admission plane: the ``DeviceInfeed`` background-feed
pattern generalized to inference requests (docs/serve.md).

``DeviceInfeed`` (data.py) keeps a bounded queue of ready batches ahead
of a consumer and measures the consumer's wait; a serving replica needs
the same shape with requests instead of batches — a bounded FIFO the
router feeds asynchronously, the batcher drains into free decode slots,
and telemetry measures (queue depth, time-in-queue, deadline misses).
Unlike the infeed the queue must also run BACKWARD: a draining replica
re-routes its unstarted requests to peers (``drain()``), which is why
admission hands out ``Request`` objects rather than opaque batches.

Deterministic by construction: FIFO order, integer virtual-time stamps,
no wall-clock reads — the chaos soak's byte-identity contract
(docs/serve.md) starts here.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..common import metrics as metrics_lib
from . import tracing

_M_QUEUE_DEPTH = metrics_lib.gauge(
    "hvd_tpu_serve_queue_depth",
    "requests queued ahead of the decode slots, summed over this "
    "process's replicas")
_M_LATENCY = metrics_lib.histogram(
    "hvd_tpu_serve_latency_seconds",
    "end-to-end request latency: arrival -> last generated token "
    "(virtual time in simulation, wall time live)")
_M_DEADLINE_MISSES = metrics_lib.counter(
    "hvd_tpu_serve_deadline_misses_total",
    "requests whose deadline (deadline_s from arrival; 0 = none) was "
    "missed, by where the miss was detected: reason=retire (completed "
    "late) or reason=shed (admission control judged the deadline "
    "infeasible and shed before prefill) — honest under load shedding "
    "(docs/serve.md 'Overload & tenancy')",
    labels=("reason",))
_M_REJECTED = metrics_lib.counter(
    "hvd_tpu_serve_rejected_total",
    "typed request rejections, by reason: queue_full = a bounded "
    "RequestQueue refused a submit (the router tries the next replica "
    "or overflows — never an unrecorded drop), brownout = the ladder's "
    "reject_admission rung refused a non-latency-tier request at "
    "cluster admission (docs/serve.md)",
    labels=("reason",))
for _reason in ("retire", "shed"):
    _M_DEADLINE_MISSES.labels(reason=_reason)
for _reason in ("queue_full", "brownout"):
    _M_REJECTED.labels(reason=_reason)
del _reason


@dataclasses.dataclass
class Request:
    """One inference request. ``arrival_t`` is stamped by the traffic
    source (virtual seconds); ``deadline_s`` is the per-request latency
    budget from arrival (0 = none) the batcher tracks."""

    rid: int
    prompt: Tuple[int, ...]
    max_new_tokens: int
    arrival_t: float = 0.0
    deadline_s: float = 0.0
    # Sampling lane (docs/serve.md): temperature 0 = greedy argmax
    # (the historical default — byte-identical to pre-sampling
    # engines); > 0 samples from softmax(logits / temperature) under a
    # per-request PRNG lane seeded by (sample_seed, rid, position) —
    # deterministic per request regardless of batching, slot
    # assignment, or mid-sequence migration, so the seeded-repeat
    # event-digest contract keeps holding.
    temperature: float = 0.0
    sample_seed: int = 0
    # Filled at completion.
    tokens: Tuple[int, ...] = ()
    finish_t: Optional[float] = None
    replica: Optional[str] = None
    reroutes: int = 0
    migrations: int = 0
    # Per-phase timeline (virtual seconds). ``admit_t`` is stamped by
    # ``RequestQueue.take`` at every admission (a re-admission after a
    # kill or reroute overwrites it — the phases below describe the
    # attempt that completed); ``first_token_t`` by the prefill that
    # emitted token 0.
    admit_t: Optional[float] = None
    first_token_t: Optional[float] = None
    # Multi-tenancy (docs/serve.md "Overload & tenancy"): the SLO
    # class this request bills to ("latency" / "throughput" / "batch";
    # "" = unclassed legacy traffic, which ranks with the latency
    # tier). ``outcome`` is stamped exactly once by whichever terminal
    # path ends the journey: finished | shed | rejected (the
    # zero-silent-drops accounting contract).
    slo_class: str = ""
    outcome: str = ""

    @property
    def latency_s(self) -> Optional[float]:
        if self.finish_t is None:
            return None
        return self.finish_t - self.arrival_t

    @property
    def queue_wait_s(self) -> Optional[float]:
        """Arrival -> (last) admission onto a replica's decode slots."""
        if self.admit_t is None:
            return None
        return self.admit_t - self.arrival_t

    @property
    def ttft_s(self) -> Optional[float]:
        """Time to first token: arrival -> prefill emits token 0."""
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.arrival_t

    @property
    def tpot_s(self) -> Optional[float]:
        """Time per output token after the first (decode cadence)."""
        if (self.finish_t is None or self.first_token_t is None
                or len(self.tokens) < 2):
            return None
        return ((self.finish_t - self.first_token_t)
                / (len(self.tokens) - 1))

    @property
    def deadline_missed(self) -> bool:
        return bool(self.deadline_s > 0 and self.latency_s is not None
                    and self.latency_s > self.deadline_s)


class RequestQueue:
    """Bounded FIFO between the router and one replica's batcher.

    ``submit`` enqueues (router side, any thread); ``take(n, now)``
    dequeues up to n for admission (batcher side) and records each
    request's time-in-queue; ``drain()`` empties the queue for
    re-routing — the unstarted half of a graceful drain. Thread-safe;
    iteration order is strict FIFO so a seeded run replays exactly.

    **Class-aware mode** (docs/serve.md "Overload & tenancy"):
    ``set_classes(name -> priority)`` switches ``take`` from FIFO to
    strict priority across SLO classes with earliest-deadline-first
    inside a class. The sort key is ``(priority, arrival_t +
    deadline_s, arrival_t, rid)`` — every component is fixed at
    arrival (the deadline clock never restarts), so a re-admitted
    request (``insert_by_arrival``) competes at exactly the position
    it held before losing its slot: the arrival-position contract is
    preserved by construction. Unclassed requests rank as priority 0
    (with the latency tier); no-deadline requests sort after
    deadlined peers of their class."""

    def __init__(self, maxsize: int = 0):
        self._q: deque = deque()
        self._maxsize = int(maxsize)
        self._lock = threading.Lock()
        self.submitted = 0
        self.rejected = 0
        self._class_order: Optional[Dict[str, int]] = None
        # Stamped by the owning batcher so admission telemetry carries
        # the replica identity (standalone queues default to "mixed").
        self.role = "mixed"
        self.replica = ""

    def set_classes(self,
                    priorities: Optional[Dict[str, int]]) -> None:
        """Enable class-aware ordering (name -> strict priority, lower
        first); ``None`` restores plain FIFO."""
        with self._lock:
            self._class_order = (dict(priorities)
                                 if priorities is not None else None)

    def _class_key(self, req: Request) -> Tuple:
        order = self._class_order or {}
        deadline = (req.arrival_t + req.deadline_s
                    if req.deadline_s > 0 else float("inf"))
        return (order.get(req.slo_class, 0), deadline,
                req.arrival_t, req.rid)

    def submit(self, req: Request, now: Optional[float] = None) -> bool:
        """Enqueue; False when the queue is at maxsize. A refusal is
        TYPED, never silent: the rejected counter, the
        ``hvd_tpu_serve_rejected_total{reason="queue_full"}`` metric,
        and an ``abort`` span (detail ``queue_full``) all record it —
        the router tries the next replica or overflows; a standalone
        caller owns shedding loudly (docs/serve.md)."""
        with self._lock:
            if self._maxsize and len(self._q) >= self._maxsize:
                self.rejected += 1
                full = True
            else:
                self._q.append(req)
                self.submitted += 1
                _M_QUEUE_DEPTH.inc()
                full = False
        if not full:
            return True
        _M_REJECTED.labels(reason="queue_full").inc()
        tr = tracing.tracer()
        if tr.enabled:
            t = now if now is not None else req.arrival_t
            tr.abort(req, self.replica, t, cause="queue_full")
        return False

    def take(self, n: int, now: float = 0.0) -> List[Request]:
        """Dequeue up to ``n`` requests for admission at virtual time
        ``now``: stamps ``admit_t`` on each request and records its
        time-in-queue (the queue-wait histogram + a ``queue`` span).
        Class-aware mode picks the ``n`` best by the class key instead
        of the queue head (stable: FIFO breaks exact-key ties)."""
        out: List[Request] = []
        with self._lock:
            if self._class_order is not None and len(self._q) > 1:
                ranked = sorted(self._q, key=self._class_key)
                out = ranked[:int(n)]
                for req in out:
                    self._q.remove(req)
            else:
                while self._q and len(out) < int(n):
                    out.append(self._q.popleft())
            _M_QUEUE_DEPTH.dec(len(out))
        if out:
            tr = tracing.tracer()
            for req in out:
                req.admit_t = now
                if tr.enabled:
                    tr.queue_admit(req, self.replica, now)
        return out

    def requeue_front(self, reqs: List[Request]) -> None:
        """Put re-routed requests BACK at the head (they already waited
        elsewhere; FIFO fairness follows arrival, not re-route time)."""
        with self._lock:
            for req in reversed(reqs):
                self._q.appendleft(req)
            _M_QUEUE_DEPTH.inc(len(reqs))

    def insert_by_arrival(self, req: Request) -> None:
        """Re-insert a re-routed / fallback-re-prefill request at its
        ARRIVAL position: the deadline clock runs from ``arrival_t``
        and never restarts, so a request that already waited (and then
        lost its slot to a kill, drain, or failed warm handoff) must
        not also wait behind requests that arrived after it. Bypasses
        ``maxsize`` — this is work the cluster already admitted once;
        shedding it here would drop a request, and the drain runbook's
        contract is zero drops (docs/serve.md)."""
        key = (req.arrival_t, req.rid)
        with self._lock:
            idx = len(self._q)
            for i, queued in enumerate(self._q):
                if (queued.arrival_t, queued.rid) > key:
                    idx = i
                    break
            self._q.insert(idx, req)
            _M_QUEUE_DEPTH.inc()

    def drain(self) -> List[Request]:
        """Empty the queue for re-routing (graceful-drain step 1)."""
        with self._lock:
            out = list(self._q)
            self._q.clear()
            _M_QUEUE_DEPTH.dec(len(out))
        return out

    def depth(self) -> int:
        with self._lock:
            return len(self._q)

    def __len__(self) -> int:
        return self.depth()


def record_completion(req: Request) -> None:
    """Completion telemetry shared by every retire/finish path: latency
    histogram + deadline-miss counter (one definition of 'done')."""
    lat = req.latency_s
    if lat is not None:
        _M_LATENCY.observe(lat)
    if req.deadline_missed:
        _M_DEADLINE_MISSES.labels(reason="retire").inc()


def record_rejection(reason: str) -> None:
    """A typed terminal rejection at cluster admission (e.g. the
    brownout ladder's reject_admission rung) — same counter as the
    queue-full refusals, different reason."""
    _M_REJECTED.labels(reason=reason).inc()


def record_shed_miss() -> None:
    """A deadline miss detected AT ADMISSION (the request was shed as
    infeasible before prefill) — counted under reason="shed" so the
    miss metric stays honest under load shedding (docs/serve.md)."""
    _M_DEADLINE_MISSES.labels(reason="shed").inc()
