"""Seeded open-loop traffic for the serve bench/chaos surfaces
(docs/serve.md).

Open-loop (arrivals ignore the server's state) is the honest serving
benchmark shape: a closed loop self-throttles under overload and hides
queueing collapse. Arrivals are Poisson (exponential inter-arrival
times at ``rate_rps``), prompt/output lengths are drawn from mixed
seeded distributions — everything derives from ``numpy``'s
``default_rng(seed)``, so the same seed replays the byte-identical
request sequence (the chaos soak and the bench repeat-determinism
check both rely on this).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .queue import Request


@dataclasses.dataclass
class TrafficTrace:
    """A materialized request sequence (arrival-sorted)."""

    seed: int
    requests: List[Request]

    @property
    def duration_s(self) -> float:
        return self.requests[-1].arrival_t if self.requests else 0.0

    def __len__(self) -> int:
        return len(self.requests)


def poisson_trace(seed: int, n_requests: int, rate_rps: float,
                  prompt_lens: Sequence[int] = (4, 8, 16),
                  output_lens: Sequence[int] = (4, 8, 16, 32),
                  vocab_size: int = 128,
                  deadline_s: float = 0.0,
                  temperature: float = 0.0,
                  shared_prefix_len: int = 0,
                  class_mix: Optional[Sequence[Tuple[str, float]]]
                  = None,
                  class_deadlines: Optional[Dict[str, float]]
                  = None) -> TrafficTrace:
    """Seeded open-loop trace: Poisson arrivals at ``rate_rps``, prompt
    and output lengths drawn uniformly from the given mixes, prompt
    tokens uniform over ``[1, vocab_size)`` (0 is reserved for pad).
    ``deadline_s`` stamps every request with a latency budget.
    ``temperature`` > 0 stamps every request with that sampling
    temperature plus a seeded per-request ``sample_seed`` (drawn from
    this trace's own rng — the PRNG lane the engine folds with
    (rid, position)), so a sampled trace replays byte-identically under
    the same trace seed; 0 keeps the greedy default.
    ``shared_prefix_len`` > 0 prepends ONE seeded token sequence of
    that length to every prompt — the shared-system-prompt traffic
    shape the prefix-reuse arm measures (docs/serve.md); the drawn
    ``prompt_lens`` then size each request's unique tail.
    ``class_mix`` — mixed tenancy (docs/serve.md "Overload &
    tenancy"): ``[("latency", 0.5), ("throughput", 0.3), ...]`` stamps
    each request's ``slo_class``, drawn by weight from this trace's
    rng strictly AFTER every pre-existing draw, so a trace without a
    mix replays byte-identically to earlier releases.
    ``class_deadlines`` (name -> seconds) stamps per-class deadlines
    onto classed requests that the flat ``deadline_s`` did not —
    giving control-OFF baselines the same deadline accounting as
    control-ON runs."""
    if n_requests < 1 or rate_rps <= 0:
        raise ValueError(
            f"need n_requests >= 1 and rate_rps > 0, got "
            f"{n_requests}/{rate_rps}")
    rng = np.random.default_rng(seed)
    shared: Tuple[int, ...] = ()
    if shared_prefix_len > 0:
        shared = tuple(int(t) for t in rng.integers(
            1, vocab_size, int(shared_prefix_len)))
    gaps = rng.exponential(1.0 / rate_rps, size=n_requests)
    arrivals = np.cumsum(gaps)
    plens = rng.choice(np.asarray(prompt_lens), size=n_requests)
    olens = rng.choice(np.asarray(output_lens), size=n_requests)
    sseeds = (rng.integers(0, 2 ** 31 - 1, size=n_requests)
              if temperature > 0 else np.zeros(n_requests, np.int64))
    reqs = []
    for i in range(n_requests):
        prompt: Tuple[int, ...] = shared + tuple(
            int(t) for t in rng.integers(1, vocab_size, int(plens[i])))
        reqs.append(Request(
            rid=i, prompt=prompt, max_new_tokens=int(olens[i]),
            arrival_t=float(arrivals[i]), deadline_s=deadline_s,
            temperature=float(temperature),
            sample_seed=int(sseeds[i])))
    if class_mix:
        names = [str(n) for n, _ in class_mix]
        weights = np.asarray([float(w) for _, w in class_mix])
        if (weights < 0).any() or weights.sum() <= 0:
            raise ValueError(
                f"class_mix weights must be non-negative with a "
                f"positive sum, got {list(class_mix)}")
        # One extra draw block at the very end: pre-existing seeded
        # traces (no mix) consume the identical rng stream.
        picks = rng.choice(len(names), size=n_requests,
                           p=weights / weights.sum())
        deadlines = class_deadlines or {}
        for req, pick in zip(reqs, picks):
            req.slo_class = names[int(pick)]
            if req.deadline_s == 0:
                req.deadline_s = float(
                    deadlines.get(req.slo_class, 0.0))
    return TrafficTrace(seed=seed, requests=reqs)
