"""One replica's decode plane: slots, jitted prefill/decode, retire
(docs/serve.md).

Exactly TWO compiled programs serve every request mix, because request
variety is data, not shape:

* ``prefill`` — (1, max_prompt_len) tokens + a length scalar: the
  prompt's KV lines land in a fresh single-slot cache (pad lines
  invalidated), and the first output token is the argmax at position
  ``length - 1``. Admission scatters the slot into the batch cache
  (``kvcache.write_slot``) — dynamic slot index, no recompile.
* ``decode`` — one token per slot across ALL slots: (slots, 1) last
  tokens against the (slots, max_len, ...) ring cache. Finished/empty
  slots decode garbage that is never read — cheaper than a ragged
  program per occupancy pattern, and the reason sequences of any
  length mix share the step.

Sampling is greedy argmax — deterministic, the repeat-identity
contract. The decode step is bracketed with flight-recorder events
(op ``serve``), so a hung replica's black box names the decode batch it
never completed, the same attribution the training collectives get
(docs/podmon.md).
"""

from __future__ import annotations

import functools
import os
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..common import flightrec as flightrec_lib
from ..common import metrics as metrics_lib
from . import kvcache as kv_lib
from .queue import Request, record_completion

_M_TOKENS = metrics_lib.counter(
    "hvd_tpu_serve_tokens_total",
    "tokens processed by the serve engines, by kind "
    "(prompt = prefilled, generated = decoded)",
    labels=("kind",))
for _k in ("prompt", "generated"):
    _M_TOKENS.labels(kind=_k)
del _k
_M_ACTIVE = metrics_lib.gauge(
    "hvd_tpu_serve_active_requests",
    "requests currently holding a decode slot, summed over this "
    "process's replicas")
_M_CACHE_BYTES = metrics_lib.gauge(
    "hvd_tpu_serve_kv_cache_bytes",
    "allocated KV-cache bytes, by replica (int8 storage shows the "
    "~4x reduction over fp32 here)",
    labels=("replica",))


class DecodeEngine:
    """Slots + cache + the two jitted programs for ONE replica.

    ``model`` is a GPT-family flax module whose ``apply`` supports the
    ``cache=`` incremental path (models/gpt.py); ``params`` its
    variables. Greedy decode; ``eos_id`` (optional) ends a sequence
    early, ``max_new_tokens`` always bounds it.
    """

    def __init__(self, model, params, slots: int = 4, max_len: int = 32,
                 max_prompt_len: int = 16, kv_kind: str = "fp32",
                 eos_id: Optional[int] = None, name: str = "r0",
                 programs=None):
        if max_prompt_len > max_len:
            raise ValueError(
                f"max_prompt_len {max_prompt_len} exceeds the cache's "
                f"max_len {max_len}")
        self.model = model
        self.params = params
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.max_prompt_len = int(max_prompt_len)
        self.kv_kind = kv_kind
        self.eos_id = eos_id
        self.name = name
        from ..models.gpt import init_kv_cache

        self.cache = init_kv_cache(model, self.slots, self.max_len,
                                   kind=kv_kind)
        self._single = init_kv_cache(model, 1, self.max_len,
                                     kind=kv_kind)
        _M_CACHE_BYTES.labels(replica=name).set(
            kv_lib.cache_nbytes(self.cache))
        # Per-slot host state (the python side of the batcher loop).
        self.requests: List[Optional[Request]] = [None] * self.slots
        self.generated: List[List[int]] = [[] for _ in range(self.slots)]
        self.last_tokens = np.zeros((self.slots,), np.int32)
        self.decode_steps = 0
        if programs is None:
            programs = compile_programs(model)
        (self._prefill, self._decode, self._write_slot,
         self._reset_slot) = programs

    # -- admission -----------------------------------------------------------

    def free_slots(self) -> List[int]:
        return [i for i, r in enumerate(self.requests) if r is None]

    def active_count(self) -> int:
        return self.slots - len(self.free_slots())

    def admit(self, req: Request, now: float = 0.0) -> int:
        """Prefill ``req`` into a free slot; returns the slot. The
        prompt is truncated to the engine's ``max_prompt_len`` window
        (documented serving contract, docs/serve.md)."""
        free = self.free_slots()
        if not free:
            raise RuntimeError(f"replica {self.name}: no free slot")
        slot = free[0]
        prompt = list(req.prompt)[-self.max_prompt_len:]
        padded = np.zeros((1, self.max_prompt_len), np.int32)
        padded[0, :len(prompt)] = prompt
        single, first = self._prefill(
            self.params, jnp.asarray(padded),
            jnp.asarray(len(prompt), jnp.int32), self._single,
            jnp.asarray(req.temperature, jnp.float32),
            jnp.asarray(req.sample_seed & 0x7FFFFFFF, jnp.int32),
            jnp.asarray(req.rid, jnp.int32))
        self.cache = self._write_slot(self.cache, slot, single)
        self.requests[slot] = req
        req.replica = self.name
        tok = int(first)
        self.generated[slot] = [tok]
        self.last_tokens[slot] = tok
        _M_TOKENS.labels(kind="prompt").inc(len(prompt))
        _M_TOKENS.labels(kind="generated").inc()
        _M_ACTIVE.inc()
        return slot

    # -- the decode step -----------------------------------------------------

    def step(self, now: float = 0.0) -> List[Request]:
        """One decode round across every slot; retires and returns the
        requests that finished this step (their ``tokens``/``finish_t``
        filled)."""
        if self.active_count() == 0:
            return []
        rec = flightrec_lib.recorder()
        step_name = f"serve.decode.{self.name}"
        rec.record_submit(step_name, "serve")
        temps = np.zeros((self.slots,), np.float32)
        seeds = np.zeros((self.slots,), np.int32)
        rids = np.zeros((self.slots,), np.int32)
        poss = np.zeros((self.slots,), np.int32)
        for slot, req in enumerate(self.requests):
            if req is None:
                continue
            temps[slot] = req.temperature
            seeds[slot] = req.sample_seed & 0x7FFFFFFF
            rids[slot] = req.rid
            poss[slot] = len(self.generated[slot])
        try:
            logits, self.cache, next_tokens = self._decode(
                self.params, self.cache,
                jnp.asarray(self.last_tokens), jnp.asarray(temps),
                jnp.asarray(seeds), jnp.asarray(rids),
                jnp.asarray(poss))
            next_np = np.asarray(next_tokens)
        except BaseException:
            rec.record_complete(step_name, outcome="error")
            raise
        rec.annotate(step_name,
                     nbytes=kv_lib.cache_nbytes(self.cache),
                     wire=self.kv_kind)
        rec.record_complete(step_name)
        self.decode_steps += 1
        finished: List[Request] = []
        for slot, req in enumerate(self.requests):
            if req is None:
                continue
            done = False
            if len(self.generated[slot]) >= req.max_new_tokens:
                # The finishing token was produced by the PREVIOUS
                # round (or prefill); this round's output for the slot
                # is discarded.
                done = True
            else:
                tok = int(next_np[slot])
                self.generated[slot].append(tok)
                self.last_tokens[slot] = tok
                _M_TOKENS.labels(kind="generated").inc()
                done = (len(self.generated[slot]) >= req.max_new_tokens
                        or (self.eos_id is not None
                            and tok == self.eos_id))
            if done:
                finished.append(self.retire(slot, now))
        return finished

    def request_done(self, slot: int) -> bool:
        """True when the slot's sequence already hit its stop condition
        (a 1-token request finishes at prefill; the batcher retires it
        without waiting for a decode round)."""
        req = self.requests[slot]
        if req is None:
            return False
        toks = self.generated[slot]
        return bool(len(toks) >= req.max_new_tokens
                    or (self.eos_id is not None and toks
                        and toks[-1] == self.eos_id))

    def retire(self, slot: int, now: float) -> Request:
        req = self.requests[slot]
        req.tokens = tuple(self.generated[slot])
        req.finish_t = now
        record_completion(req)
        self.requests[slot] = None
        self.generated[slot] = []
        self.cache = self._reset_slot(self.cache, slot)
        _M_ACTIVE.dec()
        return req

    # -- drain / teardown ----------------------------------------------------

    def abort_all(self) -> List[Request]:
        """Hard abort (replica kill): every in-flight request comes
        back UNFINISHED for re-routing — generated tokens are dropped
        and the peer re-prefills from the prompt (no dropped
        requests, docs/serve.md drain runbook)."""
        out = []
        for slot, req in enumerate(self.requests):
            if req is None:
                continue
            req.reroutes += 1
            req.replica = None
            out.append(req)
            self.requests[slot] = None
            self.generated[slot] = []
            self.cache = self._reset_slot(self.cache, slot)
            _M_ACTIVE.dec()
        return out

    def export_slot(self, slot: int) -> Dict[str, Any]:
        """A slot's warm cache as the int8 block-scaled wire blob
        (``kvcache.export_slot`` — the Pallas quantization path), for
        peers that accept mid-sequence migration instead of a
        re-prefill."""
        return kv_lib.export_slot(self.cache, slot)

    def migrate_out(self, slot: int):
        """Evict one in-flight sequence WITH its warm state: returns
        ``(request, wire_blob, generated_tokens)`` — the int8
        block-scaled cache export plus the host-side decode state a
        peer needs to continue mid-sequence (the graceful-drain default,
        docs/serve.md). The slot frees immediately; nothing completes."""
        req = self.requests[slot]
        if req is None:
            raise RuntimeError(f"replica {self.name}: slot {slot} empty")
        blob = kv_lib.export_slot(self.cache, slot)
        generated = list(self.generated[slot])
        self.requests[slot] = None
        self.generated[slot] = []
        self.cache = self._reset_slot(self.cache, slot)
        _M_ACTIVE.dec()
        return req, blob, generated

    def admit_migrated(self, req: Request, blob: Dict[str, Any],
                       generated, now: float = 0.0) -> int:
        """Land a migrated sequence in a free slot: the wire blob
        imports into the cache (``kvcache.import_slot`` — dequantized
        through the same Pallas path) and decode continues from the
        last generated token — no re-prefill. Same-geometry engines
        only (the cluster's factory guarantees it)."""
        free = self.free_slots()
        if not free:
            raise RuntimeError(f"replica {self.name}: no free slot")
        slot = free[0]
        self.cache = kv_lib.import_slot(self.cache, slot, blob)
        self.requests[slot] = req
        req.replica = self.name
        req.migrations += 1
        self.generated[slot] = list(generated)
        self.last_tokens[slot] = generated[-1] if generated else 0
        _M_ACTIVE.inc()
        return slot

    def close(self) -> None:
        """Zero this replica's labeled gauges when it leaves the
        cluster — a departed replica's cache is freed, so a stale
        ``kv_cache_bytes`` series would overstate live HBM on every
        pod scrape."""
        _M_CACHE_BYTES.labels(replica=self.name).set(0)


def _sample_token(row, temp, seed, rid, pos):
    """One slot's next token: greedy argmax at ``temp == 0`` (the
    historical deterministic default — bit-identical to the
    pre-sampling engine), else a categorical draw from
    ``softmax(logits / temp)`` under the per-request PRNG lane
    ``fold_in(fold_in(PRNGKey(seed), rid), pos)``. The KEY is
    deterministic in (seed, rid, position) alone — never the slot or
    replica — so re-batching, slot reassignment and migration cannot
    perturb the randomness (the event-digest repeat contract,
    docs/serve.md). The LOGITS are the cache's: a warm migration over
    the int8 wire carries the kvcache round-trip's bounded rounding
    (docs/serve.md parity table), which can shift a near-tie token."""
    row = row.astype(jnp.float32)
    greedy = jnp.argmax(row, axis=-1).astype(jnp.int32)
    key = jax.random.fold_in(
        jax.random.fold_in(jax.random.PRNGKey(seed), rid), pos)
    sampled = jax.random.categorical(
        key, row / jnp.maximum(temp, 1e-6)).astype(jnp.int32)
    return jnp.where(temp > 0.0, sampled, greedy)


def _prefill_fn(model, params, tokens, length, single_cache, temp,
                seed, rid):
    """(1, P) prompt -> (single-slot cache, first output token)."""
    logits, cache = model.apply(params, tokens, cache=single_cache)
    # Pad lines (written at positions >= length) must never be
    # attendable; the write head rewinds to the true prompt length.
    sp = cache["slot_pos"]
    cache = {
        "layers": cache["layers"],
        "pos": jnp.full_like(cache["pos"], length),
        "slot_pos": jnp.where(sp >= length, -1, sp),
    }
    first = _sample_token(logits[0, length - 1], temp, seed, rid,
                          jnp.zeros((), jnp.int32))
    return cache, first


def _decode_fn(model, params, cache, last_tokens, temps, seeds, rids,
               poss):
    """(slots,) last tokens -> (logits, cache, next tokens). Per-slot
    sampling state (temperature / seed / rid / position) rides data
    arrays, so every request mix shares the ONE compiled program."""
    logits, cache = model.apply(params, last_tokens[:, None],
                                cache=cache)
    nxt = jax.vmap(_sample_token)(logits[:, 0], temps, seeds, rids,
                                  poss)
    return logits, cache, nxt


ENV_KV_DTYPE = "HVD_TPU_SERVE_KV_DTYPE"   # fp32 | int8 cache storage
ENV_SLOTS = "HVD_TPU_SERVE_SLOTS"         # decode slots per replica
ENV_MAX_LEN = "HVD_TPU_SERVE_MAX_LEN"     # ring-buffer cache lines


def engine_defaults_from_env(env=None) -> Dict[str, Any]:
    """The env-tunable engine geometry (docs/serve.md knob table):
    ``HVD_TPU_SERVE_KV_DTYPE`` / ``HVD_TPU_SERVE_SLOTS`` /
    ``HVD_TPU_SERVE_MAX_LEN``, as DecodeEngine kwargs."""
    env = env if env is not None else os.environ
    out: Dict[str, Any] = {}
    kind = env.get(ENV_KV_DTYPE)
    if kind:
        if kind not in kv_lib.KINDS:
            raise ValueError(
                f"{ENV_KV_DTYPE}={kind!r}: known kinds {kv_lib.KINDS}")
        out["kv_kind"] = kind
    for env_name, kwarg in ((ENV_SLOTS, "slots"),
                            (ENV_MAX_LEN, "max_len")):
        raw = env.get(env_name)
        if raw:
            try:
                out[kwarg] = int(raw)
            except ValueError:
                raise ValueError(
                    f"{env_name}={raw!r} must be an integer")
    return out


def compile_programs(model):
    """The jitted serving programs for ``model``, built ONCE and shared
    by every replica: jax.jit caches on the wrapper's identity, so an
    engine building its own wrappers would re-trace + recompile per
    replica — and the kill → grow restore path would pay a full XLA
    compile before serving its first request."""
    return (jax.jit(functools.partial(_prefill_fn, model)),
            jax.jit(functools.partial(_decode_fn, model)),
            jax.jit(kv_lib.write_slot),
            jax.jit(kv_lib.reset_slot))


def make_engine_factory(model, params, **kw) -> Callable[[str],
                                                         DecodeEngine]:
    """Factory the replica controller uses to start replicas (grow /
    restart after a kill): same model+params+geometry+compiled
    programs, fresh cache."""
    programs = compile_programs(model)

    def factory(name: str) -> DecodeEngine:
        return DecodeEngine(model, params, name=name,
                            programs=programs, **kw)
    return factory
