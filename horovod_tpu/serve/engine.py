"""One replica's decode plane: slots, jitted prefill/decode, retire
(docs/serve.md).

Exactly TWO compiled programs serve every request mix, because request
variety is data, not shape:

* ``prefill`` — (1, max_prompt_len) tokens + a length scalar: the
  prompt's KV lines land in a single-slot cache (pad lines
  invalidated), and the first output token is the argmax at position
  ``length - 1``. The cache may arrive WARM at a base position — the
  shared-prefix fork (``serve/prefix.py``) imports a stored prefix
  blob and prefills only the remainder; base 0 is the fresh-prompt
  case, same program. Admission scatters the slot into the batch cache
  (``kvcache.write_slot``) — dynamic slot index, no recompile.
* ``decode`` — one token per slot across ALL slots: (slots, 1) last
  tokens against the (slots, max_len, ...) ring cache. Finished/empty
  slots decode garbage that is never read — cheaper than a ragged
  program per occupancy pattern, and the reason sequences of any
  length mix share the step.

Two optional levers extend the plane without changing its shape
(docs/serve.md):

* **tp-sharded decode** — ``parallel=`` (a ParallelSpec with a tp
  axis) wraps both programs in ``jax.shard_map``: params replicate,
  the KV ring shards on the HEADS axis (the same Megatron head grid
  training uses, models/gpt.py), and the row-parallel output
  projection is the block's one allreduce. The per-head int8 block
  quantization operates head-vector-wise, so shards quantize
  bit-identically to the unsharded cache.
* **speculative decoding** — ``draft_model``/``spec_k`` add a draft
  propose (k tokens, one scanned program) + target verify (ONE
  batched (slots, k) incremental step) + cache rewind per round.
  Greedy acceptance emits exactly the tokens the non-speculative
  engine would (bit-identical by induction: a greedy token is only
  committed when its full context matched the true rollout).

Sampling is greedy argmax — deterministic, the repeat-identity
contract. The decode step is bracketed with flight-recorder events
(op ``serve``), so a hung replica's black box names the decode batch it
never completed, the same attribution the training collectives get
(docs/podmon.md).
"""

from __future__ import annotations

import functools
import os
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..common import flightrec as flightrec_lib
from ..common import metrics as metrics_lib
from . import kvcache as kv_lib
from . import tracing
from .queue import Request, record_completion

_M_TOKENS = metrics_lib.counter(
    "hvd_tpu_serve_tokens_total",
    "tokens processed by the serve engines, by kind "
    "(prompt = prefilled, generated = decoded)",
    labels=("kind",))
for _k in ("prompt", "generated"):
    _M_TOKENS.labels(kind=_k)
del _k
_M_ACTIVE = metrics_lib.gauge(
    "hvd_tpu_serve_active_requests",
    "requests currently holding a decode slot, summed over this "
    "process's replicas")
_M_CACHE_BYTES = metrics_lib.gauge(
    "hvd_tpu_serve_kv_cache_bytes",
    "allocated KV-cache bytes, by replica (int8 storage shows the "
    "~4x reduction over fp32 here)",
    labels=("replica",))
_M_SPEC = metrics_lib.counter(
    "hvd_tpu_serve_spec_tokens_total",
    "speculative-decode draft tokens by verification outcome "
    "(accepted / rejected) — accepted / (accepted + rejected) is the "
    "draft acceptance rate (docs/serve.md)",
    labels=("outcome",))
for _o in ("accepted", "rejected"):
    _M_SPEC.labels(outcome=_o)
del _o


class DecodeEngine:
    """Slots + cache + the two jitted programs for ONE replica.

    ``model`` is a GPT-family flax module whose ``apply`` supports the
    ``cache=`` incremental path (models/gpt.py); ``params`` its
    variables. Greedy decode; ``eos_id`` (optional) ends a sequence
    early, ``max_new_tokens`` always bounds it.

    ``parallel`` (a ParallelSpec with a tp axis) runs the two programs
    tp-sharded under ``jax.shard_map`` — the model must carry the same
    ``tp_axis`` and the params stay the dense-compatible replicated
    tree. ``prefix_cache`` (a shared :class:`serve.prefix.PrefixCache`)
    turns common prompt prefixes into slot forks instead of re-prefill.
    ``draft_model``/``draft_params``/``spec_k`` enable greedy
    speculative decoding (draft proposes k, target verifies in one
    batched step); the draft must share the target's vocab.
    """

    def __init__(self, model, params, slots: int = 4, max_len: int = 32,
                 max_prompt_len: int = 16, kv_kind: str = "fp32",
                 eos_id: Optional[int] = None, name: str = "r0",
                 programs=None, parallel=None, prefix_cache=None,
                 draft_model=None, draft_params=None, spec_k: int = 0,
                 spec_programs=None):
        if max_prompt_len > max_len:
            raise ValueError(
                f"max_prompt_len {max_prompt_len} exceeds the cache's "
                f"max_len {max_len}")
        self.model = model
        self.params = params
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.max_prompt_len = int(max_prompt_len)
        self.kv_kind = kv_kind
        self.eos_id = eos_id
        self.name = name
        self.parallel = parallel if (parallel is not None
                                     and parallel.tp_axis) else None
        self.prefix_cache = prefix_cache
        self.spec_k = int(spec_k) if draft_model is not None else 0
        # Runtime gate the brownout ladder's spec_off rung flips
        # (docs/serve.md "Overload & tenancy"): speculation pauses
        # without recompiling or discarding the draft state, and the
        # plain rounds keep the draft ring mirrored so re-enabling is
        # exact.
        self.spec_enabled = True
        self.draft_model = draft_model
        self.draft_params = draft_params
        if self.spec_k and self.parallel is not None:
            raise ValueError(
                "speculative decoding and tp-sharded decode are "
                "separate serve levers (docs/serve.md); enable one "
                "per engine")
        if self.parallel is not None \
                and getattr(model, "tp_axis", None) \
                != self.parallel.tp_axis:
            raise ValueError(
                f"parallel spec shards heads over "
                f"{self.parallel.tp_axis!r} but the model's tp_axis is "
                f"{getattr(model, 'tp_axis', None)!r} — construct the "
                "model with the matching axis (models/gpt.py)")
        from ..models.gpt import init_kv_cache

        self.cache = init_kv_cache(model, self.slots, self.max_len,
                                   kind=kv_kind)
        self._single = init_kv_cache(model, 1, self.max_len,
                                     kind=kv_kind)
        _M_CACHE_BYTES.labels(replica=name).set(
            kv_lib.cache_nbytes(self.cache))
        # Per-slot host state (the python side of the batcher loop).
        self.requests: List[Optional[Request]] = [None] * self.slots
        self.generated: List[List[int]] = [[] for _ in range(self.slots)]
        self.last_tokens = np.zeros((self.slots,), np.int32)
        self.decode_steps = 0
        # Prefill work actually computed (prefix reuse subtracts the
        # forked tokens) — the serve bench's prefill-reduction A/B.
        self.prefill_tokens = 0
        if programs is None:
            programs = compile_programs(model, parallel=self.parallel,
                                        cache_template=self._single)
        (self._prefill, self._decode, self._write_slot,
         self._reset_slot) = programs
        if self.spec_k:
            if getattr(draft_model, "vocab_size", None) \
                    != model.vocab_size:
                raise ValueError(
                    "draft and target must share a vocab: draft "
                    f"{getattr(draft_model, 'vocab_size', None)} vs "
                    f"target {model.vocab_size}")
            if spec_programs is None:
                spec_programs = compile_spec_programs(
                    model, draft_model, self.spec_k)
            self._spec = spec_programs
            # Draft cache: fp32 always — the draft is tiny, so the
            # int8 storage saving is noise and fp32 keeps its
            # proposals exactly reproducible across kv_kind arms.
            self.draft_cache = init_kv_cache(
                draft_model, self.slots, self.max_len, kind="fp32")
            self._draft_single = init_kv_cache(
                draft_model, 1, self.max_len, kind="fp32")
            self.spec_rounds = 0
            self.spec_fallback_rounds = 0
            self.spec_proposed = 0
            self.spec_accepted = 0

    # -- admission -----------------------------------------------------------

    def free_slots(self) -> List[int]:
        return [i for i, r in enumerate(self.requests) if r is None]

    def active_count(self) -> int:
        return self.slots - len(self.free_slots())

    def admit(self, req: Request, now: float = 0.0) -> int:
        """Prefill ``req`` into a free slot; returns the slot. The
        prompt is truncated to the engine's ``max_prompt_len`` window
        (documented serving contract, docs/serve.md). With a
        ``prefix_cache``, a stored common prefix forks via exact slot
        copy (import + rewind) and only the remainder prefills — the
        prompt-token accounting counts the remainder, which is how the
        prefix A/B shows prefill work strictly reduced."""
        free = self.free_slots()
        if not free:
            raise RuntimeError(f"replica {self.name}: no free slot")
        slot = free[0]
        prompt = list(req.prompt)[-self.max_prompt_len:]
        base = 0
        single_src = self._single
        if self.prefix_cache is not None:
            hit = self.prefix_cache.lookup(prompt)
            # The pad lines of the remainder prefill land at positions
            # base .. base + max_prompt_len - 1; refuse a fork that
            # would ring-wrap them over the reused prefix lines.
            if hit is not None \
                    and hit[0] + self.max_prompt_len <= self.max_len:
                base, blob = hit
                single_src = kv_lib.rewind_slots(
                    kv_lib.import_slot(self._single, 0, blob),
                    jnp.full((1,), base, jnp.int32))
                self.prefix_cache.note_hit(base)
        remainder = prompt[base:]
        padded = np.zeros((1, self.max_prompt_len), np.int32)
        padded[0, :len(remainder)] = remainder
        single, first = self._prefill(
            self.params, jnp.asarray(padded),
            jnp.asarray(len(remainder), jnp.int32), single_src,
            jnp.asarray(req.temperature, jnp.float32),
            jnp.asarray(req.sample_seed & 0x7FFFFFFF, jnp.int32),
            jnp.asarray(req.rid, jnp.int32))
        if self.prefix_cache is not None and base == 0 \
                and len(prompt) > 1:
            # Store fresh full prefills only: an exact (unquantized)
            # slot copy, so a future fork decodes bit-identically to a
            # fresh prefill. Re-inserting fork-extended caches would
            # compound nothing useful — the common prefix is already
            # stored.
            self.prefix_cache.insert(
                tuple(prompt), kv_lib.export_slot(single, 0,
                                                  exact=True))
        self.cache = self._write_slot(self.cache, slot, single)
        if self.spec_k:
            # Warm the draft's ring for this slot from the FULL prompt
            # (the draft is cheap; its cache must mirror the target's
            # positions for proposals to line up).
            dpad = np.zeros((1, self.max_prompt_len), np.int32)
            dpad[0, :len(prompt)] = prompt
            zero = jnp.zeros((), jnp.int32)
            dsingle, _ = self._spec["draft_prefill"](
                self.draft_params, jnp.asarray(dpad),
                jnp.asarray(len(prompt), jnp.int32),
                self._draft_single, jnp.zeros((), jnp.float32), zero,
                zero)
            self.draft_cache = self._write_slot(self.draft_cache, slot,
                                                dsingle)
        self.requests[slot] = req
        req.replica = self.name
        req.first_token_t = now
        tok = int(first)
        self.generated[slot] = [tok]
        self.last_tokens[slot] = tok
        self.prefill_tokens += len(remainder)
        _M_TOKENS.labels(kind="prompt").inc(len(remainder))
        _M_TOKENS.labels(kind="generated").inc()
        _M_ACTIVE.inc()
        tr = tracing.tracer()
        if tr.enabled:
            if base:
                tr.prefix_fork(req.rid, self.name, now, base)
            tr.prefill(req, self.name, now, len(remainder))
        return slot

    # -- the decode step -----------------------------------------------------

    def step(self, now: float = 0.0) -> List[Request]:
        """One decode round across every slot; retires and returns the
        requests that finished this step (their ``tokens``/``finish_t``
        filled). With speculative decoding enabled the round emits up
        to ``spec_k`` tokens per slot (bit-identical to the 1-token
        rounds); rounds that cannot speculate safely fall back to the
        plain step."""
        if self.active_count() == 0:
            return []
        if self.spec_k:
            if self.spec_enabled and self._spec_ready():
                return self._spec_step(now)
            self.spec_fallback_rounds += 1
            # Keep the draft's ring mirrored through plain rounds so
            # later speculative rounds see the true context.
            zeros_f = jnp.zeros((self.slots,), jnp.float32)
            zeros_i = jnp.zeros((self.slots,), jnp.int32)
            _, self.draft_cache, _ = self._spec["draft_decode"](
                self.draft_params, self.draft_cache,
                jnp.asarray(self.last_tokens), zeros_f, zeros_i,
                zeros_i, zeros_i)
        rec = flightrec_lib.recorder()
        step_name = f"serve.decode.{self.name}"
        rec.record_submit(step_name, "serve")
        temps = np.zeros((self.slots,), np.float32)
        seeds = np.zeros((self.slots,), np.int32)
        rids = np.zeros((self.slots,), np.int32)
        poss = np.zeros((self.slots,), np.int32)
        for slot, req in enumerate(self.requests):
            if req is None:
                continue
            temps[slot] = req.temperature
            seeds[slot] = req.sample_seed & 0x7FFFFFFF
            rids[slot] = req.rid
            poss[slot] = len(self.generated[slot])
        try:
            logits, self.cache, next_tokens = self._decode(
                self.params, self.cache,
                jnp.asarray(self.last_tokens), jnp.asarray(temps),
                jnp.asarray(seeds), jnp.asarray(rids),
                jnp.asarray(poss))
            next_np = np.asarray(next_tokens)
        except BaseException:
            rec.record_complete(step_name, outcome="error")
            raise
        rec.annotate(step_name,
                     nbytes=kv_lib.cache_nbytes(self.cache),
                     wire=self.kv_kind,
                     trace=self._trace_csv() if rec.enabled else None)
        rec.record_complete(step_name)
        self.decode_steps += 1
        finished: List[Request] = []
        for slot, req in enumerate(self.requests):
            if req is None:
                continue
            done = False
            if len(self.generated[slot]) >= req.max_new_tokens:
                # The finishing token was produced by the PREVIOUS
                # round (or prefill); this round's output for the slot
                # is discarded.
                done = True
            else:
                tok = int(next_np[slot])
                self.generated[slot].append(tok)
                self.last_tokens[slot] = tok
                _M_TOKENS.labels(kind="generated").inc()
                done = (len(self.generated[slot]) >= req.max_new_tokens
                        or (self.eos_id is not None
                            and tok == self.eos_id))
            if done:
                finished.append(self.retire(slot, now))
        return finished

    # -- speculative decoding (docs/serve.md) --------------------------------

    def _spec_ready(self) -> bool:
        """A round may speculate iff every active slot is greedy
        (temperature 0 — the acceptance rule is exact only for argmax)
        and no slot's k-token burst would ring-wrap: a wrapped write
        overwrites the oldest line, and the post-verify rewind cannot
        restore what was overwritten."""
        pos = np.asarray(self.cache["pos"])
        for slot, req in enumerate(self.requests):
            if req is None:
                continue
            if req.temperature > 0.0:
                return False
            if int(pos[slot]) + self.spec_k > self.max_len:
                return False
        return True

    def _spec_step(self, now: float) -> List[Request]:
        """One speculative round: draft proposes ``spec_k`` tokens per
        slot (a scanned program over its own ring), the target verifies
        them in ONE batched (slots, k) incremental step, and the
        longest greedily-matching prefix commits — plus the target's
        own correction token, so every round emits at least one token
        and at most k. Both rings then rewind to the committed
        position (data ops only). Greedy output is bit-identical to
        the plain step by induction: a token is committed only when
        its entire context matched the true rollout."""
        k = self.spec_k
        rec = flightrec_lib.recorder()
        step_name = f"serve.decode.{self.name}"
        rec.record_submit(step_name, "serve")
        pos_before = np.asarray(self.cache["pos"]).copy()
        try:
            last = jnp.asarray(self.last_tokens)
            self.draft_cache, drafts = self._spec["propose"](
                self.draft_params, self.draft_cache, last)
            # Verify feeds [t_n, d_1 .. d_{k-1}]: position i's logits
            # see the context up to draft i, so greedy[i] is the true
            # next token GIVEN that context.
            verify_in = jnp.concatenate([last[:, None],
                                         drafts[:, :k - 1]], axis=1)
            greedy, self.cache = self._spec["verify"](
                self.params, self.cache, verify_in)
            g = np.asarray(greedy)
            d = np.asarray(drafts)
        except BaseException:
            rec.record_complete(step_name, outcome="error")
            raise
        new_pos = np.zeros((self.slots,), np.int32)
        finished: List[Request] = []
        done_slots: List[int] = []
        tr = tracing.tracer()
        for slot, req in enumerate(self.requests):
            if req is None:
                continue
            new_pos[slot] = pos_before[slot]
            if len(self.generated[slot]) >= req.max_new_tokens:
                # Finishing token produced by a previous round; this
                # round's output for the slot is discarded (same rule
                # as the plain step).
                done_slots.append(slot)
                continue
            m = 0
            while m < k - 1 and d[slot, m] == g[slot, m]:
                m += 1
            self.spec_proposed += k
            self.spec_accepted += m
            _M_SPEC.labels(outcome="accepted").inc(m)
            _M_SPEC.labels(outcome="rejected").inc(k - m)
            if tr.enabled:
                tr.spec_round(req.rid, self.name, now, m, k)
            committed = 0
            done = False
            for i in range(m + 1):
                tok = int(g[slot, i])
                self.generated[slot].append(tok)
                self.last_tokens[slot] = tok
                committed += 1
                _M_TOKENS.labels(kind="generated").inc()
                done = (len(self.generated[slot]) >= req.max_new_tokens
                        or (self.eos_id is not None
                            and tok == self.eos_id))
                if done:
                    break
            new_pos[slot] = pos_before[slot] + committed
            if done:
                done_slots.append(slot)
        npj = jnp.asarray(new_pos)
        self.cache = self._spec["rewind"](self.cache, npj)
        self.draft_cache = self._spec["rewind"](self.draft_cache, npj)
        for slot in done_slots:
            finished.append(self.retire(slot, now))
        rec.annotate(step_name,
                     nbytes=kv_lib.cache_nbytes(self.cache),
                     wire=self.kv_kind,
                     trace=self._trace_csv() if rec.enabled else None)
        rec.record_complete(step_name)
        self.decode_steps += 1
        self.spec_rounds += 1
        return finished

    def spec_acceptance_rate(self) -> float:
        """Accepted draft tokens / proposed draft tokens over this
        engine's speculative rounds (0 when none ran)."""
        return (self.spec_accepted / self.spec_proposed
                if self.spec_k and self.spec_proposed else 0.0)

    def _trace_csv(self) -> str:
        """Active request ids as a CSV — the trace-correlation stamp the
        flight recorder carries per decode event (``analyze_serve.py
        --flight`` joins on it)."""
        return ",".join(str(r.rid) for r in self.requests
                        if r is not None)

    def request_done(self, slot: int) -> bool:
        """True when the slot's sequence already hit its stop condition
        (a 1-token request finishes at prefill; the batcher retires it
        without waiting for a decode round)."""
        req = self.requests[slot]
        if req is None:
            return False
        toks = self.generated[slot]
        return bool(len(toks) >= req.max_new_tokens
                    or (self.eos_id is not None and toks
                        and toks[-1] == self.eos_id))

    def retire(self, slot: int, now: float) -> Request:
        req = self.requests[slot]
        req.tokens = tuple(self.generated[slot])
        req.finish_t = now
        record_completion(req)
        tracing.tracer().retire(req, self.name, now)
        self.requests[slot] = None
        self.generated[slot] = []
        self.cache = self._reset_slot(self.cache, slot)
        if self.spec_k:
            self.draft_cache = self._reset_slot(self.draft_cache, slot)
        _M_ACTIVE.dec()
        return req

    # -- drain / teardown ----------------------------------------------------

    def abort_all(self, now: Optional[float] = None) -> List[Request]:
        """Hard abort (replica kill): every in-flight request comes
        back UNFINISHED for re-routing — generated tokens are dropped
        and the peer re-prefills from the prompt (no dropped
        requests, docs/serve.md drain runbook)."""
        out = []
        tr = tracing.tracer()
        for slot, req in enumerate(self.requests):
            if req is None:
                continue
            if tr.enabled:
                tr.abort(req, self.name, now)
            req.reroutes += 1
            req.replica = None
            out.append(req)
            self.requests[slot] = None
            self.generated[slot] = []
            self.cache = self._reset_slot(self.cache, slot)
            if self.spec_k:
                self.draft_cache = self._reset_slot(self.draft_cache,
                                                    slot)
            _M_ACTIVE.dec()
        return out

    def export_slot(self, slot: int) -> Dict[str, Any]:
        """A slot's warm cache as the int8 block-scaled wire blob
        (``kvcache.export_slot`` — the Pallas quantization path), for
        peers that accept mid-sequence migration instead of a
        re-prefill."""
        return kv_lib.export_slot(self.cache, slot)

    def migrate_out(self, slot: int, now: Optional[float] = None,
                    kind: str = "migrate"):
        """Evict one in-flight sequence WITH its warm state: returns
        ``(request, wire_blob, generated_tokens)`` — the int8
        block-scaled cache export plus the host-side decode state a
        peer needs to continue mid-sequence (the graceful-drain default,
        docs/serve.md). The slot frees immediately; nothing completes.
        When tracing is on the trace stamp rides the blob (top-level
        ``"trace"`` key — ``kvcache.import_slot`` only reads ``layers``
        / ``pos`` / ``slot_pos``, so the transport is unchanged) and
        ``admit_migrated`` on the destination closes the wire span."""
        req = self.requests[slot]
        if req is None:
            raise RuntimeError(f"replica {self.name}: slot {slot} empty")
        blob = kv_lib.export_slot(self.cache, slot)
        tr = tracing.tracer()
        if tr.enabled:
            stamp = tr.export(req, self.name, now, kind)
            if stamp is not None:
                blob["trace"] = stamp
        generated = list(self.generated[slot])
        self.requests[slot] = None
        self.generated[slot] = []
        self.cache = self._reset_slot(self.cache, slot)
        if self.spec_k:
            self.draft_cache = self._reset_slot(self.draft_cache, slot)
        _M_ACTIVE.dec()
        return req, blob, generated

    def admit_migrated(self, req: Request, blob: Dict[str, Any],
                       generated, now: float = 0.0) -> int:
        """Land a migrated sequence in a free slot: the wire blob
        imports into the cache (``kvcache.import_slot`` — dequantized
        through the same Pallas path) and decode continues from the
        last generated token — no re-prefill. Same-geometry engines
        only (the cluster's factory guarantees it). With speculative
        decoding the draft ring gets no warm state (the wire carries
        the target cache only) — proposals for the slot degrade until
        it retires, but the verify step keeps the output exact."""
        free = self.free_slots()
        if not free:
            raise RuntimeError(f"replica {self.name}: no free slot")
        slot = free[0]
        stamp = blob.pop("trace", None) if isinstance(blob, dict) else None
        self.cache = kv_lib.import_slot(self.cache, slot, blob)
        tr = tracing.tracer()
        if tr.enabled:
            tr.import_blob(req, self.name, now, stamp)
        self.requests[slot] = req
        req.replica = self.name
        req.migrations += 1
        self.generated[slot] = list(generated)
        self.last_tokens[slot] = generated[-1] if generated else 0
        _M_ACTIVE.inc()
        return slot

    def close(self) -> None:
        """Zero this replica's labeled gauges when it leaves the
        cluster — a departed replica's cache is freed, so a stale
        ``kv_cache_bytes`` series would overstate live HBM on every
        pod scrape."""
        _M_CACHE_BYTES.labels(replica=self.name).set(0)


def _sample_token(row, temp, seed, rid, pos):
    """One slot's next token: greedy argmax at ``temp == 0`` (the
    historical deterministic default — bit-identical to the
    pre-sampling engine), else a categorical draw from
    ``softmax(logits / temp)`` under the per-request PRNG lane
    ``fold_in(fold_in(PRNGKey(seed), rid), pos)``. The KEY is
    deterministic in (seed, rid, position) alone — never the slot or
    replica — so re-batching, slot reassignment and migration cannot
    perturb the randomness (the event-digest repeat contract,
    docs/serve.md). The LOGITS are the cache's: a warm migration over
    the int8 wire carries the kvcache round-trip's bounded rounding
    (docs/serve.md parity table), which can shift a near-tie token."""
    row = row.astype(jnp.float32)
    greedy = jnp.argmax(row, axis=-1).astype(jnp.int32)
    key = jax.random.fold_in(
        jax.random.fold_in(jax.random.PRNGKey(seed), rid), pos)
    sampled = jax.random.categorical(
        key, row / jnp.maximum(temp, 1e-6)).astype(jnp.int32)
    return jnp.where(temp > 0.0, sampled, greedy)


def _prefill_fn(model, params, tokens, length, single_cache, temp,
                seed, rid):
    """(1, P) prompt -> (single-slot cache, first output token). The
    incoming cache's write head is the BASE: 0 for a fresh prompt, the
    stored prefix length for a prefix fork (serve/prefix.py) — the new
    tokens land at base..base+length-1 and the rewind math is
    base-relative, so both cases share this one compiled program."""
    base = single_cache["pos"]                       # (1,) int32
    logits, cache = model.apply(params, tokens, cache=single_cache)
    # Pad lines (written at positions >= base + length) must never be
    # attendable; the write head rewinds to the true prompt end.
    end = base + length
    sp = cache["slot_pos"]
    cache = {
        "layers": cache["layers"],
        "pos": jnp.broadcast_to(end, cache["pos"].shape),
        "slot_pos": jnp.where(sp >= end[:, None], -1, sp),
    }
    first = _sample_token(logits[0, length - 1], temp, seed, rid,
                          jnp.zeros((), jnp.int32))
    return cache, first


def _decode_fn(model, params, cache, last_tokens, temps, seeds, rids,
               poss):
    """(slots,) last tokens -> (logits, cache, next tokens). Per-slot
    sampling state (temperature / seed / rid / position) rides data
    arrays, so every request mix shares the ONE compiled program."""
    logits, cache = model.apply(params, last_tokens[:, None],
                                cache=cache)
    nxt = jax.vmap(_sample_token)(logits[:, 0], temps, seeds, rids,
                                  poss)
    return logits, cache, nxt


def _spec_propose_fn(model, params, cache, last_tokens, k: int):
    """Draft proposal: scan ``k`` greedy decode steps over the draft's
    own ring — (slots,) last tokens -> (cache, (slots, k) drafts
    d_1..d_k). One compiled program per engine (k is static)."""
    def body(carry, _):
        cache, toks = carry
        logits, cache = model.apply(params, toks[:, None], cache=cache)
        nxt = jnp.argmax(logits[:, 0].astype(jnp.float32),
                         axis=-1).astype(jnp.int32)
        return (cache, nxt), nxt

    (cache, _), drafts = jax.lax.scan(body, (cache, last_tokens), None,
                                      length=k)
    return cache, jnp.moveaxis(drafts, 0, 1)


def _spec_verify_fn(model, params, cache, tokens):
    """Target verification: the (slots, k) proposed burst through the
    SAME incremental program shape decode uses — logits at position i
    see the context up to draft i, so ``argmax`` per position is the
    true greedy continuation given that context. Returns
    ((slots, k) greedy tokens, advanced cache — the caller rewinds to
    the committed positions)."""
    logits, cache = model.apply(params, tokens, cache=cache)
    return (jnp.argmax(logits.astype(jnp.float32),
                       axis=-1).astype(jnp.int32), cache)


ENV_KV_DTYPE = "HVD_TPU_SERVE_KV_DTYPE"   # fp32 | int8 cache storage
ENV_SLOTS = "HVD_TPU_SERVE_SLOTS"         # decode slots per replica
ENV_MAX_LEN = "HVD_TPU_SERVE_MAX_LEN"     # ring-buffer cache lines


def engine_defaults_from_env(env=None) -> Dict[str, Any]:
    """The env-tunable engine geometry (docs/serve.md knob table):
    ``HVD_TPU_SERVE_KV_DTYPE`` / ``HVD_TPU_SERVE_SLOTS`` /
    ``HVD_TPU_SERVE_MAX_LEN``, as DecodeEngine kwargs."""
    env = env if env is not None else os.environ
    out: Dict[str, Any] = {}
    kind = env.get(ENV_KV_DTYPE)
    if kind:
        if kind not in kv_lib.KINDS:
            raise ValueError(
                f"{ENV_KV_DTYPE}={kind!r}: known kinds {kv_lib.KINDS}")
        out["kv_kind"] = kind
    for env_name, kwarg in ((ENV_SLOTS, "slots"),
                            (ENV_MAX_LEN, "max_len")):
        raw = env.get(env_name)
        if raw:
            try:
                out[kwarg] = int(raw)
            except ValueError:
                raise ValueError(
                    f"{env_name}={raw!r} must be an integer")
    return out


def compile_programs(model, parallel=None, cache_template=None):
    """The jitted serving programs for ``model``, built ONCE and shared
    by every replica: jax.jit caches on the wrapper's identity, so an
    engine building its own wrappers would re-trace + recompile per
    replica — and the kill → grow restore path would pay a full XLA
    compile before serving its first request.

    ``parallel`` (a ParallelSpec with a tp axis) wraps prefill/decode
    in ``jax.shard_map`` over ``parallel.mesh``: params and tokens
    replicate; the cache's K/V and scale leaves shard on their HEADS
    axis (rank >= 3 — k/v are (slots, lines, heads, head_dim), scales
    (slots, lines, heads)); the bookkeeping vectors replicate. The
    logits/next-token outputs are replicated — valid because the
    row-parallel output projection already allreduced inside the model
    (models/gpt.py). ``cache_template`` supplies the cache treedef the
    specs mirror (any slot count — specs do not depend on it)."""
    if parallel is not None and parallel.tp_axis:
        if cache_template is None:
            raise ValueError(
                "tp-sharded serve programs need a cache_template to "
                "derive the per-leaf shard specs")
        from jax.sharding import PartitionSpec as P

        tp = parallel.tp_axis
        mesh = parallel.mesh(jax.devices()[:parallel.total])
        cspec = jax.tree.map(
            lambda leaf: P(None, None, tp) if leaf.ndim >= 3 else P(),
            cache_template)
        rep = P()
        prefill = jax.jit(jax.shard_map(
            functools.partial(_prefill_fn, model), mesh=mesh,
            in_specs=(rep, rep, rep, cspec, rep, rep, rep),
            out_specs=(cspec, rep), check_vma=False))
        decode = jax.jit(jax.shard_map(
            functools.partial(_decode_fn, model), mesh=mesh,
            in_specs=(rep, cspec, rep, rep, rep, rep, rep),
            out_specs=(rep, cspec, rep), check_vma=False))
        # Slot scatter/reset are elementwise over the cache pytree —
        # plain jit partitions them under the arrays' shardings.
        return (prefill, decode, jax.jit(kv_lib.write_slot),
                jax.jit(kv_lib.reset_slot))
    return (jax.jit(functools.partial(_prefill_fn, model)),
            jax.jit(functools.partial(_decode_fn, model)),
            jax.jit(kv_lib.write_slot),
            jax.jit(kv_lib.reset_slot))


def compile_spec_programs(model, draft_model, spec_k: int):
    """The speculative-decoding program set, built once and shared by
    every replica (same retrace economics as ``compile_programs``):
    the draft's own prefill/decode pair, the k-step scanned propose,
    the batched target verify, and the ring rewind."""
    draft_prefill, draft_decode, _, _ = compile_programs(draft_model)
    return {
        "draft_prefill": draft_prefill,
        "draft_decode": draft_decode,
        "propose": jax.jit(functools.partial(
            _spec_propose_fn, draft_model, k=int(spec_k))),
        "verify": jax.jit(functools.partial(_spec_verify_fn, model)),
        "rewind": jax.jit(kv_lib.rewind_slots),
    }


def make_engine_factory(model, params, parallel=None, draft_model=None,
                        draft_params=None, spec_k: int = 0,
                        prefix_cache=None,
                        **kw) -> Callable[[str], DecodeEngine]:
    """Factory the replica controller uses to start replicas (grow /
    restart after a kill): same model+params+geometry+compiled
    programs, fresh cache. The serve levers thread through: every
    replica shares one ``parallel`` spec, one ``prefix_cache``, and one
    compiled draft/verify program set."""
    if parallel is not None and parallel.tp_axis:
        from ..models.gpt import init_kv_cache

        template = init_kv_cache(model, 1, kw.get("max_len", 32),
                                 kind=kw.get("kv_kind", "fp32"))
        programs = compile_programs(model, parallel=parallel,
                                    cache_template=template)
    else:
        programs = compile_programs(model)
    spec_programs = None
    if spec_k and draft_model is not None:
        spec_programs = compile_spec_programs(model, draft_model,
                                              spec_k)

    def factory(name: str) -> DecodeEngine:
        return DecodeEngine(model, params, name=name,
                            programs=programs, parallel=parallel,
                            prefix_cache=prefix_cache,
                            draft_model=draft_model,
                            draft_params=draft_params, spec_k=spec_k,
                            spec_programs=spec_programs, **kw)
    return factory
