"""Cross-request shared-prefix KV reuse (docs/serve.md).

Production traffic repeats itself: the same system prompt heads most
requests, and a naive engine re-prefills it every admission. This
cache stores each fresh full prefill's single-slot cache as an EXACT
slot copy (``kvcache.export_slot(exact=True)`` — no wire, so no
rounding) keyed by a content hash of the prompt tokens. On the next
admission the engine looks up the stored prompt sharing the LONGEST
common prefix, forks it (import + ``rewind_slots`` to the common
length — causal attention means a token's KV depends only on the
tokens before it, so the truncated lines are bit-identical to a fresh
prefill of the prefix), and prefills only the remainder.

Deterministic by construction: insertion order is the request order,
lookup ties break toward the earliest-inserted entry, and eviction is
FIFO under the ``HVD_TPU_SERVE_PREFIX_CAP`` entry bound — a seeded
replay hits and evicts identically, keeping the serve event-digest
contract.

The cache is SHARED cluster-wide (``make_engine_factory`` threads one
instance into every replica), which is what makes "common system
prompts prefill once" true across the pool, not per replica.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from ..common import metrics as metrics_lib
from ..common.config import runtime_env

_M_HITS = metrics_lib.counter(
    "hvd_tpu_serve_prefix_hits_total",
    "admissions that forked a stored shared prefix instead of "
    "prefilling it (docs/serve.md)")
_M_SAVED = metrics_lib.counter(
    "hvd_tpu_serve_prefix_tokens_saved_total",
    "prompt tokens NOT prefilled thanks to shared-prefix forks — the "
    "prefix-reuse A/B's strictly-reduced prefill work")

DEFAULT_CAP = 8


def _content_hash(prompt: Sequence[int]) -> str:
    """Content hash of a token sequence — the cache key (dtype-pinned
    so the same tokens hash identically on every host)."""
    return hashlib.sha256(
        np.asarray(prompt, np.int32).tobytes()).hexdigest()


def prefix_cap_from_env() -> int:
    """``HVD_TPU_SERVE_PREFIX_CAP`` (registry-routed): max stored
    entries, 0 disables the cache entirely."""
    raw = runtime_env("SERVE_PREFIX_CAP")
    if not raw:
        return DEFAULT_CAP
    try:
        cap = int(raw)
    except ValueError:
        raise ValueError(
            f"HVD_TPU_SERVE_PREFIX_CAP={raw!r} must be an integer")
    if cap < 0:
        raise ValueError(
            f"HVD_TPU_SERVE_PREFIX_CAP must be >= 0, got {cap}")
    return cap


class PrefixCache:
    """Bounded, content-hashed store of prefilled prompt caches.

    ``insert(prompt, blob)`` stores a fresh full prefill (exact slot
    export); ``lookup(prompt)`` returns ``(common_len, blob)`` for the
    stored prompt with the longest common prefix — clamped to
    ``len(prompt) - 1`` so at least one remainder token prefills (the
    first output token's logits must be computed fresh)."""

    def __init__(self, cap: int = DEFAULT_CAP):
        self.cap = int(cap)
        # key -> (prompt tuple, blob); OrderedDict = FIFO eviction.
        self._entries: "OrderedDict[str, Tuple[Tuple[int, ...], Any]]" \
            = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.tokens_saved = 0

    def __len__(self) -> int:
        return len(self._entries)

    def insert(self, prompt: Tuple[int, ...], blob: Dict) -> bool:
        """Store one fresh prefill; False when disabled, duplicate, or
        too short to ever fork (a 1-token prompt has no usable
        prefix)."""
        if self.cap <= 0 or len(prompt) < 2:
            return False
        key = _content_hash(prompt)
        if key in self._entries:
            return False
        while len(self._entries) >= self.cap:
            self._entries.popitem(last=False)
        self._entries[key] = (tuple(prompt), blob)
        return True

    def lookup(self, prompt: Sequence[int]
               ) -> Optional[Tuple[int, Any]]:
        """Longest-common-prefix match over the stored prompts
        (earliest-inserted entry wins a length tie — deterministic).
        Returns ``(common_len, blob)`` with ``1 <= common_len <
        len(prompt)``, or None."""
        prompt = list(prompt)
        best_len = 0
        best_blob = None
        limit = len(prompt) - 1
        for stored, blob in self._entries.values():
            n = 0
            for a, b in zip(stored, prompt):
                if a != b:
                    break
                n += 1
            n = min(n, limit)
            if n > best_len:
                best_len, best_blob = n, blob
        if best_len < 1:
            self.misses += 1
            return None
        return best_len, best_blob

    def note_hit(self, saved_tokens: int) -> None:
        """Called by the engine when a fork actually happened (the
        engine may still refuse a lookup result on a ring-wrap
        guard)."""
        self.hits += 1
        self.tokens_saved += int(saved_tokens)
        _M_HITS.inc()
        _M_SAVED.inc(saved_tokens)

    def stats(self) -> Dict[str, int]:
        return {"entries": len(self._entries), "hits": self.hits,
                "misses": self.misses,
                "tokens_saved": self.tokens_saved}
