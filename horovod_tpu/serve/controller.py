"""SLO-driven replica control: the autoscale decision machinery
repurposed for serving (docs/serve.md).

Training autoscaling (common/autoscale.py) turns per-rank step-time
telemetry into ``keep | grow | shrink | evict`` through policies-as-
data and a deterministic decision log. Serving needs the same control
plane with different signals — request-latency SLOs (p99 over a
completion window) and queue depth instead of step-time skew — and one
different mechanism: replicas leave by GRACEFUL DRAIN (stop admitting,
finish in-flight, re-route the queue) rather than eviction, because a
replica holds irreplaceable in-flight state the way a training rank
does not.

Same contracts as the training plane, deliberately:

* :class:`SLOPolicy` — every threshold is data
  (``HVD_TPU_SERVE_POLICY`` file/inline JSON +
  ``HVD_TPU_SERVE_<FIELD>`` env overrides), validation names the bad
  field.
* Decisions reuse ``common/autoscale.Decision`` — the same
  ``{"seq", "action", "target", "reason"}`` JSON-lines log
  (``HVD_TPU_SERVE_LOG``), deterministic fields only, so a seeded
  chaos run replays byte-identically
  (tools/chaos_soak.py --family serve).
* The elastic ``HostManager`` plugs in unchanged: a killed replica's
  host is blacklisted with the same TTL/strike machinery, and grow
  consults the usable-host set before starting a replica.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..common.autoscale import Decision
from ..common import metrics as metrics_lib
from . import overload as overload_lib
from . import tracing
from .batcher import ContinuousBatcher
from .engine import DecodeEngine
from . import queue as queue_lib
from .queue import Request
from .traffic import TrafficTrace
from ..common.config import runtime_env

logger = logging.getLogger("horovod_tpu")

_M_HANDOFFS = metrics_lib.counter(
    "hvd_tpu_serve_handoffs_total",
    "prefilled sequences handed from the prefill pool to the decode "
    "pool over the warm-KV int8 wire (disaggregated serving, "
    "docs/serve.md)")

ENV_POLICY = "HVD_TPU_SERVE_POLICY"   # policy file path or inline JSON
ENV_LOG = "HVD_TPU_SERVE_LOG"         # decision log (JSONL)


def _truthy(raw: Optional[str]) -> bool:
    return (raw or "").strip().lower() in ("1", "true", "yes", "on")


def _count_by(items) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for it in items:
        out[str(it)] = out.get(str(it), 0) + 1
    return out


@dataclasses.dataclass
class SLOPolicy:
    """Every serving-SLO threshold — data, not code (docs/serve.md has
    the schema table and recipes)."""

    enabled: bool = True
    # Controller cadence (virtual seconds in simulation).
    tick_interval_s: float = 0.25
    # Completion window the latency percentiles cover.
    window: int = 16
    # Grow when the windowed p99 exceeds this (0 = off).
    target_p99_s: float = 0.0
    # Per-phase SLOs over the same completion window (0 = off), fed by
    # the request timeline the tracer stamps (docs/serve.md "Tracing &
    # goodput"). TTFT pressure is admission/prefill pressure — grow the
    # PREFILL pool; TPOT pressure is decode cadence pressure — grow the
    # DECODE pool. Classic (non-disagg) clusters grow an undifferentiated
    # replica either way.
    ttft_target_s: float = 0.0
    tpot_target_s: float = 0.0
    # Grow when total queued requests exceed this (0 = off).
    max_queue_depth: int = 0
    # Drain one replica when instantaneous slot occupancy falls below
    # this AND every queue is empty (0 = never shrink on load).
    low_occupancy: float = 0.0
    # Replica-count floor/ceiling. A kill that drops the cluster below
    # min_replicas restores capacity immediately (no cooldown).
    min_replicas: int = 1
    max_replicas: int = 4
    grow_cooldown_s: float = 1.0
    shrink_cooldown_s: float = 2.0
    # How a graceful drain relocates IN-FLIGHT sequences
    # (docs/serve.md): "migrate" (the DEFAULT) hands each one to a peer
    # WITH its warm KV cache over the int8 wire
    # (kvcache.export_slot/import_slot) — decode continues
    # mid-sequence, no re-prefill, and the drained replica leaves on
    # the next tick instead of lingering until its longest sequence
    # finishes; "local" keeps the historical behavior (in-flight
    # sequences finish on the draining replica). Sequences that find
    # no free peer slot fall back to a re-prefill re-route — never
    # dropped.
    drain_mode: str = "migrate"
    # Disaggregated pools only (docs/serve.md): grow the DECODE pool
    # when prefilled sequences waiting for a decode slot exceed this
    # (0 = off). Queue-depth pressure grows the PREFILL pool; this is
    # the matching back-pressure signal for the other pool.
    max_handoff_depth: int = 0
    # --- Overload control & multi-tenancy (docs/serve.md "Overload &
    # tenancy"; horovod_tpu/serve/overload.py). ``overload`` is the
    # master switch: off (the default) keeps every pre-existing
    # cluster byte-identical. Each SLO class is three scalars —
    # deadline default (0 = none), strict cross-class priority (lower
    # = served first), and retry budget (re-route attempts allowed
    # before the request is shed; self-limiting retries).
    overload: bool = False
    latency_deadline_s: float = 0.0
    latency_priority: int = 0
    latency_retry_budget: int = 4
    throughput_deadline_s: float = 0.0
    throughput_priority: int = 1
    throughput_retry_budget: int = 2
    batch_deadline_s: float = 0.0
    batch_priority: int = 2
    batch_retry_budget: int = 1
    # Deadline-aware admission: shed when safety x estimated latency
    # (queue-wait + TTFT residual + max_new_tokens x TPOT, windowed
    # p99s) exceeds the request's remaining deadline budget.
    admission_safety: float = 1.0
    # Brownout ladder (overload.BROWNOUT_RUNGS): queue depth >=
    # enter_depth for enter_ticks consecutive ticks climbs one rung;
    # depth <= exit_depth for exit_ticks descends one. enter_depth 0
    # disables the ladder; the band between the thresholds is the
    # hysteresis dead zone.
    brownout_enter_depth: int = 0
    brownout_exit_depth: int = 0
    brownout_enter_ticks: int = 2
    brownout_exit_ticks: int = 2
    # The clamp_tokens rung caps throughput-tier max_new_tokens at
    # this while active (brownout partial answers over timeouts).
    brownout_clamp_tokens: int = 4

    @classmethod
    def field_names(cls) -> Tuple[str, ...]:
        return tuple(f.name for f in dataclasses.fields(cls))

    @classmethod
    def from_dict(cls, data: Dict) -> "SLOPolicy":
        if not isinstance(data, dict):
            raise ValueError(
                f"serve policy must be a JSON object, got "
                f"{type(data).__name__}")
        known = cls.field_names()
        unknown = sorted(set(data) - set(known))
        if unknown:
            raise ValueError(
                f"serve policy: unknown field(s) {unknown}; known "
                f"fields: {sorted(known)}")
        policy = cls()
        for name, value in data.items():
            default = getattr(policy, name)
            try:
                if isinstance(default, bool):
                    if isinstance(value, str):
                        value = _truthy(value)
                    value = bool(value)
                elif isinstance(default, int):
                    value = int(value)
                elif isinstance(default, float):
                    value = float(value)
            except (TypeError, ValueError):
                raise ValueError(
                    f"serve policy: field {name!r} must be a "
                    f"{type(default).__name__}, got {value!r}")
            setattr(policy, name, value)
        policy.validate()
        return policy

    def validate(self) -> "SLOPolicy":
        for name in ("tick_interval_s", "target_p99_s", "ttft_target_s",
                     "tpot_target_s", "low_occupancy",
                     "grow_cooldown_s", "shrink_cooldown_s",
                     "latency_deadline_s", "throughput_deadline_s",
                     "batch_deadline_s", "latency_retry_budget",
                     "throughput_retry_budget", "batch_retry_budget",
                     "brownout_enter_depth", "brownout_exit_depth",
                     "brownout_clamp_tokens"):
            if getattr(self, name) < 0:
                raise ValueError(
                    f"serve policy: field {name!r} must be >= 0, got "
                    f"{getattr(self, name)}")
        for name in ("window", "min_replicas", "max_replicas"):
            if getattr(self, name) < 1:
                raise ValueError(
                    f"serve policy: field {name!r} must be >= 1, got "
                    f"{getattr(self, name)}")
        if self.max_queue_depth < 0:
            raise ValueError(
                "serve policy: field 'max_queue_depth' must be >= 0 "
                f"(0 disables), got {self.max_queue_depth}")
        if self.max_handoff_depth < 0:
            raise ValueError(
                "serve policy: field 'max_handoff_depth' must be >= 0 "
                f"(0 disables), got {self.max_handoff_depth}")
        for name in ("brownout_enter_ticks", "brownout_exit_ticks"):
            if getattr(self, name) < 1:
                raise ValueError(
                    f"serve policy: field {name!r} must be >= 1 "
                    f"(hysteresis streak length), got "
                    f"{getattr(self, name)}")
        if self.admission_safety <= 0:
            raise ValueError(
                "serve policy: field 'admission_safety' must be > 0 "
                f"(a latency-estimate multiplier), got "
                f"{self.admission_safety}")
        if 0 < self.brownout_enter_depth <= self.brownout_exit_depth:
            raise ValueError(
                "serve policy: brownout_exit_depth "
                f"{self.brownout_exit_depth} must be < "
                f"brownout_enter_depth {self.brownout_enter_depth} "
                "(the gap is the hysteresis band)")
        if self.low_occupancy > 1.0:
            raise ValueError(
                "serve policy: field 'low_occupancy' is a fraction in "
                f"[0, 1], got {self.low_occupancy}")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"serve policy: max_replicas {self.max_replicas} < "
                f"min_replicas {self.min_replicas}")
        if self.drain_mode not in ("migrate", "local"):
            raise ValueError(
                "serve policy: field 'drain_mode' must be 'migrate' "
                f"(warm-KV handoff, the default) or 'local', got "
                f"{self.drain_mode!r}")
        return self

    @classmethod
    def from_json(cls, text: str) -> "SLOPolicy":
        try:
            data = json.loads(text)
        except json.JSONDecodeError as e:
            raise ValueError(f"serve policy: invalid JSON ({e})")
        return cls.from_dict(data)

    @classmethod
    def load(cls, source: str) -> "SLOPolicy":
        source = source.strip()
        if source.startswith("@"):
            with open(source[1:]) as f:
                return cls.from_json(f.read())
        if source.startswith("{"):
            return cls.from_json(source)
        with open(source) as f:
            return cls.from_json(f.read())

    @classmethod
    def from_env(cls, env=None) -> "SLOPolicy":
        """``HVD_TPU_SERVE_POLICY`` (file or inline JSON) as the base,
        then any ``HVD_TPU_SERVE_<FIELD>`` env knob overrides its
        field — same layering as the training AutoscalePolicy, audited
        by tools/check_parity.py check_serve_surface."""
        env = os.environ if env is None else env
        raw = env.get(ENV_POLICY)
        policy = cls.load(raw) if raw else cls()
        overrides: Dict = {}
        for name in cls.field_names():
            val = env.get("HVD_TPU_SERVE_" + name.upper())
            if val is not None:
                overrides[name] = val
        if overrides:
            merged = dataclasses.asdict(policy)
            merged.update(overrides)
            policy = cls.from_dict(merged)
        return policy

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), sort_keys=True)


class ServeController:
    """Turns cluster telemetry (windowed latency percentiles, queue
    depths, occupancy, replica loss) into deterministic
    ``keep | grow | drain`` decisions, logged exactly like the training
    autoscaler's."""

    def __init__(self, policy: SLOPolicy,
                 log_path: Optional[str] = None):
        self.policy = policy
        self._log_path = (log_path if log_path is not None
                          else runtime_env("SERVE_LOG") or None)
        self.decisions: List[Decision] = []
        self._seq = 0
        self._latencies: deque = deque(maxlen=max(1, policy.window))
        self._ttfts: deque = deque(maxlen=max(1, policy.window))
        self._tpots: deque = deque(maxlen=max(1, policy.window))
        self._queue_waits: deque = deque(maxlen=max(1, policy.window))
        # Overload control (docs/serve.md "Overload & tenancy"): the
        # brownout ladder lives with the controller so its transitions
        # share the decision log's seq space with grow/drain.
        self.brownout = overload_lib.BrownoutLadder(policy)
        self._last_grow_t = -float("inf")
        self._last_shrink_t = -float("inf")
        self._last_tick_t = -float("inf")

    # -- evidence feeds ------------------------------------------------------

    def observe_completion(self, req: Request) -> None:
        if req.latency_s is not None:
            self._latencies.append(req.latency_s)
        if req.ttft_s is not None:
            self._ttfts.append(req.ttft_s)
        if req.tpot_s is not None:
            self._tpots.append(req.tpot_s)
        if req.queue_wait_s is not None:
            self._queue_waits.append(req.queue_wait_s)

    @staticmethod
    def _windowed(window: deque) -> Optional[float]:
        if not window:
            return None
        return float(np.percentile(np.asarray(window), 99))

    def windowed_p99(self) -> Optional[float]:
        return self._windowed(self._latencies)

    def windowed_ttft_p99(self) -> Optional[float]:
        return self._windowed(self._ttfts)

    def windowed_tpot_p99(self) -> Optional[float]:
        return self._windowed(self._tpots)

    def windowed_queue_wait_p99(self) -> Optional[float]:
        return self._windowed(self._queue_waits)

    # -- decision plumbing (the autoscale contract) --------------------------

    def _record(self, decision: Decision) -> Decision:
        if decision.action != "keep":
            self._seq += 1
            decision.seq = self._seq
            self.decisions.append(decision)
            logger.warning("serve: decision #%d %s target=%s (%s)",
                           decision.seq, decision.action,
                           decision.target, decision.reason)
            if self._log_path:
                try:
                    with open(self._log_path, "a") as f:
                        f.write(decision.log_line() + "\n")
                except OSError:
                    pass  # the log is evidence, never a failure mode
        return decision

    def decision_log(self) -> List[str]:
        return [d.log_line() for d in self.decisions
                if d.action != "keep"]

    # -- triggers ------------------------------------------------------------

    def note_replica_lost(self, name: str) -> Decision:
        """A replica died mid-stream: the kill IS a drain (its queue
        and in-flight re-route) — record it so the log names the kill
        before the restoring grow."""
        return self._record(Decision(action="drain", target=name,
                                     reason="replica_lost"))

    def tick(self, now: float, live: int, draining: int,
             queue_depth: int, occupancy: float,
             below_min: bool,
             shrink_candidate: Optional[str] = None,
             handoff_depth: int = 0,
             restore_role: Optional[str] = None,
             disagg: bool = False) -> Decision:
        """One control evaluation. Returns the (single) decision; the
        cluster applies grow/drain. At most one reshape per tick —
        reshape, then re-measure, same hysteresis discipline as the
        training engine.

        Disaggregated mode (``disagg=True``, docs/serve.md): the same
        single policy governs BOTH pools, but each signal names the
        pool it grows — queue depth is admission pressure (grow
        ``prefill``), p99 and handoff depth are decode pressure (grow
        ``decode``), and a restore names the role that fell below its
        floor (``restore_role``). Targets become ``"role:1"`` strings;
        classic mode keeps the historical ``"1"``."""
        p = self.policy
        if now - self._last_tick_t < p.tick_interval_s \
                and not below_min:
            return Decision(action="keep")
        self._last_tick_t = now
        active = live - draining
        if p.overload:
            # Brownout ladder: evaluated every full tick, logged like
            # grow/drain but never consuming the one-reshape budget —
            # degradation and capacity decisions compose.
            moved = self.brownout.tick(queue_depth)
            if moved is not None:
                level, rung, why = moved
                self._record(Decision(
                    action="brownout", target=f"level:{level}",
                    reason=f"{rung}:{why}"))
                tracing.tracer().brownout(level, rung, why, now)

        def _grow_target(role: str) -> str:
            return f"{role}:1" if disagg else "1"

        if below_min:
            # Restore the floor immediately — a kill must not wait out
            # a cooldown while requests queue on the survivors.
            self._last_grow_t = now
            target = (f"{restore_role}:1" if disagg and restore_role
                      else "1")
            return self._record(Decision(
                action="grow", target=target,
                reason="restore_capacity"))
        grow_ok = (active < p.max_replicas
                   and now - self._last_grow_t >= p.grow_cooldown_s)
        if grow_ok and p.target_p99_s > 0:
            p99 = self.windowed_p99()
            if p99 is not None and p99 > p.target_p99_s:
                self._last_grow_t = now
                return self._record(Decision(
                    action="grow", target=_grow_target("decode"),
                    reason="slo_p99"))
        if grow_ok and p.ttft_target_s > 0:
            # TTFT = arrival -> first token: the pressure lives in
            # admission + prefill, so the prefill pool grows.
            ttft = self.windowed_ttft_p99()
            if ttft is not None and ttft > p.ttft_target_s:
                self._last_grow_t = now
                return self._record(Decision(
                    action="grow", target=_grow_target("prefill"),
                    reason="slo_ttft"))
        if grow_ok and p.tpot_target_s > 0:
            # TPOT = decode cadence after the first token: decode
            # slots are the bottleneck, so the decode pool grows.
            tpot = self.windowed_tpot_p99()
            if tpot is not None and tpot > p.tpot_target_s:
                self._last_grow_t = now
                return self._record(Decision(
                    action="grow", target=_grow_target("decode"),
                    reason="slo_tpot"))
        if grow_ok and p.max_queue_depth > 0 \
                and queue_depth > p.max_queue_depth:
            self._last_grow_t = now
            return self._record(Decision(
                action="grow", target=_grow_target("prefill"),
                reason="queue_depth"))
        if grow_ok and disagg and p.max_handoff_depth > 0 \
                and handoff_depth > p.max_handoff_depth:
            self._last_grow_t = now
            return self._record(Decision(
                action="grow", target="decode:1",
                reason="handoff_depth"))
        if (p.low_occupancy > 0 and active > p.min_replicas
                and queue_depth == 0 and handoff_depth == 0
                and occupancy < p.low_occupancy
                and shrink_candidate is not None
                and now - self._last_shrink_t >= p.shrink_cooldown_s):
            self._last_shrink_t = now
            return self._record(Decision(
                action="drain", target=shrink_candidate,
                reason="low_occupancy"))
        return Decision(action="keep")


class ServeCluster:
    """Multi-replica serving: a router over per-replica batchers, the
    SLO controller, and a virtual-time run loop (the CPU-simulated
    server of docs/serve.md — deterministic by construction: the clock
    is decode rounds x ``step_s``).

    ``engine_factory(name) -> DecodeEngine`` starts replicas (grow and
    kill-restore reuse it); ``host_manager`` (optional, the elastic
    ``HostManager``) maps replicas onto hosts — a killed replica
    blacklists its host and grow requires a usable one.

    ``roles`` switches on prefill/decode DISAGGREGATION
    (docs/serve.md): ``{"prefill": 1, "decode": 2}`` starts one
    prefill-role and two decode-role replicas instead of ``replicas``
    mixed ones. Prefill replicas admit + prefill and export every
    finished slot as a warm-KV wire blob; the cluster hands each blob
    to the decode replica with the most free slots the same round
    (``pending_handoffs`` buffers the overflow — its depth is the
    back-pressure signal ``max_handoff_depth`` watches). The per-role
    counts are FLOORS: a kill restores the lost role, growth lands in
    the role each decision names, and shrink only touches the decode
    pool above its floor. ``roles=None`` (default) is the classic
    mixed cluster, byte-identical to before.
    """

    def __init__(self, engine_factory: Callable[[str], DecodeEngine],
                 policy: Optional[SLOPolicy] = None, replicas: int = 2,
                 step_s: float = 0.05, log_path: Optional[str] = None,
                 host_manager=None,
                 host_of: Optional[Callable[[str], str]] = None,
                 roles: Optional[Dict[str, int]] = None,
                 clock: Optional[Callable[[], float]] = None):
        self.factory = engine_factory
        # Wall-clock source for the run() report only; a virtual-time
        # harness injects its own so the report stays deterministic
        # (hvdlint sim-clock discipline).
        self._clock = clock if clock is not None else time.monotonic
        self.policy = policy if policy is not None \
            else SLOPolicy.from_env()
        self.step_s = float(step_s)
        self._now = 0.0
        self.controller = ServeController(self.policy,
                                          log_path=log_path)
        # A cluster run is one trace session: the ledger resets here so
        # seeded repeat runs produce byte-identical summaries.
        self.tracer = tracing.tracer()
        self.tracer.begin_session()
        self.host_manager = host_manager
        self.host_of = host_of or (lambda name: name)
        self.batchers: Dict[str, ContinuousBatcher] = {}
        self.events: List[Tuple] = []
        self.completed: List[Request] = []
        # Overload-control terminal outcomes (docs/serve.md "Overload
        # & tenancy"): every admitted request lands in exactly one of
        # completed / shed / rejected — report() asserts the zero-
        # silent-drops identity over the three.
        self.shed: List[Request] = []
        self.rejected: List[Request] = []
        self._classes = (overload_lib.classes_from_policy(self.policy)
                         if self.policy.overload else {})
        self._class_priorities = (
            overload_lib.class_priorities(self.policy)
            if self.policy.overload else None)
        self.overflow: deque = deque()
        # Prefilled sequences awaiting a decode slot:
        # (request, wire_blob, generated) FIFO — disaggregation only.
        self.pending_handoffs: deque = deque()
        self.rounds = 0
        self._next_id = 0
        self._handoffs_done = 0
        # Counters from replicas that already left (kill / finished
        # drain) so report() totals survive replica churn.
        self._closed_prefill_tokens = 0
        self._closed_spec_proposed = 0
        self._closed_spec_accepted = 0
        self.disagg = roles is not None
        if self.disagg:
            unknown = sorted(set(roles) - {"prefill", "decode"})
            if unknown:
                raise ValueError(
                    f"serve roles: unknown role(s) {unknown}; known: "
                    f"['decode', 'prefill']")
            self.role_floor = {"prefill": int(roles.get("prefill", 1)),
                               "decode": int(roles.get("decode", 1))}
            for role, count in self.role_floor.items():
                if count < 1:
                    raise ValueError(
                        f"serve roles: role {role!r} needs >= 1 "
                        f"replica, got {count}")
            for _ in range(self.role_floor["prefill"]):
                self._start_replica("prefill")
            for _ in range(self.role_floor["decode"]):
                self._start_replica("decode")
        else:
            self.role_floor = {}
            for _ in range(max(1, int(replicas))):
                self._start_replica()

    # -- replica lifecycle ---------------------------------------------------

    # How many candidate replica ids _start_replica scans for one
    # whose host is usable before declaring growth blocked (replica
    # ids are monotonic; skipped ids are simply never used).
    _GROW_SCAN = 16

    def _start_replica(self, role: Optional[str] = None
                       ) -> Optional[str]:
        name = f"r{self._next_id}"
        consumed = 1
        if self.host_manager is not None:
            # The new replica's OWN host must be usable (not
            # blacklisted) and not already hosting a replica — scan
            # forward through candidate ids until one maps to such a
            # host (deterministic: a pure function of cluster state).
            usable = set(self.host_manager.current_hosts())
            used = {self.host_of(n) for n in self.batchers}
            for k in range(self._GROW_SCAN):
                cand = f"r{self._next_id + k}"
                host = self.host_of(cand)
                if host in usable and host not in used:
                    name, consumed = cand, k + 1
                    break
            else:
                self.events.append((self.rounds, "grow_blocked",
                                    "no_usable_host"))
                return None
        self._next_id += consumed
        b_role = role or "mixed"
        self.batchers[name] = ContinuousBatcher(
            self.factory(name), role=b_role,
            class_priorities=self._class_priorities)
        self.tracer.set_role(name, b_role)
        if self.disagg:
            self.events.append((self.rounds, "replica_start", name,
                                b_role))
        else:
            self.events.append((self.rounds, "replica_start", name))
        return name

    def live(self) -> List[str]:
        return sorted(self.batchers)

    def serving(self) -> List[str]:
        """Replicas accepting new ROUTED work (live, not draining, and
        not decode-role — decode replicas receive sequences only via
        the warm-KV handoff, never from the router)."""
        return sorted(n for n, b in self.batchers.items()
                      if not b.draining and b.role != "decode")

    def pool(self, role: str) -> List[str]:
        """Live non-draining replicas of one role (disaggregation)."""
        return sorted(n for n, b in self.batchers.items()
                      if not b.draining and b.role == role)

    def _close_batcher(self, b: ContinuousBatcher) -> None:
        """Fold a departing replica's monotonic counters into the
        cluster totals (report() must survive replica churn), then
        close it."""
        eng = b.engine
        self._closed_prefill_tokens += getattr(eng, "prefill_tokens", 0)
        self._closed_spec_proposed += getattr(eng, "spec_proposed", 0)
        self._closed_spec_accepted += getattr(eng, "spec_accepted", 0)
        b.close()

    def kill_replica(self, name: str) -> None:
        """Hard replica loss (the chaos site): queued + in-flight
        requests re-route to peers, the host is blacklisted, the
        controller logs the kill; the next tick restores capacity.
        Disaggregation: blobs this replica already exported into
        ``pending_handoffs`` stay valid (the wire blob is
        self-contained host data) — only its queued/in-flight requests
        re-route, and a killed prefill replica's sequences re-prefill
        from the queue at their ORIGINAL arrival position
        (``insert_by_arrival``) — zero dropped requests."""
        b = self.batchers.pop(name, None)
        if b is None:
            return
        rerouted = b.abort(self._now)
        if b.outbox:
            # Blobs exported this round but not yet pumped: still
            # valid, deliver them normally.
            self.pending_handoffs.extend(b.outbox)
            b.outbox = []
        self._close_batcher(b)
        self.events.append((self.rounds, "replica_kill", name,
                            len(rerouted)))
        self.events.extend((self.rounds, "batcher", name) + e
                           for e in b.events)
        if self.host_manager is not None:
            self.host_manager.blacklist(self.host_of(name))
        self.controller.note_replica_lost(name)
        self._reroute(rerouted)

    def _reroute(self, reqs: List[Request]) -> None:
        for req in reqs:
            req.replica = None
            if self.policy.overload and self._retry_exhausted(req):
                continue
            if not self._route(req):
                self.overflow.append(req)

    # -- overload control: admission + terminal outcomes ---------------------

    def _class_of(self, req: Request):
        return self._classes.get(req.slo_class or "latency")

    def _retry_exhausted(self, req: Request) -> bool:
        """Per-class retry budgets make shed/re-routed retries
        self-limiting: a request past its budget is SHED (a typed
        terminal outcome) instead of circling the cluster amplifying
        the overload."""
        cls = self._class_of(req)
        if cls is None or req.reroutes <= cls.retry_budget:
            return False
        self._shed(req, "retry_budget")
        return True

    def _shed(self, req: Request, reason: str) -> None:
        req.outcome = "shed"
        self.shed.append(req)
        overload_lib.record_shed(req.slo_class, reason)
        if reason == "deadline":
            # The miss is already certain at admission — count it now
            # so the miss metric stays honest under shedding.
            queue_lib.record_shed_miss()
        self.events.append((self.rounds, "shed", req.rid, reason))
        if self.tracer.enabled:
            self.tracer.shed(req, self._now, reason)

    def _reject(self, req: Request, reason: str) -> None:
        req.outcome = "rejected"
        self.rejected.append(req)
        queue_lib.record_rejection(reason)
        self.events.append((self.rounds, "reject", req.rid, reason))
        if self.tracer.enabled:
            self.tracer.reject(req, self._now, reason)

    def _admission_gate(self, req: Request) -> bool:
        """Deadline-aware admission (docs/serve.md "Overload &
        tenancy"): stamp the class deadline, apply the active brownout
        rungs, and shed requests that cannot feasibly meet their
        deadline BEFORE spending prefill on them. Returns True when
        the request reached a terminal outcome here."""
        p = self.policy
        cls = self._class_of(req)
        if cls is not None and req.deadline_s == 0 \
                and cls.deadline_s > 0:
            req.deadline_s = cls.deadline_s
        ladder = self.controller.brownout
        if ladder.active("reject_admission") \
                and req.slo_class not in ("", "latency"):
            self._reject(req, "brownout")
            return True
        if ladder.active("shed_batch") and req.slo_class == "batch":
            self._shed(req, "brownout")
            return True
        if ladder.active("clamp_tokens") \
                and req.slo_class == "throughput":
            req.max_new_tokens = min(req.max_new_tokens,
                                     max(1, p.brownout_clamp_tokens))
        if req.deadline_s > 0:
            est = overload_lib.admission_estimate(
                self.controller, req.max_new_tokens)
            if est is not None:
                budget = (req.arrival_t + req.deadline_s) - self._now
                if p.admission_safety * est > budget:
                    self._shed(req, "deadline")
                    return True
        return False

    # -- routing -------------------------------------------------------------

    def submit(self, req: Request) -> None:
        if self.tracer.enabled:
            self.tracer.enqueue(req, self._now)
        if self.policy.overload and self._admission_gate(req):
            return
        if not self._route(req):
            self.overflow.append(req)

    def _route(self, req: Request) -> bool:
        """Least-loaded live non-draining replica (queued + active),
        name order breaking ties — deterministic. A bounded queue may
        refuse (``submit`` returns False); the next-least-loaded
        replica is tried before the request overflows.

        A request that already held a slot once (``reroutes`` or
        ``migrations`` > 0 — kill, drain, or no-free-slot re-prefill
        fallback) re-enters at its ARRIVAL position via
        ``insert_by_arrival``: its deadline clock has been running
        since ``arrival_t``, so it must not queue behind requests that
        arrived after it."""
        order = sorted(self.serving(), key=lambda n: (
            len(self.batchers[n].queue)
            + self.batchers[n].engine.active_count(), n))
        readmit = bool(req.reroutes or req.migrations)
        for name in order:
            if readmit:
                self.batchers[name].queue.insert_by_arrival(req)
                ok = True
            else:
                ok = self.batchers[name].queue.submit(req,
                                                      now=self._now)
            if ok:
                self.events.append((self.rounds, "route", req.rid,
                                    name, req.reroutes))
                return True
        return False

    # -- control -------------------------------------------------------------

    def queue_depth(self) -> int:
        return (sum(len(b.queue) for b in self.batchers.values())
                + len(self.overflow))

    def occupancy(self, role: Optional[str] = None) -> float:
        bs = [b for b in self.batchers.values()
              if role is None or b.role == role]
        if not bs:
            return 0.0
        return (sum(b.engine.active_count() for b in bs)
                / max(1, sum(b.engine.slots for b in bs)))

    def _shrink_candidate(self) -> Optional[str]:
        """Deterministic drain victim: the newest serving replica —
        disaggregation shrinks only the DECODE pool (a prefill replica
        is the admission path; its floor is load-bearing) and respects
        the decode floor."""
        if self.disagg:
            decode = self.pool("decode")
            if len(decode) <= self.role_floor["decode"]:
                return None
            return max(decode, key=lambda n: (int(n[1:]), n))
        serving = self.serving()
        if len(serving) <= self.policy.min_replicas:
            return None
        return max(serving, key=lambda n: (int(n[1:]), n))

    def _apply(self, decision) -> None:
        if decision.action == "grow":
            target = str(decision.target or "")
            role = target.split(":", 1)[0] if ":" in target else None
            self._start_replica(role)
        elif decision.action == "drain" \
                and decision.reason == "low_occupancy" \
                and decision.target in self.batchers:
            self.events.append((self.rounds, "drain", decision.target))
            b = self.batchers[decision.target]
            self._reroute(b.start_drain("shrink"))
            if self.policy.drain_mode == "migrate":
                self._migrate_inflight(decision.target)

    def _migrate_inflight(self, target: str) -> None:
        """The warm-KV drain default (docs/serve.md): each of the
        draining replica's in-flight sequences moves to the peer with
        the most free slots (name order breaking ties — deterministic)
        WITH its int8-wire cache blob; a sequence with no free peer
        slot falls back to a re-prefill re-route. Either way the
        drained replica empties NOW and leaves on the next tick."""
        moved = self.batchers[target].migrate_requests(self._now)
        t_role = self.batchers[target].role
        for req, blob, generated in moved:
            # A warm blob must land on a like-for-like peer: in
            # disaggregated mode a decode replica's sequences move to
            # other DECODE replicas (prefill slots never hold decoding
            # sequences); classic mode uses any serving peer.
            if self.disagg:
                peer_names = [n for n in self.pool(t_role)
                              if n != target]
            else:
                peer_names = [n for n in self.serving() if n != target]
            peers = sorted(
                peer_names,
                key=lambda n: (-self.batchers[n].migratable_slots(), n))
            placed = False
            for name in peers:
                if self.batchers[name].migratable_slots() <= 0:
                    continue
                self.batchers[name].admit_migrated(req, blob,
                                                   generated, self._now)
                self.events.append((self.rounds, "migrate", req.rid,
                                    target, name))
                placed = True
                break
            if not placed:
                # No warm landing spot: re-prefill on a peer (the
                # historical path) — zero dropped requests either way.
                req.reroutes += 1
                req.replica = None
                self._reroute([req])

    def tick(self) -> None:
        if self.host_manager is not None:
            self.host_manager.update_available_hosts()
        live = len(self.batchers)
        draining = sum(1 for b in self.batchers.values() if b.draining)
        if self.disagg:
            below_role = None
            for role in ("prefill", "decode"):
                if len(self.pool(role)) < self.role_floor[role]:
                    below_role = role
                    break
            decision = self.controller.tick(
                self._now, live, draining, self.queue_depth(),
                self.occupancy(role="decode"),
                below_role is not None,
                shrink_candidate=self._shrink_candidate(),
                handoff_depth=len(self.pending_handoffs),
                restore_role=below_role, disagg=True)
        else:
            below_min = (live - draining) < self.policy.min_replicas
            decision = self.controller.tick(
                self._now, live, draining, self.queue_depth(),
                self.occupancy(), below_min,
                shrink_candidate=self._shrink_candidate())
        self._apply(decision)
        if self.policy.overload:
            # The spec_off rung flips every engine's runtime flag (the
            # mildest rung: lose the speculative speedup, keep every
            # request); exit restores it the same way.
            spec_on = not self.controller.brownout.active("spec_off")
            for b in self.batchers.values():
                b.engine.spec_enabled = spec_on
        # Finished drains leave the cluster.
        for name in self.live():
            b = self.batchers[name]
            if b.draining and b.drained:
                self._close_batcher(b)
                self.events.append((self.rounds, "drained", name))
                self.events.extend((self.rounds, "batcher", name) + e
                                   for e in b.events)
                self.batchers.pop(name)

    # -- disaggregation: the prefill -> decode handoff wire ------------------

    def _pump_handoffs(self) -> None:
        """Deliver pending prefilled sequences to the decode pool,
        FIFO, each to the decode replica with the most free slots (name
        order breaking ties — deterministic). A blob with no free
        decode slot this round WAITS in ``pending_handoffs`` — its KV
        is already computed, so re-prefilling would waste the work; the
        deque's depth is the controller's ``max_handoff_depth``
        back-pressure signal."""
        while self.pending_handoffs:
            req, blob, generated = self.pending_handoffs[0]
            peers = sorted(
                self.pool("decode"),
                key=lambda n: (-self.batchers[n].migratable_slots(), n))
            dst = next((n for n in peers
                        if self.batchers[n].migratable_slots() > 0),
                       None)
            if dst is None:
                break
            self.pending_handoffs.popleft()
            self.batchers[dst].admit_migrated(req, blob, generated,
                                              self._now)
            _M_HANDOFFS.inc()
            self._handoffs_done += 1
            self.events.append((self.rounds, "handoff", req.rid, dst))

    # -- the run loop --------------------------------------------------------

    def run(self, trace: TrafficTrace, max_rounds: int = 100000,
            round_hook: Optional[Callable[["ServeCluster", int],
                                          None]] = None) -> Dict:
        """Drive the seeded open-loop trace to completion in virtual
        time. ``round_hook(cluster, round_idx)`` is the chaos injection
        point (e.g. kill a replica at round k). Returns the report —
        latency percentiles, token counts, occupancy, the deterministic
        event list, and the decision log."""
        pending = deque(trace.requests)
        wall0 = self._clock()
        while self.rounds < max_rounds:
            while pending and pending[0].arrival_t <= self._now:
                self.submit(pending.popleft())
            if self.overflow:
                self._reroute([self.overflow.popleft()
                               for _ in range(len(self.overflow))])
            if round_hook is not None:
                round_hook(self, self.rounds)
            self.tick()
            # Disaggregation runs the round in wire order: prefill
            # replicas first (their outboxes fill), then the handoff
            # pump, then decode replicas — a sequence prefilled this
            # round starts decoding this same round. Classic mode is
            # the historical single pass (every batcher is "mixed", so
            # the decode pass matches nothing).
            for name in self.live():
                b = self.batchers[name]
                if b.role == "decode":
                    continue
                for req in b.run_step(self._now):
                    self.completed.append(req)
                    self.controller.observe_completion(req)
                if self.tracer.enabled:
                    self.tracer.account(name, b.last_round_state,
                                        self.step_s)
                if b.outbox:
                    self.pending_handoffs.extend(b.outbox)
                    b.outbox = []
            if self.disagg:
                self._pump_handoffs()
                for name in self.live():
                    b = self.batchers[name]
                    if b.role != "decode":
                        continue
                    for req in b.run_step(self._now):
                        self.completed.append(req)
                        self.controller.observe_completion(req)
                    if self.tracer.enabled:
                        self.tracer.account(name, b.last_round_state,
                                            self.step_s)
            self.rounds += 1
            self._now += self.step_s
            if not pending and not self.queue_depth() \
                    and not self.pending_handoffs \
                    and all(b.engine.active_count() == 0
                            for b in self.batchers.values()):
                break
        wall_s = self._clock() - wall0
        self.tracer.maybe_dump()
        return self.report(len(trace.requests), wall_s)

    def report(self, submitted: int, wall_s: float = 0.0) -> Dict:
        lats = [r.latency_s for r in self.completed
                if r.latency_s is not None]
        arr = np.asarray(lats) if lats else np.zeros((1,))

        def _pcts(vals):
            a = np.asarray(vals) if vals else np.zeros((1,))
            return (round(float(np.percentile(a, 50)), 6),
                    round(float(np.percentile(a, 99)), 6))

        ttft_p50, ttft_p99 = _pcts(
            [r.ttft_s for r in self.completed if r.ttft_s is not None])
        tpot_p50, tpot_p99 = _pcts(
            [r.tpot_s for r in self.completed if r.tpot_s is not None])
        qw_p50, qw_p99 = _pcts(
            [r.queue_wait_s for r in self.completed
             if r.queue_wait_s is not None])
        gen_tokens = sum(len(r.tokens) for r in self.completed)
        occ = [b.mean_occupancy() for b in self.batchers.values()
               if b.steps]
        for name in self.live():
            self.events.extend(
                (self.rounds, "batcher", name) + e
                for e in self.batchers[name].events)
        prefill_tokens = self._closed_prefill_tokens + sum(
            getattr(b.engine, "prefill_tokens", 0)
            for b in self.batchers.values())
        spec_proposed = self._closed_spec_proposed + sum(
            getattr(b.engine, "spec_proposed", 0)
            for b in self.batchers.values())
        spec_accepted = self._closed_spec_accepted + sum(
            getattr(b.engine, "spec_accepted", 0)
            for b in self.batchers.values())
        extra = {}
        if self.disagg:
            extra = {"handoffs": self._handoffs_done,
                     "pending_handoffs": len(self.pending_handoffs)}
        if self.policy.overload:
            # Terminal-outcome accounting + per-class latency tails
            # (the A/B evidence surface): completed + shed + rejected
            # must equal submitted — "dropped" means SILENTLY lost and
            # the overload chaos family asserts it stays 0.
            by_class: Dict[str, List[float]] = {}
            for r in self.completed:
                if r.latency_s is not None:
                    by_class.setdefault(r.slo_class or "latency",
                                        []).append(r.latency_s)
            extra = {
                **extra,
                "shed": len(self.shed),
                "rejected": len(self.rejected),
                "shed_by_reason": dict(sorted(
                    _count_by(e[3] for e in self.events
                              if e[1] == "shed").items())),
                "brownout_level": self.controller.brownout.level,
                "brownout_max_level":
                    self.controller.brownout.max_level,
                "class_latency_p99_s": {
                    cls: round(float(np.percentile(
                        np.asarray(vals), 99)), 6)
                    for cls, vals in sorted(by_class.items())},
                "class_completed": {
                    cls: len(vals)
                    for cls, vals in sorted(by_class.items())},
            }
        terminal = (len(self.completed) + len(self.shed)
                    + len(self.rejected))
        return {
            **extra,
            "prefill_tokens": prefill_tokens,
            "spec_proposed": spec_proposed,
            "spec_accepted": spec_accepted,
            "spec_acceptance_rate": round(
                spec_accepted / spec_proposed, 4)
            if spec_proposed else 0.0,
            "submitted": submitted,
            "completed": len(self.completed),
            "dropped": submitted - terminal,
            "rounds": self.rounds,
            "virtual_s": round(self._now, 6),
            "wall_s": round(wall_s, 3),
            "latency_p50_s": round(float(np.percentile(arr, 50)), 6),
            "latency_p99_s": round(float(np.percentile(arr, 99)), 6),
            # Per-phase percentiles from the request timeline (the
            # tracer's span metrics aggregate the same stamps).
            "ttft_p50_s": ttft_p50,
            "ttft_p99_s": ttft_p99,
            "tpot_p50_s": tpot_p50,
            "tpot_p99_s": tpot_p99,
            "queue_wait_p50_s": qw_p50,
            "queue_wait_p99_s": qw_p99,
            # Per-replica goodput attribution ({} with tracing off).
            "goodput": self.tracer.goodput_snapshot(),
            "generated_tokens": gen_tokens,
            "tokens_per_virtual_s": round(
                gen_tokens / self._now, 3) if self._now else 0.0,
            "tokens_per_wall_s": round(
                gen_tokens / wall_s, 3) if wall_s else 0.0,
            "mean_occupancy": round(
                sum(occ) / len(occ), 4) if occ else 0.0,
            "max_reroutes": max((r.reroutes for r in self.completed),
                                default=0),
            "deadline_misses": sum(1 for r in self.completed
                                   if r.deadline_missed),
            "events": self.events,
            "decisions": self.controller.decision_log(),
        }
