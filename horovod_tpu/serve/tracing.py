"""Request-scoped tracing + goodput attribution for the serve plane.

Every request that enters a :class:`~.controller.ServeCluster` leaves a
*span ledger* here: enqueue -> queue wait -> prefill (first token) ->
prefix fork -> handoff export / wire / import -> decode -> speculative
rounds -> migration -> retire.  A journey that crosses the prefill and
decode pools reassembles into ONE trace because the trace stamp rides
the warm-KV blob through ``migrate_out`` / ``admit_migrated`` (the same
transport ``export_slot`` / ``import_slot`` already use), keyed by the
request id.

Design rules (the flightrec / metrics philosophy):

* **NOOP singleton** — with ``HVD_TPU_SERVE_TRACE=0`` every call site
  shares one disabled tracer and hot paths pay a single bool check.
  Nothing is recorded, no metric is observed, and the seeded event
  digests are bit-identical to a tree without this module.
* **Clock injection** — the tracer never reads a wall clock on a span
  path.  Callers pass the serve plane's virtual ``now`` explicitly; the
  injected ``clock`` exists only as a fallback for interactive use
  (hvdlint ``sim-clock`` applies).  Seeded repeat runs therefore produce
  byte-identical :meth:`ServeTracer.summary` ledgers.
* **Metrics derive from spans** — the TTFT / TPOT / queue-wait /
  handoff histograms and the per-replica goodput ledger below are
  observed at span-record time, never from a second code path.

The span schema is shared with the ``tools/analyze_serve.py`` reader;
``tools/check_parity.py check_serve_trace_surface`` byte-compares the
two ``TRACE_SPAN_KEYS`` literals so they cannot drift.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..common import metrics as metrics_lib
from ..common.config import runtime_env

# Kept in byte-sync with tools/analyze_serve.py (check_serve_trace_surface).
TRACE_SCHEMA_VERSION = 1
TRACE_SPAN_KEYS = ("rid", "phase", "replica", "role", "t0", "t1", "detail")

#: Every phase a span may carry, in journey order.  ``enqueue``/``retire``
#: and the ``*_export``/``*_import`` landings are point spans (t0 == t1);
#: the rest are intervals measured in the cluster's virtual seconds.
TRACE_PHASES = (
    "enqueue",        # request routed to a replica queue (t = arrival)
    "queue",          # enqueue/abort -> admission on a replica
    "prefill",        # prompt prefill; emits the first token
    "prefix_fork",    # prefix-cache hit forked warm KV (detail = base len)
    "handoff_export", # warm-KV blob packed for a pool handoff / migration
    "handoff_wire",   # export -> import wait across the handoff transport
    "handoff_import", # blob landed on the destination replica
    "decode",         # first token / import -> last token
    "spec",           # one speculative round (detail = accepted/proposed)
    "migrate",        # drain-driven migration wire wait (export -> import)
    "abort",          # replica loss dropped in-flight state (salvage start)
    "retire",         # completion (detail = generated token count)
    "shed",           # overload control shed the request (detail = reason)
    "reject",         # admission refused the request (detail = reason)
    "brownout",       # ladder transition, rid -1 (detail = dir:rung:level)
)

#: Phases that CLOSE a journey: a rid with any of these (and no
#: pending warm-KV export) is not an orphan. ``brownout`` spans are
#: cluster-scoped (rid -1), never a request journey.
TRACE_TERMINAL_PHASES = ("retire", "shed", "reject", "brownout")

GOODPUT_STATES = ("decode", "prefill", "idle", "drain")
_ROLES = ("mixed", "prefill", "decode")

_M_TTFT = metrics_lib.histogram(
    "hvd_tpu_serve_ttft_seconds",
    "time to first token (arrival -> prefill emits token 0), by the "
    "role of the replica that prefilled (docs/serve.md)",
    labels=("role",))
_M_TPOT = metrics_lib.histogram(
    "hvd_tpu_serve_tpot_seconds",
    "time per output token after the first (decode cadence), by the "
    "role of the replica that retired the request",
    labels=("role",))
_M_QUEUE_WAIT = metrics_lib.histogram(
    "hvd_tpu_serve_queue_wait_seconds",
    "time spent queued before admission (re-admissions after a kill "
    "or reroute observe the wait since the abort), by admitting role",
    labels=("role",))
_M_HANDOFF = metrics_lib.histogram(
    "hvd_tpu_serve_handoff_seconds",
    "warm-KV export -> import wire wait across pools, by the role of "
    "the importing replica",
    labels=("role",))
for _r in _ROLES:
    _M_TTFT.labels(role=_r)
    _M_TPOT.labels(role=_r)
    _M_QUEUE_WAIT.labels(role=_r)
    _M_HANDOFF.labels(role=_r)
del _r
_M_GOODPUT = metrics_lib.counter(
    "hvd_tpu_serve_goodput_seconds_total",
    "virtual seconds each replica spent per state (decode / prefill "
    "= goodput, idle / drain = overhead); the pod goodput fraction on "
    "/pod/serve is (decode+prefill) / total",
    labels=("replica", "state"))

_TRACE_DUMP_NAME = "serve_trace.jsonl"


def _round6(v: float) -> float:
    return round(float(v), 6)


class ServeTracer:
    """Per-request span ledger + per-replica goodput accounting.

    All record methods take the caller's virtual ``now``; the injected
    ``clock`` is only a fallback when no time is supplied.  Methods
    no-op when ``enabled`` is False — call sites may also pre-check the
    bool to skip argument construction on hot paths.
    """

    def __init__(self, enabled: bool = True, size: Optional[int] = None,
                 clock=None):
        self.enabled = bool(enabled)
        if size is None:
            size = int(runtime_env("SERVE_TRACE_SIZE", "4096"))
        self.size = max(1, int(size))
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        self._spans: Dict[int, List[Dict[str, Any]]] = {}
        self._order: List[int] = []          # rid insertion order
        self._done: deque = deque()          # retired rids, oldest first
        self._roles: Dict[str, str] = {}     # replica name -> role
        self._pending_export: Dict[int, Tuple[float, str]] = {}
        self._decode_start: Dict[int, float] = {}
        self._goodput: Dict[str, Dict[str, float]] = {}
        self.dropped_traces = 0
        # Last ladder level seen (overload control; 0 = no brownout) —
        # tracked even when disabled so /pod/serve stays honest.
        self.brownout_level = 0

    # -- plumbing ------------------------------------------------------------

    def begin_session(self) -> None:
        """Reset ledgers for a fresh cluster run (keeps the enabled bit)."""
        with self._lock:
            self._spans.clear()
            self._order.clear()
            self._done.clear()
            self._roles.clear()
            self._pending_export.clear()
            self._decode_start.clear()
            self._goodput.clear()
            self.dropped_traces = 0
            self.brownout_level = 0

    def set_role(self, replica: str, role: str) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._roles[replica] = role

    def role_of(self, replica: str) -> str:
        return self._roles.get(replica, "mixed")

    def _now(self, now: Optional[float]) -> float:
        return self._clock() if now is None else now

    def span(self, rid: int, phase: str, replica: str, t0: float,
             t1: float, detail: str = "") -> None:
        """Append one span to ``rid``'s ledger (the only write path)."""
        if not self.enabled:
            return
        role = self.role_of(replica)
        rec = {"rid": int(rid), "phase": phase, "replica": replica,
               "role": role, "t0": _round6(t0), "t1": _round6(t1),
               "detail": str(detail)}
        with self._lock:
            if rid not in self._spans:
                self._spans[rid] = []
                self._order.append(rid)
            self._spans[rid].append(rec)

    def _last_t(self, rid: int, default: float) -> float:
        spans = self._spans.get(rid)
        if not spans:
            return default
        return spans[-1]["t1"]

    # -- journey record points (callers pass virtual time) -------------------

    def enqueue(self, req, now: Optional[float] = None) -> None:
        if not self.enabled:
            return
        t = req.arrival_t if req.arrival_t is not None else self._now(now)
        # Router-side: no replica is assigned yet (and a re-run over the
        # same trace objects must not see run-1's placement).
        self.span(req.rid, "enqueue", "", t, t)

    def queue_admit(self, req, replica: str, now: Optional[float]) -> None:
        """Admission off a replica queue; observes the queue-wait hist."""
        if not self.enabled:
            return
        t1 = self._now(now)
        t0 = self._last_t(req.rid, req.arrival_t)
        self.span(req.rid, "queue", replica, t0, t1,
                  detail=str(req.reroutes))
        _M_QUEUE_WAIT.labels(role=self.role_of(replica)).observe(
            max(0.0, t1 - t0))

    def prefill(self, req, replica: str, now: Optional[float],
                ntokens: int) -> None:
        """Prompt prefill emitted the first token; observes TTFT."""
        if not self.enabled:
            return
        t = self._now(now)
        self.span(req.rid, "prefill", replica, t, t, detail=str(ntokens))
        with self._lock:
            self._decode_start[req.rid] = t
        _M_TTFT.labels(role=self.role_of(replica)).observe(
            max(0.0, t - req.arrival_t))

    def prefix_fork(self, rid: int, replica: str, now: Optional[float],
                    base_len: int) -> None:
        if not self.enabled:
            return
        t = self._now(now)
        self.span(rid, "prefix_fork", replica, t, t, detail=str(base_len))

    def spec_round(self, rid: int, replica: str, now: Optional[float],
                   accepted: int, proposed: int) -> None:
        if not self.enabled:
            return
        t = self._now(now)
        self.span(rid, "spec", replica, t, t,
                  detail=f"{accepted}/{proposed}")

    def export(self, req, replica: str, now: Optional[float],
               kind: str) -> Optional[Dict[str, Any]]:
        """Warm-KV blob leaves ``replica``.  Returns the stamp that rides
        the blob through the handoff transport (None when disabled)."""
        if not self.enabled:
            return None
        t = self._now(now)
        self.span(req.rid, "handoff_export", replica, t, t, detail=kind)
        with self._lock:
            self._pending_export[req.rid] = (t, kind)
        return {"rid": int(req.rid), "t": _round6(t), "kind": kind}

    def import_blob(self, req, replica: str, now: Optional[float],
                    stamp: Optional[Dict[str, Any]]) -> None:
        """Warm-KV blob landed on ``replica``; closes the wire span."""
        if not self.enabled:
            return
        t = self._now(now)
        if stamp is None:
            with self._lock:
                pend = self._pending_export.pop(req.rid, None)
        else:
            pend = (float(stamp.get("t", t)), str(stamp.get("kind",
                                                            "handoff")))
            with self._lock:
                self._pending_export.pop(req.rid, None)
        if pend is not None:
            t0, kind = pend
            phase = "migrate" if kind == "migrate" else "handoff_wire"
            self.span(req.rid, phase, replica, t0, t)
            if kind != "migrate":
                _M_HANDOFF.labels(role=self.role_of(replica)).observe(
                    max(0.0, t - t0))
        self.span(req.rid, "handoff_import", replica, t, t)
        with self._lock:
            self._decode_start[req.rid] = t

    def abort(self, req, replica: str, now: Optional[float],
              cause: str = "replica_lost") -> None:
        """In-flight state dropped (replica kill); the salvage journey
        (re-prefill or re-import) continues under the same rid."""
        if not self.enabled:
            return
        t = self._now(now)
        self.span(req.rid, "abort", replica, t, t, detail=cause)
        with self._lock:
            self._decode_start.pop(req.rid, None)

    def retire(self, req, replica: str, now: Optional[float]) -> None:
        """Request completed; closes the decode span, observes TPOT."""
        if not self.enabled:
            return
        t = self._now(now)
        with self._lock:
            d0 = self._decode_start.pop(req.rid, None)
        if d0 is None:
            d0 = req.admit_t if req.admit_t is not None else t
        ntok = len(req.tokens)
        self.span(req.rid, "decode", replica, d0, t, detail=str(ntok))
        self.span(req.rid, "retire", replica, t, t, detail=str(ntok))
        tpot = req.tpot_s
        if tpot is not None:
            _M_TPOT.labels(role=self.role_of(replica)).observe(tpot)
        self._close(req.rid)

    def _close(self, rid: int) -> None:
        """Shared terminal bookkeeping: drop in-flight state and evict
        the oldest closed journeys past the retention cap."""
        with self._lock:
            self._pending_export.pop(rid, None)
            self._decode_start.pop(rid, None)
            self._done.append(rid)
            while len(self._done) > self.size:
                old = self._done.popleft()
                if self._spans.pop(old, None) is not None:
                    self._order.remove(old)
                    self.dropped_traces += 1

    def shed(self, req, now: Optional[float],
             reason: str = "deadline") -> None:
        """Overload control shed the request before prefill — a
        TERMINAL span (the journey is closed, not orphaned)."""
        if not self.enabled:
            return
        t = self._now(now)
        self.span(req.rid, "shed", "", t, t, detail=reason)
        self._close(req.rid)

    def reject(self, req, now: Optional[float],
               reason: str = "brownout") -> None:
        """Admission refused the request — a TERMINAL span."""
        if not self.enabled:
            return
        t = self._now(now)
        self.span(req.rid, "reject", "", t, t, detail=reason)
        self._close(req.rid)

    def brownout(self, level: int, rung: str, direction: str,
                 now: Optional[float]) -> None:
        """Ladder transition, recorded cluster-scoped under rid -1 so
        /pod/serve and the trace ledger show when each rung engaged
        (docs/serve.md 'Overload & tenancy')."""
        self.brownout_level = int(level)
        if not self.enabled:
            return
        t = self._now(now)
        self.span(-1, "brownout", "", t, t,
                  detail=f"{direction}:{rung}:level={level}")

    # -- goodput -------------------------------------------------------------

    def account(self, replica: str, state: str, dt: float) -> None:
        """Attribute ``dt`` virtual seconds of ``replica`` to ``state``."""
        if not self.enabled:
            return
        with self._lock:
            per = self._goodput.setdefault(replica, {})
            per[state] = per.get(state, 0.0) + dt
        _M_GOODPUT.labels(replica=replica, state=state).inc(dt)

    def goodput_snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {rep: {st: _round6(v) for st, v in sorted(per.items())}
                    for rep, per in sorted(self._goodput.items())}

    def goodput_fraction(self) -> Optional[float]:
        """(decode + prefill) / total over every replica; None if empty."""
        total = useful = 0.0
        for per in self.goodput_snapshot().values():
            for st, v in per.items():
                total += v
                if st in ("decode", "prefill"):
                    useful += v
        if total <= 0.0:
            return None
        return _round6(useful / total)

    # -- read side -----------------------------------------------------------

    def trace(self, rid: int) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._spans.get(rid, ()))

    def rids(self) -> List[int]:
        with self._lock:
            return list(self._order)

    def orphans(self) -> List[int]:
        """Rids whose journey never closed: no terminal span (retire /
        shed / reject), or a warm-KV export that was never imported.
        Empty after a clean run — also under overload, where shed and
        rejected requests close their journeys explicitly."""
        out = []
        with self._lock:
            for rid in self._order:
                phases = {s["phase"] for s in self._spans[rid]}
                if phases.isdisjoint(TRACE_TERMINAL_PHASES) \
                        or rid in self._pending_export:
                    out.append(rid)
        return out

    def span_count(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._spans.values())

    def summary(self) -> Dict[str, Any]:
        """Deterministic ledger snapshot (the byte-identity surface)."""
        with self._lock:
            spans = [[s[k] for k in TRACE_SPAN_KEYS]
                     for rid in sorted(self._spans)
                     for s in self._spans[rid]]
        return {"schema": TRACE_SCHEMA_VERSION,
                "spans": spans,
                "goodput": self.goodput_snapshot(),
                "dropped_traces": self.dropped_traces}

    def digest(self) -> str:
        blob = json.dumps(self.summary(), sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()[:16]

    def pod_view(self, exemplars: int = 3) -> Dict[str, Any]:
        """The /pod/serve aggregation: per-role percentiles over span
        durations, goodput fraction, and slowest-request exemplars."""
        per_role: Dict[str, Dict[str, List[float]]] = {}
        journeys: List[Tuple[float, int]] = []
        with self._lock:
            items = [(rid, list(self._spans[rid]))
                     for rid in self._order if rid >= 0]
        shed = rejected = 0
        for rid, spans in items:
            for s in spans:
                if s["phase"] == "shed":
                    shed += 1
                elif s["phase"] == "reject":
                    rejected += 1
            t_first = min(s["t0"] for s in spans)
            t_last = max(s["t1"] for s in spans)
            journeys.append((_round6(t_last - t_first), rid))
            for s in spans:
                metric = {"queue": "queue_wait", "handoff_wire": "handoff",
                          "decode": "decode"}.get(s["phase"])
                if metric is None:
                    continue
                bucket = per_role.setdefault(s["role"], {})
                bucket.setdefault(metric, []).append(s["t1"] - s["t0"])
        roles_out: Dict[str, Dict[str, float]] = {}
        for role, buckets in sorted(per_role.items()):
            row: Dict[str, float] = {}
            for metric, vals in sorted(buckets.items()):
                vals.sort()
                row[f"{metric}_p50_s"] = _round6(_pct(vals, 0.50))
                row[f"{metric}_p99_s"] = _round6(_pct(vals, 0.99))
            roles_out[role] = row
        journeys.sort(reverse=True)
        slowest = []
        for total, rid in journeys[:max(0, int(exemplars))]:
            spans = self.trace(rid)
            slowest.append({
                "rid": rid, "total_s": total,
                "spans": [{k: s[k] for k in TRACE_SPAN_KEYS}
                          for s in spans]})
        return {"enabled": self.enabled,
                "requests": len(items),
                "spans": self.span_count(),
                "orphans": len(self.orphans()),
                "shed": shed,
                "rejected": rejected,
                "brownout_level": self.brownout_level,
                "roles": roles_out,
                "goodput": self.goodput_snapshot(),
                "goodput_fraction": self.goodput_fraction(),
                "slowest": slowest}

    # -- persistence ---------------------------------------------------------

    def dump(self, path: str) -> str:
        """Write one JSONL line per request trace plus a head meta line."""
        tmp = path + ".tmp"
        with self._lock:
            items = [(rid, list(self._spans[rid])) for rid in self._order]
        with open(tmp, "w") as f:
            f.write(json.dumps({"schema": TRACE_SCHEMA_VERSION,
                                "goodput": self.goodput_snapshot(),
                                "roles": dict(sorted(self._roles.items()))},
                               sort_keys=True) + "\n")
            for rid, spans in items:
                f.write(json.dumps({"rid": rid, "spans": spans},
                                   sort_keys=True) + "\n")
        os.replace(tmp, path)
        return path

    def maybe_dump(self) -> Optional[str]:
        """Dump to ``$HVD_TPU_SERVE_TRACE_DIR/serve_trace.jsonl`` if the
        knob is set (called by the cluster at end of run)."""
        if not self.enabled:
            return None
        directory = runtime_env("SERVE_TRACE_DIR", "")
        if not directory:
            return None
        os.makedirs(directory, exist_ok=True)
        return self.dump(os.path.join(directory, _TRACE_DUMP_NAME))


def _pct(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


# -- module singleton (the flightrec `recorder()` pattern) -------------------

_TRACER: Optional[ServeTracer] = None
_NOOP: Optional[ServeTracer] = None
_SINGLETON_LOCK = threading.Lock()


def _truthy(raw: Optional[str], default: bool) -> bool:
    if raw is None or raw == "":
        return default
    return raw.strip().lower() not in ("0", "false", "no", "off")


def tracer() -> ServeTracer:
    """The process-wide tracer.  With ``HVD_TPU_SERVE_TRACE=0`` this is
    one shared no-op instance: every record method returns after a
    single bool check and nothing is ever allocated."""
    global _TRACER, _NOOP
    with _SINGLETON_LOCK:
        if not _truthy(runtime_env("SERVE_TRACE"), True):
            if _NOOP is None:
                _NOOP = ServeTracer(enabled=False, size=1)
            return _NOOP
        if _TRACER is None:
            _TRACER = ServeTracer(enabled=True)
        return _TRACER


def reset() -> None:
    """Drop both singletons (tests flip the knob between runs)."""
    global _TRACER, _NOOP
    with _SINGLETON_LOCK:
        _TRACER = None
        _NOOP = None
