"""``hvd.serve`` — distributed inference serving on the training
substrate (docs/serve.md).

The north star serves heavy traffic; everything below this package
optimizes training. ``hvd.serve`` closes the gap with the pieces the
substrate already grew: GPT decode over an explicit ring-buffer KV
cache (``kvcache`` — fp32 or block-scaled int8 storage reusing the
Pallas wire quantization), a continuous batcher generalizing the
``DeviceInfeed`` background-feed pattern to request queues
(``queue``/``batcher``), a per-replica decode engine with
flight-recorder events on the decode path (``engine``), seeded
open-loop traffic (``traffic``), and an SLO-driven replica controller
repurposing the autoscale decision machinery — p99 latency / queue
depth instead of step-time skew, graceful drain, deterministic
decision log (``controller``).

Public surface (all lazily imported; ``import horovod_tpu as hvd`` then
``hvd.serve.X``):

* ``Request``, ``RequestQueue`` — the admission plane.
* ``TrafficTrace``, ``poisson_trace`` — seeded open-loop load.
* ``DecodeEngine``, ``ContinuousBatcher`` — one replica's decode loop.
* ``SLOPolicy``, ``ServeController``, ``ServeCluster`` — the
  multi-replica control plane (``roles=`` switches on prefill/decode
  disaggregation).
* ``PrefixCache`` — cross-request shared-prefix KV reuse (``prefix``).
* ``kvcache`` — the cache pytree ops (init/export/import, int8).
* ``init_kv_cache`` — re-exported model-geometry cache constructor.
* ``ServeTracer``, ``tracer`` — the request-scoped span ledger +
  goodput attribution (``tracing``; ``HVD_TPU_SERVE_TRACE``).
* ``SLOClass``, ``BrownoutLadder``, ``SLO_CLASSES``,
  ``BROWNOUT_RUNGS`` — multi-tenant overload control: class table,
  deadline-aware admission, brownout degradation ladder
  (``overload``; docs/serve.md "Overload & tenancy").
"""

from __future__ import annotations

_LAZY = {
    "Request": ("queue", "Request"),
    "RequestQueue": ("queue", "RequestQueue"),
    "TrafficTrace": ("traffic", "TrafficTrace"),
    "poisson_trace": ("traffic", "poisson_trace"),
    "DecodeEngine": ("engine", "DecodeEngine"),
    "ContinuousBatcher": ("batcher", "ContinuousBatcher"),
    "SLOPolicy": ("controller", "SLOPolicy"),
    "ServeController": ("controller", "ServeController"),
    "ServeCluster": ("controller", "ServeCluster"),
    "PrefixCache": ("prefix", "PrefixCache"),
    "init_kv_cache": ("..models.gpt", "init_kv_cache"),
    "ServeTracer": ("tracing", "ServeTracer"),
    "tracer": ("tracing", "tracer"),
    "SLOClass": ("overload", "SLOClass"),
    "BrownoutLadder": ("overload", "BrownoutLadder"),
    "SLO_CLASSES": ("overload", "SLO_CLASSES"),
    "BROWNOUT_RUNGS": ("overload", "BROWNOUT_RUNGS"),
}

_LAZY_MODULES = ("kvcache", "queue", "batcher", "engine", "controller",
                 "traffic", "prefix", "tracing", "overload")

__all__ = sorted(list(_LAZY) + list(_LAZY_MODULES))


def __getattr__(name):
    import importlib

    if name in _LAZY:
        mod_name, attr = _LAZY[name]
        mod = importlib.import_module(
            mod_name if mod_name.startswith(".." ) else "." + mod_name,
            __name__)
        val = getattr(mod, attr)
        globals()[name] = val
        return val
    if name in _LAZY_MODULES:
        mod = importlib.import_module("." + name, __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(
        f"module 'horovod_tpu.serve' has no attribute {name!r}")
