"""Continuous batching: the per-replica serving loop (docs/serve.md).

Static batching decodes a batch until its LONGEST sequence finishes —
short requests pay the long tail's latency and finished slots burn
compute. Continuous batching retires a sequence the step it finishes
and admits a queued request into the freed slot on the very next step,
which is where serving throughput actually comes from (Orca/vLLM's
core scheduling idea). The loop per decode round:

    admit (queue -> free slots, unless draining)
    decode (one jitted step across all slots)
    retire (finished sequences complete + free their slots)

Graceful drain (the controller's shrink path and the replica-kill
runbook): ``start_drain()`` stops admission and empties the queue for
re-routing; in-flight sequences keep decoding locally until
``drained``. Every transition lands in a deterministic event list —
``(step, event, ...)`` integer tuples — which is the byte-identity
surface the serve chaos family replays (tools/chaos_soak.py --family
serve).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..common import metrics as metrics_lib
from .engine import DecodeEngine
from .queue import Request, RequestQueue

_M_DRAINS = metrics_lib.counter(
    "hvd_tpu_serve_drains_total",
    "replica drains started, by cause (shrink = SLO scale-down, "
    "kill = replica loss)",
    labels=("cause",))
for _c in ("shrink", "kill"):
    _M_DRAINS.labels(cause=_c)
del _c
_M_OCCUPANCY = metrics_lib.gauge(
    "hvd_tpu_serve_batch_occupancy",
    "active decode slots / total slots of the last decode round, "
    "by replica",
    labels=("replica",))
_M_MIGRATIONS = metrics_lib.counter(
    "hvd_tpu_serve_kv_migrations_total",
    "in-flight sequences moved between replicas with their warm KV "
    "cache (int8 wire export/import) instead of a re-prefill — the "
    "default graceful-drain path (docs/serve.md)")


ROLES = ("mixed", "prefill", "decode")


class ContinuousBatcher:
    """One replica's admission + decode + retire loop over a
    :class:`DecodeEngine` and its :class:`RequestQueue`.

    ``role`` splits the loop for prefill/decode disaggregation
    (docs/serve.md): a ``"prefill"`` replica admits + prefills, then
    immediately exports each finished slot (warm-KV wire blob) into
    its ``outbox`` for the cluster to hand to the decode pool — its
    slots free every round, so prefill throughput is slots/round. A
    ``"decode"`` replica never admits from its queue; sequences arrive
    only via ``admit_migrated``. ``"mixed"`` (the default) is the
    classic combined loop."""

    def __init__(self, engine: DecodeEngine,
                 queue: Optional[RequestQueue] = None,
                 role: str = "mixed",
                 class_priorities=None):
        if role not in ROLES:
            raise ValueError(
                f"unknown batcher role {role!r}; known: {ROLES}")
        self.engine = engine
        self.queue = queue if queue is not None else RequestQueue()
        self.name = engine.name
        self.role = role
        # Admission telemetry (queue spans / queue-wait histogram)
        # carries the replica identity via the queue.
        self.queue.role = role
        self.queue.replica = self.name
        if class_priorities is not None:
            # Multi-tenant overload control (docs/serve.md "Overload &
            # tenancy"): admission becomes strict-priority across SLO
            # classes, EDF within one.
            self.queue.set_classes(class_priorities)
        self.draining = False
        # Goodput attribution for the last run_step round: "prefill" /
        # "decode" when the round did useful work, "idle" when slots
        # sat empty, "drain" while draining (tracing.GOODPUT_STATES).
        self.last_round_state = "idle"
        self.completed: List[Request] = []
        self.events: List[Tuple] = []
        self.outbox: List[Tuple] = []
        self.steps = 0
        self._occ_sum = 0.0
        self._occ_n = 0

    # -- drain lifecycle -----------------------------------------------------

    def start_drain(self, cause: str = "shrink") -> List[Request]:
        """Stop admitting; hand back the queued (unstarted) requests
        for re-routing. In-flight sequences keep decoding here until
        :attr:`drained`."""
        if not self.draining:
            self.draining = True
            _M_DRAINS.labels(cause=cause).inc()
            self.events.append((self.steps, "drain_start", cause))
        rerouted = self.queue.drain()
        for req in rerouted:
            req.reroutes += 1
            self.events.append((self.steps, "reroute", req.rid))
        return rerouted

    @property
    def drained(self) -> bool:
        return (self.draining and self.engine.active_count() == 0
                and len(self.queue) == 0)

    def migrate_requests(self, now: Optional[float] = None) -> List[Tuple]:
        """Graceful-drain step 2, warm-handoff form (the DEFAULT —
        docs/serve.md): every in-flight sequence leaves WITH its int8
        block-scaled cache blob and generated-so-far tokens, so a peer
        continues mid-sequence instead of re-prefilling (or instead of
        this replica lingering until its longest sequence finishes).
        Returns ``[(request, wire_blob, generated), ...]``; the cluster
        places them on peers with free slots."""
        out = []
        for slot, req in enumerate(self.engine.requests):
            if req is None:
                continue
            req, blob, generated = self.engine.migrate_out(
                slot, now, kind="migrate")
            self.events.append((self.steps, "migrate_out", req.rid,
                                len(generated)))
            out.append((req, blob, generated))
        return out

    def admit_migrated(self, req, blob, generated,
                       now: float = 0.0) -> int:
        """Land a migrated sequence (warm cache + decode state) in one
        of this replica's free slots."""
        slot = self.engine.admit_migrated(req, blob, generated, now)
        _M_MIGRATIONS.inc()
        self.events.append((self.steps, "migrate_in", req.rid, slot))
        return slot

    def migratable_slots(self) -> int:
        """Free slots available to receive migrated sequences (serving
        replicas only — a draining replica never admits)."""
        return 0 if self.draining else len(self.engine.free_slots())

    def abort(self, now: Optional[float] = None) -> List[Request]:
        """Replica kill: queued AND in-flight requests come back for
        re-routing (in-flight restart from their prompts on a peer —
        zero dropped requests)."""
        out = self.start_drain(cause="kill")
        aborted = self.engine.abort_all(now)
        for req in aborted:
            self.events.append((self.steps, "abort", req.rid))
        return out + aborted

    # -- the serving loop ----------------------------------------------------

    def run_step(self, now: float = 0.0) -> List[Request]:
        """One admit/decode/retire round; returns the requests that
        completed this round."""
        finished: List[Request] = []
        admitted = 0
        if not self.draining and self.role != "decode":
            for req in self.queue.take(len(self.engine.free_slots()),
                                       now):
                slot = self.engine.admit(req, now)
                admitted += 1
                self.events.append((self.steps, "admit", req.rid, slot))
                if self.engine.request_done(slot):
                    # 1-token/instant-EOS request: complete at prefill.
                    finished.append(self.engine.retire(slot, now))
                elif self.role == "prefill":
                    # Disaggregation: the freshly prefilled slot leaves
                    # NOW as a warm-KV wire blob; the cluster hands it
                    # to the decode pool this same round.
                    handoff = self.engine.migrate_out(slot, now,
                                                      kind="handoff")
                    self.outbox.append(handoff)
                    self.events.append((self.steps, "handoff_out",
                                        handoff[0].rid))
        occ = self.engine.active_count() / max(1, self.engine.slots)
        self._occ_sum += occ
        self._occ_n += 1
        _M_OCCUPANCY.labels(replica=self.name).set(occ)
        if self.role != "prefill":
            finished.extend(self.engine.step(now))
        if self.draining:
            self.last_round_state = "drain"
        elif self.role == "prefill":
            self.last_round_state = "prefill" if admitted else "idle"
        elif occ > 0.0 or finished:
            # A round that prefilled into a mixed replica still decodes
            # the same step, so "decode" wins the attribution.
            self.last_round_state = "decode"
        else:
            self.last_round_state = "idle"
        for req in finished:
            self.events.append((self.steps, "finish", req.rid,
                                len(req.tokens)))
        self.completed.extend(finished)
        self.steps += 1
        return finished

    def mean_occupancy(self) -> float:
        return self._occ_sum / self._occ_n if self._occ_n else 0.0

    def close(self) -> None:
        """Zero this replica's labeled gauges on departure (kill or
        finished drain) — replica names are monotonic, so stale series
        would otherwise accumulate one dead gauge per departed replica
        for the life of the process."""
        _M_OCCUPANCY.labels(replica=self.name).set(0)
        self.engine.close()
