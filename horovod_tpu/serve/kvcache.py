"""Paged ring-buffer KV cache for incremental GPT decode (docs/serve.md).

The cache is a plain pytree so one jitted decode step serves every
request mix: per layer ``k``/``v`` slabs laid out as (slots, max_len,
heads, head_dim), plus shared per-slot bookkeeping — ``pos`` (total
tokens written, the ring write head) and ``slot_pos`` (each cache
line's GLOBAL sequence position, -1 = empty). Sequences of different
lengths share the one compiled program because validity is data, not
shape: attention masks on ``slot_pos`` (occupied AND causally visible),
and a write at global position p lands in line ``p % max_len`` — past
``max_len`` the ring overwrites the oldest line, truncating attention
to the last ``max_len`` tokens.

Two storage formats, selected by ``kind``:

* ``"fp32"`` — k/v stored in the model dtype (the parity baseline).
* ``"int8"`` — block-scaled int8, one fp32 absmax scale per
  (slot, line, head) block: the same ``round(x * 127 / absmax)``
  recipe as ``ops/pallas_kernels.quantize_int8`` applied at KV-cache
  granularity (per head-vector instead of per 32x128 tile, so a
  single-token write stays one fused scatter). ~4x less HBM + wire
  per cached token; the decode parity bound vs fp32 is documented in
  docs/serve.md and enforced by tests/test_serve.py.

Whole-cache movement (slot migration between replicas, drain handoff)
reuses the Pallas wire path directly: :func:`export_slot` /
:func:`import_slot` ship a slot's lines through
``ops/pallas_kernels.quantize_int8`` — the EQuARX-style block-scaled
wire format gradients and MoE dispatch already ride.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops import pallas_kernels as pk

KINDS = ("fp32", "int8")


def init_cache(num_layers: int, slots: int, max_len: int, num_heads: int,
               head_dim: int, kind: str = "fp32",
               dtype: Any = jnp.float32) -> Dict[str, Any]:
    """Fresh all-empty cache pytree. ``kind`` picks the storage format
    (KINDS); ``dtype`` is the fp32-kind storage/compute dtype."""
    if kind not in KINDS:
        raise ValueError(f"unknown kv-cache kind {kind!r}; known: {KINDS}")
    shape = (slots, max_len, num_heads, head_dim)
    layers = []
    for _ in range(num_layers):
        if kind == "int8":
            layers.append({
                "k_q": jnp.zeros(shape, jnp.int8),
                "k_s": jnp.zeros(shape[:3], jnp.float32),
                "v_q": jnp.zeros(shape, jnp.int8),
                "v_s": jnp.zeros(shape[:3], jnp.float32),
            })
        else:
            layers.append({"k": jnp.zeros(shape, dtype),
                           "v": jnp.zeros(shape, dtype)})
    return {
        "layers": tuple(layers),
        "pos": jnp.zeros((slots,), jnp.int32),
        "slot_pos": jnp.full((slots, max_len), -1, jnp.int32),
    }


def cache_kind(cache: Dict[str, Any]) -> str:
    """Storage format, recovered from the pytree structure (the format
    is structural, so it is static under jit)."""
    return "int8" if "k_q" in cache["layers"][0] else "fp32"


def max_len(cache: Dict[str, Any]) -> int:
    return int(cache["slot_pos"].shape[1])


def num_slots(cache: Dict[str, Any]) -> int:
    return int(cache["slot_pos"].shape[0])


def cache_nbytes(cache: Dict[str, Any]) -> int:
    """Total bytes of the cache storage (the
    ``hvd_tpu_serve_kv_cache_bytes`` accounting — int8 shows the ~4x
    reduction over fp32 here)."""
    return sum(leaf.size * leaf.dtype.itemsize
               for leaf in jax.tree.leaves(cache))


# -- the block-scale recipe at KV granularity --------------------------------

def quantize_heads(x):
    """Block-scaled int8 over the trailing head_dim axis: one fp32
    absmax scale per head vector — ``pallas_kernels.quantize_int8``'s
    recipe (absmax/127, round-to-nearest, clip) at the granularity a
    single-token cache write needs. Returns ``(q, scales)`` with
    ``scales.shape == x.shape[:-1]``."""
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)
    scales = jnp.maximum(absmax, 1e-30) / 127.0
    q = jnp.clip(jnp.round(xf / scales[..., None]), -127, 127)
    return q.astype(jnp.int8), scales


def dequantize_heads(q, scales, dtype=jnp.float32):
    """Inverse of :func:`quantize_heads`."""
    return (q.astype(jnp.float32) * scales[..., None]).astype(dtype)


# -- write / read ------------------------------------------------------------

def layer_write(layer: Dict[str, Any], idx, k_new, v_new
                ) -> Dict[str, Any]:
    """Scatter the new tokens' K/V into their ring lines.

    ``idx`` is (slots, s_in) int32 — each new token's cache line
    (``global_pos % max_len``); ``k_new``/``v_new`` are
    (slots, s_in, heads, head_dim). One batched scatter, identical for
    prefill (s_in = prompt) and decode (s_in = 1)."""
    b = jnp.arange(idx.shape[0])[:, None]
    if "k_q" in layer:
        kq, ks = quantize_heads(k_new)
        vq, vs = quantize_heads(v_new)
        return {
            "k_q": layer["k_q"].at[b, idx].set(kq),
            "k_s": layer["k_s"].at[b, idx].set(ks),
            "v_q": layer["v_q"].at[b, idx].set(vq),
            "v_s": layer["v_s"].at[b, idx].set(vs),
        }
    return {"k": layer["k"].at[b, idx].set(k_new.astype(layer["k"].dtype)),
            "v": layer["v"].at[b, idx].set(v_new.astype(layer["v"].dtype))}


def layer_read(layer: Dict[str, Any], dtype=jnp.float32
               ) -> Tuple[Any, Any]:
    """The full (slots, max_len, heads, head_dim) K/V slabs in compute
    dtype (dequantized for the int8 kind); invalid lines are masked by
    the caller via ``slot_pos``."""
    if "k_q" in layer:
        return (dequantize_heads(layer["k_q"], layer["k_s"], dtype),
                dequantize_heads(layer["v_q"], layer["v_s"], dtype))
    return layer["k"].astype(dtype), layer["v"].astype(dtype)


def rewind_slots(cache: Dict[str, Any], new_pos) -> Dict[str, Any]:
    """Truncate every slot's sequence to ``new_pos`` (a (slots,) int32
    vector of global positions) by DATA ops alone: the write head moves
    back and every line at a global position >= its slot's new_pos is
    invalidated. The payload stays — masked lines are never read. This
    is how speculative decode discards rejected draft tokens and how
    prefix reuse forks a shared prompt at its common length; callers
    must not rewind across a ring wrap (a line overwritten since the
    rewind point is gone — the engine's wrap guard enforces this)."""
    new_pos = new_pos.astype(jnp.int32)
    sp = cache["slot_pos"]
    return {
        "layers": cache["layers"],
        "pos": new_pos,
        "slot_pos": jnp.where(sp >= new_pos[:, None], -1, sp),
    }


def reset_slot(cache: Dict[str, Any], slot) -> Dict[str, Any]:
    """Mark one slot empty (pos = 0, every line invalid). The k/v
    payload is left in place — ``slot_pos`` = -1 already masks it out
    of every read, so zeroing would be a wasted memory pass."""
    return {
        "layers": cache["layers"],
        "pos": cache["pos"].at[slot].set(0),
        "slot_pos": cache["slot_pos"].at[slot].set(-1),
    }


def write_slot(cache: Dict[str, Any], slot, single: Dict[str, Any]
               ) -> Dict[str, Any]:
    """Copy a 1-slot cache (e.g. a fresh prefill) into ``slot`` of a
    multi-slot cache of the same geometry/kind."""
    layers = tuple(
        {k: dst[k].at[slot].set(src[k][0]) for k in dst}
        for dst, src in zip(cache["layers"], single["layers"]))
    return {
        "layers": layers,
        "pos": cache["pos"].at[slot].set(single["pos"][0]),
        "slot_pos": cache["slot_pos"].at[slot].set(single["slot_pos"][0]),
    }


# -- wire movement: the Pallas block-quantized export ------------------------

def export_slot(cache: Dict[str, Any], slot: int,
                use_pallas: Optional[bool] = None,
                exact: bool = False) -> Dict[str, Any]:
    """One slot's cache lines as an int8 block-scaled wire blob —
    every fp32/model-dtype K/V leaf rides
    ``pallas_kernels.quantize_int8`` (int8 leaves ship as-is); the
    bookkeeping vectors travel exact. The int8 kind's fp32 SCALE leaves
    (``k_s``/``v_s``) also ship raw: re-quantizing a scale vector is
    lossy, and shipping it exact makes an int8 -> int8 migration a
    bit-exact round trip (tests/test_serve.py pins it). This is the
    warm-cache migration path: a draining replica can hand a long
    in-flight sequence to a peer at ~4x fewer bytes instead of
    re-running its whole prefill.

    ``exact=True`` ships EVERY leaf raw — the intra-host slot-copy
    form the shared-prefix cache uses (docs/serve.md): no wire, so no
    reason to round, and a forked prefix decodes bit-identically to a
    fresh prefill."""
    out_layers = []
    for layer in cache["layers"]:
        packed = {}
        for name, leaf in layer.items():
            arr = leaf[slot]
            if exact or arr.dtype == jnp.int8 or name.endswith("_s"):
                packed[name] = {"raw": arr}
            else:
                q, s, n = pk.quantize_int8(arr, use_pallas=use_pallas)
                packed[name] = {"q": q, "s": s, "n": n,
                                "shape": arr.shape,
                                "dtype": str(arr.dtype)}
        out_layers.append(packed)
    return {
        "layers": out_layers,
        "pos": cache["pos"][slot],
        "slot_pos": cache["slot_pos"][slot],
    }


def import_slot(cache: Dict[str, Any], slot: int, blob: Dict[str, Any],
                use_pallas: Optional[bool] = None) -> Dict[str, Any]:
    """Inverse of :func:`export_slot`: land a wire blob in ``slot`` of a
    same-geometry cache."""
    layers = []
    for dst, packed in zip(cache["layers"], blob["layers"]):
        new = {}
        for name, leaf in dst.items():
            item = packed[name]
            if "raw" in item:
                arr = item["raw"]
            else:
                arr = pk.dequantize_int8(
                    item["q"], item["s"], item["n"], item["shape"],
                    dtype=jnp.dtype(item["dtype"]),
                    use_pallas=use_pallas)
            new[name] = leaf.at[slot].set(arr)
        layers.append(new)
    return {
        "layers": tuple(layers),
        "pos": cache["pos"].at[slot].set(blob["pos"]),
        "slot_pos": cache["slot_pos"].at[slot].set(blob["slot_pos"]),
    }
