"""KerasEstimator — the reference's Spark Keras estimator
(spark/keras/estimator.py:106-390: serialize a keras model, train it
inside cluster workers under a Horovod DistributedOptimizer with the
broadcast/metric callbacks, return a transformer) re-hosted on the
executor pool + Store.

The keras model crosses the process boundary as (architecture JSON,
weights, serialized optimizer/loss) — keras models do not pickle — and
each worker rebuilds it, wraps the optimizer in
``horovod_tpu.tensorflow.DistributedOptimizer``, and fits on its rank
shard with ``BroadcastGlobalVariablesCallback`` +
``MetricAverageCallback``, exactly the remote-trainer recipe of the
reference (spark/keras/remote.py).
"""

from __future__ import annotations


from .common.config import runtime_env
from typing import Any, Dict, List, Optional

import numpy as np

from .estimator import (load_parquet_shard, load_parquet_val,
                         rank_shard, split_validation,
                         stage_data, validate_data_format)
from .store import Store


def _serialize_model(model) -> Dict[str, Any]:
    import tensorflow as tf

    return {
        "arch_json": model.to_json(),
        "weights": model.get_weights(),
        "optimizer": tf.keras.optimizers.serialize(model.optimizer)
        if model.optimizer is not None else None,
        "loss": model.loss if isinstance(model.loss, str) else None,
    }


def _keras_train_worker(store: Store, run_id: str,
                        blob: Dict[str, Any], loss, optimizer_cfg,
                        epochs: int, batch_size: int,
                        has_val: bool,
                        data_format: str = "pickle") -> Dict[str, Any]:
    """Runs in each executor worker (reference spark/keras/remote.py
    RemoteTrainer): rank-sharded fit under the TF shim's distributed
    optimizer + callbacks; rank 0 persists weights/history."""
    import tensorflow as tf

    import horovod_tpu as hvd
    import horovod_tpu.tensorflow as hvdtf

    hvd.init()
    nproc = max(int(runtime_env("NUM_PROC", "1")), 1)
    rank = int(runtime_env("PROC_ID", "0"))

    if data_format == "parquet":
        Xs, ys = load_parquet_shard(store, run_id, rank, nproc)
        val = load_parquet_val(store, run_id) if has_val else None
    else:
        X, y = store.read_obj(store.get_data_path(run_id, "train"))
        val = store.read_obj(store.get_data_path(run_id, "val")) \
            if has_val else None
        # Equalized shards: uneven per-epoch batch counts would
        # desynchronize the per-step allreduce collectives across
        # ranks (the reference remote trainer equalizes
        # steps_per_epoch too).
        Xs, ys = rank_shard(X, y, rank, nproc)
    if val is not None:
        val = (np.asarray(val[0]), np.asarray(val[1]))

    opt_cfg = optimizer_cfg or blob["optimizer"]
    opt = tf.keras.optimizers.deserialize(opt_cfg) if opt_cfg \
        else tf.keras.optimizers.SGD()
    if loss is None and blob["loss"] is None:
        raise ValueError(
            "loss is not serializable from the compiled model (only "
            "string losses cross the worker boundary); pass "
            "KerasEstimator(loss=...) explicitly")
    model = tf.keras.models.model_from_json(blob["arch_json"])
    model.set_weights(blob["weights"])
    model.compile(optimizer=hvdtf.DistributedOptimizer(opt),
                  loss=loss or blob["loss"])

    hist = model.fit(
        Xs, ys, epochs=epochs, batch_size=batch_size, verbose=0,
        validation_data=val,
        callbacks=[hvdtf.BroadcastGlobalVariablesCallback(0),
                   hvdtf.MetricAverageCallback()])

    history = [float(v) for v in hist.history["loss"]]
    val_history = [float(v)
                   for v in hist.history.get("val_loss", [])]
    if rank == 0:
        store.write_obj(
            store.path_join(store.get_checkpoint_path(run_id),
                            "keras_final.pkl"),
            {"arch_json": blob["arch_json"],
             "weights": model.get_weights()})
        store.write_obj(
            store.path_join(store.get_logs_path(run_id),
                            "history.pkl"),
            {"train": history, "val": val_history})
    return {"rank": rank, "history": history,
            "val_history": val_history}


class TrainedKerasModel:
    """The fitted transformer (reference KerasModel Spark Transformer):
    host-side batched predict over the persisted weights."""

    def __init__(self, model, store: Store, run_id: str,
                 history=None, val_history=None):
        self.model = model
        self.store = store
        self.run_id = run_id
        self.history = history or []
        self.val_history = val_history or []

    @classmethod
    def load(cls, store: Store, run_id: str) -> "TrainedKerasModel":
        import tensorflow as tf

        blob = store.read_obj(store.path_join(
            store.get_checkpoint_path(run_id), "keras_final.pkl"))
        model = tf.keras.models.model_from_json(blob["arch_json"])
        model.set_weights(blob["weights"])
        history: List[float] = []
        val_history: List[float] = []
        hist_path = store.path_join(store.get_logs_path(run_id),
                                    "history.pkl")
        if store.exists(hist_path):
            logged = store.read_obj(hist_path)
            history = logged.get("train", [])
            val_history = logged.get("val", [])
        return cls(model, store, run_id, history, val_history)

    def transform(self, X, batch_size: int = 1024) -> np.ndarray:
        outs = [np.asarray(self.model(X[i:i + batch_size]))
                for i in range(0, len(X), batch_size)]
        if outs:
            return np.concatenate(outs)
        out_shape = tuple(d for d in self.model.output_shape[1:])
        return np.empty((0,) + out_shape, np.float32)


class KerasEstimator:
    """fit/transform for tf.keras models over the executor pool
    (reference spark/keras/estimator.py KerasEstimator).

    Usage::

        model = tf.keras.Sequential([...]); model.compile(...)
        est = KerasEstimator(model=model, store=store, num_proc=2,
                             epochs=5, batch_size=32)
        trained = est.fit(X, y)
        pred = trained.transform(X_test)
    """

    def __init__(self, model, store: Optional[Store] = None,
                 loss: Optional[str] = None, optimizer=None,
                 num_proc: int = 2, epochs: int = 1,
                 batch_size: int = 32, run_id: Optional[str] = None,
                 worker_env: Optional[Dict[str, str]] = None,
                 data_format: str = "pickle"):
        validate_data_format(data_format)
        self.model = model
        self.store = store
        self.loss = loss
        self.optimizer = optimizer
        self.num_proc = num_proc
        self.epochs = epochs
        self.batch_size = batch_size
        self.run_id = run_id
        self.worker_env = worker_env
        self.data_format = data_format

    def fit(self, X, y, validation=None,
            executor=None) -> TrainedKerasModel:
        import time

        import tensorflow as tf

        from .executor import Executor

        if self.store is None:
            raise ValueError("KerasEstimator requires a store=")
        run_id = self.run_id or f"krun_{int(time.time() * 1000):x}"
        X, y, validation = split_validation(X, y, validation)
        stage_data(self.store, run_id, X, y, validation,
                   self.data_format, num_shards=self.num_proc)

        blob = _serialize_model(self.model)
        opt_cfg = tf.keras.optimizers.serialize(self.optimizer) \
            if self.optimizer is not None else None
        args = (self.store, run_id, blob, self.loss, opt_cfg,
                self.epochs, self.batch_size, validation is not None,
                self.data_format)
        if executor is not None:
            results = executor.run(_keras_train_worker, args=args)
        else:
            with Executor(np=self.num_proc,
                          env=self.worker_env) as ex:
                results = ex.run(_keras_train_worker, args=args)

        del results  # rank order only; load() reads the persisted
        # history so the Store stays the single source of truth.
        return TrainedKerasModel.load(self.store, run_id)
