"""Store abstraction — persistent artifact storage for estimator runs.

Reference: horovod/spark/common/store.py:1-504 (``Store`` with
LocalStore/HDFSStore: per-run checkpoint/logs directories, train/val data
paths, read/write/exists primitives, ``Store.create`` scheme dispatch).

TPU rebuild: the capability without the Spark/HDFS dependency — a small
filesystem protocol with a local implementation and a gated GCS
implementation (the storage TPU pods actually sit next to). Arrays and
objects cross as pickle blobs; orbax checkpoints write through
``get_checkpoint_path`` directly.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Iterator


class Store:
    """Abstract per-run artifact store (reference store.py Store)."""

    @classmethod
    def create(cls, prefix_path: str, **kwargs) -> "Store":
        """Scheme dispatch (reference Store.create: HDFS vs local —
        store.py:60-78). Any URL scheme (hdfs://, s3://, memory://, ...)
        routes to the fsspec-backed store; gs:// prefers the dedicated
        GCS store when gcsfs is present."""
        if prefix_path.startswith("gs://"):
            # No fsspec fallback: resolving gs:// through fsspec needs
            # the same gcsfs package, so the curated error is strictly
            # more actionable.
            return GCSStore(prefix_path, **kwargs)
        if "://" in prefix_path:
            return FsspecStore(prefix_path, **kwargs)
        return LocalStore(prefix_path, **kwargs)

    # -- filesystem primitives --------------------------------------------

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def read(self, path: str) -> bytes:
        raise NotImplementedError

    def write(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    def mkdirs(self, path: str) -> None:
        raise NotImplementedError

    def listdir(self, path: str) -> Iterator[str]:
        raise NotImplementedError

    def path_join(self, *parts: str) -> str:
        raise NotImplementedError

    def open(self, path: str, mode: str = "rb"):
        """Streaming file handle — the primitive the columnar (parquet)
        data path reads/writes through."""
        raise NotImplementedError

    # -- object layer ------------------------------------------------------

    def write_obj(self, path: str, obj: Any) -> None:
        self.write(path, pickle.dumps(obj))

    def read_obj(self, path: str) -> Any:
        return pickle.loads(self.read(path))

    # -- run layout (reference: get_checkpoint_path/get_logs_path/
    #    get_train_data_path, store.py) -----------------------------------

    def prefix(self) -> str:
        raise NotImplementedError

    def get_run_path(self, run_id: str) -> str:
        return self.path_join(self.prefix(), "runs", run_id)

    def get_checkpoint_path(self, run_id: str) -> str:
        return self.path_join(self.get_run_path(run_id), "checkpoints")

    def get_logs_path(self, run_id: str) -> str:
        return self.path_join(self.get_run_path(run_id), "logs")

    def get_data_path(self, run_id: str, name: str = "train") -> str:
        return self.path_join(self.get_run_path(run_id),
                              f"{name}_data.pkl")


class LocalStore(Store):
    """Filesystem store rooted at ``prefix_path`` (reference LocalStore)."""

    def __init__(self, prefix_path: str):
        self._prefix = os.path.abspath(prefix_path)
        os.makedirs(self._prefix, exist_ok=True)

    def prefix(self) -> str:
        return self._prefix

    def path_join(self, *parts: str) -> str:
        return os.path.join(*parts)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def read(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def write(self, path: str, data: bytes) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    def mkdirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def listdir(self, path: str):
        return iter(sorted(os.listdir(path)) if os.path.isdir(path)
                    else [])

    def open(self, path: str, mode: str = "rb"):
        if "w" in mode:
            os.makedirs(os.path.dirname(path), exist_ok=True)
        return open(path, mode)


class FsspecStore(Store):
    """URL-addressed store over any fsspec filesystem — the HDFSStore
    analog (reference store.py HDFSStore:1-504 rides pyarrow's HDFS
    client; fsspec is the ecosystem's superset: hdfs://, s3://, gcs://,
    memory://, ...). The filesystem is resolved once from the prefix
    scheme; paths keep their fully-qualified URL form so run layouts
    copy-paste between backends."""

    def __init__(self, prefix_path: str, **storage_options):
        import fsspec

        self._fs, _ = fsspec.core.url_to_fs(prefix_path,
                                            **storage_options)
        self._prefix = prefix_path.rstrip("/")
        self._fs.makedirs(self._strip(self._prefix), exist_ok=True)

    def _strip(self, path: str) -> str:
        return self._fs._strip_protocol(path)

    def prefix(self) -> str:
        return self._prefix

    def path_join(self, *parts: str) -> str:
        return "/".join(p.strip("/") if i else p.rstrip("/")
                        for i, p in enumerate(parts))

    def exists(self, path: str) -> bool:
        return self._fs.exists(self._strip(path))

    def read(self, path: str) -> bytes:
        with self._fs.open(self._strip(path), "rb") as f:
            return f.read()

    def write(self, path: str, data: bytes) -> None:
        p = self._strip(path)
        parent = p.rsplit("/", 1)[0]
        if parent:
            self._fs.makedirs(parent, exist_ok=True)
        with self._fs.open(p, "wb") as f:
            f.write(data)

    def mkdirs(self, path: str) -> None:
        self._fs.makedirs(self._strip(path), exist_ok=True)

    def listdir(self, path: str):
        p = self._strip(path)
        if not self._fs.exists(p):
            return iter([])
        return iter(sorted(
            name.rsplit("/", 1)[-1]
            for name in self._fs.ls(p, detail=False)))

    def open(self, path: str, mode: str = "rb"):
        p = self._strip(path)
        if "w" in mode:
            parent = p.rsplit("/", 1)[0]
            if parent:
                self._fs.makedirs(parent, exist_ok=True)
        return self._fs.open(p, mode)


class GCSStore(Store):
    """GCS store (the HDFSStore analog for TPU pods). Gated on gcsfs /
    fsspec being installed — this image has neither, so construction
    raises with a clear message rather than half-working."""

    def __init__(self, prefix_path: str, **kwargs):
        try:
            import gcsfs  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "GCSStore requires gcsfs; pip install gcsfs or use a "
                "LocalStore prefix (reference parity: HDFSStore likewise "
                "requires pyarrow/hdfs)") from e
        import gcsfs

        self._fs = gcsfs.GCSFileSystem(**kwargs)
        self._prefix = prefix_path.rstrip("/")

    def prefix(self) -> str:
        return self._prefix

    def path_join(self, *parts: str) -> str:
        return "/".join(p.strip("/") if i else p.rstrip("/")
                        for i, p in enumerate(parts))

    def exists(self, path: str) -> bool:  # pragma: no cover - needs GCS
        return self._fs.exists(path)

    def read(self, path: str) -> bytes:  # pragma: no cover - needs GCS
        with self._fs.open(path, "rb") as f:
            return f.read()

    def write(self, path: str, data: bytes) -> None:  # pragma: no cover
        with self._fs.open(path, "wb") as f:
            f.write(data)

    def mkdirs(self, path: str) -> None:  # pragma: no cover - needs GCS
        pass  # GCS has no directories

    def listdir(self, path: str):  # pragma: no cover - needs GCS
        return iter(self._fs.ls(path))

    def open(self, path: str, mode: str = "rb"):  # pragma: no cover
        return self._fs.open(path, mode)
