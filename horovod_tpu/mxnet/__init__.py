"""MXNet binding shim — the reference ``horovod.mxnet`` API surface
hosted on the TPU-native collective engine.

Reference: horovod/mxnet/__init__.py:39-149 (DistributedOptimizer wrapping
``mx.optimizer.Optimizer`` with per-gradient allreduce folded into
``rescale_grad``; gluon DistributedTrainer overriding ``_allreduce_grads``;
``broadcast_parameters`` incl. deferred-initialization injection) +
horovod/mxnet/mpi_ops.py:54-261 (allreduce(_)/allgather/broadcast(_)/
alltoall on NDArrays).

Role in the TPU framework: same as the torch shim — host-side MXNet
components (data pipelines, legacy gluon models, evaluation) get the five
collectives backed by the engine/controller/fusion machinery so a
migration can move one piece at a time. Tensors cross at the numpy
boundary via ``NDArray.asnumpy()`` / ``tensor[:] = ...``; the shim is
duck-typed against that protocol, so it is importable (and testable)
without mxnet installed — only ``DistributedTrainer`` requires the real
``mx.gluon.Trainer`` base class.
"""

from __future__ import annotations

import warnings
from typing import Optional

import numpy as np

import horovod_tpu as _hvd
from horovod_tpu.ops.collectives import ReduceOp

try:  # pragma: no cover - exercised only where mxnet is installed
    import mxnet as mx

    _HAS_MXNET = True
except ImportError:
    mx = None
    _HAS_MXNET = False

# re-exported basics (reference mxnet/__init__.py surface)
init = _hvd.init
shutdown = _hvd.shutdown
is_initialized = _hvd.is_initialized
rank = _hvd.rank
size = _hvd.size
local_rank = _hvd.local_rank
local_size = _hvd.local_size
Average, Sum, Adasum, Min, Max, Product = (
    _hvd.Average, _hvd.Sum, _hvd.Adasum, _hvd.Min, _hvd.Max, _hvd.Product)
# object helpers (reference mxnet/functions.py:27-92 broadcast_object /
# allgather_object — cloudpickle over the engine's byte collectives)
broadcast_object = _hvd.broadcast_object
allgather_object = _hvd.allgather_object
# capability queries (reference mxnet re-exports of basics.py:160-258)
from horovod_tpu.common.basics import export_capability_queries as _ecq

_ecq(globals())


def _engine():
    from horovod_tpu.common import basics

    return basics.context().engine


def _to_numpy(tensor) -> np.ndarray:
    """NDArray / numpy / buffer -> host numpy (the mpi_ops.cc
    tensor_util.cc boundary)."""
    if hasattr(tensor, "asnumpy"):
        return tensor.asnumpy()
    return np.asarray(tensor)


def _replicated(tensor):
    return _engine().replicate(_to_numpy(tensor))


def _to_host(dt) -> np.ndarray:
    return np.asarray(dt.addressable_shards[0].data)[0]


def _write_back(tensor, value: np.ndarray):
    """In-place write honoring the NDArray protocol (``t[:] = v``)."""
    if tensor.shape == ():
        raise ValueError("in-place collectives need a non-scalar tensor")
    tensor[:] = value
    return tensor


# -- collectives (reference mxnet/mpi_ops.py) -------------------------------

def allreduce(tensor, average: bool = True, name: Optional[str] = None,
              priority: int = 0, prescale_factor: float = 1.0,
              postscale_factor: float = 1.0):
    """Reference mpi_ops.py:54-101 — note the mxnet surface uses
    ``average: bool`` rather than a ReduceOp. ``priority`` orders the
    mxnet engine's async dispatch; XLA's scheduler owns ordering here, so
    it is accepted and ignored."""
    op = Average if average else Sum
    out = _engine().allreduce(_replicated(tensor), op, name,
                              prescale_factor, postscale_factor)
    result = _to_host(out)
    if hasattr(tensor, "asnumpy") and mx is not None:
        return mx.nd.array(result, dtype=result.dtype)
    return result.astype(_to_numpy(tensor).dtype)


def allreduce_(tensor, average: bool = True, name: Optional[str] = None,
               priority: int = 0, prescale_factor: float = 1.0,
               postscale_factor: float = 1.0):
    return _write_back(tensor, _to_numpy(
        allreduce(tensor, average, name, priority, prescale_factor,
                  postscale_factor)))


def allgather(tensor, name: Optional[str] = None, priority: int = 0):
    out = _to_host(_engine().allgather(_replicated(tensor), name))
    result = out.reshape((-1,) + tuple(_to_numpy(tensor).shape[1:]))
    if hasattr(tensor, "asnumpy") and mx is not None:
        return mx.nd.array(result, dtype=result.dtype)
    return result


def broadcast(tensor, root_rank: int = 0, name: Optional[str] = None,
              priority: int = 0):
    out = _to_host(_engine().broadcast(_replicated(tensor), root_rank,
                                       name))
    if hasattr(tensor, "asnumpy") and mx is not None:
        return mx.nd.array(out, dtype=out.dtype)
    return out


def broadcast_(tensor, root_rank: int = 0, name: Optional[str] = None,
               priority: int = 0):
    return _write_back(tensor, _to_numpy(
        broadcast(tensor, root_rank, name, priority)))


def alltoall(tensor, splits=None, name: Optional[str] = None,
             priority: int = 0):
    e = _engine()
    if splits is not None:
        return e.alltoallv(_to_numpy(tensor), splits, name)
    out = _to_host(e.alltoall(_replicated(tensor), name))
    return out


# -- DistributedOptimizer (reference mxnet/__init__.py:39-84) ---------------

class DistributedOptimizer:
    """Wraps an mxnet optimizer: ``update``/``update_multi_precision``
    allreduce the gradient (SUM) before delegating, with the average
    folded into ``rescale_grad`` (the reference's trick: normalizing
    rescale_grad by size is equivalent to, and faster than, averaging in
    the collective — mxnet/__init__.py:44-48).

    Duck-typed delegation wrapper (the reference subclasses
    ``mx.optimizer.Optimizer`` purely for isinstance; all behavior is
    delegation there too)."""

    def __init__(self, optimizer, gradient_predivide_factor: float = 1.0):
        self._optimizer = optimizer
        self._optimizer.rescale_grad *= (
            gradient_predivide_factor / size())
        self._gradient_predivide_factor = gradient_predivide_factor

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def create_state_multi_precision(self, index, weight):
        return self._optimizer.create_state_multi_precision(index, weight)

    def _do_allreduce(self, index, grad):
        if size() == 1:
            return
        pre = 1.0 / self._gradient_predivide_factor
        if isinstance(index, (tuple, list)):
            for i in range(len(index)):
                allreduce_(grad[i], average=False, name=str(index[i]),
                           priority=-i, prescale_factor=pre)
        else:
            allreduce_(grad, average=False, name=str(index),
                       prescale_factor=pre)

    def update(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update(index, weight, grad, state)

    def update_multi_precision(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update_multi_precision(index, weight, grad, state)

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def set_lr_mult(self, args_lr_mult):
        self._optimizer.set_lr_mult(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self._optimizer.set_wd_mult(args_wd_mult)


def allreduce_grads_inplace(params, prefix: str = "",
                            gradient_predivide_factor: float = 1.0
                            ) -> None:
    """SUM-allreduce every trainable parameter's gradient in place — the
    body of DistributedTrainer._allreduce_grads (reference
    mxnet/__init__.py:128-139), shared so the flow is testable without
    the gluon Trainer base class. ``params``: iterable of objects with
    ``grad_req`` and ``list_grad()``."""
    if size() == 1:
        return
    pre = 1.0 / gradient_predivide_factor
    for i, param in enumerate(params):
        if param.grad_req != "null":
            allreduce_(param.list_grad()[0], average=False,
                       name=prefix + str(i), priority=-i,
                       prescale_factor=pre)


if _HAS_MXNET:  # pragma: no cover - requires mxnet
    class DistributedTrainer(mx.gluon.Trainer):
        """Reference mxnet/__init__.py:92-139: gluon Trainer whose
        gradient reduction rides the engine's collectives instead of
        kvstore, with averaging folded into ``_scale``."""

        def __init__(self, params, optimizer, optimizer_params=None,
                     gradient_predivide_factor: float = 1.0,
                     prefix: Optional[str] = None):
            if isinstance(optimizer, DistributedOptimizer):
                optimizer = optimizer._optimizer
                warnings.warn("DistributedTrainer does not take "
                              "DistributedOptimizer as its optimizer. "
                              "We have unwrapped it for you.")
            super().__init__(params, optimizer,
                             optimizer_params=optimizer_params,
                             kvstore=None)
            self._scale *= gradient_predivide_factor / size()
            self._gradient_predivide_factor = gradient_predivide_factor
            assert prefix is None or isinstance(prefix, str)
            self._prefix = prefix if prefix else ""

        def _allreduce_grads(self):
            allreduce_grads_inplace(self._params, self._prefix,
                                    self._gradient_predivide_factor)
else:
    class DistributedTrainer:  # noqa: D401 - import-gated stub
        """Requires mxnet (gluon Trainer base class)."""

        def __init__(self, *a, **k):
            raise ImportError(
                "DistributedTrainer requires mxnet; the rest of the "
                "horovod_tpu.mxnet surface (collectives, "
                "DistributedOptimizer, broadcast_parameters) is "
                "mxnet-optional")


# -- broadcast_parameters (reference mxnet/__init__.py:142-196) -------------

def _append_broadcast_init(param, root_rank, name):
    import types

    init_impl = getattr(param, "_init_impl")

    def wrapped_init_impl(self, *args, **kwargs):
        init_impl(*args, **kwargs)
        broadcast_(self.data(), root_rank=root_rank, name=name)

    return types.MethodType(wrapped_init_impl, param)


def broadcast_parameters(params, root_rank: int = 0,
                         prefix: Optional[str] = None) -> None:
    """Broadcast a dict / gluon ParameterDict of parameters from
    ``root_rank``; deferred-initialization parameters get the broadcast
    injected after their init (reference mxnet/__init__.py:142-196)."""
    if size() == 1:
        return
    assert prefix is None or isinstance(prefix, str)
    prefix = prefix if prefix else ""
    if not isinstance(params, dict) and not hasattr(params, "items"):
        raise ValueError(f"invalid params of type: {type(params)}")

    deferred_error = ()
    if _HAS_MXNET:  # pragma: no cover - requires mxnet
        deferred_error = (mx.gluon.parameter.DeferredInitializationError,)

    tensors, names = [], []
    for name, p in sorted(params.items()):
        try:
            if hasattr(p, "data") and callable(p.data):
                tensors.append(p.data())
            else:
                tensors.append(p)
            names.append(prefix + str(name))
        except deferred_error:  # pragma: no cover - requires mxnet
            p._init_impl = _append_broadcast_init(
                p, root_rank, prefix + str(name))

    for tensor, name in zip(tensors, names):
        broadcast_(tensor, root_rank, name=name)
