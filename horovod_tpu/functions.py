"""Object broadcast/allgather utilities.

Reference: horovod/tensorflow/functions.py:47-172 (broadcast_object /
allgather_object serialize arbitrary Python objects through the byte-tensor
collectives) and torch/functions.py:30-108 (broadcast_parameters /
broadcast_optimizer_state).

Under single-controller JAX a Python object held by the controller is
already "on every rank", so in single-process mode these are (checked)
identities; in multi-process mode they serialize over the process-level
coordination channel (jax multihost utils / the distributed KV store) —
the same role the reference's byte-tensor bcast plays.
"""

from __future__ import annotations

import io
import itertools
import pickle
import threading
from typing import Any, List

import numpy as np

from .common import basics

# Per-NAME sequence numbers keep KV keys unique across repeated calls with
# the same name (the coordination-service KV store has set-once semantics;
# without this, epoch 2's broadcast would collide with — or worse, silently
# read — epoch 1's bytes). Counters are per name, not global: all processes
# that USE a given name must execute the same call sequence for that name
# (the reference's name-keyed negotiation makes the same assumption), but
# calls under other names — e.g. a process-set-scoped broadcast only set
# members perform — no longer desynchronize unrelated names' counters on
# the processes that skip them. Corollary: a name must not be used both
# set-scoped and world-scoped.
_seq_lock = threading.Lock()
_seq: dict = {}


def _next_seq(name: str) -> int:
    with _seq_lock:
        it = _seq.setdefault(name, itertools.count())
        return next(it)


def _kv_broadcast_bytes(data: bytes, root_rank: int, key: str) -> bytes:
    """Broadcast bytes across processes via the distributed KV store."""
    import jax

    if jax.process_count() == 1:
        return data
    from jax._src import distributed as jdist

    client = jdist.global_state.client
    if jax.process_index() == root_rank:
        client.key_value_set_bytes(key, data)
        return data
    return client.blocking_key_value_get_bytes(key, 60_000)


def broadcast_object(obj: Any, root_rank: int = 0,
                     name: str = "obj") -> Any:
    """Serialize ``obj`` on root and return it on every process
    (reference: functions.py:98-135)."""
    buf = io.BytesIO()
    pickle.dump(obj, buf, protocol=pickle.HIGHEST_PROTOCOL)
    key = f"hvd_tpu/bcast/{name}/{_next_seq(name)}"
    data = _kv_broadcast_bytes(buf.getvalue(), root_rank, key)
    return pickle.loads(data)


def allgather_object(obj: Any, name: str = "obj") -> List[Any]:
    """Gather one object per process into a list ordered by process index
    (reference: functions.py:137-172)."""
    import jax

    if jax.process_count() == 1:
        return [obj]
    from jax._src import distributed as jdist

    client = jdist.global_state.client
    me = jax.process_index()
    n = jax.process_count()
    seq = _next_seq(name)
    buf = io.BytesIO()
    pickle.dump(obj, buf, protocol=pickle.HIGHEST_PROTOCOL)
    client.key_value_set_bytes(f"hvd_tpu/ag/{name}/{seq}/{me}",
                               buf.getvalue())
    out = []
    for r in range(n):
        data = client.blocking_key_value_get_bytes(
            f"hvd_tpu/ag/{name}/{seq}/{r}", 60_000)
        out.append(pickle.loads(data))
    return out


def broadcast_variables(tree, root_rank: int = 0):
    """Eager broadcast of a pytree of arrays via the engine; every leaf
    comes back with its original shape holding root's value (reference:
    tensorflow/functions.py:47 broadcast_variables — in-place same-shape
    assignment). For the in-jit path use
    horovod_tpu.optim.broadcast_parameters."""
    ctx = basics.context()
    import jax

    def one(v):
        arr = np.asarray(v)
        # Replicate explicitly: _as_distributed would mis-read a leaf whose
        # leading dim happens to equal world size as an already rank-major
        # stack and scatter it, corrupting e.g. an (8, d) weight on an
        # 8-rank mesh.
        out = ctx.engine.broadcast(ctx.engine.replicate(arr), root_rank)
        # Rows are identical post-broadcast; fetch only this process's
        # first addressable shard row instead of device_get'ing the full
        # (size, *shape) stack (a size× overfetch on big param trees).
        shard = np.asarray(out.addressable_shards[0].data)
        return shard[0].astype(arr.dtype, copy=False)

    return jax.tree.map(one, tree)
