"""Worker entry for ElasticRayExecutor.run(): loads the cloudpickled
user function, executes it (the user's fn does its own
``@hvd.elastic.run`` state handling, like the reference's
worker_fn contract, ray/elastic.py:241-264), and drops this rank's
return value where the driver collects it."""

import os
import pickle
import sys
from ..common.config import runtime_env


def main(fn_path: str, results_dir: str) -> int:
    import cloudpickle

    with open(fn_path, "rb") as f:
        worker_fn = cloudpickle.load(f)
    value = worker_fn()
    rank = runtime_env("PROC_ID", "0")
    world = runtime_env("NUM_PROC", "1")
    os.makedirs(results_dir, exist_ok=True)
    # World size in the name lets the driver keep only the final
    # topology's values when earlier epochs were aborted mid-write.
    name = f"rank_{rank}_of_{world}.pkl"
    tmp = os.path.join(results_dir, f".{name}.tmp")
    with open(tmp, "wb") as f:
        pickle.dump(value, f)
    os.replace(tmp, os.path.join(results_dir, name))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1], sys.argv[2]))
