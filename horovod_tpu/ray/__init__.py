"""Ray cluster integration — the reference's ``horovod.ray`` surface
(ray/runner.py:90-482: ``BaseHorovodWorker``, ``Coordinator``,
``RayExecutor``; ray/elastic.py: ``RayHostDiscovery``) re-hosted on the
TPU engine.

Design collapse vs the reference: the reference needs ``NodeColocator``
actors + placement groups to pin NCCL peers and pick NICs
(ray/runner.py:90-176). Here workers bootstrap ONE ``jax.distributed``
world from env vars (the same bootstrap the CLI launcher and the
process-pool :mod:`horovod_tpu.executor` use), so colocation reduces to
grouping registered hostnames into local ranks — the ``Coordinator``'s
job — and the data plane is XLA-over-ICI/DCN, not NCCL-over-NIC.

``ray`` is imported lazily at call time: the adapter is importable (and
its protocol testable, via an API-faithful stand-in installed in
``sys.modules['ray']`` — see tests/fake_ray.py) on machines without
ray. On a real cluster, actors are real Ray processes; each worker
process sets its slot env THEN initializes the engine, exactly like a
launcher-spawned slot.
"""

from __future__ import annotations

import os
import socket
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional


def _ray():
    import ray

    return ray


# -- settings (reference ray/runner.py:22-42 MiniSettings) ------------------

@dataclass
class MiniSettings:
    """Start/placement knobs (reference MiniSettings)."""

    timeout_s: int = 300
    placement_group_timeout_s: int = 100
    extra_env: Dict[str, str] = field(default_factory=dict)

    @property
    def start_timeout(self) -> int:
        return self.timeout_s


# -- worker actor (reference ray/runner.py:48-88 BaseHorovodWorker) ---------

class BaseHorovodWorker:
    """Runs inside a Ray actor process. Mirrors the reference's worker:
    report hostname, accept env updates, execute functions. The engine
    (hvd.init()) is created lazily by the user's fn AFTER env arrives,
    so the jax.distributed bootstrap sees the slot env."""

    def __init__(self, world_rank: int = 0, world_size: int = 1):
        self.world_rank = world_rank
        self.world_size = world_size
        self.executable: Any = None

    def hostname(self) -> str:
        return socket.gethostname()

    def free_port(self) -> int:
        """Probe a free port ON THIS HOST — the jax.distributed
        coordinator binds inside rank 0's process, so the port must be
        free where rank 0 lives, not on the driver machine."""
        s = socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def update_env_vars(self, env_vars: Dict[str, str]) -> None:
        """Apply BEFORE any jax/engine import in this process."""
        os.environ.update({k: str(v) for k, v in env_vars.items()})

    def env_vars(self) -> Dict[str, str]:
        return dict(os.environ)

    def start_executable(self, executable_cls: Optional[type] = None,
                         executable_args: Optional[list] = None,
                         executable_kwargs: Optional[dict] = None) -> None:
        """Instantiate the user's class inside the worker (reference
        start_executable — after env arrives, so its __init__ may init
        the engine)."""
        if executable_cls is not None:
            self.executable = executable_cls(*(executable_args or []),
                                             **(executable_kwargs or {}))

    def execute(self, fn: Callable) -> Any:
        """fn(executable) — reference worker execute contract."""
        return fn(self.executable)

    def shutdown_engine(self) -> None:
        import horovod_tpu as hvd

        if hvd.is_initialized():
            hvd.shutdown()


# -- coordinator (reference ray/runner.py:178-248) --------------------------

class Coordinator:
    """Collects (hostname, world_rank) registrations and derives the
    per-worker slot env: global/local/cross ranks plus the
    jax.distributed coordinator address (reference
    establish_rendezvous builds the gloo rendezvous env the same way).
    """

    def __init__(self, settings: Optional[MiniSettings] = None):
        self.settings = settings or MiniSettings()
        self.hostnames_by_rank: Dict[int, str] = {}
        self.coordinator_port: Optional[int] = None

    @property
    def world_size(self) -> int:
        return len(self.hostnames_by_rank)

    @property
    def hoststring(self) -> str:
        hosts: Dict[str, List[int]] = {}
        for rank in sorted(self.hostnames_by_rank):
            hosts.setdefault(self.hostnames_by_rank[rank], []).append(rank)
        return ",".join(f"{h}:{len(r)}" for h, r in hosts.items())

    def register(self, hostname: str, world_rank: int) -> None:
        self.hostnames_by_rank[world_rank] = hostname

    def finalize_registration(self) -> Dict[int, Dict[str, str]]:
        """Per-rank env (reference returns rank/size/local/cross vars;
        here the HVD_TPU_* bootstrap the engine's topology reads)."""
        by_host: Dict[str, List[int]] = {}
        for rank in sorted(self.hostnames_by_rank):
            by_host.setdefault(self.hostnames_by_rank[rank], []).append(rank)

        rank0_host = self.hostnames_by_rank.get(0, "127.0.0.1")
        if self.coordinator_port is None:
            # Fallback probe on the CALLING machine — callers that can
            # reach rank 0's host (RayExecutor.start does, via the
            # worker's free_port()) should set coordinator_port first:
            # a port free here may be taken over there.
            s = socket.socket()
            s.bind(("", 0))
            self.coordinator_port = s.getsockname()[1]
            s.close()
        coordinator = f"{rank0_host}:{self.coordinator_port}"

        envs: Dict[int, Dict[str, str]] = {}
        for host, ranks in by_host.items():
            for local_rank, rank in enumerate(ranks):
                envs[rank] = {
                    "HVD_TPU_COORDINATOR": coordinator,
                    "HVD_TPU_NUM_PROC": str(self.world_size),
                    "HVD_TPU_PROC_ID": str(rank),
                    "HVD_TPU_LOCAL_RANK": str(local_rank),
                    "HVD_TPU_LOCAL_SIZE": str(len(ranks)),
                    "HVD_TPU_CROSS_RANK":
                        str(sorted(by_host).index(host)),
                    "HVD_TPU_CROSS_SIZE": str(len(by_host)),
                    **self.settings.extra_env,
                }
        return envs


# -- executor (reference ray/runner.py:250-482) -----------------------------

class RayExecutor:
    """Persistent Horovod worker pool on Ray actors.

    Surface parity with the reference RayExecutor: ``create_settings``,
    ``start(executable_cls=...)``, ``run``, ``run_remote``, ``execute``,
    ``execute_single``, ``shutdown``, ``num_workers``.

    Example::

        ray.init(address="auto")
        ex = RayExecutor(RayExecutor.create_settings(300), num_workers=4)
        ex.start()
        ex.run(train_fn)          # fn may hvd.init() + use collectives
        ex.shutdown()
    """

    @classmethod
    def create_settings(cls, timeout_s: int = 300,
                        ssh_identity_file: Optional[str] = None,
                        ssh_str: Optional[str] = None) -> MiniSettings:
        # ssh args accepted for signature parity; Ray actors need no ssh.
        return MiniSettings(timeout_s=timeout_s)

    def __init__(self, settings: Optional[MiniSettings] = None,
                 num_workers: int = 1, cpus_per_worker: int = 1,
                 use_gpu: bool = False, gpus_per_worker: int = 0,
                 env: Optional[Dict[str, str]] = None):
        self.settings = settings or MiniSettings()
        self._num_workers = int(num_workers)
        self.cpus_per_worker = cpus_per_worker
        self.use_gpu = use_gpu          # accepted for parity; TPU/CPU here
        self.gpus_per_worker = gpus_per_worker
        self.env = dict(env or {})
        self.workers: List[Any] = []
        self.coordinator = Coordinator(self.settings)
        self._started = False

    @property
    def num_workers(self) -> int:
        return self._num_workers

    def start(self,
              executable_cls: Optional[type] = None,
              executable_args: Optional[list] = None,
              executable_kwargs: Optional[dict] = None,
              extra_env_vars: Optional[Dict[str, str]] = None) -> None:
        """Create the actors, run the registration round, push each
        worker its slot env, then instantiate ``executable_cls`` inside
        each worker (reference start(): create_workers → Coordinator
        registration → establish_rendezvous → update_env_vars →
        start_executable fan-outs)."""
        ray = _ray()
        remote_cls = ray.remote(BaseHorovodWorker)
        opts: Dict[str, Any] = {"num_cpus": self.cpus_per_worker}
        if self.use_gpu and self.gpus_per_worker:
            opts["num_gpus"] = self.gpus_per_worker
        remote_cls = remote_cls.options(**opts)
        self.workers = [
            remote_cls.remote(world_rank=rank,
                              world_size=self._num_workers)
            for rank in range(self._num_workers)]

        hostnames = ray.get([w.hostname.remote() for w in self.workers])
        for rank, hostname in enumerate(hostnames):
            self.coordinator.register(hostname, rank)
        # Reserve the jax.distributed coordinator port on rank 0's HOST
        # (it binds inside rank 0's actor process).
        self.coordinator.coordinator_port = ray.get(
            self.workers[0].free_port.remote())
        envs = self.coordinator.finalize_registration()

        base = {**self.env, **(extra_env_vars or {})}
        ray.get([
            w.update_env_vars.remote({**base, **envs[rank]})
            for rank, w in enumerate(self.workers)])
        if executable_cls is not None:
            ray.get([w.start_executable.remote(
                        executable_cls, executable_args,
                        executable_kwargs)
                     for w in self.workers])
        self._started = True

    def run_remote(self, fn: Callable, args: Optional[list] = None,
                   kwargs: Optional[dict] = None) -> List[Any]:
        """Dispatch without blocking; returns the object refs
        (reference run_remote)."""
        if not self._started:
            raise RuntimeError("RayExecutor not started — call start()")
        call = _IgnoreExecutable(fn, tuple(args or ()), kwargs or {})
        return [w.execute.remote(call) for w in self.workers]

    def run(self, fn: Callable, args: Optional[list] = None,
            kwargs: Optional[dict] = None) -> List[Any]:
        """Run ``fn`` on every worker, rank order results (reference
        run contract)."""
        return _ray().get(self.run_remote(fn, args, kwargs))

    def execute(self, fn: Callable[[Any], Any]) -> List[Any]:
        """Apply ``fn(executable)`` on every worker (reference execute
        — for executable_cls users)."""
        if not self._started:
            raise RuntimeError("RayExecutor not started — call start()")
        return _ray().get([w.execute.remote(fn) for w in self.workers])

    def execute_single(self, fn: Callable, args: Optional[list] = None,
                       kwargs: Optional[dict] = None, rank: int = 0
                       ) -> Any:
        """One worker only; fn must not issue collectives."""
        if not self._started:
            raise RuntimeError("RayExecutor not started — call start()")
        call = _IgnoreExecutable(fn, tuple(args or ()), kwargs or {})
        return _ray().get(self.workers[rank].execute.remote(call))

    def shutdown(self) -> None:
        ray = _ray()
        if self.workers:
            try:
                ray.get([w.shutdown_engine.remote()
                         for w in self.workers],
                        timeout=self.settings.timeout_s)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
            for w in self.workers:
                ray.kill(w)
        self.workers = []
        self._started = False


class _IgnoreExecutable:
    """Picklable bridge for run()/execute_single(): the worker's
    execute(fn) channel passes the executable, which plain functions
    don't take — swallow it and call fn(*args, **kwargs)."""

    def __init__(self, fn: Callable, args: tuple, kwargs: dict):
        self._fn, self._args, self._kwargs = fn, args, kwargs

    def __call__(self, _executable) -> Any:
        return self._fn(*self._args, **self._kwargs)


# -- elastic (reference ray/elastic.py) -------------------------------------

@dataclass
class ElasticSettings:
    """create_settings product (reference ray/elastic.py:97-152).

    ``elastic_timeout``: seconds to wait for >= min_np slots before the
    job fails. ``timeout_s``: worker graceful-exit window on a topology
    change (seconds between the interrupt being published and workers
    being terminated). ``max_np=None`` means UNCAPPED — the job grows
    to whatever the cluster offers (the reference's 'entire Ray cluster
    is available' contract)."""

    min_np: int = 1
    max_np: Optional[int] = None
    reset_limit: Optional[int] = None
    elastic_timeout: int = 600
    # None = fall through to the HVD_TPU_ELASTIC_GRACE_SECS env knob
    # (default 30) — an explicit value here overrides it.
    timeout_s: Optional[int] = None
    extra_env: Dict[str, str] = field(default_factory=dict)


class ElasticRayExecutor:
    """Elastic jobs with hosts/slots discovered from the LIVE Ray
    cluster state (reference ElasticRayExecutor, ray/elastic.py:61-300:
    "leverages the Ray global state to detect available hosts").

    Rides the framework's elastic driver (runner/elastic_driver.py:
    rank-stable assignments, blacklist, topology-version interrupt
    channel) with :class:`RayHostDiscovery` as the discovery source —
    nodes joining/leaving the Ray cluster grow/shrink the job between
    commit points.

    Example::

        ray.init(address="auto")
        settings = ElasticRayExecutor.create_settings(min_np=1)
        executor = ElasticRayExecutor(settings, cpus_per_slot=2)
        executor.start()
        results = executor.run(train_fn)   # fn uses @hvd.elastic.run
    """

    @staticmethod
    def create_settings(min_np: int = 1, max_np: Optional[int] = None,
                        reset_limit: Optional[int] = None,
                        elastic_timeout: int = 600,
                        timeout_s: Optional[int] = None,
                        extra_env: Optional[Dict[str, str]] = None
                        ) -> ElasticSettings:
        """No silent **kwargs: a typoed setting must error, not be
        discarded (the reference forwards to Settings which validates
        the same way)."""
        return ElasticSettings(min_np=min_np, max_np=max_np,
                               reset_limit=reset_limit,
                               elastic_timeout=elastic_timeout,
                               timeout_s=timeout_s,
                               extra_env=dict(extra_env or {}))

    def __init__(self, settings: Optional[ElasticSettings] = None,
                 use_gpu: bool = False, cpus_per_slot: int = 1,
                 gpus_per_slot: int = 1,
                 env_vars: Optional[Dict[str, str]] = None,
                 override_discovery: bool = True):
        self.settings = settings or ElasticSettings()
        self.env_vars = dict(env_vars or {})
        self.discovery: Optional["RayHostDiscovery"] = None
        if override_discovery:
            self.discovery = RayHostDiscovery(
                use_gpu=use_gpu, cpus_per_slot=cpus_per_slot,
                gpus_per_slot=gpus_per_slot)

    def start(self) -> None:
        """Validate the cluster serves at least min_np slots."""
        if self.discovery is None:
            raise RuntimeError("no discovery source; construct with "
                               "override_discovery=True or set "
                               ".discovery")
        hosts = self.discovery.find_available_hosts_and_slots()
        if sum(hosts.values()) < self.settings.min_np:
            raise RuntimeError(
                f"Ray cluster offers {sum(hosts.values())} slots < "
                f"min_np={self.settings.min_np}")

    def run(self, worker_fn: Callable) -> List[Any]:
        """Run ``worker_fn`` elastically; returns the FINAL topology's
        completed worker values in numeric rank order (reference run
        contract — the fn handles its own elastic state via
        hvd.elastic.run)."""
        import argparse
        import sys
        import tempfile

        import cloudpickle

        from ..runner.elastic_driver import run_elastic

        if self.discovery is None:
            raise RuntimeError("no discovery source; construct with "
                               "override_discovery=True or set "
                               ".discovery")
        with tempfile.TemporaryDirectory(prefix="hvd_ray_elastic_") \
                as tmp:
            fn_path = os.path.join(tmp, "fn.pkl")
            results_dir = os.path.join(tmp, "results")
            with open(fn_path, "wb") as f:
                cloudpickle.dump(worker_fn, f)

            hosts = self.discovery.find_available_hosts_and_slots()
            np_now = min(sum(hosts.values()),
                         self.settings.max_np or sum(hosts.values()))
            # max_np=None means uncapped: run_elastic folds None to
            # num_proc, which would freeze the job at today's cluster
            # size — pass an effectively-infinite cap instead so new
            # nodes grow the world.
            args = argparse.Namespace(
                num_proc=np_now, min_np=self.settings.min_np,
                max_np=self.settings.max_np or 2 ** 30,
                host_discovery_script=None, hosts=None, ssh_port=None)
            rc = run_elastic(
                args,
                [sys.executable, "-m", "horovod_tpu.ray.elastic_worker",
                 fn_path, results_dir],
                env_extra={**self.settings.extra_env, **self.env_vars},
                discovery=self.discovery,
                reset_limit=self.settings.reset_limit,
                slot_wait_timeout_s=self.settings.elastic_timeout,
                grace_secs=self.settings.timeout_s)
            if rc != 0:
                raise RuntimeError(
                    f"elastic run failed with exit code {rc}")
            return self._collect_results(results_dir)

    @staticmethod
    def _collect_results(results_dir: str) -> List[Any]:
        """Keep only the FINAL topology's values: files are named
        rank_{rank}_of_{np}; an aborted epoch's leftovers (different
        world size, or a rank >= the final size) must not mix in. The
        final epoch is identified by the newest file's world size."""
        import pickle

        if not os.path.isdir(results_dir):
            return []
        entries = []  # (mtime, rank, np, path)
        for name in os.listdir(results_dir):
            if not (name.startswith("rank_") and name.endswith(".pkl")):
                continue
            try:
                rank_s, np_s = name[len("rank_"):-len(".pkl")] \
                    .split("_of_")
                rank, world = int(rank_s), int(np_s)
            except ValueError:
                continue
            path = os.path.join(results_dir, name)
            entries.append((os.path.getmtime(path), rank, world, path))
        if not entries:
            return []
        final_world = max(entries)[2]
        by_rank = {}
        for _, rank, world, path in sorted(entries):
            if world == final_world and rank < world:
                by_rank[rank] = path  # later mtime wins per rank
        results = []
        for rank in sorted(by_rank):
            with open(by_rank[rank], "rb") as f:
                results.append(pickle.load(f))
        return results


# -- elastic discovery (reference ray/elastic.py:34-74) ---------------------

class RayHostDiscovery:
    """Feeds the elastic driver from the live Ray cluster state: every
    alive node with CPU (or GPU when use_gpu) resources contributes
    ``slots`` worker slots (reference RayHostDiscovery.find_...)."""

    def __init__(self, use_gpu: bool = False, cpus_per_slot: int = 1,
                 gpus_per_slot: int = 1):
        self.use_gpu = use_gpu
        self.cpus_per_slot = cpus_per_slot
        self.gpus_per_slot = gpus_per_slot

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        ray = _ray()
        hosts: Dict[str, int] = {}
        for node in ray.nodes():
            if not node.get("Alive", False):
                continue
            resources = node.get("Resources", {})
            hostname = node.get("NodeManagerHostname") \
                or node.get("NodeManagerAddress", "unknown")
            if self.use_gpu:
                slots = int(resources.get("GPU", 0) // self.gpus_per_slot)
            else:
                slots = int(resources.get("CPU", 0) // self.cpus_per_slot)
            if slots > 0:
                hosts[hostname] = slots
        return hosts
