"""Standalone Keras binding — the reference ``horovod.keras`` namespace
(reference keras/__init__.py:36-177) hosted on the TPU-native engine.

Everything rides the TensorFlow shim (`horovod_tpu.tensorflow`), which is
the host-boundary migration surface; TPU training throughput belongs on
the JAX path (``hvd.DistributedOptimizer`` inside ``spmd_step``). This
module exists so ``import horovod.keras as hvd`` scripts port with only
the package name changing.
"""

from __future__ import annotations

from typing import Optional

import horovod_tpu as _hvd
import horovod_tpu.tensorflow as _tf_shim
from horovod_tpu.ops.collectives import ReduceOp

from . import callbacks, elastic  # noqa: F401  (public submodules)

# -- basics (reference keras/__init__.py re-exports) ------------------------
init = _hvd.init
shutdown = _hvd.shutdown
is_initialized = _hvd.is_initialized
rank = _hvd.rank
size = _hvd.size
local_rank = _hvd.local_rank
local_size = _hvd.local_size
cross_rank = _hvd.cross_rank
cross_size = _hvd.cross_size
Average, Sum, Adasum, Min, Max, Product = (
    _hvd.Average, _hvd.Sum, _hvd.Adasum, _hvd.Min, _hvd.Max, _hvd.Product)
Compression = _hvd.Compression

allgather = _tf_shim.allgather
broadcast = _tf_shim.broadcast
broadcast_variables = _tf_shim.broadcast_variables
join = _tf_shim.join
# capability queries (reference keras re-exports of basics.py:160-258)
from horovod_tpu.common.basics import (  # noqa: E402
    CAPABILITY_QUERY_NAMES as _CQN,
    export_capability_queries as _ecq,
)

_ecq(globals())


def allreduce(value, name: Optional[str] = None, average: bool = True,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0):
    """Keras-surface allreduce (reference keras/__init__.py:98-113 —
    ``average`` flag instead of a ReduceOp)."""
    op: ReduceOp = Average if average else Sum
    return _tf_shim.allreduce(value, op=op, name=name,
                              prescale_factor=prescale_factor,
                              postscale_factor=postscale_factor)


def DistributedOptimizer(optimizer, name: Optional[str] = None,
                         device_dense: str = "", device_sparse: str = "",
                         compression=None, sparse_as_dense: bool = False,
                         gradient_predivide_factor: float = 1.0,
                         op: ReduceOp = Average,
                         backward_passes_per_step: int = 1,
                         average_aggregated_gradients: bool = False):
    """Reference keras/__init__.py:36-86 signature (op restricted to
    Average/Sum there too; predivide splits averaging around the sum).
    ``device_dense`` / ``device_sparse`` / ``compression`` are accepted
    for drop-in compatibility but ignored: device placement is XLA's job
    on TPU, and the host-boundary shim does not compress
    (docs/performance.md §5 — compressed collectives live on the JAX
    surface). The aggregation kwargs are this framework's extension with
    the reference TF-surface defaults."""
    del name, device_dense, device_sparse, compression
    if op not in (Average, Sum):
        raise ValueError("op currently only supports Average and Sum "
                         "(reference keras/__init__.py:73)")
    return _tf_shim.DistributedOptimizer(
        optimizer, op=op,
        backward_passes_per_step=backward_passes_per_step,
        average_aggregated_gradients=average_aggregated_gradients,
        sparse_as_dense=sparse_as_dense,
        gradient_predivide_factor=gradient_predivide_factor)


def broadcast_global_variables(root_rank: int = 0, model=None) -> None:
    """Reference keras/__init__.py:88-97. TF1 collected "global
    variables" from the graph; Keras 3 has no global collection, so pass
    the ``model`` (its variables + optimizer variables are broadcast) or
    use ``callbacks.BroadcastGlobalVariablesCallback`` inside ``fit``."""
    if model is None:
        raise ValueError(
            "Keras 3 has no global-variable collection; pass model= or "
            "use hvd.callbacks.BroadcastGlobalVariablesCallback")
    variables = list(model.variables)
    if getattr(model, "optimizer", None) is not None:
        variables += list(model.optimizer.variables)
    broadcast_variables(variables, root_rank)


def _wrap_optimizer_class(cls):
    """Deserialization shim: Keras resolves the saved class name through
    custom_objects and calls ``from_config`` — return the distributed
    wrap of the freshly built inner optimizer."""

    class _Wrapped:
        @staticmethod
        def from_config(config, custom_objects=None):  # noqa: ARG004
            del custom_objects
            return DistributedOptimizer(cls.from_config(config))

    _Wrapped.__name__ = f"Distributed{cls.__name__}"
    return _Wrapped


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=None):
    """Load a saved Keras model whose optimizer was (or should become) a
    DistributedOptimizer (reference keras/__init__.py:143-177): every
    optimizer class in ``keras.optimizers`` — plus any
    ``custom_optimizers`` — is registered under both its own name and
    its ``Distributed*`` alias, so models saved before OR after wrapping
    reload with the wrap applied and optimizer state intact."""
    del compression  # signature parity; see DistributedOptimizer note
    import keras

    mapping = dict(custom_objects or {})
    seen = {}
    for attr in dir(keras.optimizers):
        cls = getattr(keras.optimizers, attr)
        if (isinstance(cls, type)
                and issubclass(cls, keras.optimizers.Optimizer)
                and cls is not keras.optimizers.Optimizer):
            seen[cls.__name__] = cls
    for cls in custom_optimizers or ():
        seen[cls.__name__] = cls
        # Custom classes aren't in keras' registry, so deserialization
        # DOES consult custom_objects for the plain name — register the
        # wrap there so an unwrapped-save reloads wrapped.
        mapping.setdefault(cls.__name__, _wrap_optimizer_class(cls))
    for cls_name, cls in seen.items():
        # Covers models saved AFTER wrapping: "DistributedAdam" is not a
        # keras-module name, so deserialization consults custom_objects.
        mapping.setdefault(f"Distributed{cls_name}",
                           _wrap_optimizer_class(cls))
    model = keras.models.load_model(filepath, custom_objects=mapping)

    # Models saved BEFORE wrapping deserialize through keras' own module
    # registry (custom_objects is not consulted for built-in names), so
    # wrap post-load: swap in the distributed subclass IN PLACE, keeping
    # the restored slot variables (from_config would zero them).
    opt = getattr(model, "optimizer", None)
    if opt is not None and not type(opt).__name__.startswith("Distributed"):
        donor = DistributedOptimizer(type(opt).from_config(opt.get_config()))
        opt.__class__ = type(donor)
    return model


__all__ = [
    "init", "shutdown", "is_initialized", "rank", "size", "local_rank",
    "local_size", "cross_rank", "cross_size", "Average", "Sum", "Adasum",
    "Min", "Max", "Product", "Compression", "allreduce", "allgather",
    "broadcast", "broadcast_variables", "broadcast_global_variables",
    "DistributedOptimizer", "load_model", "callbacks", "elastic", "join",
    *_CQN,
]
