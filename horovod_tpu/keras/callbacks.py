"""Keras callbacks namespace (reference keras/callbacks.py:22-207).

The factories live on the TensorFlow shim (they build
``tf.keras.callbacks.Callback`` subclasses at call time so importing this
module never imports TF); this module gives them the reference's import
path: ``hvd.callbacks.MetricAverageCallback()``.
"""

from horovod_tpu.tensorflow import (  # noqa: F401
    BestModelCheckpoint,
    BroadcastGlobalVariablesCallback,
    LearningRateScheduleCallback,
    LearningRateWarmupCallback,
    MetricAverageCallback,
)

__all__ = [
    "BroadcastGlobalVariablesCallback", "MetricAverageCallback",
    "LearningRateScheduleCallback", "LearningRateWarmupCallback",
    "BestModelCheckpoint",
]
