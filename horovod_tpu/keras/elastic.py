"""Elastic Keras surface (reference keras/elastic.py:22-86).

``KerasState`` is the TF-shim keras state (weights + optimizer slots to
host numpy, rank-0 sync on topology change); the three callbacks drive a
``State`` from inside ``model.fit`` with the reference's semantics
(_keras/elastic.py CommitStateCallbackImpl / UpdateBatchStateCallbackImpl
/ UpdateEpochStateCallbackImpl).
"""

from __future__ import annotations

from horovod_tpu.common.elastic import run  # noqa: F401  (re-export)
from horovod_tpu.tensorflow.elastic import TensorFlowKerasState


class KerasState(TensorFlowKerasState):
    """Reference keras/elastic.py:22-31. When no optimizer is given the
    compiled model's own optimizer is snapshotted too, so rollback
    rewinds momentum/variance slots alongside the weights."""

    def __init__(self, model, optimizer=None, **kwargs):
        if optimizer is None:
            optimizer = getattr(model, "optimizer", None)
        super().__init__(model, optimizer, **kwargs)


def _callback_base():
    import tensorflow as tf

    return tf.keras.callbacks.Callback


def CommitStateCallback(state, batches_per_commit: int = 1):
    """Commit ``state`` every ``batches_per_commit`` batches and at each
    epoch end (reference _keras/elastic.py CommitStateCallbackImpl —
    the counter resets at train begin so ranks stay consistent across
    sync events)."""
    Base = _callback_base()

    class _Cb(Base):
        def on_train_begin(self, logs=None):  # noqa: ARG002
            del logs
            self._remaining = batches_per_commit

        def on_batch_end(self, batch, logs=None):  # noqa: ARG002
            del logs
            self._remaining -= 1
            if self._remaining == 0:
                state.commit()
                self._remaining = batches_per_commit

        def on_epoch_end(self, epoch, logs=None):  # noqa: ARG002
            del logs
            state.commit()

    return _Cb()


def UpdateBatchStateCallback(state):
    """Track ``state.batch`` through fit (reference _keras/elastic.py
    UpdateBatchStateCallbackImpl tracking semantics).

    The reference additionally shortened the restart epoch by mutating
    ``callback.params['steps']`` — a Keras-2 trainer contract that Keras
    3 ignores (the epoch iterator is built from fit's own arguments;
    callback params are write-only metadata). To avoid replaying
    committed batches after an elastic restart, pass
    ``steps_per_epoch=<total> - state.batch`` to the resume ``fit``
    call; this callback keeps ``state.batch`` correct for exactly that.
    """
    Base = _callback_base()

    class _Cb(Base):
        def on_batch_end(self, batch, logs=None):  # noqa: ARG002
            del logs
            state.batch = batch

        def on_epoch_end(self, epoch, logs=None):  # noqa: ARG002
            del logs
            state.batch = 0

    return _Cb()


def UpdateEpochStateCallback(state):
    """Track the GLOBAL epoch count across resets: keras numbers epochs
    from 0 every fit, so the state's epoch at train begin becomes the
    offset (reference _keras/elastic.py UpdateEpochStateCallbackImpl)."""
    Base = _callback_base()

    class _Cb(Base):
        def on_train_begin(self, logs=None):  # noqa: ARG002
            del logs
            self._initial_epoch = state.epoch

        def on_epoch_end(self, epoch, logs=None):  # noqa: ARG002
            del logs
            state.epoch = self._initial_epoch + epoch + 1

    return _Cb()


__all__ = ["KerasState", "CommitStateCallback", "UpdateBatchStateCallback",
           "UpdateEpochStateCallback", "run"]
