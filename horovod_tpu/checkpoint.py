"""Async checkpoint/resume subsystem.

The reference has no monolithic checkpoint layer — it composes elastic
``State.save/restore/sync`` held in host memory (common/elastic.py:95-110),
``broadcast_object`` for restart consistency (tensorflow/functions.py:47-135)
and rank-0-only Keras ``BestModelCheckpoint`` (keras/callbacks.py:157), with
Spark's Store persisting to HDFS/S3 (spark/common/store.py). SURVEY.md §5
calls for a real async checkpoint layer to reach capability parity on TPU —
this module provides it over orbax (async device→host→disk with the step
function still running), plus a pure-pickle fallback store for objects.

Design notes (TPU-first):
- Saves are asynchronous: the device→host copy happens immediately, the
  disk write on a background thread (orbax AsyncCheckpointer), so the
  training step is blocked only for the HBM readout, not the filesystem.
- In multi-process jobs every process participates (orbax coordinates
  per-shard writes); the ``rank0_only`` flag exists for the reference's
  single-writer semantics when saving replicated trees.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Optional

import jax


class CheckpointManager:
    """Versioned, async, garbage-collected checkpoint directory.

    Capability analog of elastic State persistence + Spark Store
    (reference spark/common/store.py:1-504) re-built on orbax.

    Usage::

        mgr = hvd.checkpoint.CheckpointManager("/ckpts", max_to_keep=3)
        mgr.save(step, {"params": params, "opt_state": opt_state})
        tree = mgr.restore()            # latest, original structure
    """

    def __init__(self, directory: str, max_to_keep: int = 5,
                 save_interval_steps: int = 1,
                 rank0_only: bool = False):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        if rank0_only:
            import warnings

            # Kept for API compatibility only: single-writer semantics
            # are provided by orbax's storage layer (each shard written
            # exactly once); skipping save() calls on non-zero ranks
            # would deadlock orbax's cross-process barriers.
            warnings.warn(
                "rank0_only is a no-op: every process must call save() "
                "(orbax runs cross-process barriers) and orbax already "
                "writes each shard exactly once", DeprecationWarning,
                stacklevel=2)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=True,
            ),
        )

    # -- write side --------------------------------------------------------

    def save(self, step: int, tree: Any, force: bool = False) -> bool:
        """Async-save ``tree`` at ``step``; returns False if the manager's
        save-interval policy skipped it.

        ``rank0_only`` is single-WRITER semantics, not single-CALLER: in a
        multi-process job every process must still call save() — orbax's
        save/finalize runs cross-process barriers, so skipping the call on
        non-zero ranks would deadlock process 0 — while orbax itself
        guarantees each shard is written exactly once (and replicated
        trees are written by their primary replica only). Restore is
        symmetric: every process calls restore() and receives the data,
        covering the reference's broadcast-after-rank0-restore pattern."""
        return self._mgr.save(
            step, args=self._ocp.args.StandardSave(tree), force=force)

    def wait(self) -> None:
        """Block until all in-flight async saves hit disk."""
        self._mgr.wait_until_finished()

    # -- read side ---------------------------------------------------------

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def restore(self, step: Optional[int] = None,
                target: Any = None) -> Any:
        """Restore ``step`` (default: latest). ``target`` — an example tree
        (or abstract tree of jax.ShapeDtypeStruct) used to restore with
        matching shardings/dtypes; without it, arrays come back as numpy.
        """
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoints under {self.directory}")
        if target is not None:
            abstract = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                               sharding=getattr(
                                                   x, "sharding", None))
                if hasattr(x, "shape") else x, target)
            return self._mgr.restore(
                step, args=self._ocp.args.StandardRestore(abstract))
        # No target: restore as plain numpy. An explicit StandardRestore()
        # (no abstract tree) is required — orbax's CompositeCheckpointHandler
        # refuses a bare restore(step) without a handler registry or
        # CheckpointArgs (API drift in orbax >= 0.5).
        return self._mgr.restore(
            step, args=self._ocp.args.StandardRestore())

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.wait()
        self.close()


class ObjectStore:
    """Pickle store for small host objects (rng state, epoch counters,
    dataloader cursors) alongside array checkpoints — the analog of the
    reference's Store metadata files (spark/common/store.py) and
    ObjectState host-memory snapshots (common/elastic.py:95-110)."""

    def __init__(self, directory: str):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)

    def _path(self, name: str) -> str:
        return os.path.join(self.directory, f"{name}.pkl")

    def put(self, name: str, obj: Any) -> None:
        tmp = self._path(name) + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(obj, f)
        os.replace(tmp, self._path(name))

    def get(self, name: str, default: Any = None) -> Any:
        try:
            with open(self._path(name), "rb") as f:
                return pickle.load(f)
        except FileNotFoundError:
            return default

    def exists(self, name: str) -> bool:
        return os.path.exists(self._path(name))


def save_state(state, directory: str, step: int,
               max_to_keep: int = 5) -> None:
    """One-shot: persist an elastic ``JaxState``'s committed snapshot to
    disk so a job can resume across full restarts (capability the
    reference reaches via Spark Store; common/elastic.py State only
    survives within a process). Persists the last *committed* snapshot —
    host-side copies that are valid even if live attributes are mid-step
    device arrays or the mesh is already gone."""
    mgr = CheckpointManager(directory, max_to_keep=max_to_keep)
    arrays = {}
    objects = {}
    for k, v in state.committed_items():
        # Only pure numeric-array pytrees go to orbax; anything with
        # non-numeric leaves (e.g. a metadata dict of strings — which the
        # JaxState snapshot turns into numpy <U arrays that do have
        # .shape) goes to the pickle store — tensorstore rejects str/object
        # dtypes.
        if _is_numeric_array(v) or _is_tree(v):
            arrays[k] = v
        else:
            objects[k] = v
    try:
        mgr.save(step, {"arrays": arrays}, force=True)
        mgr.wait()
    finally:
        mgr.close()
    ObjectStore(directory).put("state_objects", {"step": step, **objects})


def restore_state(state, directory: str) -> int:
    """Inverse of :func:`save_state`; loads the latest step into ``state``
    attributes and returns the step number."""
    mgr = CheckpointManager(directory)
    try:
        restored = mgr.restore()
    finally:
        mgr.close()
    for k, v in restored["arrays"].items():
        setattr(state, k, v)
    objs = ObjectStore(directory).get("state_objects", {})
    step = objs.pop("step", 0)
    for k, v in objs.items():
        setattr(state, k, v)
    state.save()  # committed snapshot = what we just restored
    return step


def _is_numeric_array(x) -> bool:
    if not (hasattr(x, "shape") and hasattr(x, "dtype")):
        return False
    import numpy as np

    # kind: 'U'nicode / byte'S'tring / 'O'bject are unserializable by
    # tensorstore; everything else (incl. ml_dtypes like bfloat16, kind
    # 'V'/'f') is fine.
    return np.dtype(x.dtype).kind not in ("U", "S", "O")


def _is_tree(v) -> bool:
    leaves = jax.tree.leaves(v)
    return bool(leaves) and all(_is_numeric_array(x) for x in leaves)
