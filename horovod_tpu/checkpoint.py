"""Async checkpoint/resume subsystem.

The reference has no monolithic checkpoint layer — it composes elastic
``State.save/restore/sync`` held in host memory (common/elastic.py:95-110),
``broadcast_object`` for restart consistency (tensorflow/functions.py:47-135)
and rank-0-only Keras ``BestModelCheckpoint`` (keras/callbacks.py:157), with
Spark's Store persisting to HDFS/S3 (spark/common/store.py). SURVEY.md §5
calls for a real async checkpoint layer to reach capability parity on TPU —
this module provides it over orbax (async device→host→disk with the step
function still running), plus a pure-pickle fallback store for objects.

Design notes (TPU-first):
- Saves are asynchronous: the device→host copy happens immediately, the
  disk write on a background thread (orbax AsyncCheckpointer), so the
  training step is blocked only for the HBM readout, not the filesystem.
- In multi-process jobs every process participates (orbax coordinates
  per-shard writes); the ``rank0_only`` flag exists for the reference's
  single-writer semantics when saving replicated trees.
- **Verified checkpoints** (docs/integrity.md): each finalized step
  gets a CRC32C+size sidecar manifest (``hvd_integrity.json``, written
  atomically via tmp + ``os.replace`` inside the step directory — orbax
  itself already commits the step via atomic rename). ``restore()``
  verifies against the sidecar and, on corruption (a torn write, a
  flipped bit, a truncated payload), walks back through the last-good
  chain instead of silently loading garbage; the SIGTERM preemption
  commit (common/elastic.py → save_state) rides the same path. Results
  land on ``hvd_tpu_checkpoint_verify_total{result=}`` and corruptions
  bump RecoveryStats. The ``checkpoint_corrupt`` chaos site
  (common/faults.py) corrupts a just-written step so the whole chain is
  testable end to end.
"""

from __future__ import annotations

import json
import logging
import os
import pickle
import zlib
from typing import Any, Dict, Optional

import jax

from .common import faults as faults_lib
from .common import metrics as metrics_lib
from .common.exceptions import CheckpointCorruptError

logger = logging.getLogger("horovod_tpu")

SIDECAR_NAME = "hvd_integrity.json"

_M_VERIFY = metrics_lib.counter(
    "hvd_tpu_checkpoint_verify_total",
    "checkpoint integrity verifications by result (ok / corrupt / "
    "missing sidecar)",
    labels=("result",))
for _r in ("ok", "corrupt", "missing"):
    _M_VERIFY.labels(result=_r)
del _r

try:  # true CRC32C (the GCS/tensorstore checksum) when available
    import google_crc32c as _crc32c_mod

    _CRC_ALGO = "crc32c"

    def _crc_file(path: str) -> str:
        h = _crc32c_mod.Checksum()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        return h.digest().hex()
except ImportError:  # pragma: no cover — stdlib fallback
    _CRC_ALGO = "crc32"

    def _crc_file(path: str) -> str:
        crc = 0
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                crc = zlib.crc32(chunk, crc)
        return f"{crc & 0xFFFFFFFF:08x}"


class CheckpointManager:
    """Versioned, async, garbage-collected checkpoint directory.

    Capability analog of elastic State persistence + Spark Store
    (reference spark/common/store.py:1-504) re-built on orbax.

    Usage::

        mgr = hvd.checkpoint.CheckpointManager("/ckpts", max_to_keep=3)
        mgr.save(step, {"params": params, "opt_state": opt_state})
        tree = mgr.restore()            # latest, original structure
    """

    def __init__(self, directory: str, max_to_keep: int = 5,
                 save_interval_steps: int = 1,
                 rank0_only: bool = False,
                 verify: Optional[bool] = None):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        # Verified checkpoints (docs/integrity.md): None resolves the
        # HVD_TPU_CHECKPOINT_VERIFY knob / init(checkpoint_verify=)
        # default (True).
        if verify is None:
            from .common import basics

            if basics.is_initialized():
                verify = basics.context().config.checkpoint_verify
            else:
                from .common.config import _env_bool

                verify = _env_bool("CHECKPOINT_VERIFY", True)
        self.verify = bool(verify)
        # Step chosen by the most recent restore() (after any verified
        # walk-back) — lets callers pair host-side objects with it.
        self.last_restored_step: Optional[int] = None
        self.directory = os.path.abspath(directory)
        if rank0_only:
            import warnings

            # Kept for API compatibility only: single-writer semantics
            # are provided by orbax's storage layer (each shard written
            # exactly once); skipping save() calls on non-zero ranks
            # would deadlock orbax's cross-process barriers.
            warnings.warn(
                "rank0_only is a no-op: every process must call save() "
                "(orbax runs cross-process barriers) and orbax already "
                "writes each shard exactly once", DeprecationWarning,
                stacklevel=2)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=True,
            ),
        )

    # -- write side --------------------------------------------------------

    def save(self, step: int, tree: Any, force: bool = False) -> bool:
        """Async-save ``tree`` at ``step``; returns False if the manager's
        save-interval policy skipped it.

        ``rank0_only`` is single-WRITER semantics, not single-CALLER: in a
        multi-process job every process must still call save() — orbax's
        save/finalize runs cross-process barriers, so skipping the call on
        non-zero ranks would deadlock process 0 — while orbax itself
        guarantees each shard is written exactly once (and replicated
        trees are written by their primary replica only). Restore is
        symmetric: every process calls restore() and receives the data,
        covering the reference's broadcast-after-rank0-restore pattern.

        With ``verify`` on, every FINALIZED step additionally gets its
        CRC+size sidecar manifest (written here for previously completed
        async saves, and in :meth:`wait` once the in-flight ones land) —
        saves stay async; only the cheap manifest write trails them."""
        saved = self._mgr.save(
            step, args=self._ocp.args.StandardSave(tree), force=force)
        if self.verify:
            self._finalize_sidecars()
            if saved and faults_lib.active():
                spec = faults_lib.maybe_checkpoint_corrupt()
                if spec is not None:
                    # Chaos site "checkpoint_corrupt": finalize THIS
                    # step, then corrupt it — the torn-write the
                    # verified restore path must survive.
                    self._mgr.wait_until_finished()
                    self._finalize_sidecars()
                    self._corrupt_step(step, spec.mode or "bitflip")
        return saved

    def wait(self) -> None:
        """Block until all in-flight async saves hit disk (and, with
        ``verify`` on, their integrity sidecars are written)."""
        self._mgr.wait_until_finished()
        if self.verify:
            self._finalize_sidecars()

    # -- integrity sidecars (docs/integrity.md) ----------------------------

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, str(step))

    def _sidecar_path(self, step: int) -> str:
        return os.path.join(self._step_dir(step), SIDECAR_NAME)

    def _manifest(self, step: int) -> Dict[str, Dict[str, Any]]:
        root = self._step_dir(step)
        files: Dict[str, Dict[str, Any]] = {}
        for dirpath, _dirs, names in os.walk(root):
            for name in sorted(names):
                if name == SIDECAR_NAME or name.endswith(".tmp"):
                    continue
                path = os.path.join(dirpath, name)
                rel = os.path.relpath(path, root)
                files[rel] = {"size": os.path.getsize(path),
                              "crc": _crc_file(path)}
        return files

    def _finalize_sidecars(self) -> None:
        """Write the CRC+size sidecar for every finalized step that
        lacks one (orbax lists a step only after its atomic
        rename-commit, so everything here is complete). Atomic: tmp +
        os.replace — a crash mid-write leaves no half sidecar.
        Multi-process: process 0 alone computes the manifests (one
        CRC pass over the step, not N racing redundant ones)."""
        if jax.process_count() > 1 and jax.process_index() != 0:
            return
        for step in self._mgr.all_steps():
            sidecar = self._sidecar_path(step)
            if os.path.exists(sidecar):
                continue
            try:
                payload = {"algo": _CRC_ALGO, "step": int(step),
                           "files": self._manifest(step)}
                tmp = sidecar + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(payload, f)
                os.replace(tmp, sidecar)
            except OSError:  # sidecars are best-effort at write time;
                pass         # restore treats a missing one as "missing"

    def verify_step(self, step: int) -> str:
        """Verify one step against its sidecar: ``"ok"`` | ``"corrupt"``
        (size/CRC mismatch, missing payload file) | ``"missing"`` (no
        sidecar — e.g. a pre-verification checkpoint; accepted with a
        warning on restore). Emits
        ``hvd_tpu_checkpoint_verify_total{result=}``."""
        result = self._verify_quiet(step)
        _M_VERIFY.labels(result=result).inc()
        if result == "corrupt":
            faults_lib.stats.bump("checkpoint_corruptions")
        return result

    def _verify_quiet(self, step: int) -> str:
        sidecar = self._sidecar_path(step)
        try:
            with open(sidecar) as f:
                payload = json.load(f)
        except FileNotFoundError:
            return "missing"
        except (OSError, ValueError):
            return "corrupt"
        if payload.get("algo") != _CRC_ALGO:
            # Mixed-algorithm directories (crc32c writer, zlib reader)
            # cannot be verified — treat like a missing sidecar rather
            # than flagging a healthy checkpoint corrupt.
            return "missing"
        root = self._step_dir(step)
        for rel, meta in payload.get("files", {}).items():
            path = os.path.join(root, rel)
            try:
                if os.path.getsize(path) != meta["size"]:
                    return "corrupt"
                if _crc_file(path) != meta["crc"]:
                    return "corrupt"
            except OSError:
                return "corrupt"
        return "ok"

    def _corrupt_step(self, step: int, mode: str = "bitflip") -> None:
        """Chaos helper (the ``checkpoint_corrupt`` injection site):
        damage a finalized step — ``bitflip`` flips a byte in the
        largest payload file, ``truncate`` halves it, ``sidecar``
        corrupts the manifest itself."""
        root = self._step_dir(step)
        if mode == "sidecar":
            try:
                with open(self._sidecar_path(step), "w") as f:
                    f.write("{corrupt")
            except OSError:
                pass
            return
        best, best_size = None, -1
        for dirpath, _dirs, names in os.walk(root):
            for name in names:
                if name == SIDECAR_NAME:
                    continue
                p = os.path.join(dirpath, name)
                s = os.path.getsize(p)
                if s > best_size:
                    best, best_size = p, s
        if best is None:
            return
        logger.warning("chaos: corrupting checkpoint step %d (%s, %s)",
                       step, mode, os.path.relpath(best, root))
        if mode == "truncate":
            with open(best, "r+b") as f:
                f.truncate(max(best_size // 2, 0))
        else:  # bitflip
            with open(best, "r+b") as f:
                f.seek(best_size // 2)
                b = f.read(1)
                f.seek(best_size // 2)
                f.write(bytes([(b[0] ^ 0xFF) if b else 0xFF]))

    # -- read side ---------------------------------------------------------

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def restore(self, step: Optional[int] = None,
                target: Any = None) -> Any:
        """Restore ``step`` (default: latest). ``target`` — an example tree
        (or abstract tree of jax.ShapeDtypeStruct) used to restore with
        matching shardings/dtypes; without it, arrays come back as numpy.

        With ``verify`` on: the step is checked against its CRC+size
        sidecar first. A corrupt LATEST step (torn write, bit rot) makes
        the default restore walk back through the last-good chain —
        oldest corruption logged, ``checkpoint_verify_total{result=
        "corrupt"}`` bumped — and raises
        :class:`CheckpointCorruptError` only when NO verified step
        remains. An explicitly pinned ``step`` that fails verification
        raises immediately (no silent substitution). Steps without a
        sidecar (pre-verification checkpoints) restore with a warning.
        """
        if step is None:
            step = self._latest_verified_step()
        elif self.verify and self.verify_step(step) == "corrupt":
            raise CheckpointCorruptError(
                f"checkpoint step {step} under {self.directory} failed "
                f"integrity verification ({_CRC_ALGO}+size sidecar "
                "mismatch); refusing to load a corrupt checkpoint that "
                "was pinned explicitly")
        if step is None:
            raise FileNotFoundError(
                f"no checkpoints under {self.directory}")
        self.last_restored_step = step
        if target is not None:
            abstract = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                               sharding=getattr(
                                                   x, "sharding", None))
                if hasattr(x, "shape") else x, target)
            return self._mgr.restore(
                step, args=self._ocp.args.StandardRestore(abstract))
        # No target: restore as plain numpy. An explicit StandardRestore()
        # (no abstract tree) is required — orbax's CompositeCheckpointHandler
        # refuses a bare restore(step) without a handler registry or
        # CheckpointArgs (API drift in orbax >= 0.5).
        return self._mgr.restore(
            step, args=self._ocp.args.StandardRestore())

    def latest_verified_step(self) -> Optional[int]:
        """Public twin of the walk-back resolver: the step a default
        ``restore()`` would load. With ``verify`` off this is simply
        the latest step."""
        return self._latest_verified_step()

    def _latest_verified_step(self) -> Optional[int]:
        """Newest step that passes verification — the walk-back through
        the last-good chain (corrupt steps are skipped with a warning,
        never deleted: the operator may want forensics)."""
        steps = sorted(self._mgr.all_steps(), reverse=True)
        if not steps:
            return None
        if not self.verify:
            return steps[0]
        corrupt = []
        for step in steps:
            result = self.verify_step(step)
            if result == "corrupt":
                corrupt.append(step)
                logger.warning(
                    "checkpoint step %d failed integrity verification "
                    "(%s+size sidecar mismatch); walking back to the "
                    "previous verified step", step, _CRC_ALGO)
                continue
            if result == "missing":
                logger.warning(
                    "checkpoint step %d has no integrity sidecar "
                    "(pre-verification checkpoint?); restoring "
                    "unverified", step)
            if corrupt:
                logger.warning(
                    "checkpoint: restored step %d after skipping "
                    "corrupt step(s) %s", step, corrupt)
            return step
        raise CheckpointCorruptError(
            f"every checkpoint under {self.directory} failed integrity "
            f"verification (corrupt steps: {corrupt}); no last-good "
            "step to fall back to")

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.wait()
        self.close()


class ObjectStore:
    """Pickle store for small host objects (rng state, epoch counters,
    dataloader cursors) alongside array checkpoints — the analog of the
    reference's Store metadata files (spark/common/store.py) and
    ObjectState host-memory snapshots (common/elastic.py:95-110)."""

    def __init__(self, directory: str):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)

    def _path(self, name: str) -> str:
        return os.path.join(self.directory, f"{name}.pkl")

    def put(self, name: str, obj: Any) -> None:
        tmp = self._path(name) + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(obj, f)
        os.replace(tmp, self._path(name))

    def get(self, name: str, default: Any = None) -> Any:
        try:
            with open(self._path(name), "rb") as f:
                return pickle.load(f)
        except FileNotFoundError:
            return default

    def exists(self, name: str) -> bool:
        return os.path.exists(self._path(name))


def save_state(state, directory: str, step: int,
               max_to_keep: int = 5) -> None:
    """One-shot: persist an elastic ``JaxState``'s committed snapshot to
    disk so a job can resume across full restarts (capability the
    reference reaches via Spark Store; common/elastic.py State only
    survives within a process). Persists the last *committed* snapshot —
    host-side copies that are valid even if live attributes are mid-step
    device arrays or the mesh is already gone."""
    mgr = CheckpointManager(directory, max_to_keep=max_to_keep)
    arrays = {}
    objects = {}
    for k, v in state.committed_items():
        # Only pure numeric-array pytrees go to orbax; anything with
        # non-numeric leaves (e.g. a metadata dict of strings — which the
        # JaxState snapshot turns into numpy <U arrays that do have
        # .shape) goes to the pickle store — tensorstore rejects str/object
        # dtypes.
        if _is_numeric_array(v) or _is_tree(v):
            arrays[k] = v
        else:
            objects[k] = v
    try:
        mgr.save(step, {"arrays": arrays}, force=True)
        mgr.wait()
        kept_steps = mgr.all_steps()
    finally:
        mgr.close()
    store = ObjectStore(directory)
    # Step-scoped objects so the verified walk-back (a corrupt latest
    # array step falling back to an earlier one) can pick up the
    # MATCHING host objects; the unscoped name stays for compatibility.
    store.put(f"state_objects_{step}", {"step": step, **objects})
    store.put("state_objects", {"step": step, **objects})
    # Prune step-scoped pickles alongside orbax's step GC — only steps
    # that can still be walk-back targets are worth keeping.
    import glob
    import re as re_mod

    live = {int(s) for s in kept_steps}
    for path in glob.glob(os.path.join(store.directory,
                                       "state_objects_*.pkl")):
        m = re_mod.fullmatch(r"state_objects_(\d+)\.pkl",
                             os.path.basename(path))
        if m and int(m.group(1)) not in live:
            try:
                os.remove(path)
            except OSError:
                pass


def restore_state(state, directory: str) -> int:
    """Inverse of :func:`save_state`; loads the latest VERIFIED step
    (docs/integrity.md walk-back) into ``state`` attributes and returns
    the step number. On a walk-back the step-scoped host objects
    matching the restored array step are loaded too, so arrays and
    objects never mix commits."""
    mgr = CheckpointManager(directory)
    try:
        # One restore call: the default path resolves (and verifies —
        # once) the latest good step and records it on the manager.
        restored = mgr.restore()
        target = getattr(mgr, "last_restored_step", None)
    finally:
        mgr.close()
    for k, v in restored["arrays"].items():
        setattr(state, k, v)
    store = ObjectStore(directory)
    objs = store.get(f"state_objects_{target}") \
        if target is not None else None
    if objs is None:
        objs = store.get("state_objects", {})
    step = objs.pop("step", 0)
    for k, v in objs.items():
        setattr(state, k, v)
    state.save()  # committed snapshot = what we just restored
    return step


def save_sharded(tree, directory: str, step: int,
                 max_to_keep: int = 5) -> None:
    """Persist a pytree of SHARDED ``jax.Array`` leaves (ZeRO-2/3 param
    shards + optimizer state, docs/zero.md) WITHOUT gathering: each
    leaf is decomposed into its addressable per-device pieces
    (``addressable_shards`` — a host fetch of this process's 1/N
    slices, never an all-gather collective) and the pieces are written
    individually. Replicated leaves (step counters, guard scalars)
    store one copy. Rides :class:`CheckpointManager`, so the CRC+size
    verify sidecar and the walk-back chain apply unchanged.

    Restore with :func:`restore_sharded`: the SAME world layout maps
    pieces straight back onto their devices; a CHANGED shard grid (an
    elastic respec — docs/elastic.md "hybrid worlds") reshards on
    restore using the per-piece index boxes recorded in the meta
    sidecar. A changed GLOBAL shape still goes through the gathered
    full state (``ZeroOptimizer.gather_state`` / ``reshard_state``)."""
    leaves, _ = jax.tree.flatten(tree)
    arrays = {}
    meta = []
    import numpy as np

    for li, leaf in enumerate(leaves):
        shards = getattr(leaf, "addressable_shards", None)
        # Replication is decided by the SHARDING, never by the local
        # shard count: in a multi-process world a cross-host sharded
        # array has ONE addressable shard per process, and classifying
        # it as replicated would silently persist a 1/N slice under the
        # whole-leaf key.
        if shards is None or getattr(leaf, "is_fully_replicated", True):
            arrays[f"l{li}"] = np.asarray(jax.device_get(
                leaf.addressable_data(0)
                if hasattr(leaf, "addressable_data") else leaf))
            meta.append(("replicated", 1))
        else:
            ndev = len(getattr(leaf.sharding, "device_set", ()))
            if ndev and ndev > len(shards):
                raise NotImplementedError(
                    f"save_sharded: leaf {li} spans {ndev} devices but "
                    f"only {len(shards)} are addressable from this "
                    "process — the per-rank file layout is "
                    "single-controller only; multi-host jobs carry "
                    "state through the gathered full form "
                    "(ZeroOptimizer.gather_state, docs/zero.md)")
            ordered = sorted(shards, key=lambda s: s.device.id)
            boxes = []
            for si, sh in enumerate(ordered):
                arrays[f"l{li}_s{si}"] = np.asarray(
                    jax.device_get(sh.data))
                boxes.append(_norm_index(sh.index, leaf.shape))
            # 3-tuple meta: the index boxes make the pieces
            # self-describing, so a DIFFERENT shard grid can reshard
            # on restore (replicated duplicates dedupe by box).
            meta.append(("sharded", len(ordered), boxes))
    # Meta sidecar FIRST: meta without arrays is harmless (restore
    # selects a verified array step and looks its meta up), arrays
    # without meta would turn a mid-save crash into an unrecoverable
    # FileNotFoundError instead of a walk-back.
    ObjectStore(directory).put(f"sharded_meta_{step}",
                               {"step": step, "meta": meta})
    mgr = CheckpointManager(directory, max_to_keep=max_to_keep)
    try:
        # Overwrite semantics: a crash-replay resume legitimately
        # re-saves steps the dead run already wrote (including the torn
        # one the verified restore walked back PAST) — the stale dir
        # must yield, not raise.
        stale = mgr._step_dir(step)
        if os.path.isdir(stale):
            import shutil

            shutil.rmtree(stale, ignore_errors=True)
            mgr._mgr.reload()
        mgr.save(step, {"arrays": arrays}, force=True)
        mgr.wait()
    finally:
        mgr.close()


def restore_sharded(template, directory: str):
    """Inverse of :func:`save_sharded`: rebuild the sharded pytree onto
    the devices of ``template`` (a same-structure pytree of live
    ``jax.Array`` leaves — e.g. freshly initialized shards/state in the
    resumed world, carrying the target shardings). Loads the latest
    VERIFIED step (the walk-back chain) and returns ``(tree, step)``.

    The template's shard grid need not match the checkpoint's: on a
    mismatch (an elastic respec changed dp/pp/tp — docs/elastic.md
    "hybrid worlds") each TARGET shard is assembled from the recorded
    source pieces overlapping its index box and placed directly on its
    own device — reshard-on-restore, with no full gather and no
    full-value host assembly. Requires the index boxes
    :func:`save_sharded` has recorded since schema'ing them into the
    meta sidecar; older 2-tuple metas keep the strict same-grid
    contract."""
    mgr = CheckpointManager(directory)
    try:
        restored = mgr.restore()
        step = mgr.last_restored_step
    finally:
        mgr.close()
    arrays = restored["arrays"]
    meta_rec = ObjectStore(directory).get(f"sharded_meta_{step}")
    if meta_rec is None:
        raise FileNotFoundError(
            f"no sharded_meta_{step} sidecar in {directory} — this "
            "checkpoint was not written by save_sharded")
    meta = meta_rec["meta"]
    leaves, treedef = jax.tree.flatten(template)
    if len(leaves) != len(meta):
        raise ValueError(
            f"template has {len(leaves)} leaves but the checkpoint "
            f"recorded {len(meta)} — structure changed across the "
            "round-trip")
    out = []
    for li, (leaf, rec) in enumerate(zip(leaves, meta)):
        kind, nsh = rec[0], rec[1]
        boxes = rec[2] if len(rec) > 2 else None
        if kind == "replicated":
            val = arrays[f"l{li}"]
            sharding = getattr(leaf, "sharding", None)
            out.append(jax.device_put(val, sharding)
                       if sharding is not None else _jnp_asarray(val))
            continue
        shards = sorted(leaf.addressable_shards,
                        key=lambda s: s.device.id)
        # Same GRID means same piece count AND same per-position index
        # boxes: an equal count over a different axis (a pp->tp respec
        # on the same device set) must reshard, not pass pieces
        # through positionally onto the wrong cells.
        same_grid = len(shards) == nsh
        if same_grid and boxes is not None:
            same_grid = all(
                _norm_index(sh.index, leaf.shape) ==
                [list(b) for b in box]
                for sh, box in zip(shards, boxes))
        if not same_grid:
            if boxes is None:
                raise ValueError(
                    f"leaf {li}: checkpoint holds {nsh} shards but the "
                    f"template's sharding has {len(shards)} and the "
                    "meta sidecar predates index boxes — restore into "
                    "the SAME world layout, or go through the gathered "
                    "full state (docs/zero.md)")
            out.append(_reshard_on_restore(li, leaf, shards, arrays,
                                           boxes))
            continue
        pieces = [jax.device_put(arrays[f"l{li}_s{si}"], sh.device)
                  for si, sh in enumerate(shards)]
        out.append(jax.make_array_from_single_device_arrays(
            leaf.shape, leaf.sharding, pieces))
    return jax.tree.unflatten(treedef, out), step


def _norm_index(index, shape):
    """A Shard.index (tuple of slices into the global array) as
    concrete ``[start, stop]`` pairs — picklable, comparable, and
    valid without the live sharding."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = int(dim) if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def _reshard_on_restore(li, leaf, shards, arrays, boxes):
    """Assemble each TARGET shard of ``leaf`` from the checkpoint
    pieces whose recorded index boxes overlap it (docs/elastic.md):
    duplicates (replication across an unrelated mesh axis) dedupe by
    box, every target cell must be covered exactly, and only
    per-target-shard slices ever materialize on host — the full value
    is never assembled, which is what lets a respec'd world restore a
    bigger world's state without a gather."""
    import numpy as np

    # Dedupe replicated duplicates: one source piece per distinct box.
    sources = {}
    for si, box in enumerate(boxes):
        sources.setdefault(tuple(tuple(b) for b in box), f"l{li}_s{si}")
    implied = [max(b[1] for b in key) for key in zip(
        *[k for k in sources])]
    if list(leaf.shape) != implied:
        raise ValueError(
            f"leaf {li}: checkpoint global shape {implied} vs template "
            f"{list(leaf.shape)} — reshard-on-restore remaps shard "
            "grids, not shapes; a changed global goes through the "
            "gathered full state (docs/zero.md)")
    pieces = []
    for sh in shards:
        tbox = _norm_index(sh.index, leaf.shape)
        key = tuple(tuple(b) for b in tbox)
        if key in sources:            # exact grid cell — pass through
            val = arrays[sources[key]]
        else:
            dtype = arrays[next(iter(sources.values()))].dtype
            val = np.zeros([hi - lo for lo, hi in tbox], dtype=dtype)
            covered = 0
            for sbox, name in sources.items():
                ov = [(max(tl, sl), min(th, sh_)) for (tl, th), (sl, sh_)
                      in zip(tbox, sbox)]
                if any(hi <= lo for lo, hi in ov):
                    continue
                src_sl = tuple(slice(lo - sl, hi - sl) for (lo, hi),
                               (sl, _) in zip(ov, sbox))
                dst_sl = tuple(slice(lo - tl, hi - tl) for (lo, hi),
                               (tl, _) in zip(ov, tbox))
                val[dst_sl] = arrays[name][src_sl]
                vol = 1
                for lo, hi in ov:
                    vol *= hi - lo
                covered += vol
            if covered != val.size:
                raise ValueError(
                    f"leaf {li}: target shard {tbox} only covered "
                    f"{covered}/{val.size} cells by the checkpoint's "
                    "pieces — the recorded shard grid does not tile "
                    "the template's global shape")
        pieces.append(jax.device_put(val, sh.device))
    return jax.make_array_from_single_device_arrays(
        leaf.shape, leaf.sharding, pieces)


def _jnp_asarray(x):
    import jax.numpy as jnp

    return jnp.asarray(x)


def _is_numeric_array(x) -> bool:
    if not (hasattr(x, "shape") and hasattr(x, "dtype")):
        return False
    import numpy as np

    # kind: 'U'nicode / byte'S'tring / 'O'bject are unserializable by
    # tensorstore; everything else (incl. ml_dtypes like bfloat16, kind
    # 'V'/'f') is fine.
    return np.dtype(x.dtype).kind not in ("U", "S", "O")


def _is_tree(v) -> bool:
    leaves = jax.tree.leaves(v)
    return bool(leaves) and all(_is_numeric_array(x) for x in leaves)
