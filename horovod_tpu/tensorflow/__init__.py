"""TensorFlow binding shim — the reference ``horovod.tensorflow`` API
surface hosted on the TPU-native collective engine.

Reference: horovod/tensorflow/__init__.py (allreduce :54-154,
DistributedOptimizer :465-561, DistributedGradientTape :564-629),
horovod/tensorflow/functions.py:47-135 (broadcast_variables),
horovod/keras + horovod/_keras (callbacks, create_distributed_optimizer).

Role: like the torch shim (horovod_tpu/torch), this serves host-side TF
components during migration — tf.data pipelines, Keras-on-CPU evaluation,
legacy TF training scripts. Tensors cross at the numpy boundary; the
collectives run on the engine's XLA path. TPU *training* belongs on the
JAX surface (hvd.DistributedOptimizer / spmd_step).
"""

from __future__ import annotations

from typing import Iterable, Optional

import numpy as np

import horovod_tpu as _hvd
from horovod_tpu.ops.collectives import ReduceOp

# re-exported basics (reference tensorflow/__init__.py surface)
init = _hvd.init
shutdown = _hvd.shutdown
is_initialized = _hvd.is_initialized
rank = _hvd.rank
size = _hvd.size
local_rank = _hvd.local_rank
local_size = _hvd.local_size
cross_rank = _hvd.cross_rank
cross_size = _hvd.cross_size
Average, Sum, Adasum, Min, Max, Product = (
    _hvd.Average, _hvd.Sum, _hvd.Adasum, _hvd.Min, _hvd.Max, _hvd.Product)
Compression = _hvd.Compression


def _tf():
    import tensorflow as tf

    return tf


def _engine():
    from horovod_tpu.common import basics

    return basics.context().engine


def _replicated(tensor):
    """TF tensor -> explicitly replicated distributed tensor (same
    leading-dim==size hazard note as the torch shim's _replicated)."""
    return _engine().replicate(np.asarray(tensor))


def _to_host(dt) -> np.ndarray:
    """Distributed (size, *shape) result -> this rank's row, via the
    first addressable shard only (no full-stack device_get)."""
    return np.asarray(dt.addressable_shards[0].data)[0]


# -- collectives (reference tensorflow/__init__.py:54-208) ------------------

def _bridge(np_fn, tensor, out_shape=None):
    """Run ``np_fn(numpy_array) -> numpy_array`` against a TF tensor in
    either eager or graph context. Inside a tf.function the call bridges
    through py_function so the engine collective runs at execution time —
    the role the reference's registered TF ops play
    (tensorflow/mpi_ops.cc HorovodAllreduceOp)."""
    tf = _tf()
    if tf.is_tensor(tensor) and not tf.executing_eagerly():
        out = tf.py_function(lambda t: np_fn(t.numpy()), [tensor],
                             tensor.dtype)
        out.set_shape(out_shape if out_shape is not None else tensor.shape)
        return out
    return tf.convert_to_tensor(np_fn(np.asarray(tensor)))


def _allreduce_np(arr: np.ndarray, op: ReduceOp, name: Optional[str],
                  prescale_factor: float, postscale_factor: float,
                  compression=None) -> np.ndarray:
    out = _engine().allreduce(_engine().replicate(arr), op, name,
                              prescale_factor, postscale_factor,
                              compression)
    return _to_host(out).astype(arr.dtype, copy=False)


def allreduce(tensor, op: ReduceOp = Average, name: Optional[str] = None,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              compression=None):
    return _bridge(
        lambda a: _allreduce_np(a, op, name, prescale_factor,
                                postscale_factor, compression), tensor)


def _grouped_allreduce_np(arrs, op: ReduceOp, name: Optional[str],
                          compression=None):
    """Fused grouped reduction via the engine's bucketed allreduce_tree
    (one collective per fusion bucket, not one per tensor)."""
    e = _engine()
    dts = [e.replicate(a) for a in arrs]
    outs = e.allreduce_tree(dts, op, name, compression)
    return [_to_host(o).astype(a.dtype, copy=False)
            for o, a in zip(outs, arrs)]


def grouped_allreduce(tensors, op: ReduceOp = Average,
                      name: Optional[str] = None, compression=None):
    tf = _tf()
    tensors = list(tensors)
    if not tensors:
        return []
    if any(tf.is_tensor(t) for t in tensors) and not tf.executing_eagerly():
        outs = tf.py_function(
            lambda *ts: _grouped_allreduce_np(
                [t.numpy() for t in ts], op, name, compression),
            tensors, [t.dtype for t in tensors])
        for o, t in zip(outs, tensors):
            o.set_shape(t.shape)
        return list(outs)
    return [tf.convert_to_tensor(o) for o in _grouped_allreduce_np(
        [np.asarray(t) for t in tensors], op, name, compression)]


def allgather(tensor, name: Optional[str] = None):
    """Concatenate along dim 0 over ranks (reference allgather)."""
    tf = _tf()
    e = _engine()

    def np_fn(arr):
        out = _to_host(e.allgather(e.replicate(arr), name))
        return out.reshape((-1,) + arr.shape[1:]).astype(arr.dtype,
                                                         copy=False)

    out_shape = None
    if tf.is_tensor(tensor) and tensor.shape.rank and \
            tensor.shape[0] is not None:
        out_shape = tf.TensorShape([tensor.shape[0] * size()]).concatenate(
            tensor.shape[1:])
    return _bridge(np_fn, tensor, out_shape)


def broadcast(tensor, root_rank: int = 0, name: Optional[str] = None):
    e = _engine()
    return _bridge(
        lambda arr: _to_host(e.broadcast(e.replicate(arr), root_rank,
                                         name)).astype(arr.dtype,
                                                       copy=False),
        tensor)


def alltoall(tensor, name: Optional[str] = None):
    e = _engine()
    return _bridge(
        lambda arr: _to_host(e.alltoall(e.replicate(arr), name)).astype(
            arr.dtype, copy=False),
        tensor)


def broadcast_variables(variables, root_rank: int = 0) -> None:
    """In-place assign of root's values onto tf.Variables (reference
    tensorflow/functions.py:47 broadcast_variables)."""
    for i, v in enumerate(variables):
        v.assign(broadcast(v.value(), root_rank,
                           name=f"bcast.{getattr(v, 'name', i)}"))


broadcast_object = _hvd.broadcast_object
allgather_object = _hvd.allgather_object


# -- DistributedGradientTape (reference tensorflow/__init__.py:564-629) -----

class _DistributedGradientTape:
    def __init__(self, tape, op: ReduceOp = Average,
                 compression=None):
        self._tape = tape
        self._op = op
        self._compression = compression

    def __getattr__(self, item):
        return getattr(self._tape, item)

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def gradient(self, target, sources, output_gradients=None, **kwargs):
        """Same contract as tf.GradientTape.gradient (structure of the
        result mirrors ``sources``; extra kwargs like
        unconnected_gradients pass through), with every gradient
        allreduced via the fused grouped path."""
        tf = _tf()
        grads = self._tape.gradient(target, sources, output_gradients,
                                    **kwargs)
        flat = tf.nest.flatten(grads)
        present = [(i, g) for i, g in enumerate(flat) if g is not None]
        if present:
            reduced = grouped_allreduce([g for _, g in present],
                                        op=self._op, name="tape.grads",
                                        compression=self._compression)
            for (i, _), r in zip(present, reduced):
                flat[i] = r
        return tf.nest.pack_sequence_as(grads, flat)


def DistributedGradientTape(tape, op: ReduceOp = Average,
                            compression=None) -> _DistributedGradientTape:
    return _DistributedGradientTape(tape, op, compression)


# -- Keras optimizer wrapper (reference _keras/__init__.py:28-135) ----------

def DistributedOptimizer(optimizer, op: ReduceOp = Average,
                         name: Optional[str] = None):
    """Wrap a keras optimizer so apply_gradients allreduces first. Like
    the reference (_keras/__init__.py:28-135 create_distributed_optimizer)
    this dynamically subclasses the optimizer's own class and rebuilds it
    from config — keras requires a genuine Optimizer instance in
    compile()."""
    cls = optimizer.__class__
    reduce_op = op

    def apply_gradients(self, grads_and_vars, *args, **kwargs):
        gv = list(grads_and_vars)
        present = [(i, g) for i, (g, _) in enumerate(gv) if g is not None]
        if present:
            reduced = grouped_allreduce([g for _, g in present],
                                        op=reduce_op, name="opt.grads")
            gv = [list(x) for x in gv]
            for (i, _), r in zip(present, reduced):
                gv[i][0] = r
            gv = [tuple(x) for x in gv]
        return super(dist_cls, self).apply_gradients(gv, *args, **kwargs)

    dist_cls = type(f"Distributed{cls.__name__}", (cls,),
                    {"apply_gradients": apply_gradients})
    return dist_cls.from_config(optimizer.get_config())


# -- Keras callbacks (reference keras/callbacks.py) -------------------------

def _keras_callback_base():
    import tensorflow as tf

    return tf.keras.callbacks.Callback


def BroadcastGlobalVariablesCallback(root_rank: int = 0):
    """Keras callback: broadcast all model/optimizer variables from root
    at train start (reference _keras/callbacks.py
    BroadcastGlobalVariablesCallbackImpl)."""
    Base = _keras_callback_base()

    class _Cb(Base):
        def __init__(self):
            super().__init__()
            self._done = False

        def on_train_begin(self, logs=None):
            if self._done:
                return
            broadcast_variables(self.model.variables, root_rank)
            self._done = True

    return _Cb()


def MetricAverageCallback():
    """Keras callback: allreduce-average epoch metrics (reference
    _keras/callbacks.py MetricAverageCallbackImpl)."""
    Base = _keras_callback_base()

    class _Cb(Base):
        def on_epoch_end(self, epoch, logs=None):
            if not logs:
                return
            for k, v in list(logs.items()):
                if isinstance(v, (int, float, np.floating)):
                    out = allreduce(np.full((), float(v), np.float32),
                                    op=Average, name=f"metric.{k}")
                    logs[k] = float(np.asarray(out))

    return _Cb()
