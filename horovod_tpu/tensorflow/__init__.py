"""TensorFlow binding shim — the reference ``horovod.tensorflow`` API
surface hosted on the TPU-native collective engine.

Reference: horovod/tensorflow/__init__.py (allreduce :54-154,
DistributedOptimizer :465-561, DistributedGradientTape :564-629),
horovod/tensorflow/functions.py:47-135 (broadcast_variables),
horovod/keras + horovod/_keras (callbacks, create_distributed_optimizer).

Role: like the torch shim (horovod_tpu/torch), this serves host-side TF
components during migration — tf.data pipelines, Keras-on-CPU evaluation,
legacy TF training scripts. Tensors cross at the numpy boundary; the
collectives run on the engine's XLA path. TPU *training* belongs on the
JAX surface (hvd.DistributedOptimizer / spmd_step).
"""

from __future__ import annotations

from typing import Iterable, Optional

import numpy as np

import horovod_tpu as _hvd
from horovod_tpu.ops.collectives import ReduceOp

# re-exported basics (reference tensorflow/__init__.py surface)
init = _hvd.init
shutdown = _hvd.shutdown
is_initialized = _hvd.is_initialized
rank = _hvd.rank
size = _hvd.size
local_rank = _hvd.local_rank
local_size = _hvd.local_size
cross_rank = _hvd.cross_rank
cross_size = _hvd.cross_size
Average, Sum, Adasum, Min, Max, Product = (
    _hvd.Average, _hvd.Sum, _hvd.Adasum, _hvd.Min, _hvd.Max, _hvd.Product)
Compression = _hvd.Compression
# graceful early exit (reference tensorflow join, operations.cc:1085-1109)
join = _hvd.join
# capability queries (reference TF re-exports of basics.py:160-258)
from horovod_tpu.common.basics import export_capability_queries as _ecq

_ecq(globals())


def _tf():
    import tensorflow as tf

    return tf


def _no_autograph(fn):
    """Keep autograph OUT of the shim (reference ops are C++ kernels —
    autograph never sees them; here the 'kernel' is Python engine code,
    and letting autograph trace/convert through it is both slow and
    fragile: converted engine helpers have been observed resurfacing
    from autograph's cache with broken signatures). Applied lazily so
    importing the shim does not import tensorflow."""
    import functools

    cell = []

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if not cell:  # convert once, off the per-op hot path
            cell.append(
                _tf().autograph.experimental.do_not_convert(fn))
        return cell[0](*args, **kwargs)

    return wrapper


def _engine(process_set=None):
    # Membership check + sub-mesh engine routing live on the core
    # surface (horovod_tpu._engine / process_set.py). The TF collectives
    # replicate explicitly via e.replicate(...) (same leading-dim==size
    # hazard note as the torch shim's _replicated).
    return _hvd._engine(process_set)


def _to_host(dt) -> np.ndarray:
    """Distributed (size, *shape) result -> this rank's row, via the
    first addressable shard only (no full-stack device_get)."""
    return np.asarray(dt.addressable_shards[0].data)[0]


# -- collectives (reference tensorflow/__init__.py:54-208) ------------------

def _bridge(np_fn, tensor, out_shape=None):
    """Run ``np_fn(numpy_array) -> numpy_array`` against a TF tensor in
    either eager or graph context. Inside a tf.function the call bridges
    through py_function so the engine collective runs at execution time —
    the role the reference's registered TF ops play
    (tensorflow/mpi_ops.cc HorovodAllreduceOp)."""
    tf = _tf()
    if tf.is_tensor(tensor) and not tf.executing_eagerly():
        out = tf.py_function(lambda t: np_fn(t.numpy()), [tensor],
                             tensor.dtype)
        out.set_shape(out_shape if out_shape is not None else tensor.shape)
        return out
    return tf.convert_to_tensor(np_fn(np.asarray(tensor)))


def _allreduce_np(arr: np.ndarray, op: ReduceOp, name: Optional[str],
                  prescale_factor: float, postscale_factor: float,
                  compression=None, process_set=None) -> np.ndarray:
    e = _engine(process_set)
    out = e.allreduce(e.replicate(arr), op, name,
                      prescale_factor, postscale_factor,
                      compression)
    return _to_host(out).astype(arr.dtype, copy=False)


@_no_autograph
def allreduce(tensor, op: ReduceOp = Average, name: Optional[str] = None,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              compression=None, sparse_as_dense: bool = False,
              process_set=None):
    """Dense allreduce; a tf.IndexedSlices input takes the
    SPARSE-AS-ALLGATHER path (reference tensorflow/__init__.py:92-108):
    values and indices are allgathered — the mathematical equivalent of
    summing the sparse gradients — with AVERAGE dividing the gathered
    values by size. ``sparse_as_dense=True`` densifies first instead
    (the reference's DistributedOptimizer knob)."""
    tf = _tf()
    if isinstance(tensor, tf.IndexedSlices):
        if sparse_as_dense:
            return allreduce(tf.convert_to_tensor(tensor), op, name,
                             prescale_factor, postscale_factor,
                             compression, process_set=process_set)
        if op not in (Average, Sum):
            raise NotImplementedError(
                "sparse allreduce supports Average/Sum (reference "
                "tensorflow/__init__.py:101)")
        # Ragged gather: ranks may hold different numbers of slices (the
        # normal case for embedding gradients) — allgather_local
        # negotiates per-rank row counts through the controller. A
        # process-set engine has NO controller (process_set.py builds it
        # controller=None), so in a multi-process world the per-process
        # row counts could silently diverge: fail loudly instead.
        import jax

        if process_set is not None and jax.process_count() > 1:
            raise NotImplementedError(
                "sparse (IndexedSlices) allreduce over a process_set is "
                "not supported in multi-process worlds: the set engine "
                "has no controller to negotiate ragged row counts. Use "
                "sparse_as_dense=True, which reduces a dense tensor.")
        e = _engine(process_set)
        n = _hvd._communicator_size(process_set)
        values = tf.convert_to_tensor(e.allgather_local(
            np.asarray(tensor.values), name=f"{name or 'sparse'}.values"))
        indices = tf.convert_to_tensor(e.allgather_local(
            np.asarray(tensor.indices),
            name=f"{name or 'sparse'}.indices"))
        if op == Average:
            values = values / n
        return tf.IndexedSlices(values, indices,
                                dense_shape=tensor.dense_shape)
    return _bridge(
        lambda a: _allreduce_np(a, op, name, prescale_factor,
                                postscale_factor, compression,
                                process_set), tensor)


def _grouped_allreduce_np(arrs, op: ReduceOp, name: Optional[str],
                          compression=None, prescale_factor=1.0,
                          postscale_factor=1.0, process_set=None):
    """Fused grouped reduction via the engine's bucketed allreduce_tree
    (one collective per fusion bucket, not one per tensor)."""
    e = _engine(process_set)
    dts = [e.replicate(a) for a in arrs]
    outs = e.allreduce_tree(dts, op, name, compression,
                            prescale_factor=prescale_factor,
                            postscale_factor=postscale_factor)
    return [_to_host(o).astype(a.dtype, copy=False)
            for o, a in zip(outs, arrs)]


@_no_autograph
def grouped_allreduce(tensors, op: ReduceOp = Average,
                      name: Optional[str] = None, compression=None,
                      prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0, process_set=None):
    tf = _tf()
    tensors = list(tensors)
    if not tensors:
        return []
    if any(tf.is_tensor(t) for t in tensors) and not tf.executing_eagerly():
        outs = tf.py_function(
            lambda *ts: _grouped_allreduce_np(
                [t.numpy() for t in ts], op, name, compression,
                prescale_factor, postscale_factor, process_set),
            tensors, [t.dtype for t in tensors])
        for o, t in zip(outs, tensors):
            o.set_shape(t.shape)
        return list(outs)
    return [tf.convert_to_tensor(o) for o in _grouped_allreduce_np(
        [np.asarray(t) for t in tensors], op, name, compression,
        prescale_factor, postscale_factor, process_set)]


@_no_autograph
def allgather(tensor, name: Optional[str] = None, process_set=None):
    """Concatenate along dim 0 over ranks (reference allgather)."""
    tf = _tf()
    e = _engine(process_set)

    def np_fn(arr):
        out = _to_host(e.allgather(e.replicate(arr), name))
        return out.reshape((-1,) + arr.shape[1:]).astype(arr.dtype,
                                                         copy=False)

    gather_n = _hvd._communicator_size(process_set)
    out_shape = None
    if tf.is_tensor(tensor) and tensor.shape.rank and \
            tensor.shape[0] is not None:
        out_shape = tf.TensorShape(
            [tensor.shape[0] * gather_n]).concatenate(tensor.shape[1:])
    return _bridge(np_fn, tensor, out_shape)


@_no_autograph
def reducescatter(tensor, op: Optional[ReduceOp] = None,
                  name: Optional[str] = None, process_set=None):
    """This rank's 1/n slice of the elementwise reduction over dim 0
    (the later-Horovod TF surface; absent from the pinned era). The
    default op matches upstream's reducescatter default (Average), so a
    drop-in migration keeps its scaling; the default flipped from Sum
    in round 4, so a defaulted call warns once per process (see
    horovod_tpu.reducescatter)."""
    if op is None:
        op = _hvd._reducescatter_default_op()
    tf = _tf()
    e = _engine(process_set)

    def np_fn(arr):
        out = _to_host(e.reducescatter(e.replicate(arr), op, name))
        return out.astype(arr.dtype, copy=False)

    n = _hvd._communicator_size(process_set)
    out_shape = None
    if tf.is_tensor(tensor) and tensor.shape.rank and \
            tensor.shape[0] is not None:
        if tensor.shape[0] % n != 0:
            # Fail loudly instead of declaring a floor-divided static
            # shape that silently disagrees with the engine.
            raise ValueError(
                f"reducescatter dim 0 ({tensor.shape[0]}) must be "
                f"divisible by the communicator size ({n})")
        out_shape = tf.TensorShape(
            [tensor.shape[0] // n]).concatenate(tensor.shape[1:])
    return _bridge(np_fn, tensor, out_shape)


@_no_autograph
def grouped_allgather(tensors, name: Optional[str] = None,
                      process_set=None):
    # name=None passes through per leaf: the engine auto-names each
    # uniquely (a constant default prefix would collide across calls).
    return [allgather(t, f"{name}.{i}" if name else None,
                      process_set=process_set)
            for i, t in enumerate(tensors)]


@_no_autograph
def grouped_reducescatter(tensors, op: Optional[ReduceOp] = None,
                          name: Optional[str] = None, process_set=None):
    return [reducescatter(t, op, f"{name}.{i}" if name else None,
                          process_set=process_set)
            for i, t in enumerate(tensors)]


@_no_autograph
def broadcast(tensor, root_rank: int = 0, name: Optional[str] = None,
              process_set=None):
    """With ``process_set``, ``root_rank`` is the GLOBAL rank of the
    root (resolution happens in horovod_tpu.broadcast)."""
    e = _engine(process_set)
    return _bridge(
        lambda arr: _to_host(_hvd.broadcast(
            e.replicate(arr), root_rank, name,
            process_set=process_set)).astype(arr.dtype, copy=False),
        tensor)


@_no_autograph
def alltoall(tensor, name: Optional[str] = None, process_set=None):
    e = _engine(process_set)
    return _bridge(
        lambda arr: _to_host(e.alltoall(e.replicate(arr), name)).astype(
            arr.dtype, copy=False),
        tensor)


def broadcast_variables(variables, root_rank: int = 0,
                        process_set=None) -> None:
    """In-place assign of root's values onto tf.Variables (reference
    tensorflow/functions.py:47 broadcast_variables). Handles both
    tf.Variable (.value() method) and keras-3 Variable (.value
    property) via convert_to_tensor."""
    tf = _tf()
    for i, v in enumerate(variables):
        v.assign(broadcast(tf.convert_to_tensor(v), root_rank,
                           name=f"bcast.{getattr(v, 'name', i)}",
                           process_set=process_set))


broadcast_object = _hvd.broadcast_object
allgather_object = _hvd.allgather_object


def BroadcastGlobalVariablesHook(root_rank: int = 0, device: str = "",
                                 process_set=None):
    """TF1 estimator/MonitoredSession hook (reference
    tensorflow/__init__.py:211-244): broadcasts ALL global variables
    from ``root_rank`` right after session creation, so every worker
    starts from identical state under random init or a root-only
    checkpoint restore.

    Factory returning a ``tf.compat.v1.train.SessionRunHook`` instance
    (a factory, not a module-level class, because the shim loads TF
    lazily). Mechanics differ from the reference by design: the
    reference builds an in-graph broadcast op; here values round-trip
    through the engine's XLA broadcast at ``after_create_session`` time
    and re-enter the graph through placeholder-fed assigns — graph-mode
    sessions can't host the JAX collective, and a one-time startup
    broadcast has no steady-state performance budget. ``device`` is
    accepted for API parity and ignored (placement is XLA's business).

    Usage (drop-in):
        hooks = [hvd.BroadcastGlobalVariablesHook(0)]
        with tf.compat.v1.train.MonitoredTrainingSession(
                hooks=hooks, ...) as sess: ...
    """
    tf = _tf()
    v1 = tf.compat.v1
    e = _engine(process_set)

    class _BroadcastGlobalVariablesHook(v1.train.SessionRunHook):
        def __init__(self):
            self.root_rank = root_rank
            self._vars = []
            self._placeholders = []
            self._assigns = []

        def begin(self):
            # Graph-build time: one placeholder-fed assign per global
            # variable (ops must exist before the session finalizes the
            # graph — MonitoredSession forbids post-begin graph edits).
            self._vars = list(v1.global_variables())
            self._placeholders = [
                v1.placeholder(v.dtype.base_dtype, v.shape)
                for v in self._vars]
            self._assigns = [
                v1.assign(v, ph)
                for v, ph in zip(self._vars, self._placeholders)]

        def after_create_session(self, session, coord):
            values = session.run(self._vars)
            for i, (var, ph, assign, val) in enumerate(
                    zip(self._vars, self._placeholders, self._assigns,
                        values)):
                arr = np.asarray(val)
                out = _to_host(_hvd.broadcast(
                    e.replicate(arr), self.root_rank,
                    name=f"v1hook.{getattr(var, 'name', i)}",
                    process_set=process_set))
                out = out.astype(arr.dtype, copy=False)
                session.run(assign,
                            feed_dict={ph: out.reshape(arr.shape)})

    return _BroadcastGlobalVariablesHook()


# -- DistributedGradientTape (reference tensorflow/__init__.py:564-629) -----

class _DistributedGradientTape:
    def __init__(self, tape, op: ReduceOp = Average,
                 compression=None, process_set=None):
        self._tape = tape
        self._op = op
        self._compression = compression
        self._process_set = process_set

    def __getattr__(self, item):
        return getattr(self._tape, item)

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def gradient(self, target, sources, output_gradients=None, **kwargs):
        """Same contract as tf.GradientTape.gradient (structure of the
        result mirrors ``sources``; extra kwargs like
        unconnected_gradients pass through), with every gradient
        allreduced via the fused grouped path."""
        tf = _tf()
        grads = self._tape.gradient(target, sources, output_gradients,
                                    **kwargs)
        flat = tf.nest.flatten(grads)
        present = [(i, g) for i, g in enumerate(flat) if g is not None]
        if present:
            reduced = grouped_allreduce([g for _, g in present],
                                        op=self._op, name="tape.grads",
                                        compression=self._compression,
                                        process_set=self._process_set)
            for (i, _), r in zip(present, reduced):
                flat[i] = r
        return tf.nest.pack_sequence_as(grads, flat)


def DistributedGradientTape(tape, op: ReduceOp = Average,
                            compression=None,
                            process_set=None) -> _DistributedGradientTape:
    return _DistributedGradientTape(tape, op, compression, process_set)


# -- Keras optimizer wrapper (reference _keras/__init__.py:28-135) ----------

def _reduce_grads_and_vars(gv, reduce_op, name_prefix,
                           sparse_as_dense=False,
                           gradient_predivide_factor=1.0,
                           process_set=None):
    """Reduce a grads_and_vars list: dense grads through ONE fused
    grouped allreduce, IndexedSlices through the sparse-as-allgather
    path (reference _make_allreduce_grads_fn semantics, incl. the
    predivide split: scale by 1/f before the SUM and f/size after —
    size being the communicator's, i.e. the set's when one is given)."""
    tf = _tf()
    pre = post = 1.0
    sparse_op = reduce_op
    if gradient_predivide_factor != 1.0:
        f = gradient_predivide_factor
        n = _hvd._communicator_size(process_set)
        # Dense path: split the average around a SUM. The sparse
        # (allgather) path keeps the original AVERAGE — predivide is a
        # dense-reduction scaling trick and must not turn gathered
        # slices into an unscaled sum.
        reduce_op, pre, post = Sum, 1.0 / f, f / n
    gv = [list(x) for x in gv]
    dense = [(i, g) for i, (g, _) in enumerate(gv)
             if g is not None and not isinstance(g, tf.IndexedSlices)]
    sparse = [(i, g) for i, (g, _) in enumerate(gv)
              if isinstance(g, tf.IndexedSlices)]
    if dense:
        reduced = grouped_allreduce([g for _, g in dense],
                                    op=reduce_op,
                                    name=f"{name_prefix}.grads",
                                    prescale_factor=pre,
                                    postscale_factor=post,
                                    process_set=process_set)
    else:
        reduced = []
    for (i, _), r in zip(dense, reduced):
        gv[i][0] = r
    for i, g in sparse:
        gv[i][0] = allreduce(g, op=sparse_op,
                             name=f"{name_prefix}.sparse{i}",
                             sparse_as_dense=sparse_as_dense,
                             process_set=process_set)
    return [tuple(x) for x in gv]


def DistributedOptimizer(optimizer, op: ReduceOp = Average,
                         name: Optional[str] = None,
                         backward_passes_per_step: int = 1,
                         average_aggregated_gradients: bool = False,
                         sparse_as_dense: bool = False,
                         gradient_predivide_factor: float = 1.0,
                         process_set=None):
    """Wrap a keras optimizer so apply_gradients allreduces first. Like
    the reference (_keras/__init__.py:28-135 create_distributed_optimizer)
    this dynamically subclasses the optimizer's own class and rebuilds it
    from config — keras requires a genuine Optimizer instance in
    compile().

    ``backward_passes_per_step > 1`` aggregates that many local
    apply_gradients calls before one fused allreduce + global apply (the
    LocalGradientAggregationHelper, reference
    tensorflow/gradient_aggregation.py:16 /
    gradient_aggregation_eager.py); ``average_aggregated_gradients``
    divides the aggregate by the pass count (reference default False:
    aggregated passes SUM unless asked to average).
    ``gradient_predivide_factor`` splits averaging around the sum —
    1/f before, f/size after (reference tensorflow/__init__.py:487)."""
    if gradient_predivide_factor != 1.0 and op != Average:
        raise ValueError(
            "gradient_predivide_factor requires op=Average (reference "
            "tensorflow/__init__.py:507)")
    cls = optimizer.__class__
    reduce_op = op
    k = int(backward_passes_per_step)

    def apply_gradients(self, grads_and_vars, *args, **kwargs):
        tf = _tf()
        gv = list(grads_and_vars)
        if k > 1:
            if not tf.executing_eagerly():
                # Graph mode: Python counters would only advance at TRACE
                # time, baking one branch into the graph — so the state
                # lives in tf.Variables and the flush is a tf.cond (the
                # reference's LocalGradientAggregationHelper,
                # gradient_aggregation.py:16).
                return self._hvd_graph_aggregate(gv, args, kwargs)
            # Eager helper semantics (gradient_aggregation_eager.py):
            # bank the grads; reduce+apply on the k-th call.
            if not hasattr(self, "_hvd_agg"):
                self._hvd_agg = {}
                self._hvd_agg_count = 0
            for i, (g, _) in enumerate(gv):
                if g is None:
                    continue
                if isinstance(g, tf.IndexedSlices):
                    g = tf.convert_to_tensor(g)
                acc = self._hvd_agg.get(i)
                self._hvd_agg[i] = g if acc is None else acc + g
            self._hvd_agg_count += 1
            if self._hvd_agg_count < k:
                return None
            scale = 1.0 / k if average_aggregated_gradients else 1.0
            gv = [list(x) for x in gv]
            for i, acc in self._hvd_agg.items():
                gv[i][0] = acc * scale
            gv = [tuple(x) for x in gv]
            self._hvd_agg = {}
            self._hvd_agg_count = 0
        reduced = _reduce_grads_and_vars(gv, reduce_op, "opt",
                                         sparse_as_dense,
                                         gradient_predivide_factor,
                                         process_set)
        return super(dist_cls, self).apply_gradients(reduced, *args,
                                                     **kwargs)

    def _hvd_graph_aggregate(self, gv, fwd_args, fwd_kwargs):
        """tf.Variable-backed local aggregation for traced (tf.function)
        apply_gradients — accumulate every call, tf.cond-flush through
        the fused reduce on the k-th (reference
        gradient_aggregation.py)."""
        tf = _tf()
        variables = [v for _, v in gv]
        if not hasattr(self, "_hvd_agg_vars"):
            with tf.init_scope():
                self._hvd_counter = tf.Variable(
                    0, dtype=tf.int64, trainable=False,
                    name="hvd_agg_counter")
                # Accumulators only for connected (non-None) gradients —
                # a None gradient stays None through the flush, matching
                # the eager path (an all-zeros stand-in would still move
                # momentum/weight-decay state on untouched variables).
                self._hvd_agg_idx = [i for i, (g, _) in enumerate(gv)
                                     if g is not None]
                self._hvd_agg_vars = [
                    tf.Variable(tf.zeros(gv[i][1].shape,
                                         dtype=gv[i][0].dtype),
                                trainable=False, name="hvd_agg")
                    for i in self._hvd_agg_idx]
                self._hvd_agg_var_ids = [id(v) for v in variables]
        if [id(v) for v in variables] != self._hvd_agg_var_ids:
            raise ValueError(
                "apply_gradients called with a different variable list "
                "than the first call; local gradient aggregation keys "
                "its accumulators to a stable grads_and_vars order")
        assigns = []
        for acc, i in zip(self._hvd_agg_vars, self._hvd_agg_idx):
            g = gv[i][0]
            if g is None:
                continue
            if isinstance(g, tf.IndexedSlices):
                g = tf.convert_to_tensor(g)
            assigns.append(acc.assign_add(tf.cast(g, acc.dtype)))
        with tf.control_dependencies(assigns):
            count = self._hvd_counter.assign_add(1)

        def _flush():
            scale = (1.0 / k) if average_aggregated_gradients else 1.0
            grads = [None] * len(gv)
            for acc, i in zip(self._hvd_agg_vars, self._hvd_agg_idx):
                grads[i] = tf.convert_to_tensor(acc) * scale
            reduced = _reduce_grads_and_vars(
                list(zip(grads, variables)), reduce_op, "opt",
                sparse_as_dense, gradient_predivide_factor, process_set)
            result = super(dist_cls, self).apply_gradients(
                reduced, *fwd_args, **fwd_kwargs)
            # Order the zeroing after the apply for v1-graph fetches
            # too: control_dependencies accepts Operations as well as
            # Tensors, so gate only on None.
            deps = [result] if result is not None else []
            with tf.control_dependencies(deps):
                zeros = [acc.assign(tf.zeros_like(acc))
                         for acc in self._hvd_agg_vars]
            with tf.control_dependencies(zeros):
                return tf.constant(True)

        return tf.cond(tf.equal(count % k, 0), _flush,
                       lambda: tf.constant(False))

    dist_cls = type(f"Distributed{cls.__name__}", (cls,),
                    {"apply_gradients": apply_gradients,
                     "_hvd_graph_aggregate": _hvd_graph_aggregate})
    return dist_cls.from_config(optimizer.get_config())


def _DistributedAdasumOptimizer(optimizer, name: Optional[str] = None):
    """Delta-based Adasum optimizer (reference
    tensorflow/__init__.py:368-462 _DistributedAdasumOptimizer): each
    rank applies the inner optimizer LOCALLY, extracts the resulting
    weight delta, rolls the weights back, Adasum-reduces the delta, and
    applies the reduced delta — so the adaptive-summation math sees
    optimizer-shaped steps, not raw gradients."""
    cls = optimizer.__class__

    def apply_gradients(self, grads_and_vars, *args, **kwargs):
        tf = _tf()
        gv = list(grads_and_vars)
        variables = [v for _, v in gv]
        before = [tf.identity(v) for v in variables]
        result = super(adasum_cls, self).apply_gradients(gv, *args,
                                                         **kwargs)
        deltas = [v - b for v, b in zip(variables, before)]
        reduced = grouped_allreduce(deltas, op=Adasum,
                                    name="adasum.delta")
        for v, b, d in zip(variables, before, reduced):
            v.assign(b + d)
        return result

    adasum_cls = type(f"DistributedAdasum{cls.__name__}", (cls,),
                      {"apply_gradients": apply_gradients})
    return adasum_cls.from_config(optimizer.get_config())


# -- Keras callbacks (reference keras/callbacks.py) -------------------------

def _keras_callback_base():
    import tensorflow as tf

    return tf.keras.callbacks.Callback


def BroadcastGlobalVariablesCallback(root_rank: int = 0):
    """Keras callback: broadcast all model/optimizer variables from root
    at train start (reference _keras/callbacks.py
    BroadcastGlobalVariablesCallbackImpl)."""
    Base = _keras_callback_base()

    class _Cb(Base):
        def __init__(self):
            super().__init__()
            self._done = False

        def on_train_begin(self, logs=None):
            if self._done:
                return
            broadcast_variables(self.model.variables, root_rank)
            self._done = True

    return _Cb()


def MetricAverageCallback():
    """Keras callback: allreduce-average epoch metrics (reference
    _keras/callbacks.py MetricAverageCallbackImpl)."""
    Base = _keras_callback_base()

    class _Cb(Base):
        def on_epoch_end(self, epoch, logs=None):
            if not logs:
                return
            for k, v in list(logs.items()):
                if isinstance(v, (int, float, np.floating)):
                    out = allreduce(np.full((), float(v), np.float32),
                                    op=Average, name=f"metric.{k}")
                    logs[k] = float(np.asarray(out))

    return _Cb()


def _set_keras_lr(optimizer, lr: float) -> None:
    # keras 3 uses .learning_rate; tf.keras 2 accepts either name.
    attr = ("learning_rate" if hasattr(optimizer, "learning_rate")
            else "lr")
    setattr(optimizer, attr, lr)


def LearningRateScheduleCallback(initial_lr: float, multiplier,
                                 start_epoch: int = 0,
                                 end_epoch: Optional[int] = None,
                                 staircase: bool = True,
                                 steps_per_epoch: Optional[int] = None):
    """Keras callback: lr = initial_lr * multiplier(epoch) within
    [start_epoch, end_epoch] (reference _keras/callbacks.py
    LearningRateScheduleCallbackImpl — same smooth/staircase contract as
    the JAX-surface callback, horovod_tpu/callbacks.py)."""
    import math

    Base = _keras_callback_base()
    mult = multiplier if callable(multiplier) else (lambda _e: multiplier)

    class _Cb(Base):
        def __init__(self):
            super().__init__()
            self._epoch = 0.0

        def _in_range(self):
            return (self._epoch >= start_epoch
                    and (end_epoch is None or self._epoch <= end_epoch))

        def _apply(self):
            if self._in_range():
                _set_keras_lr(self.model.optimizer,
                              initial_lr * mult(self._epoch))

        def on_epoch_begin(self, epoch, logs=None):
            self._epoch = float(epoch)
            if staircase or not steps_per_epoch:
                self._apply()

        def on_batch_begin(self, batch, logs=None):
            if not staircase and steps_per_epoch:
                self._epoch = math.floor(self._epoch) + \
                    batch / steps_per_epoch
                self._apply()

    return _Cb()


def LearningRateWarmupCallback(initial_lr: float, warmup_epochs: int = 5,
                               steps_per_epoch: Optional[int] = None,
                               verbose: int = 0):
    """Keras callback: Goyal et al. gradual warmup from initial_lr/size
    to initial_lr over warmup_epochs, inert afterwards (reference
    _keras/callbacks.py LearningRateWarmupCallbackImpl)."""
    n = size()

    def mult(epoch: float) -> float:
        progress = min(epoch / warmup_epochs, 1.0)
        return (1.0 + progress * (n - 1)) / n

    cb = LearningRateScheduleCallback(
        initial_lr, mult, start_epoch=0, end_epoch=warmup_epochs,
        staircase=False, steps_per_epoch=steps_per_epoch)

    if verbose:
        orig = cb.on_epoch_begin

        def on_epoch_begin(epoch, logs=None):
            orig(epoch, logs)
            if epoch == warmup_epochs:
                print(f"Epoch {epoch}: finished gradual learning rate "
                      f"warmup to {initial_lr}.")

        cb.on_epoch_begin = on_epoch_begin
    return cb


def BestModelCheckpoint(filepath: str, monitor: str = "val_loss",
                        mode: str = "auto", save_best_only: bool = True,
                        **kwargs):
    """Keras ModelCheckpoint that only rank 0 writes (reference
    keras/callbacks.py:157 BestModelCheckpoint: save_best_only rank-0
    writer). The decision metric must already be rank-consistent (use
    MetricAverageCallback before it)."""
    tf = _tf()
    if not save_best_only:
        raise ValueError(
            "BestModelCheckpoint requires save_best_only=True "
            "(reference keras/callbacks.py BestModelCheckpoint)")

    class _Cb(tf.keras.callbacks.ModelCheckpoint):
        def _save_model(self, *args, **kw):
            if rank() == 0:
                super()._save_model(*args, **kw)

    return _Cb(filepath=filepath, monitor=monitor, mode=mode,
               save_best_only=True, **kwargs)
