"""Alias of horovod_tpu.keras.elastic (reference
horovod/tensorflow/keras/elastic.py)."""

from horovod_tpu.keras.elastic import *  # noqa: F401,F403
from horovod_tpu.keras.elastic import __all__  # noqa: F401
