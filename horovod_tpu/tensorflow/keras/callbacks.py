"""Alias of horovod_tpu.keras.callbacks (reference
horovod/tensorflow/keras/callbacks.py)."""

from horovod_tpu.keras.callbacks import *  # noqa: F401,F403
from horovod_tpu.keras.callbacks import __all__  # noqa: F401
