"""`horovod.tensorflow.keras` namespace alias (the reference ships the
keras binding twice — standalone keras and tf.keras flavors,
horovod/tensorflow/keras/__init__.py; both surfaces are identical here
because Keras 3 is the only keras)."""

from horovod_tpu.keras import *  # noqa: F401,F403
from horovod_tpu.keras import __all__, callbacks, elastic  # noqa: F401
