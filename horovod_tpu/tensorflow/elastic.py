"""TensorFlowKerasState — elastic state for the TF/keras shim.

Reference: horovod/tensorflow/elastic.py:91-155 (TensorFlowKerasState:
snapshot model weights + optimizer variables to host, restore on
rollback, broadcast on sync) and :156-196 (TensorFlowState over plain
variable lists).
"""

from __future__ import annotations

from ..common.elastic import ObjectState


def _optimizer_weights(optimizer):
    """Keras-3 and tf.keras-2 compatible optimizer variable access."""
    if hasattr(optimizer, "variables"):
        vs = optimizer.variables
        return list(vs() if callable(vs) else vs)
    return []


class TensorFlowKerasState(ObjectState):
    """Elastic state for a keras model (+ optionally its optimizer):
    ``save()`` snapshots weights to host numpy, ``restore()`` rolls them
    back after a collective failure, ``sync()`` broadcasts rank 0's
    weights after a topology change (reference tensorflow/elastic.py
    TensorFlowKerasState semantics)."""

    def __init__(self, model, optimizer=None, **kwargs):
        object.__setattr__(self, "model", model)
        object.__setattr__(self, "optimizer", optimizer)
        object.__setattr__(self, "_saved_model", None)
        object.__setattr__(self, "_saved_opt", None)
        super().__init__(**kwargs)
        self.save()

    def _snapshot(self):
        model_w = [w.copy() for w in self.model.get_weights()]
        opt_w = None
        if self.optimizer is not None:
            import numpy as np

            opt_w = [np.array(v) for v in
                     _optimizer_weights(self.optimizer)]
        return model_w, opt_w

    def save(self):
        model_w, opt_w = self._snapshot()
        object.__setattr__(self, "_saved_model", model_w)
        object.__setattr__(self, "_saved_opt", opt_w)
        super().save()

    def restore(self):
        if self._saved_model is not None:
            self.model.set_weights([w.copy()
                                    for w in self._saved_model])
        if self._saved_opt is not None and self.optimizer is not None:
            current = _optimizer_weights(self.optimizer)
            for var, val in zip(current, self._saved_opt):
                var.assign(val)
            # Slot variables created AFTER the snapshot (lazy keras
            # build) did not exist at the committed point — their
            # committed value is zero, not whatever the failed steps
            # left behind.
            for var in current[len(self._saved_opt):]:
                var.assign(var * 0)
        super().restore()

    def sync(self):
        from . import broadcast_variables

        broadcast_variables(self.model.variables, root_rank=0)
        if self.optimizer is not None:
            opt_vars = _optimizer_weights(self.optimizer)
            if opt_vars:
                broadcast_variables(opt_vars, root_rank=0)
        super().sync()  # ends with self.save() → one full snapshot


class TensorFlowState(ObjectState):
    """Elastic state over a plain list of tf.Variables (reference
    tensorflow/elastic.py:156-196)."""

    def __init__(self, variables, **kwargs):
        object.__setattr__(self, "variables", list(variables))
        object.__setattr__(self, "_saved_vars", None)
        super().__init__(**kwargs)
        self.save()

    def save(self):
        import numpy as np

        object.__setattr__(self, "_saved_vars",
                           [np.array(v) for v in self.variables])
        super().save()

    def restore(self):
        if self._saved_vars is not None:
            for var, val in zip(self.variables, self._saved_vars):
                var.assign(val)
        super().restore()

    def sync(self):
        from . import broadcast_variables

        broadcast_variables(self.variables, root_rank=0)
        super().sync()  # ends with self.save()
