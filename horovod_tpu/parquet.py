"""Columnar (parquet) data path — the Petastorm-equivalent.

Reference: horovod/spark/common/util.py:1-712 prepares DataFrames as
Petastorm parquet stores and each training worker reads ONLY its shard
(``make_batch_reader`` with ``cur_shard=rank, shard_count=size``).
TPU rebuild: pyarrow parquet shard files written/read through the
:class:`~horovod_tpu.store.Store` filesystem protocol, so the same
dataset works on local disk, HDFS, S3, or GCS (FsspecStore). N-d rows
ride flattened ``list<item>`` columns with the row shape recorded in
the file schema metadata.

Why columnar instead of the estimator's default pickle blob: a pickle
is loaded WHOLE by every worker (size × overfetch); parquet shards let
each rank open only ``files[rank::size]`` — the property that makes
the reference's Petastorm path scale past memory.
"""

from __future__ import annotations

import json
from typing import Dict, Iterator, List, Optional

import numpy as np

from .store import Store

_META_KEY = b"horovod_tpu.shapes"
_MANIFEST = "_manifest.json"


def write_parquet_shards(store: Store, dir_path: str,
                         columns: Dict[str, np.ndarray],
                         num_shards: int = 4) -> List[str]:
    """Split aligned column arrays row-wise into ``num_shards`` parquet
    files under ``dir_path``; returns the file paths. N-d columns are
    flattened per row; shapes land in schema metadata.

    A ``_manifest.json`` written LAST lists exactly this write's shard
    files plus per-column dtype/shape — readers trust the manifest, so
    a re-used directory (same run_id, fewer shards) never leaks a
    previous write's leftover parts into the dataset."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    lengths = {k: len(v) for k, v in columns.items()}
    if len(set(lengths.values())) != 1:
        raise ValueError(f"column lengths differ: {lengths}")
    nrows = next(iter(lengths.values()))
    num_shards = max(1, min(num_shards, nrows))
    shapes = {k: list(np.asarray(v).shape[1:]) for k, v in columns.items()}
    meta = {_META_KEY: json.dumps(shapes).encode()}

    paths: List[str] = []
    bounds = np.linspace(0, nrows, num_shards + 1, dtype=int)
    for shard in range(num_shards):
        lo, hi = int(bounds[shard]), int(bounds[shard + 1])
        arrays, names = [], []
        for name, col in columns.items():
            part = np.asarray(col)[lo:hi]
            if part.ndim > 1:
                flat = part.reshape(len(part), -1)
                arrays.append(pa.array(list(flat)))
            else:
                arrays.append(pa.array(part))
            names.append(name)
        table = pa.Table.from_arrays(arrays, names=names)
        table = table.replace_schema_metadata(
            {**(table.schema.metadata or {}), **meta})
        path = store.path_join(dir_path, f"part-{shard:05d}.parquet")
        with store.open(path, "wb") as f:
            pq.write_table(table, f)
        paths.append(path)
    store.write(store.path_join(dir_path, _MANIFEST), json.dumps({
        "files": [f"part-{s:05d}.parquet" for s in range(num_shards)],
        "num_rows": nrows,
        "columns": {k: {"dtype": str(np.asarray(v).dtype),
                        "shape": shapes[k]}
                    for k, v in columns.items()},
    }).encode())
    return paths


class ParquetDataset:
    """Rank-sharded reader over a parquet shard directory.

    ``files[rank::size]`` belong to this rank (the reference's
    ``cur_shard``/``shard_count`` contract, spark/common/util.py) —
    shards are disjoint across ranks and their union is the full
    dataset. Iterate for ``(dict of np arrays)`` batches, or call
    :meth:`load` for the rank's full shard in memory.
    """

    def __init__(self, store: Store, dir_path: str, batch_size: int = 32,
                 rank: int = 0, size: int = 1,
                 shuffle_seed: Optional[int] = None):
        if not 0 <= rank < size:
            raise ValueError(f"rank {rank} outside world of {size}")
        self.store = store
        self.batch_size = batch_size
        self.shuffle_seed = shuffle_seed
        self._columns_meta: Dict[str, dict] = {}
        #: dataset-wide row count from the manifest (None for
        #: pre-manifest directories) — lets every rank agree on a
        #: global quantity without reading the other ranks' files.
        self.total_rows: Optional[int] = None
        manifest_path = store.path_join(dir_path, _MANIFEST)
        if store.exists(manifest_path):
            manifest = json.loads(store.read(manifest_path))
            all_files = manifest["files"]
            self._columns_meta = manifest.get("columns", {})
            self.total_rows = manifest.get("num_rows")
        else:  # pre-manifest directory: fall back to a listing
            all_files = sorted(n for n in store.listdir(dir_path)
                               if n.endswith(".parquet"))
        if not all_files:
            raise FileNotFoundError(
                f"no .parquet shards under {dir_path}")
        self.files = [store.path_join(dir_path, n)
                      for n in all_files[rank::size]]

    def _read_file(self, path: str) -> Dict[str, np.ndarray]:
        import pyarrow as pa
        import pyarrow.parquet as pq

        with self.store.open(path, "rb") as f:
            table = pq.read_table(f)
        shapes = json.loads(
            (table.schema.metadata or {}).get(_META_KEY, b"{}"))
        out = {}
        for name in table.column_names:
            col = table.column(name).combine_chunks()
            # to_numpy (not to_pylist+asarray) keeps the arrow value
            # type — float32 stays float32 instead of widening to
            # python-float64 — and skips the per-row python objects.
            if pa.types.is_list(col.type):
                arr = col.flatten().to_numpy(zero_copy_only=False) \
                    .reshape(len(col), -1)
            else:
                arr = col.to_numpy(zero_copy_only=False)
            shape = shapes.get(name, [])
            if shape:
                arr = arr.reshape((len(arr),) + tuple(shape))
            out[name] = arr
        return out

    def load(self) -> Dict[str, np.ndarray]:
        """This rank's whole shard, concatenated. A rank whose
        ``files[rank::size]`` slice is empty (more workers than shard
        files) gets 0-row arrays of the right dtype/shape — the same
        contract as the pickle path's empty ``X[rank::nproc]`` slice —
        when the manifest carries the column schema."""
        if not self.files:
            if not self._columns_meta:
                raise FileNotFoundError(
                    "this rank drew no shard files and the directory "
                    "has no manifest to synthesize an empty shard from")
            return {k: np.empty((0,) + tuple(m["shape"]),
                                dtype=np.dtype(m["dtype"]))
                    for k, m in self._columns_meta.items()}
        parts = [self._read_file(p) for p in self.files]
        return {k: np.concatenate([p[k] for p in parts])
                for k in parts[0]}

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        order = list(range(len(self.files)))
        if self.shuffle_seed is not None:
            np.random.default_rng(self.shuffle_seed).shuffle(order)
        for i in order:
            data = self._read_file(self.files[i])
            n = len(next(iter(data.values())))
            row_order = np.arange(n)
            if self.shuffle_seed is not None:
                np.random.default_rng(
                    self.shuffle_seed + i).shuffle(row_order)
            for lo in range(0, n, self.batch_size):
                idx = row_order[lo:lo + self.batch_size]
                yield {k: v[idx] for k, v in data.items()}

    def num_rows(self) -> int:
        import pyarrow.parquet as pq

        total = 0
        for p in self.files:
            with self.store.open(p, "rb") as f:
                total += pq.ParquetFile(f).metadata.num_rows
        return total
