"""Data layer: rank sharding, elastic sampling, device prefetch.

Reference equivalents:
- ``ElasticSampler`` — horovod/torch/elastic/sampler.py:24 (rank
  partitioning with processed-index tracking so an elastic reset
  repartitions only the *unprocessed* remainder of the epoch).
- The Spark data path (petastorm readers feeding per-rank shards).

TPU-native additions: :class:`DeviceInfeed` — a DOUBLE-BUFFERED device
infeed pipeline (docs/performance.md "MFU playbook"): a background
thread stages batch N+1 into HBM (``jax.device_put``, sharding-aware)
while the step consumes batch N, so the host→device transfer never sits
on the timed path; ``prefetch_to_device``/``BackgroundPrefetcher`` ride
it. ``shard_batch`` lays a global batch out rank-major for
``hvd.spmd_step``'s ``P(rank_axis)`` specs — and fuses into infeed
placement (``DeviceInfeed(shard=True)``) so only this rank's slice is
ever transferred. Consumer starvation is measurable:
``hvd_tpu_infeed_wait_seconds`` (how long the step blocked on the next
batch) + ``hvd_tpu_infeed_queue_depth`` feed ``analyze_trace.py
--metrics`` (docs/metrics.md).
"""

from __future__ import annotations

import threading
import weakref
from typing import Callable, Iterable, Iterator, List, Optional, Sequence

import numpy as np

from .common import metrics as _metrics_lib

# Infeed telemetry (docs/metrics.md): starvation is attributable only
# when the wait is measured at the consumer edge — a fast device with a
# slow input pipeline shows up HERE, not in the device trace.
_M_WAIT = _metrics_lib.histogram(
    "hvd_tpu_infeed_wait_seconds",
    "time the consumer blocked waiting for the next device batch "
    "(DeviceInfeed/BackgroundPrefetcher)")
_M_DEPTH = _metrics_lib.gauge(
    "hvd_tpu_infeed_queue_depth",
    "ready device batches queued ahead of the consumer")
_M_BATCHES = _metrics_lib.counter(
    "hvd_tpu_infeed_batches_total",
    "batches delivered through the device-infeed pipelines")
_M_BYTES = _metrics_lib.counter(
    "hvd_tpu_infeed_bytes_total",
    "host bytes handed to device placement by the infeed pipelines")


class ElasticSampler:
    """Partitions dataset indices across ranks; repartitions the
    unprocessed remainder after elastic resets.

    Framework-agnostic (index-based) version of the reference sampler.
    Include it in a ``JaxState``/``ObjectState`` (its state is plain
    picklable attributes), call :meth:`record_batch` after each step and
    :meth:`set_epoch` at epoch end; after a topology change call
    :meth:`reset` (the elastic State's on_reset hook).
    """

    def __init__(self, dataset_size: int, shuffle: bool = True,
                 seed: int = 0):
        self.dataset_size = int(dataset_size)
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.processed_indices: set = set()
        self.rank = 0
        self.num_replicas = 1
        self.remaining_indices: List[int] = []
        self.num_samples = 0
        self.total_size = 0
        self.reset()

    # -- lifecycle ---------------------------------------------------------

    def reset(self) -> None:
        """Re-read world topology and repartition the unprocessed indices
        (called at construction and after elastic resets)."""
        import horovod_tpu as hvd

        if hvd.is_initialized():
            self.rank = hvd.rank()
            self.num_replicas = hvd.size()
        else:
            self.rank, self.num_replicas = 0, 1
        self._repartition()

    def set_epoch(self, epoch: int) -> None:
        """New epoch: clear processed tracking, reshuffle deterministically
        from (seed, epoch) — identical ordering on every rank."""
        self.epoch = epoch
        self.processed_indices = set()
        self._repartition()

    def get_indices(self, batch_idx: int, batch_size: int) -> List[int]:
        """This rank's indices for batch ``batch_idx`` (reference
        get_indices)."""
        start = batch_idx * batch_size
        return self.local_indices()[start:start + batch_size]

    def record_batch(self, batch_idx: int, batch_size: int) -> None:
        """Mark the batch's indices processed (reference record_batch)."""
        self.record_indices(self.get_indices(batch_idx, batch_size))

    def record_indices(self, indices: Sequence[int]) -> None:
        self.processed_indices.update(int(i) for i in indices)

    # -- sampling ----------------------------------------------------------

    def _repartition(self) -> None:
        indices = [i for i in range(self.dataset_size)
                   if i not in self.processed_indices]
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            indices = list(rng.permutation(indices))
        self.remaining_indices = [int(i) for i in indices]
        # Pad to a multiple of num_replicas so every rank sees the same
        # number of samples (same trick as the reference / TF
        # DistributedSampler).
        n = len(self.remaining_indices)
        self.num_samples = -(-n // self.num_replicas) if n else 0
        self.total_size = self.num_samples * self.num_replicas
        if n and self.total_size > n:
            self.remaining_indices += self.remaining_indices[
                :self.total_size - n]

    def local_indices(self) -> List[int]:
        """This rank's shard (strided, reference-style)."""
        return self.remaining_indices[self.rank:self.total_size:
                                      self.num_replicas]

    def __iter__(self) -> Iterator[int]:
        return iter(self.local_indices())

    def __len__(self) -> int:
        return self.num_samples

    # -- pickling (lives inside elastic State objects) ---------------------

    def __getstate__(self):
        d = dict(self.__dict__)
        d["processed_indices"] = sorted(self.processed_indices)
        return d

    def __setstate__(self, d):
        d = dict(d)
        d["processed_indices"] = set(d["processed_indices"])
        self.__dict__.update(d)


def shard_batch(batch, rank: Optional[int] = None,
                size: Optional[int] = None):
    """Slice this rank's rows out of a global batch pytree (for
    multi-process mode; under single-controller SPMD pass the global
    batch straight to spmd_step with ``P(rank_axis)`` specs instead)."""
    import jax

    import horovod_tpu as hvd

    r = hvd.rank() if rank is None else rank
    n = hvd.size() if size is None else size

    def one(x):
        b = x.shape[0]
        if b % n:
            raise ValueError(f"batch dim {b} not divisible by size {n}")
        per = b // n
        return x[r * per:(r + 1) * per]

    return jax.tree.map(one, batch)


def _compose_shard_transform(transform: Optional[Callable]) -> Callable:
    """Fuse this rank's :func:`shard_batch` slice after ``transform`` —
    the shared ``shard=True`` path for :class:`DeviceInfeed` and
    :func:`infeed_pipeline`, so every mode slices identically and only
    1/n of the global batch ever reaches the placement path."""
    import horovod_tpu as hvd

    r = hvd.rank() if hvd.is_initialized() else 0
    n = hvd.size() if hvd.is_initialized() else 1
    base = transform
    return (lambda b: shard_batch(
        base(b) if base is not None else b, rank=r, size=n))


def _host_nbytes(batch) -> int:
    """Host-side bytes of a batch pytree — the
    ``hvd_tpu_infeed_bytes_total`` accounting, shared by every infeed
    mode so "what counts as host bytes" has one definition."""
    import jax

    return sum(getattr(leaf, "nbytes", 0)
               for leaf in jax.tree.leaves(batch))


def _place_batch(batch, sharding):
    """Sharding-aware device placement of a batch pytree (shared by
    every infeed mode: one definition of the transfer semantics)."""
    import jax

    if sharding is not None:
        return jax.tree.map(
            lambda x: jax.device_put(x, sharding), batch)
    return jax.tree.map(jax.device_put, batch)


# Live infeed instances, closed at interpreter exit: a daemon worker
# mid-device_put when the process tears down produces backend aborts
# (and an unjoined thread) — the atexit drain mirrors the
# timeline-writer pattern (common/timeline.py).
_LIVE_INFEEDS: "weakref.WeakSet" = weakref.WeakSet()
_ATEXIT_REGISTERED = False


def _close_live_infeeds() -> None:
    for infeed in list(_LIVE_INFEEDS):
        infeed.close()


class DeviceInfeed:
    """Double-buffered device infeed: a background thread keeps up to
    ``depth`` batches ALREADY PLACED on device (HBM) ahead of the
    consumer, so batch N+1's host→device transfer (and any host-side
    ``transform``) overlaps the step on batch N::

        with hvd.DeviceInfeed(host_batches, depth=2,
                              sharding=sharding) as infeed:
            for batch in infeed:
                state = train_step(state, *batch)

    ``sharding`` (a ``jax.sharding.Sharding``) places each leaf —
    under SPMD pass ``NamedSharding(mesh, P(rank_axis))`` so every
    device receives exactly its shard, with no gather/re-layout at
    dispatch. ``shard=True`` instead slices THIS RANK's rows
    (:func:`shard_batch`) on the worker thread before placement —
    multi-process mode transfers 1/n of the global batch and the full
    batch never exists on the device path. ``transform`` is an
    arbitrary host-side pre-placement hook (decode/augment), run on the
    worker thread.

    Lifecycle: iteration ends (StopIteration) after the source is
    exhausted; a worker-side exception is re-raised to the consumer
    AFTER the batches that preceded it. ``close()`` (also via context
    manager / ``with``) stops the worker, drains the queue, and JOINS
    the thread — abandoning iteration early without closing leaks
    nothing at interpreter exit (an atexit hook closes stragglers), but
    close deterministically when you can. Delivery order is the source
    order. Waits are measured into ``hvd_tpu_infeed_wait_seconds``."""

    _DONE = object()

    def __init__(self, iterator: Iterable, depth: int = 2, sharding=None,
                 transform: Optional[Callable] = None,
                 shard: bool = False):
        import queue as queue_mod

        global _ATEXIT_REGISTERED
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        if shard:
            transform = _compose_shard_transform(transform)
        self._transform = transform
        self._q: "queue_mod.Queue" = queue_mod.Queue(maxsize=depth)
        self._sharding = sharding
        self._error: Optional[BaseException] = None
        self._stop = threading.Event()
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, args=(iter(iterator),), daemon=True,
            name="hvd-device-infeed")
        if not _ATEXIT_REGISTERED:
            # Through the ONE ordered shutdown sequence (hvdlint
            # atexit-order): infeed workers stop before the Context
            # drains metrics, so their final byte counters land in the
            # drain-on-stop snapshot instead of racing it.
            from .common import shutdown as shutdown_lib

            shutdown_lib.register("data-infeeds", _close_live_infeeds,
                                  priority=15)
            _ATEXIT_REGISTERED = True
        _LIVE_INFEEDS.add(self)
        self._thread.start()

    # -- worker -------------------------------------------------------------

    def _put(self, item) -> bool:
        """Bounded put that stays responsive to close(): returns False
        when the consumer is gone."""
        import queue as queue_mod

        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue_mod.Full:
                continue
        return False

    def _run(self, it):
        try:
            for batch in it:
                if self._stop.is_set():
                    return
                if self._transform is not None:
                    batch = self._transform(batch)
                _M_BYTES.inc(_host_nbytes(batch))
                batch = _place_batch(batch, self._sharding)
                if not self._put(batch):
                    return
                _M_DEPTH.set(self._q.qsize())
        except BaseException as e:  # surfaced on the consumer's next()
            self._error = e
        finally:
            self._put(self._DONE)

    # -- consumer -----------------------------------------------------------

    def __iter__(self):
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        with _M_WAIT.time():
            item = self._q.get()
        _M_DEPTH.set(self._q.qsize())
        if item is self._DONE:
            self.close()
            if self._error is not None:
                raise self._error
            raise StopIteration
        _M_BATCHES.inc()
        return item

    def close(self) -> None:
        """Stop the worker, drain queued batches, join the thread.
        Idempotent; called by the context manager, by exhaustion, and
        (as a last resort) by the atexit hook."""
        import queue as queue_mod

        if self._closed:
            return
        self._closed = True
        self._stop.set()
        while True:  # drain so a blocked worker put() unblocks
            try:
                self._q.get_nowait()
            except queue_mod.Empty:
                break
        self._thread.join(timeout=5.0)
        _M_DEPTH.set(0)
        _LIVE_INFEEDS.discard(self)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        # Drain-on-exception included: a raising consumer must not
        # leave the worker blocked on a full queue forever.
        self.close()
        return False


class BackgroundPrefetcher(DeviceInfeed):
    """Thread-backed prefetcher (historical name; now the
    :class:`DeviceInfeed` double-buffered pipeline with the original
    ``size=`` spelling): a worker thread stays ``size`` batches ahead,
    so host preprocessing overlaps both the transfer and the step.
    Supports ``close()`` and ``with`` — see :class:`DeviceInfeed`."""

    def __init__(self, iterator: Iterable, size: int = 2, sharding=None):
        super().__init__(iterator, depth=size, sharding=sharding)


def prefetch_to_device(iterator: Iterable, size: int = 2,
                       sharding=None) -> Iterator:
    """Wrap a host batch iterator so up to ``size`` batches are already
    transferred to device (HBM) ahead of consumption — the TPU analog
    of pinned-memory prefetch, now backed by the double-buffered
    :class:`DeviceInfeed` (transfers happen on a background thread and
    genuinely overlap the step). ``sharding`` (optional
    jax.sharding.Sharding) places each batch; default = committed to
    the default device. The generator form closes the infeed when
    dropped mid-iteration (GeneratorExit → ``close()``)."""
    with DeviceInfeed(iterator, depth=size, sharding=sharding) as infeed:
        yield from infeed


def infeed_pipeline(iterator: Iterable, mode: Optional[str] = None,
                    sharding=None, transform: Optional[Callable] = None,
                    shard: bool = False) -> Iterator:
    """The bench/ablation surface over the infeed modes
    (``HVD_TPU_PREFETCH`` / ``bench.py --prefetch``; docs/performance.md):

    - ``"off"`` — place each batch on demand ON the consumer thread and
      BLOCK until it is device-resident (the full host tax on the timed
      path; the A/B baseline).
    - ``"single"`` — single-buffered: one batch staged ahead, placed on
      the consumer thread between steps (async dispatch may partially
      overlap; no worker thread).
    - ``"double"`` — the real thing: :class:`DeviceInfeed` with
      ``depth=2``, background-thread placement.

    ``mode=None`` resolves the configured default —
    ``init(prefetch=)`` / ``HVD_TPU_PREFETCH`` — falling back to
    ``double``."""
    if mode is None:
        from .common import basics

        if basics.is_initialized():
            mode = basics.context().config.prefetch
        if mode is None:
            from .common.config import _env

            mode = _env("PREFETCH")
        mode = mode or "double"
    if mode not in ("off", "single", "double"):
        raise ValueError(
            f"unknown infeed mode {mode!r}: off | single | double")
    if mode == "double":
        with DeviceInfeed(iterator, depth=2, sharding=sharding,
                          transform=transform, shard=shard) as infeed:
            yield from infeed
        return

    import jax

    if shard:
        transform = _compose_shard_transform(transform)

    def place(batch):
        if transform is not None:
            batch = transform(batch)
        _M_BYTES.inc(_host_nbytes(batch))
        return _place_batch(batch, sharding)

    it = iter(iterator)
    if mode == "off":
        for batch in it:
            with _M_WAIT.time():
                out = place(batch)
                out = jax.block_until_ready(out)
            _M_BATCHES.inc()
            yield out
        return
    # "single": one batch staged ahead on this thread.
    staged = None
    try:
        staged = place(next(it))
    except StopIteration:
        return
    while staged is not None:
        out = staged
        try:
            with _M_WAIT.time():
                staged = place(next(it))
        except StopIteration:
            staged = None
        _M_BATCHES.inc()
        yield out
