"""Data layer: rank sharding, elastic sampling, device prefetch.

Reference equivalents:
- ``ElasticSampler`` — horovod/torch/elastic/sampler.py:24 (rank
  partitioning with processed-index tracking so an elastic reset
  repartitions only the *unprocessed* remainder of the epoch).
- The Spark data path (petastorm readers feeding per-rank shards).

TPU-native additions: ``prefetch_to_device`` keeps a small queue of
batches already resident in HBM so the input pipeline overlaps the step
(the host→HBM transfer is the TPU analog of the reference's GPU
DataLoader pinned-memory prefetch), and ``shard_batch`` lays a global
batch out rank-major for ``hvd.spmd_step``'s ``P(rank_axis)`` specs.
"""

from __future__ import annotations

import collections
import threading
from typing import Iterable, Iterator, List, Optional, Sequence

import numpy as np


class ElasticSampler:
    """Partitions dataset indices across ranks; repartitions the
    unprocessed remainder after elastic resets.

    Framework-agnostic (index-based) version of the reference sampler.
    Include it in a ``JaxState``/``ObjectState`` (its state is plain
    picklable attributes), call :meth:`record_batch` after each step and
    :meth:`set_epoch` at epoch end; after a topology change call
    :meth:`reset` (the elastic State's on_reset hook).
    """

    def __init__(self, dataset_size: int, shuffle: bool = True,
                 seed: int = 0):
        self.dataset_size = int(dataset_size)
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.processed_indices: set = set()
        self.rank = 0
        self.num_replicas = 1
        self.remaining_indices: List[int] = []
        self.num_samples = 0
        self.total_size = 0
        self.reset()

    # -- lifecycle ---------------------------------------------------------

    def reset(self) -> None:
        """Re-read world topology and repartition the unprocessed indices
        (called at construction and after elastic resets)."""
        import horovod_tpu as hvd

        if hvd.is_initialized():
            self.rank = hvd.rank()
            self.num_replicas = hvd.size()
        else:
            self.rank, self.num_replicas = 0, 1
        self._repartition()

    def set_epoch(self, epoch: int) -> None:
        """New epoch: clear processed tracking, reshuffle deterministically
        from (seed, epoch) — identical ordering on every rank."""
        self.epoch = epoch
        self.processed_indices = set()
        self._repartition()

    def get_indices(self, batch_idx: int, batch_size: int) -> List[int]:
        """This rank's indices for batch ``batch_idx`` (reference
        get_indices)."""
        start = batch_idx * batch_size
        return self.local_indices()[start:start + batch_size]

    def record_batch(self, batch_idx: int, batch_size: int) -> None:
        """Mark the batch's indices processed (reference record_batch)."""
        self.record_indices(self.get_indices(batch_idx, batch_size))

    def record_indices(self, indices: Sequence[int]) -> None:
        self.processed_indices.update(int(i) for i in indices)

    # -- sampling ----------------------------------------------------------

    def _repartition(self) -> None:
        indices = [i for i in range(self.dataset_size)
                   if i not in self.processed_indices]
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            indices = list(rng.permutation(indices))
        self.remaining_indices = [int(i) for i in indices]
        # Pad to a multiple of num_replicas so every rank sees the same
        # number of samples (same trick as the reference / TF
        # DistributedSampler).
        n = len(self.remaining_indices)
        self.num_samples = -(-n // self.num_replicas) if n else 0
        self.total_size = self.num_samples * self.num_replicas
        if n and self.total_size > n:
            self.remaining_indices += self.remaining_indices[
                :self.total_size - n]

    def local_indices(self) -> List[int]:
        """This rank's shard (strided, reference-style)."""
        return self.remaining_indices[self.rank:self.total_size:
                                      self.num_replicas]

    def __iter__(self) -> Iterator[int]:
        return iter(self.local_indices())

    def __len__(self) -> int:
        return self.num_samples

    # -- pickling (lives inside elastic State objects) ---------------------

    def __getstate__(self):
        d = dict(self.__dict__)
        d["processed_indices"] = sorted(self.processed_indices)
        return d

    def __setstate__(self, d):
        d = dict(d)
        d["processed_indices"] = set(d["processed_indices"])
        self.__dict__.update(d)


def shard_batch(batch, rank: Optional[int] = None,
                size: Optional[int] = None):
    """Slice this rank's rows out of a global batch pytree (for
    multi-process mode; under single-controller SPMD pass the global
    batch straight to spmd_step with ``P(rank_axis)`` specs instead)."""
    import jax

    import horovod_tpu as hvd

    r = hvd.rank() if rank is None else rank
    n = hvd.size() if size is None else size

    def one(x):
        b = x.shape[0]
        if b % n:
            raise ValueError(f"batch dim {b} not divisible by size {n}")
        per = b // n
        return x[r * per:(r + 1) * per]

    return jax.tree.map(one, batch)


def prefetch_to_device(iterator: Iterable, size: int = 2,
                       sharding=None) -> Iterator:
    """Wrap a host batch iterator so up to ``size`` batches are already
    transferred to device (HBM) ahead of consumption. The transfer of
    batch N+1..N+size overlaps the step on batch N — the TPU analog of
    pinned-memory prefetch. ``sharding`` (optional jax.sharding.Sharding)
    places each batch; default = committed to the default device.
    """
    import jax

    def place(batch):
        if sharding is not None:
            return jax.tree.map(
                lambda x: jax.device_put(x, sharding), batch)
        return jax.tree.map(jax.device_put, batch)

    queue: collections.deque = collections.deque()
    it = iter(iterator)

    def fill():
        while len(queue) < size:
            try:
                queue.append(place(next(it)))
            except StopIteration:
                return False
        return True

    fill()
    while queue:
        out = queue.popleft()
        fill()
        yield out


class BackgroundPrefetcher:
    """Thread-backed variant of :func:`prefetch_to_device` for input
    pipelines whose host-side cost (decode, augment) is non-trivial: a
    worker thread stays ``size`` batches ahead, so host preprocessing
    overlaps both the transfer and the step."""

    _DONE = object()

    def __init__(self, iterator: Iterable, size: int = 2, sharding=None):
        import queue as queue_mod

        self._q: "queue_mod.Queue" = queue_mod.Queue(maxsize=size)
        self._sharding = sharding
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._run, args=(iter(iterator),), daemon=True)
        self._thread.start()

    def _run(self, it):
        import jax

        try:
            for batch in it:
                if self._sharding is not None:
                    batch = jax.tree.map(
                        lambda x: jax.device_put(x, self._sharding), batch)
                else:
                    batch = jax.tree.map(jax.device_put, batch)
                self._q.put(batch)
        except BaseException as e:  # surfaced on next()
            self._error = e
        finally:
            self._q.put(self._DONE)

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._DONE:
            if self._error is not None:
                raise self._error
            raise StopIteration
        return item
