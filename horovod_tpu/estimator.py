"""Estimator — fit/transform training orchestration over the executor
pool with artifacts in a Store.

Reference: horovod/spark/keras/estimator.py:106-390 (KerasEstimator.fit
runs a Horovod job inside Spark executors over partitioned data, writes
checkpoints/logs through the Store, and returns a ``HorovodModel``
transformer) + spark/torch/estimator.py. This is that L7 capability
without the Spark dependency: data and checkpoints go through
``horovod_tpu.store.Store``; the workers are the persistent Executor pool
(the RayExecutor-analog), each training on its rank's shard with
gradients averaged through the engine's collectives.
"""

from __future__ import annotations


from .common.config import runtime_env
from typing import Any, Dict, List, Optional

import numpy as np

from .store import Store


def split_validation(X, y, validation, seed: int = 0):
    """Shared validation handling for every estimator family: a float
    fraction becomes a SEEDED random (train, val) split (a head slice
    of ordered data would hold out a biased sample — the reference
    estimators split randomly too); a (Xv, yv) tuple passes through.
    Returns (X, y, validation_or_None)."""
    X, y = np.asarray(X), np.asarray(y)
    if isinstance(validation, float):
        if not 0.0 < validation < 1.0:
            raise ValueError("validation fraction must be in (0, 1)")
        idx = np.random.default_rng(seed).permutation(len(X))
        n_val = max(int(len(X) * validation), 1)
        validation = (X[idx[:n_val]], y[idx[:n_val]])
        X, y = X[idx[n_val:]], y[idx[n_val:]]
    return X, y, validation


def stage_pickle_data(store: Store, run_id: str, X, y,
                      validation) -> None:
    """Write the train (and optional val) arrays into the run layout."""
    if validation is not None:
        store.write_obj(store.get_data_path(run_id, "val"),
                        (np.asarray(validation[0]),
                         np.asarray(validation[1])))
    store.write_obj(store.get_data_path(run_id, "train"), (X, y))


def validate_data_format(data_format: str) -> str:
    if data_format not in ("pickle", "parquet"):
        raise ValueError(
            f"data_format must be 'pickle' or 'parquet', got "
            f"{data_format!r}")
    return data_format


def stage_data(store: Store, run_id: str, X, y, validation,
               data_format: str, num_shards: int) -> None:
    """One staging dispatch for every estimator family."""
    if data_format == "parquet":
        stage_parquet_data(store, run_id, X, y, validation,
                           num_shards=num_shards)
    else:
        stage_pickle_data(store, run_id, X, y, validation)


def stage_parquet_data(store: Store, run_id: str, X, y, validation,
                       num_shards: int) -> None:
    """Write train (one shard per worker) + optional val as parquet
    through the Store (the Petastorm-equivalent columnar layout)."""
    from .parquet import write_parquet_shards

    run_path = store.get_run_path(run_id)
    write_parquet_shards(
        store, store.path_join(run_path, "train_parquet"),
        {"x": X, "y": y}, num_shards=max(num_shards, 1))
    if validation is not None:
        write_parquet_shards(
            store, store.path_join(run_path, "val_parquet"),
            {"x": np.asarray(validation[0]),
             "y": np.asarray(validation[1])}, num_shards=1)


def load_parquet_shard(store: Store, run_id: str, rank: int,
                       nproc: int):
    """This rank's equalized parquet shard (reads ONLY its files).

    Equal step counts on every rank, even when the file count is not a
    multiple of nproc (round-robin file assignment then skews rows per
    rank): long shards trim and short ones pad by cycling (the
    reference DistributedSampler pads the same way) to exactly
    total_rows // nproc rows. A rank with zero files raises — the
    dataset must carry >= nproc shard files."""
    from .parquet import ParquetDataset

    ds = ParquetDataset(
        store, store.path_join(store.get_run_path(run_id),
                               "train_parquet"),
        rank=rank, size=nproc)
    shard = ds.load()
    Xs, ys = shard["x"], shard["y"]
    if nproc > 1 and ds.total_rows is not None:
        min_shard = ds.total_rows // nproc
        if min_shard == 0:
            raise ValueError(
                f"{ds.total_rows} training rows cannot feed "
                f"{nproc} workers")
        if len(Xs) == 0:
            raise ValueError(
                f"rank {rank} drew no parquet shard files (dataset "
                f"has fewer files than {nproc} workers) — rewrite "
                f"the shards with num_shards >= the worker count")
        if len(Xs) < min_shard:
            reps = -(-min_shard // len(Xs))
            Xs = np.concatenate([Xs] * reps)[:min_shard]
            ys = np.concatenate([ys] * reps)[:min_shard]
        else:
            Xs, ys = Xs[:min_shard], ys[:min_shard]
    return Xs, ys


def load_parquet_val(store: Store, run_id: str):
    from .parquet import ParquetDataset

    v = ParquetDataset(
        store, store.path_join(store.get_run_path(run_id),
                               "val_parquet")).load()
    return v["x"], v["y"]


def rank_shard(X, y, rank: int, nproc: int):
    """Strided rank shard EQUALIZED to len(X)//nproc rows (shards
    differ by <= 1 row; uneven per-epoch batch counts would leave one
    rank's collective without partners — every estimator worker must
    run the identical number of steps). Raises when a rank would be
    empty: silently training on nothing corrupts the model (NaN loss)
    with no signal."""
    if nproc <= 1:
        return X, y
    min_shard = len(X) // nproc
    if min_shard == 0:
        raise ValueError(
            f"{len(X)} training rows cannot feed {nproc} workers — "
            f"reduce num_proc or provide more data")
    return X[rank::nproc][:min_shard], y[rank::nproc][:min_shard]


def _resolve_loss(loss):
    if callable(loss):
        return loss
    import optax

    if loss == "mse":
        return lambda pred, y: ((pred - y) ** 2).mean()
    if loss == "softmax_cross_entropy":
        return lambda logits, y: \
            optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()
    raise ValueError(f"unknown loss {loss!r} (use a callable, 'mse', or "
                     "'softmax_cross_entropy')")


def _train_worker(store: Store, run_id: str, model, optimizer, loss,
                  epochs: int, batch_size: int, seed: int,
                  shuffle: bool, has_val: bool = False,
                  data_format: str = "pickle") -> Dict[str, Any]:
    """Per-worker training loop (the reference's RemoteTrainer fn,
    spark/keras/remote.py): shard by rank, grads averaged across the
    world via the engine's grouped allreduce, rank 0 checkpoints."""
    import jax

    import horovod_tpu as hvd

    hvd.init()
    nproc = max(int(runtime_env("NUM_PROC", "1")), 1)
    rank = int(runtime_env("PROC_ID", "0"))

    if data_format == "parquet":
        # Columnar path (reference Petastorm contract): this rank opens
        # ONLY its shard files — no size x overfetch of the pickle blob.
        Xs, ys = load_parquet_shard(store, run_id, rank, nproc)
        val = load_parquet_val(store, run_id) \
            if (has_val and rank == 0) else None
    else:
        X, y = store.read_obj(store.get_data_path(run_id, "train"))
        # Validation presence travels as an explicit flag (NOT file
        # existence — a reused run_id must not resurrect a previous
        # fit's stale val set), and only rank 0 evaluates it: the other
        # ranks' val_history is never consumed.
        val = None
        if has_val and rank == 0:
            val = store.read_obj(store.get_data_path(run_id, "val"))
        # Equalized rank shard (the reference trains each worker on
        # its partition; equal sizes keep the per-step grouped
        # allreduce counts aligned across ranks).
        Xs, ys = rank_shard(X, y, rank, nproc)

    loss_fn = _resolve_loss(loss)
    rng = jax.random.PRNGKey(seed)
    params = model.init(rng, Xs[:1])
    params = hvd.broadcast_object(params, root_rank=0,
                                  name=f"est.{run_id}.params")
    opt_state = optimizer.init(params)

    @jax.jit
    def local_grads(params, xb, yb):
        def f(p):
            return loss_fn(model.apply(p, xb), yb)

        return jax.value_and_grad(f)(params)

    @jax.jit
    def apply_updates(params, opt_state, grads):
        import optax

        updates, new_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_state

    @jax.jit
    def eval_loss(params, xb, yb):
        return loss_fn(model.apply(params, xb), yb)

    nrows = len(Xs)
    steps = max(nrows // batch_size, 1)
    history: List[float] = []
    val_history: List[float] = []
    shuffle_rng = np.random.default_rng(seed)
    for epoch in range(epochs):
        order = (shuffle_rng.permutation(nrows) if shuffle
                 else np.arange(nrows))
        epoch_loss = 0.0
        for s in range(steps):
            idx = order[s * batch_size:(s + 1) * batch_size]
            if len(idx) == 0:
                continue
            l, grads = local_grads(params, Xs[idx], ys[idx])
            # Average gradients across the world through the engine
            # (fusion/controller machinery included). Results come back
            # rank-major; this process's row is its reduced value.
            reduced = hvd.grouped_allreduce(
                jax.tree.map(lambda g: np.asarray(g), grads),
                op=hvd.Average, name=f"est.{run_id}.e{epoch}.s{s}")
            reduced = jax.tree.map(
                lambda d: np.asarray(d.addressable_data(0))[0], reduced)
            params, opt_state = apply_updates(params, opt_state, reduced)
            epoch_loss += float(l)
        history.append(epoch_loss / steps)
        if val is not None:
            # Full validation set, identical on every rank (reference
            # estimators report per-epoch val metrics).
            val_history.append(float(eval_loss(params, val[0], val[1])))
        if rank == 0:
            ckpt = store.path_join(store.get_checkpoint_path(run_id),
                                   f"epoch_{epoch}.pkl")
            store.write_obj(ckpt, jax.tree.map(np.asarray, params))
            store.write_obj(
                store.path_join(store.get_logs_path(run_id),
                                "history.pkl"),
                {"train": history, "val": val_history})
    if rank == 0:
        store.write_obj(
            store.path_join(store.get_checkpoint_path(run_id),
                            "final.pkl"),
            jax.tree.map(np.asarray, params))
    return {"rank": rank, "history": history,
            "val_history": val_history}


class TrainedModel:
    """The fitted transformer (reference: HorovodModel / KerasModel
    Spark Transformer, spark/keras/estimator.py:392+): host-side
    inference over the trained params, loadable from the Store."""

    def __init__(self, model, params, store: Store, run_id: str,
                 history: Optional[List[float]] = None,
                 val_history: Optional[List[float]] = None):
        self.model = model
        self.params = params
        self.store = store
        self.run_id = run_id
        self.history = history or []
        self.val_history = val_history or []

    @classmethod
    def load(cls, store: Store, run_id: str, model) -> "TrainedModel":
        params = store.read_obj(store.path_join(
            store.get_checkpoint_path(run_id), "final.pkl"))
        history: List[float] = []
        val_history: List[float] = []
        hist_path = store.path_join(store.get_logs_path(run_id),
                                    "history.pkl")
        if store.exists(hist_path):
            logged = store.read_obj(hist_path)
            if isinstance(logged, dict):
                history = logged.get("train", [])
                val_history = logged.get("val", [])
            else:  # pre-validation log format
                history = logged
        return cls(model, params, store, run_id, history, val_history)

    def transform(self, X, batch_size: int = 1024) -> np.ndarray:
        """Batched inference (the Transformer.transform contract)."""
        outs = []
        for s in range(0, len(X), batch_size):
            outs.append(np.asarray(
                self.model.apply(self.params, X[s:s + batch_size])))
        return np.concatenate(outs, axis=0)

    predict = transform


class Estimator:
    """Distributed fit/transform over the executor pool.

    Usage::

        store = hvd.store.Store.create("/tmp/run_store")
        est = hvd.estimator.Estimator(model=MLP(), optimizer=optax.adam(1e-2),
                                      loss="mse", store=store, num_proc=2,
                                      epochs=5, batch_size=16)
        trained = est.fit(X, y)
        pred = trained.transform(X_test)
    """

    def __init__(self, model, optimizer, loss: Any = "mse",
                 store: Optional[Store] = None, num_proc: int = 2,
                 epochs: int = 1, batch_size: int = 32,
                 run_id: Optional[str] = None, shuffle: bool = True,
                 seed: int = 0,
                 worker_env: Optional[Dict[str, str]] = None,
                 data_format: str = "pickle"):
        validate_data_format(data_format)
        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.store = store
        self.num_proc = num_proc
        self.epochs = epochs
        self.batch_size = batch_size
        self.run_id = run_id
        self.shuffle = shuffle
        self.seed = seed
        self.worker_env = worker_env
        self.data_format = data_format

    # -- Spark-ML-style Params surface (reference
    #    spark/common/params.py:145-270 EstimatorParams: setX/getX
    #    chainable accessors; setParams bulk form). The attribute IS the
    #    storage — no Spark Param machinery to re-create. ---------------

    _PARAMS = ("model", "optimizer", "loss", "store", "num_proc",
               "epochs", "batch_size", "run_id", "shuffle", "seed",
               "worker_env", "data_format")

    def setParams(self, **kwargs) -> "Estimator":
        for k, v in kwargs.items():
            if k not in self._PARAMS:
                raise ValueError(
                    f"unknown param {k!r}; valid: {self._PARAMS}")
            self._set_one(k, v)
        return self

    def _set_one(self, name: str, value) -> "Estimator":
        if name == "data_format" and value not in ("pickle", "parquet"):
            # Same validation as __init__ — setters must not smuggle a
            # bad format past it to fail later inside the workers.
            raise ValueError(
                f"data_format must be 'pickle' or 'parquet', got "
                f"{value!r}")
        setattr(self, name, value)
        return self

    def fit(self, X, y, validation=None, executor=None) -> TrainedModel:
        """Train over the executor pool; returns the fitted transformer.

        ``validation``: a ``(Xv, yv)`` tuple, or a float fraction of the
        training rows to hold out (the reference estimators' validation
        col/fraction contract) — per-epoch val loss lands in
        ``TrainedModel.val_history``. Pass ``executor`` to reuse a warm
        pool across fits (the RayExecutor interactive pattern);
        otherwise a pool of ``num_proc`` workers is started for this
        fit."""
        import time

        from .executor import Executor

        if self.store is None:
            raise ValueError("Estimator requires a store= "
                             "(hvd.store.Store.create(prefix))")
        run_id = self.run_id or f"run_{int(time.time() * 1000):x}"
        X, y, validation = split_validation(X, y, validation,
                                            seed=self.seed)
        # One shard per worker so the rank::size file assignment
        # gives every worker data (reference util.py repartitions to a
        # multiple of the worker count the same way).
        stage_data(self.store, run_id, X, y, validation,
                   self.data_format, num_shards=self.num_proc)

        args = (self.store, run_id, self.model, self.optimizer, self.loss,
                self.epochs, self.batch_size, self.seed, self.shuffle,
                validation is not None, self.data_format)
        if executor is not None:
            results = executor.run(_train_worker, args=args)
        else:
            with Executor(np=self.num_proc,
                          env=self.worker_env) as ex:
                results = ex.run(_train_worker, args=args)

        trained = TrainedModel.load(self.store, run_id, self.model)
        trained.history = results[0]["history"]
        trained.val_history = results[0]["val_history"]
        return trained


def _install_param_accessors() -> None:
    """setEpochs/getEpochs etc. for every Estimator param (reference
    spark/common/params.py accessor naming: snake_case param ->
    CamelCase chainable setter/getter pair)."""
    for p in Estimator._PARAMS:
        camel = "".join(s.capitalize() for s in p.split("_"))

        def setter(self, value, _p=p):
            return self._set_one(_p, value)

        def getter(self, _p=p):
            return getattr(self, _p)

        setattr(Estimator, f"set{camel}", setter)
        setattr(Estimator, f"get{camel}", getter)


_install_param_accessors()
